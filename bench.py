"""Consensus benchmark: panel + judge fully on-device, one JSON line out.

Measures the BASELINE.json headline metric — consensus tokens/sec/chip —
by running the framework's REAL path end-to-end: tpu-provider engines
behind the registry, best-effort runner fan-out, judge synthesis. Nothing
is mocked; the only bench-specific knob is TPUProvider(ignore_eos=True) so
random-init weights decode a controlled number of tokens per phase.

Output: {"metric", "value", "unit", "vs_baseline"} plus supporting fields
(p50 end-to-end latency, device kind, token counts).

vs_baseline: the reference publishes no benchmark numbers (BASELINE.md) —
its compute is remote HTTP APIs, so on-device throughput has no reference
analog. Baseline resolution order: BASELINE.json "published" value if one
ever lands, else the previous round's BENCH_r*.json (so the ratio tracks
round-over-round progress), else 1.0.
"""

from __future__ import annotations

import glob
import json
import os
import re
import statistics
import time

REPO = os.path.dirname(os.path.abspath(__file__))
MAX_TOKENS = int(os.environ.get("BENCH_MAX_TOKENS", "128"))
RUNS = int(os.environ.get("BENCH_RUNS", "3"))

PROMPT = (
    "Compare the tradeoffs between tensor parallelism and pipeline "
    "parallelism for serving large language models, and recommend a "
    "strategy for a 70B parameter model on a 16-chip accelerator pod. "
    "Consider memory capacity, interconnect bandwidth, and latency."
)


def _resolve_baseline() -> float | None:
    try:
        with open(os.path.join(REPO, "BASELINE.json")) as f:
            published = json.load(f).get("published", {})
        for v in published.values():
            if isinstance(v, (int, float)):
                return float(v)
    except (OSError, json.JSONDecodeError):
        pass
    rounds = []
    for path in glob.glob(os.path.join(REPO, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                data = json.load(f)
            # The driver wraps the bench's JSON under "parsed" (None when
            # a past round's line failed to parse); a bare {"value": ...}
            # is also accepted for hand-written baselines.
            if not isinstance(data, dict):
                continue
            if isinstance(data.get("parsed"), dict):
                data = data["parsed"]
            rounds.append((int(m.group(1)), float(data["value"])))
        except (OSError, json.JSONDecodeError, KeyError, TypeError, ValueError):
            continue
    if rounds:
        return max(rounds)[1]
    return None


def _headline() -> dict:
    """The headline consensus measurement (panel + judge, real path).

    Runs inside its own process on TPU (_run_phase_subprocess): the relay
    frees device buffers lazily, so even a release()'d headline provider
    starves later phases' subprocesses of HBM while the parent lives.
    """
    import jax

    from llm_consensus_tpu.consensus import Judge
    from llm_consensus_tpu.providers.registry import Registry
    from llm_consensus_tpu.providers.tpu import TPUProvider
    from llm_consensus_tpu.runner import Runner
    from llm_consensus_tpu.utils.context import Context

    device = jax.devices()[0]
    on_cpu = device.platform == "cpu"
    # CPU fallback (driver runs this on a real chip): tiny shapes so the
    # harness stays runnable anywhere.
    panel = ["tpu:tiny-llama", "tpu:tiny-mistral"] if on_cpu else [
        "tpu:consensus-1b", "tpu:consensus-3b"
    ]
    judge_model = "tpu:tiny-llama" if on_cpu else "tpu:consensus-1b"
    quant, kv_quant = _quant_config()
    # stream_interval=64 for the HEADLINE phase: the per-response decode
    # MFU/MBU diagnostics need at least two fetch boundaries inside
    # MAX_TOKENS (the engine's steady-state clock ticks at fetches), and
    # 64-step chunks still cover the relay's ~65 ms RTT. The throughput
    # phases use 128 (measured +20% single-stream after the round-3
    # kernel dropped step time).
    provider = TPUProvider(
        ignore_eos=True, stream_interval=64, quant=quant, kv_quant=kv_quant
    )
    # Panel + judge placed on mesh slices exactly as the CLI does it; the
    # metric divides by the chips the placement actually occupies, so it
    # stays honest whether the run lands on 1 real chip or an 8-slice.
    provider.prepare(panel, judge_model)
    used_devices: set = set()
    for m in set(panel + [judge_model]):
        mesh = provider.placement(m)
        if mesh is not None:
            used_devices.update(d.id for d in mesh.devices.flat)
    n_chips_used = max(1, len(used_devices))
    registry = Registry()
    for m in set(panel + [judge_model]):
        registry.register(m, provider)
    runner = Runner(registry, timeout=600.0, max_tokens=MAX_TOKENS)
    judge = Judge(provider, judge_model, max_tokens=MAX_TOKENS)

    mfu_samples: list[tuple[int, float]] = []  # (tokens, mfu) per response
    mbu_samples: list[tuple[int, float]] = []  # (tokens, mbu) per response

    run_no = [0]

    def one_run() -> tuple[float, int]:
        # Vary the tail of the prompt per run: identical prompts would let
        # the engines' prefix cache absorb the whole prefill, overstating
        # steady-state throughput; a fresh suffix keeps prefill honest
        # while still exercising shared-prefix reuse like real traffic.
        run_no[0] += 1
        prompt = f"{PROMPT} Consider scenario variant number {run_no[0]}."
        t0 = time.monotonic()
        tokens0 = provider.stats["tokens"]
        result = runner.run(Context.background(), panel, prompt)
        assert len(result.responses) == len(panel), result.failed_models
        for r in result.responses:
            if r.mfu is not None and r.tokens:
                mfu_samples.append((r.tokens, r.mfu))
            if r.mbu is not None and r.tokens:
                mbu_samples.append((r.tokens, r.mbu))
        consensus = judge.synthesize(Context.background(), prompt, result.responses)
        assert consensus
        return time.monotonic() - t0, provider.stats["tokens"] - tokens0

    one_run()  # warmup: compiles prefill/decode for every engine
    wall, toks = zip(*(one_run() for _ in range(RUNS)))
    # ADVICE r2: record the attention impl that actually served the timed
    # runs — a Mosaic lowering rejection on real TPUs degrades to XLA via
    # _flash_guard, which must surface as a flag, not just slower numbers.
    with provider._lock:
        panel_attn = sorted({
            getattr(e, "attn_impl", "?") for e in provider._engines.values()
        })

    total_tokens = sum(toks)
    total_time = sum(wall)
    tok_per_sec_chip = total_tokens / total_time / n_chips_used
    p50_ms = statistics.median(wall) * 1000

    def weighted(samples):
        return (
            round(sum(t * m for t, m in samples) / sum(t for t, _ in samples), 4)
            if samples
            else None
        )

    return {
        "value": round(tok_per_sec_chip, 2),
        "p50_latency_ms": round(p50_ms, 1),
        "runs": RUNS,
        "tokens_per_run": total_tokens // RUNS,
        "panel": panel,
        "judge": judge_model,
        "device": device.device_kind,
        "n_chips": n_chips_used,
        "panel_decode_mfu": weighted(mfu_samples),
        "panel_decode_mbu": weighted(mbu_samples),
        "quant": quant,
        "kv_quant": kv_quant or "bf16",
        "panel_attn_impl": panel_attn,
    }


def _headline_big() -> dict:
    """Pooled big-model headline (VERDICT r4 #4): the headline should
    track the machinery — N concurrent consensus runs (the serving load
    shape) over the biggest panel + judge that fits one chip, with each
    panel engine batching its N concurrent requests through the
    shared-prefix pool and the judge pooling its N synthesis prompts.
    Reference lifecycle analog: cmd/llm-consensus/main.go:83-276, run N
    times concurrently instead of once.
    """
    import jax
    from concurrent.futures import ThreadPoolExecutor

    from llm_consensus_tpu.consensus import Judge
    from llm_consensus_tpu.providers.registry import Registry
    from llm_consensus_tpu.providers.tpu import TPUProvider
    from llm_consensus_tpu.runner import Runner
    from llm_consensus_tpu.utils.context import Context

    device = jax.devices()[0]
    on_cpu = device.platform == "cpu"
    panel = ["tpu:tiny-llama", "tpu:tiny-mistral"] if on_cpu else [
        "tpu:consensus-3b", "tpu:consensus-1b"
    ]
    judge_model = "tpu:tiny-gemma" if on_cpu else "tpu:llama-3-8b"
    quant, kv_quant = _quant_config()
    n_conc = int(os.environ.get("BENCH_BIG_HEADLINE_CONC", "8"))
    # max_seq 1536 covers the judge prompt (panel prompt + 2 × 128-token
    # answers + template ≈ 1.0k tokens) + decode; the 12.2 GB of int8
    # weights (3b + 1b + 8b) plus three n_conc-row pools must co-reside
    # on one 16 GB chip, so KV capacity is the knob that makes it fit.
    provider = TPUProvider(
        ignore_eos=True, stream_interval=64, quant=quant,
        kv_quant=kv_quant, batch_streams=n_conc,
        max_seq=512 if on_cpu else 1536,
    )
    provider.prepare(panel, judge_model, devices=jax.devices()[:1])
    registry = Registry()
    for m in set(panel + [judge_model]):
        registry.register(m, provider)
    runner = Runner(registry, timeout=900.0, max_tokens=MAX_TOKENS)
    judge = Judge(provider, judge_model, max_tokens=MAX_TOKENS)

    def one_run(i: int, tag: str) -> None:
        prompt = f"{PROMPT} Concurrent scenario {tag}-{i}."
        result = runner.run(Context.background(), panel, prompt)
        assert len(result.responses) == len(panel), result.failed_models
        consensus = judge.synthesize(
            Context.background(), prompt, result.responses
        )
        assert consensus

    def wave(tag: str) -> tuple[float, int]:
        t0 = time.monotonic()
        tokens0 = provider.stats["tokens"]
        with ThreadPoolExecutor(n_conc) as ex:
            list(ex.map(lambda i: one_run(i, tag), range(n_conc)))
        return time.monotonic() - t0, provider.stats["tokens"] - tokens0

    wave("warmup")  # compiles every engine's pooled program set
    walls, toks = zip(*(wave(f"run{i}") for i in range(2)))
    best = max(t / w for t, w in zip(toks, walls))
    return {
        "value": round(best, 2),
        "headline_mode": f"pooled x{n_conc} concurrent consensus runs",
        "panel": panel,
        "judge": judge_model,
        "device": device.device_kind,
        "n_chips": 1,
        "runs_per_wave": n_conc,
        "tokens_per_wave": max(toks),
        "quant": quant,
        "kv_quant": kv_quant or "bf16",
    }


def _quant_config() -> tuple:
    """(quant, kv_quant) serving config from BENCH_* env.

    Weight-only int8 (ops/quant.py): decode is HBM-bound, so int8 weight
    streaming is the production-sensible default; int8 KV is also default
    since the paged decode kernel consumes codes + seq-minor scales
    directly — it halves cache HBM and measured faster than bf16 KV at
    every batch size (round 3). Values are read explicitly so ambient
    LLMC_QUANT / LLMC_KV_QUANT can't skew the record.
    """
    quant = os.environ.get("BENCH_QUANT", "int8")
    quant = "bf16" if quant in ("none", "") else quant
    kv_quant = os.environ.get("BENCH_KV_QUANT", "int8")
    kv_quant = None if kv_quant in ("none", "", "bf16") else kv_quant
    return quant, kv_quant


def main() -> None:
    import jax

    device = jax.devices()[0]
    on_cpu = device.platform == "cpu"
    quant, _ = _quant_config()
    if on_cpu:
        head = _headline()  # tiny models; no HBM pressure concerns
    else:
        head = _run_phase_subprocess(["--phase", "headline"], timeout=1800)
    # Early fallback artifact: if the driver's budget kills this process
    # mid-phase, stdout must already hold a parseable headline line —
    # the final compact summary (printed last, after all phases)
    # supersedes it as the last line when the run completes.
    baseline0 = _resolve_baseline()
    early_acc: dict = {}
    best_value: list = [head["value"]]

    def early_line(extra: dict) -> None:
        # Budget-kill protection: accumulate every phase's fields and,
        # after each phase group, (a) refresh BENCH_DETAIL.json with the
        # partial record so the line's `detail` pointer is never stale,
        # and (b) print the accumulated record as a parseable compact
        # line — the driver parses the LAST JSON line of stdout, so a
        # mid-run kill keeps everything measured so far. The final
        # summary below supersedes both on normal completion.
        early_acc.update(extra)
        record = {
            "metric": "consensus tokens/sec/chip (panel+judge, on-device)",
            "unit": "tokens/sec/chip",
            "vs_baseline": (
                round(best_value[0] / baseline0, 3)
                if baseline0 and best_value[0] else 1.0
            ),
            **early_acc,
            "value": best_value[0],
            "partial": True,
        }
        try:
            with open(os.path.join(REPO, "BENCH_DETAIL.json"), "w") as f:
                json.dump(record, f, indent=1)
        except OSError:
            pass
        print(json.dumps(_compact_summary(record)), flush=True)

    early_line(head)

    # Pooled big-model headline (VERDICT r4 #4): the headline `value`
    # should reflect what the machinery can do — N concurrent consensus
    # runs over 3b+1b panel with an 8B judge, panel served through the
    # shared-prefix pool. The classic 1b/3b sequential config stays
    # alongside as value_classic for one round of continuity.
    head_big: dict = {}
    if os.environ.get("BENCH_BIG_HEADLINE", "1") != "0" and not on_cpu:
        # OOM retry at HALVED concurrency (same gate as the ladder-point
        # retry): the 12.2 GB three-model config is the bench's tightest
        # fit and the shared relay chip's free HBM varies with neighbors
        # (lazy frees) — a measured-lower pooled headline beats a
        # silently classic one. Deterministic failures don't retry.
        try:
            base_conc = int(os.environ.get("BENCH_BIG_HEADLINE_CONC", "8"))
        except ValueError:
            base_conc = 8
        for attempt in (0, 1):
            conc = str(base_conc if attempt == 0 else max(1, base_conc // 2))
            try:
                head_big = _run_phase_subprocess(
                    ["--phase", "headline-big"], timeout=2400,
                    env={**os.environ, "BENCH_BIG_HEADLINE_CONC": conc},
                )
                best_value[0] = head_big["value"]
                early_line(head_big)
                break
            except Exception as err:  # noqa: BLE001
                # Keep the message TAIL: _run_phase_subprocess puts the
                # subprocess's final exception line at the end.
                head_big = {
                    "headline_big_error": (
                        f"{type(err).__name__}: {str(err)[-220:]}"
                    )
                }
                if attempt == 0 and "RESOURCE_EXHAUSTED" in str(err):
                    time.sleep(20)  # relay frees HBM lazily, then retry
                else:
                    break

    # Big-model capacity ladder (VERDICT r3 #3) runs FIRST among the
    # secondary phases: it carries the north-star decode-MFU result,
    # which must not sit behind ~40 minutes of 1B ladder if the
    # driver's budget kills the run early.
    big = {}
    if os.environ.get("BENCH_BIG", "") != "0" and not on_cpu:
        try:
            big = _big_ladder(quant)
        except Exception as err:  # noqa: BLE001
            big = {"big_error": f"{type(err).__name__}: {err}"[:200]}
        early_line(big)

    # Judge phase (VERDICT r3 #6): prefill+decode at the long-context
    # judge shape — the consensus workload's long pole at realistic
    # panel sizes.
    judge_fields = {}
    if os.environ.get("BENCH_JUDGE", "1") != "0" and not on_cpu:
        # judge_* measures the NORTH-STAR-CLASS judge (llama-3-8b,
        # VERDICT r4 #2); judge1b_* keeps the round-4 consensus-1b
        # numbers comparable for one more round.
        jm = os.environ.get("BENCH_JUDGE_MODEL", "llama-3-8b")
        try:
            judge_fields = _run_phase_subprocess(
                ["--phase", "judge", "--quant", quant, "--model", jm],
                timeout=1800,
            )
        except Exception as err:  # noqa: BLE001
            judge_fields = {"judge_error": f"{type(err).__name__}: {err}"[:200]}
        try:
            j1b = _run_phase_subprocess(
                ["--phase", "judge", "--quant", quant,
                 "--model", "consensus-1b"], timeout=1500,
            )
            judge_fields.update({
                k.replace("judge_", "judge1b_"): v for k, v in j1b.items()
            })
        except Exception as err:  # noqa: BLE001
            judge_fields["judge1b_error"] = (
                f"{type(err).__name__}: {err}"[:200]
            )
        if os.environ.get("BENCH_JUDGE_SERVING", "1") != "0":
            # Judge-scale serving point + prefill-overlap TTFT A/B
            # (ISSUE 4): judge_ttft_ms vs judge_ttft_classic_ms at the
            # ~4k-context point, plus the hidden-prefill wall.
            try:
                judge_fields.update(_run_phase_subprocess(
                    ["--phase", "judge-serving", "--quant", quant],
                    timeout=1800,
                ))
            except Exception as err:  # noqa: BLE001
                judge_fields["judge_serving_error"] = (
                    f"{type(err).__name__}: {err}"[:200]
                )
        jd = os.environ.get("BENCH_JUDGE_DRAFT", "consensus-1b")
        if jd and jd != "0":
            try:
                judge_fields.update(_run_phase_subprocess(
                    ["--phase", "judge-draft", "--quant", quant,
                     "--model", jm, "--draft", jd], timeout=1800,
                ))
            except Exception as err:  # noqa: BLE001
                judge_fields["judge_draft_error"] = (
                    f"{type(err).__name__}: {err}"[:200]
                )
        early_line(judge_fields)

    # -- batched serving phase (VERDICT r1 #3): aggregate throughput of N
    # concurrent same-model streams through the ContinuousBatcher. Decode
    # is HBM-bound at batch 1, so MFU only moves with batch size — this is
    # the measured route toward the >=50% decode-MFU north star.
    # Optional speculative-decoding variant (BENCH_DRAFT=<preset>): a
    # drafted single-stream generate on the big panel model, reported
    # next to the plain number. Off by default: the bench's random-init
    # weights give ~1 accepted token/round, so this measures the
    # plumbing's overhead floor, not the real-checkpoint win.
    # Optional phases are best-effort: the headline metric is the round's
    # one non-negotiable artifact, and a transient failure in a secondary
    # measurement (e.g. HBM pressure from a neighbor on a shared relay
    # chip) must degrade to a missing field, never rc=1.
    spec_fields = {}
    batched = None
    quant_matrix = None
    draft = os.environ.get("BENCH_DRAFT", "")
    # BENCH_BATCH_STREAMS (the round-2 single-point knob) still works: it
    # collapses the ladder to that one point. BENCH_BATCH_LADDER=<csv>
    # sets the full ladder; 0/empty disables the phase.
    single = os.environ.get("BENCH_BATCH_STREAMS", "")
    default_ladder = single if single else "8,32,128,256,384"
    ladder = [
        int(b)
        for b in os.environ.get("BENCH_BATCH_LADDER", default_ladder).split(",")
        if b.strip() and int(b) > 1
    ]
    if draft and not on_cpu:
        try:
            spec_fields = _draft_phase(draft, quant, "consensus-3b")
        except Exception as err:  # noqa: BLE001
            spec_fields = {"draft_error": f"{type(err).__name__}: {err}"[:200]}
    if ladder and not on_cpu:
        try:
            batched = _serving_ladder(ladder, quant)
        except Exception as err:  # noqa: BLE001
            batched = {"batched_error": f"{type(err).__name__}: {err}"[:200]}
        early_line(batched)
    if os.environ.get("BENCH_QUANT_MATRIX", "1") != "0" and not on_cpu:
        try:
            quant_matrix = _quant_matrix()
        except Exception as err:  # noqa: BLE001
            quant_matrix = {"quant_matrix_error": f"{type(err).__name__}: {err}"[:200]}
    # Experimental w8a8 capacity point (LLMC_W8A8=1 in a fresh
    # subprocess): int8 activations double the MXU matmul rate — the
    # B-scaled FLOPs term at capacity batch — at the cost of a NEW
    # rounding-error source, so it ships opt-in and reports under its
    # own clearly-labeled fields rather than in the default ladder.
    w8a8_point = {}
    if (
        os.environ.get("BENCH_W8A8", "1") != "0"
        and ladder
        and not on_cpu
        and quant == "int8"  # the lane only exists for int8 weights
    ):
        try:
            b_cap = max(ladder)
            p = _run_phase_subprocess(
                ["--phase", "ladder-point", "--streams", str(b_cap),
                 "--quant", quant],
                env={**os.environ, "LLMC_W8A8": "1"},
            )
            w8a8_point = {
                "w8a8_streams": p["streams"],
                "w8a8_tokens_per_sec_chip": p["tokens_per_sec_chip"],
                "w8a8_decode_mfu": p["decode_mfu"],
                # VERDICT r3 weak #4: w8a8_decode_mfu is normalized
                # against the DENSE BF16 peak (one scale for every lane);
                # the int8-peak variant rescales by the chip's actual
                # bf16:int8 rate ratio (2× on v5e/v5p/v6e, 1× on v4,
                # absent on v2/v3 — utils/flops.device_peak_int8_ops).
                "w8a8_decode_mfu_int8peak": _int8peak_mfu(
                    p.get("decode_mfu"), head.get("device", "")
                ),
                "w8a8_note": (
                    "experimental int8 activations (LLMC_W8A8=1): double "
                    "MXU rate on the int8-weight matmuls; mfu normalized "
                    "vs dense bf16 peak — see w8a8_decode_mfu_int8peak; "
                    "token outputs differ from the bf16-activation lane"
                ),
            }
            if os.environ.get("BENCH_W8A8_DIVERGENCE", "1") != "0":
                try:
                    w8a8_point.update(_run_phase_subprocess(
                        ["--phase", "w8a8-divergence"], timeout=1200,
                    ))
                except Exception as err:  # noqa: BLE001
                    w8a8_point["w8a8_divergence_error"] = (
                        f"{type(err).__name__}: {err}"[:200]
                    )
        except Exception as err:  # noqa: BLE001
            w8a8_point = {"w8a8_error": f"{type(err).__name__}: {err}"[:200]}

    # Occupancy-bucketing A/B (VERDICT r4 #6): both halves in the
    # driver artifact as fields, not prose.
    occ = {}
    if os.environ.get("BENCH_OCCUPANCY", "1") != "0" and not on_cpu:
        try:
            occ_on = _run_phase_subprocess(
                ["--phase", "occupancy-point"],
                env={**os.environ, "LLMC_POOL_BUCKET": "1"}, timeout=1200,
            )
            occ_off = _run_phase_subprocess(
                ["--phase", "occupancy-point"],
                env={**os.environ, "LLMC_POOL_BUCKET": "0"}, timeout=1200,
            )
            on_r = occ_on.get("decode_phase_tokens_per_sec")
            off_r = occ_off.get("decode_phase_tokens_per_sec")
            occ = {
                "occupancy_ab": {
                    "bucket_on": occ_on, "bucket_off": occ_off,
                    "speedup": (
                        round(on_r / off_r, 2) if on_r and off_r else None
                    ),
                }
            }
        except Exception as err:  # noqa: BLE001
            occ = {"occupancy_error": f"{type(err).__name__}: {err}"[:200]}

    # Cross-request paged-KV prefix sharing (kv/): warm shared-prefix
    # prefill speedup, classic-vs-pooled alternating-prefix thrash, and
    # the equal-HBM resident-stream capacity model — pool on vs off in
    # one subprocess (it builds its own engines either way).
    prefix_fields = {}
    if os.environ.get("BENCH_PREFIX_SHARING", "1") != "0" and not on_cpu:
        try:
            prefix_fields = _run_phase_subprocess(
                ["--phase", "prefix-sharing", "--quant", quant],
                timeout=1200,
            )
            early_line(prefix_fields)
        except Exception as err:  # noqa: BLE001
            prefix_fields = {
                "prefix_sharing_error": f"{type(err).__name__}: {err}"[:200]
            }

    # Pressure-governor point (ISSUE 9): HIGH-priority p50/p99 under a
    # 4× LOW overload, priority stack on vs off, preempt-resume cost.
    # CPU-runnable (tiny models) so every driver round carries the
    # numbers even without a chip.
    pressure_fields = {}
    if os.environ.get("BENCH_PRESSURE", "1") != "0":
        try:
            pressure_fields = _run_phase_subprocess(
                ["--phase", "pressure", "--quant", quant], timeout=1500,
            )
            early_line(pressure_fields)
        except Exception as err:  # noqa: BLE001
            pressure_fields = {
                "pressure_error": f"{type(err).__name__}: {err}"[:200]
            }

    # Disaggregated prefill/decode point (ISSUE 13): e2e-over-decode-
    # phase with admission prefill moved to dedicated prefill workers
    # (cross-mesh KV handoff) vs the interleaved baseline on the same
    # device budget, plus measured handoff bytes/s. Needs >= 2 devices
    # (the subprocess reports a skip marker otherwise).
    disagg_fields = {}
    if os.environ.get("BENCH_DISAGG", "1") != "0":
        try:
            disagg_fields = _run_phase_subprocess(
                ["--phase", "disagg", "--quant", quant], timeout=1500,
            )
            early_line(disagg_fields)
        except Exception as err:  # noqa: BLE001
            disagg_fields = {
                "disagg_error": f"{type(err).__name__}: {err}"[:200]
            }

    # Elastic scale-down point (ISSUE 16): HIGH-class streaming p50/p99
    # across a replica retire, live migration vs drain-and-wait, plus
    # the retiring replica's vacate time. CPU-runnable (tiny fleet) so
    # every driver round carries the numbers even without a chip.
    elastic_fields = {}
    if os.environ.get("BENCH_ELASTIC", "1") != "0":
        try:
            elastic_fields = _run_phase_subprocess(
                ["--phase", "elastic", "--quant", quant], timeout=1500,
            )
            early_line(elastic_fields)
        except Exception as err:  # noqa: BLE001
            elastic_fields = {
                "elastic_error": f"{type(err).__name__}: {err}"[:200]
            }

    # Flywheel hot-swap point (ISSUE 18): streaming p50/p99 across a
    # live checkpoint hot-swap landing under a pinned stream, the
    # engine's vacate/prep split, and the drain-and-restart outage the
    # swap path avoids. CPU-runnable (tiny model, in-process gateway).
    flywheel_fields = {}
    if os.environ.get("BENCH_FLYWHEEL", "1") != "0":
        try:
            flywheel_fields = _run_phase_subprocess(
                ["--phase", "flywheel", "--quant", quant], timeout=1500,
            )
            early_line(flywheel_fields)
        except Exception as err:  # noqa: BLE001
            flywheel_fields = {
                "flywheel_error": f"{type(err).__name__}: {err}"[:200]
            }

    # Live-observability overhead point (ISSUE 11): pooled decode tok/s
    # with the /metricsz live plane + flight recorder on vs off — the
    # continuous twin of PR 2's zero-cost-when-disabled gate (≤ 2%).
    obs_fields = {}
    if os.environ.get("BENCH_OBS", "1") != "0":
        try:
            obs_fields = _run_phase_subprocess(
                ["--phase", "obs-overhead", "--quant", quant], timeout=1200,
            )
            early_line(obs_fields)
        except Exception as err:  # noqa: BLE001
            obs_fields = {
                "obs_overhead_error": f"{type(err).__name__}: {err}"[:200]
            }

    # Integrity-plane overhead point (ISSUE 20): pooled decode tok/s
    # with the corruption-detection plane (finite-logit sentinel +
    # sampled gather verification) on vs off — gate ≤ 2% at the default
    # sampling rate. CPU-runnable (tiny model).
    integrity_fields = {}
    if os.environ.get("BENCH_INTEGRITY", "1") != "0":
        try:
            integrity_fields = _run_phase_subprocess(
                ["--phase", "integrity", "--quant", quant], timeout=1200,
            )
            early_line(integrity_fields)
        except Exception as err:  # noqa: BLE001
            integrity_fields = {
                "integrity_error": f"{type(err).__name__}: {err}"[:200]
            }

    baseline = _resolve_baseline()
    value = head_big.get("value") or head["value"]
    full = {
        "metric": "consensus tokens/sec/chip (panel+judge, on-device)",
        "unit": "tokens/sec/chip",
        "vs_baseline": round(value / baseline, 3) if baseline else 1.0,
        **head,
        **head_big,
        "value": value,
        "value_classic": head["value"],
        **spec_fields,
        **(batched or {}),
        **w8a8_point,
        **big,
        **judge_fields,
        **(quant_matrix or {}),
        **occ,
        **prefix_fields,
        **pressure_fields,
        **disagg_fields,
        **elastic_fields,
        **flywheel_fields,
        **obs_fields,
        **integrity_fields,
    }
    # VERDICT r3 weak #1: the driver keeps only the LAST ~2000 chars of
    # stdout and parses the last JSON line. Round 3 printed ONE giant
    # line whose head (metric/value/p50) was truncated away → the round's
    # headline number never made the official record. Now: the full
    # record goes to BENCH_DETAIL.json and an early stdout line, and the
    # FINAL line is a compact (≤600 char) summary that always parses.
    try:
        with open(os.path.join(REPO, "BENCH_DETAIL.json"), "w") as f:
            json.dump(full, f, indent=1)
    except OSError:
        pass  # detail file is a convenience; stdout still carries all
    print(json.dumps(full))
    print(json.dumps(_compact_summary(full)))


_COMPACT_KEYS = (
    # Priority order; later entries are dropped first if the line would
    # exceed the budget. The first four are the driver's parse contract.
    "metric", "value", "unit", "vs_baseline",
    "p50_latency_ms", "device", "headline_mode", "value_classic",
    "batched_streams", "batched_tokens_per_sec_chip", "batched_decode_mfu",
    "batched_decode_phase_tokens_per_sec", "batched_e2e_over_decode_phase",
    "judge_ttft_ms", "judge_ttft_classic_ms", "judge_overlap_hidden_s",
    "w8a8_tokens_per_sec_chip", "w8a8_decode_mfu", "w8a8_decode_mfu_int8peak",
    "big_model", "big_streams", "big_tokens_per_sec_chip", "big_decode_mfu",
    "judge_prefill_tokens_per_sec", "judge_prefill_mfu",
    "judge_decode_tokens_per_sec",
    "prefix_warm_speedup", "prefix_alt_speedup", "prefix_capacity_gain",
    "prefix_hit_token_fraction",
    "pressure_high_p99_ms", "pressure_high_p99_ms_fifo",
    "pressure_high_429", "pressure_high_429_fifo",
    "pressure_preemptions", "pressure_resume_speedup",
    "disagg_e2e_over_decode_phase", "disagg_baseline_e2e_over_decode_phase",
    "disagg_handoff_bytes_per_s", "disagg_ok",
    "elastic_high_p99_ms", "elastic_high_p99_ms_drain",
    "elastic_vacate_ms", "elastic_vacate_ms_drain", "elastic_migrations",
    "flywheel_high_p99_ms", "flywheel_high_p99_ms_noswap",
    "flywheel_swap_vacate_ms", "flywheel_restart_ms",
    "obs_overhead_pct", "obs_overhead_ok",
    "obs_overhead_tok_s_on", "obs_overhead_tok_s_off",
    "integrity_overhead_pct", "integrity_ok",
    "integrity_tok_s_on", "integrity_tok_s_off",
    "panel_decode_mfu", "quant", "kv_quant",
    "batched_attn_impl", "n_chips", "detail",
)


def _int8peak_mfu(bf16_mfu, device_kind: str):
    """Rescale a bf16-peak-normalized MFU to the chip's int8 peak; None
    when the generation has no int8 rate (see flops.device_peak_int8_ops)."""
    from llm_consensus_tpu.utils.flops import (
        device_peak_flops, device_peak_int8_ops)

    if not bf16_mfu:
        return None
    peak, ipeak = device_peak_flops(device_kind), device_peak_int8_ops(device_kind)
    if not peak or not ipeak:
        return None
    return round(bf16_mfu * peak / ipeak, 4)


def _compact_summary(full: dict, budget: int = 600) -> dict:
    """The last-line artifact: headline + best ladder/W8A8/big-model/judge
    numbers, guaranteed to fit the driver's tail capture."""
    src = dict(full)
    src["detail"] = "BENCH_DETAIL.json"
    out = {k: src[k] for k in _COMPACT_KEYS if src.get(k) is not None}
    # "detail" is protected along with the parse contract: it is the
    # pointer to the full record and must survive trimming.
    keep = ("metric", "value", "unit", "vs_baseline", "detail")
    while len(json.dumps(out)) > budget and len(out) > len(keep):
        for k in reversed(_COMPACT_KEYS):
            if k in out and k not in keep:
                del out[k]
                break
    return out


def _draft_phase(draft: str, quant: str, target: str) -> dict:
    """Single-stream decode tok/s with and without a draft attached."""
    from llm_consensus_tpu.providers.base import Request
    from llm_consensus_tpu.providers.tpu import TPUProvider
    from llm_consensus_tpu.utils.context import Context

    def measure(provider) -> float:
        # Engines released in the finally AFTER the timestamp: teardown
        # time must not skew the drafted-vs-plain comparison, and a
        # mid-phase failure must not leak HBM into the next phase.
        try:
            req = Request(
                model=f"tpu:{target}", prompt=PROMPT, max_tokens=MAX_TOKENS
            )
            provider.query(Context.background(), req)  # warmup
            t0 = time.monotonic()
            resp = provider.query(Context.background(), req)
            dt = time.monotonic() - t0
            return (resp.tokens or 0) / dt
        finally:
            provider.release()

    plain = TPUProvider(ignore_eos=True, stream_interval=128, quant=quant)
    drafted = TPUProvider(
        ignore_eos=True, stream_interval=128, quant=quant, draft=draft,
    )
    plain_tps = measure(plain)
    drafted_tps = measure(drafted)
    return {
        "draft": draft,
        "draft_target": target,
        "draft_tokens_per_sec": round(drafted_tps, 2),
        "draft_plain_tokens_per_sec": round(plain_tps, 2),
    }


def _run_phase_subprocess(argv: list, timeout: float = 900,
                          env: dict | None = None) -> dict:
    """Run one measurement phase in a FRESH process and parse its JSON.

    The relay chip frees device buffers lazily, so phases that each fit
    comfortably alone OOM when run back-to-back in one process (measured:
    the B=32 ladder point RESOURCE_EXHAUSTED after the headline phase
    had already released its engines). A subprocess gives every phase a
    clean HBM slate; the persistent XLA cache keeps recompiles cheap.
    """
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), *argv],
        capture_output=True, text=True, timeout=timeout, cwd=REPO,
        env=env,
    )
    for line in reversed(proc.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise RuntimeError(
        f"phase {argv} produced no JSON (rc={proc.returncode}): "
        f"{proc.stderr.strip()[-300:]}"
    )


def _serving_ladder(ladder: list, quant: str) -> dict:
    """Serving-path batch ladder: aggregate tok/s/chip + decode MFU/MBU
    at each B, with the same-B ``generate_batch`` aggregate alongside.

    Each point runs in its own subprocess (fresh HBM — see
    _run_phase_subprocess) and fires B concurrent requests through a
    stream-batching provider; the ``generate_batch`` reference on the
    SAME engine pins the serving-vs-static-batch ratio in the driver
    artifact (round-2 gap: serving lost ~2.4×; batched admission closed
    it). int8 KV is the ladder's serving config — it halves cache HBM
    (capacity for the large-B points) and, with the paged decode kernel
    consuming codes directly, wins at every batch size measured.
    """
    out: dict = {"batched_model": "tpu:consensus-1b", "batched_ladder": []}
    for batch_streams in ladder:
        point = None
        for attempt in range(2):
            try:
                point = _run_phase_subprocess(
                    ["--phase", "ladder-point", "--streams",
                     str(batch_streams), "--quant", quant]
                )
                break
            except Exception as err:  # noqa: BLE001
                point = {
                    "streams": batch_streams,
                    "error": f"{type(err).__name__}: {err}"[:200],
                }
                if "RESOURCE_EXHAUSTED" in str(err) and attempt == 0:
                    # Shared relay chip: neighbor HBM pressure is
                    # transient; one backoff retry before recording the
                    # point as failed.
                    time.sleep(20)
                else:
                    break
        out["batched_ladder"].append(point)
    # Outlier re-fire (VERDICT r3 weak #2): a relay stall can sink one
    # point 10× below steady state even best-of-N inside the subprocess
    # (round 3's official B=32 = 562 tok/s against a ~5.6k claim). The
    # ladder is physically non-decreasing in B until saturation, so a
    # point far below a NEIGHBOR is a measurement artifact: re-fire its
    # subprocess once and keep the better result, recording both.
    pts = out["batched_ladder"]

    def tps(p):
        return p.get("tokens_per_sec_chip")

    for i, p in enumerate(pts):
        neigh = [
            tps(q) for j, q in enumerate(pts)
            if abs(j - i) == 1 and tps(q) is not None
        ]
        if tps(p) is not None and neigh and tps(p) < 0.6 * max(neigh):
            try:
                redo = _run_phase_subprocess(
                    ["--phase", "ladder-point", "--streams",
                     str(p["streams"]), "--quant", quant]
                )
            except Exception:  # noqa: BLE001 — keep the original point
                continue
            if tps(redo) is not None and tps(redo) > tps(p):
                redo["first_attempt_tokens_per_sec"] = tps(p)
                redo["refired"] = True
                pts[i] = redo
    # Headline batched_* fields = the best ladder point (back-compat with
    # the round-2 artifact's flat fields).
    best = max(
        (p for p in pts if "tokens_per_sec_chip" in p),
        key=lambda p: p["tokens_per_sec_chip"],
        default=None,
    )
    if best is not None:
        out.update({
            "batched_streams": best["streams"],
            "batched_tokens_per_sec_chip": best["tokens_per_sec_chip"],
            "batched_decode_mfu": best["decode_mfu"],
            "batched_decode_mbu": best["decode_mbu"],
            "batched_decode_phase_tokens_per_sec": best.get(
                "decode_phase_tokens_per_sec"
            ),
            "batched_e2e_over_decode_phase": best.get(
                "e2e_over_decode_phase"
            ),
            "batched_attn_impl": best["attn_impl"],
        })
    return out


def _ladder_point(batch_streams: int, quant: str,
                  preset: str = "consensus-1b") -> dict:
    """One serving-ladder measurement (runs inside its own process)."""
    from concurrent.futures import ThreadPoolExecutor

    import jax

    from llm_consensus_tpu.engine import SamplingParams
    from llm_consensus_tpu.models.config import get_config
    from llm_consensus_tpu.providers.base import Request
    from llm_consensus_tpu.providers.tpu import TPUProvider
    from llm_consensus_tpu.utils.context import Context
    from llm_consensus_tpu.utils.flops import batched_decode_mbu, decode_mfu

    model = f"tpu:{preset}"
    cfg = get_config(preset)
    device = jax.devices()[0]
    # Cap context capacity to what the phase actually needs (prompt +
    # suffix + decode, next power of two, floor 1024): the B-slot cache's
    # HBM is capacity × slots — at B=128 the capacity cap is what lets
    # the pool fit one chip at all. Derived from MAX_TOKENS so a
    # BENCH_MAX_TOKENS override can't silently truncate streams.
    need = len(PROMPT) + 32 + MAX_TOKENS
    # Floor 1024 for the 1B ladder (keeps round-over-round points
    # comparable); big models take the tight power-of-two — at 8B the
    # KV difference (67 → 33 MB/stream at int8) is what lets a B=32
    # pool co-reside with 8 GB of weights on one 16 GB chip.
    floor = 1024 if preset == "consensus-1b" else 512
    max_seq = max(floor, 1 << (need - 1).bit_length())
    if batch_streams >= 192 and need + MAX_TOKENS <= 768:
        # Capacity points: the pool cache is capacity × slots (8.6 GB at
        # 256×1024 int8) and must co-reside with the admission prefill
        # cache; 768 slots still covers prompt + decode with margin.
        # (>=192, not >=256: the 8B int4 capacity ladder needs the same
        # cap — 192×1024 int8 KV is 12.9 GB next to 4.1 GB of weights.)
        max_seq = 768
    if quant == "int4" and batch_streams >= 256 and need <= 640:
        # 8B int4 B=256: KV at 768 slots (12.9 GB) + 4.1 GB weights
        # overruns 16 GB; 640 slots (128-granule, non-pow2 is fine)
        # still covers the single-stream-fallback prompt + decode.
        max_seq = 640
    if batch_streams >= 512 and need <= 512:
        # B=512 fits one chip only because shared-prefix rows occupy
        # suffix-sized windows; capacity just has to cover the FULL
        # prompt + decode for the single-stream fallback path.
        max_seq = 512
    ctx_len = len(PROMPT) + MAX_TOKENS // 2  # byte tokenizer ≈ 1 tok/char
    # stream_interval=64 (not the single-stream-optimal 128): with
    # MAX_TOKENS=128 a 128-step chunk makes every stream exactly one
    # chunk, so no admission-free fetch interval ever exists and the
    # decode-phase rate cannot be measured; 64-step chunks give each
    # fire a steady second chunk, and at serving batch sizes the extra
    # dispatch amortizes across rows.
    # Interleaved admission prefill (ISSUE 4): the ladder runs with the
    # serving default ON, so the e2e-vs-decode-phase ratio reflects
    # admissions overlapping decode. BENCH_PREFILL_BUDGET=0 reverts to
    # the classic stall-the-pool admission for A/B.
    prefill_budget = int(os.environ.get("BENCH_PREFILL_BUDGET", "2048") or 0)
    provider = TPUProvider(
        ignore_eos=True, stream_interval=64, quant=quant,
        kv_quant="int8", batch_streams=batch_streams, max_seq=max_seq,
        prefill_budget=prefill_budget,
    )
    # Pin to ONE device: on a multi-chip host the planner would hand the
    # model a TP mesh spanning chips, and the phase must measure per-chip
    # batching.
    provider.prepare([model], None, devices=jax.devices()[:1])

    def fire(tag: str) -> tuple[float, int]:
        reqs = [
            Request(
                model=model,
                prompt=f"{PROMPT} Stream {tag}-{i}.",
                max_tokens=MAX_TOKENS,
            )
            for i in range(batch_streams)
        ]
        t0 = time.monotonic()
        with ThreadPoolExecutor(batch_streams) as ex:
            results = list(
                ex.map(lambda r: provider.query(Context.background(), r), reqs)
            )
        return time.monotonic() - t0, sum(r.tokens or 0 for r in results)

    # Warmup until the admission/decode program set settles (burst waves
    # split nondeterministically, so one pass can miss a padded-wave
    # variant; the persistent XLA cache makes later passes cheap).
    for i in range(3):
        fire(f"warmup{i}")
    # Decode-phase accounting: snapshot the batcher's steady-state decode
    # counters AFTER warmup (warmup intervals absorb compiles), so the
    # delta over the timed fires is the pure decode-chunk rate — reported
    # NEXT TO the end-to-end aggregate, which folds admission in.
    batcher = next(iter(provider._batchers.values()))[1]
    # Adaptive best-of-N (VERDICT r3: best-of-2 demonstrably wasn't
    # enough — the official B=32 point recorded a 10×-low relay stall):
    # keep firing, up to 4, until the top two rates agree within 30%,
    # then report the max. A stalled fire only ever lowers a rate, so
    # max is the right statistic; agreement of two independent fires is
    # the evidence the max is steady state, not luck.
    # Decode-phase stats snapshot PER FIRE (ADVICE r4): diffing across
    # the union of fires let one relay-stalled fire inflate decode_s and
    # contradict the best-fire aggregate reported next to it. The stats
    # dict is REPLACED atomically by the batcher, so one reference per
    # snapshot (never indexing self.stats twice) avoids tearing
    # tokens-vs-seconds by an interval.
    rates, fire_stats, fire_walls, fire_toks = [], [], [], []
    for i in range(4):
        stats0 = batcher.stats
        wall, toks = fire(f"run{i}")
        stats1 = batcher.stats
        rates.append(toks / wall)
        fire_stats.append({k: stats1[k] - stats0[k] for k in stats0})
        fire_walls.append(wall)
        fire_toks.append(toks)
        if len(rates) >= 2 and sorted(rates)[-2] >= max(rates) / 1.3:
            break
    agg_tps = max(rates)
    best = rates.index(agg_tps)
    bstat = fire_stats[best]
    if bstat["decode_s"] <= 0:
        # Best fire retired inside one chunk (no pure-decode interval):
        # fall back to the best per-fire decode rate, same max logic.
        per = [
            s["decode_tokens"] / s["decode_s"]
            for s in fire_stats if s["decode_s"] > 0
        ]
        decode_phase_tps = max(per) if per else None
    else:
        decode_phase_tps = bstat["decode_tokens"] / bstat["decode_s"]
    # Per-phase wall bisection of the best fire (VERDICT r4 #3): the
    # e2e-vs-decode-phase gap decomposes into scheduler-side admission
    # work (establish + admit prefill + burst absorb) and fetch-side
    # tail dead-stepping; `unaccounted` is what remains of the fire wall
    # (host emit loop, dispatch, pipeline idle). Phases overlap threads,
    # so the sum can exceed wall slightly — each term is still the
    # honest wall of that phase.
    phase = {
        "wall_s": round(fire_walls[best], 3),
        "decode_s": round(bstat["decode_s"], 3),
        # impure_s: arrival intervals carrying admission-prefill /
        # establishment / compaction DEVICE time (their async dispatch
        # makes the host-side admit_s/establish_s near-zero through the
        # relay); impure_tokens are the real output tokens emitted in
        # those intervals.
        "impure_s": round(bstat["impure_s"], 3),
        "impure_tokens": bstat["impure_tokens"],
        "tail_s": round(bstat["tail_s"], 3),
        "establish_s": round(bstat["establish_s"], 3),
        "admit_s": round(bstat["admit_s"], 3),
        "absorb_s": round(bstat["absorb_s"], 3),
        "unaccounted_s": round(
            fire_walls[best] - bstat["decode_s"] - bstat["impure_s"]
            - bstat["tail_s"] - bstat["establish_s"] - bstat["admit_s"]
            - bstat["absorb_s"],
            3,
        ),
    }
    # Prefill-inclusive rate: output tokens PLUS prompt tokens actually
    # prefilled (suffixes under shared-prefix admission) over the same
    # wall — admission cost stops masquerading as pure overhead when its
    # processed tokens are counted (VERDICT r4 weak #2).
    prefill_incl_tps = (
        (fire_toks[best] + bstat["admit_tokens"]) / fire_walls[best]
    )
    pool_prefix_len = batcher._prefix_len_host
    engine = provider._engine_for(model)
    attn_impl = engine.attn_impl
    weight_bytes = {"int8": 1, "int4": 0.5}.get(engine.quant, 2)
    kv_bytes = 1 if engine.kv_quant == "int8" else 2
    # generate_batch reference on a FRESH engine (the serving provider —
    # batcher pool cache included — is released first, so the phase's
    # peak HBM is max(serving, reference), not their sum; the shared
    # relay chip's free HBM varies with neighbors). Capacity points
    # (B ≥ 256) skip the reference: generate_batch's right-aligned
    # prefill takes the XLA attention path (per-row offsets rule out the
    # flash kernel), whose one-shot score tensor at that batch is
    # infeasible — the serving path, which prefills waves left-aligned
    # through the kernel, is the only configuration that runs there.
    engine = None
    provider.release()
    import gc

    gc.collect()
    gb_tps = None
    if batch_streams < 256 and preset == "consensus-1b":
        from llm_consensus_tpu.engine import Engine

        eng = Engine(
            cfg, quant=quant if quant != "bf16" else None, kv_quant="int8",
            max_seq=max_seq, stream_interval=128,
        )
        prompts = [f"{PROMPT} Stream gb-{i}." for i in range(batch_streams)]
        s = SamplingParams(max_new_tokens=MAX_TOKENS, ignore_eos=True)
        eng.generate_batch(prompts, s)  # warmup
        t0 = time.monotonic()
        results = eng.generate_batch(prompts, s)
        gb_tps = sum(len(r.token_ids) for r in results) / (
            time.monotonic() - t0
        )
    mfu = decode_mfu(cfg, agg_tps, device.device_kind, context_len=ctx_len)
    mbu = batched_decode_mbu(
        cfg, agg_tps, batch_streams, device.device_kind, context_len=ctx_len,
        weight_bytes=weight_bytes, kv_bytes=kv_bytes,
    )
    dp_mfu = (
        decode_mfu(cfg, decode_phase_tps, device.device_kind, context_len=ctx_len)
        if decode_phase_tps else None
    )
    return {
        "model": preset,
        "streams": batch_streams,
        "fires": len(rates),
        "prefill_budget": prefill_budget,
        "tokens_per_sec_chip": round(agg_tps, 2),
        "decode_phase_tokens_per_sec": (
            round(decode_phase_tps, 2) if decode_phase_tps else None
        ),
        # The overlap headline (ISSUE 4 acceptance): end-to-end aggregate
        # over the steady decode-phase rate — 1.0 means admission prefill
        # costs no end-to-end throughput at all.
        "e2e_over_decode_phase": (
            round(agg_tps / decode_phase_tps, 3) if decode_phase_tps else None
        ),
        "decode_phase_mfu": round(dp_mfu, 4) if dp_mfu else None,
        "prefill_inclusive_tokens_per_sec": round(prefill_incl_tps, 2),
        "phase": phase,
        "pool_prefix_len": pool_prefix_len,
        "generate_batch_tokens_per_sec": (
            round(gb_tps, 2) if gb_tps else None
        ),
        "serving_vs_generate_batch": (
            round(agg_tps / gb_tps, 3) if gb_tps else None
        ),
        "decode_mfu": round(mfu, 4) if mfu else None,
        "decode_mbu": round(mbu, 4) if mbu else None,
        "device_kind": device.device_kind,
        # ADVICE r2: a Mosaic rejection on real TPUs silently degrades to
        # XLA via _flash_guard; record the impl that actually served the
        # timed runs so a fallback shows up as a flag, not just slower
        # numbers.
        "attn_impl": attn_impl,
    }


def _occupancy_point() -> dict:
    """One half of the occupancy-bucketing A/B (VERDICT r4 #6: the 2.6×
    claim lived only in BASELINE.md prose): 64 long-decode streams
    resident in a 256-slot pool (25% occupancy). Whether the pool may
    physically shrink its decode rows comes from LLMC_POOL_BUCKET in
    the environment — the driver-visible A/B runs this phase twice.
    """
    from concurrent.futures import ThreadPoolExecutor

    import jax

    from llm_consensus_tpu.providers.base import Request
    from llm_consensus_tpu.providers.tpu import TPUProvider
    from llm_consensus_tpu.utils.context import Context

    provider = TPUProvider(
        ignore_eos=True, stream_interval=64, quant="int8", kv_quant="int8",
        batch_streams=256, max_seq=768,
    )
    provider.prepare(["tpu:consensus-1b"], None, devices=jax.devices()[:1])

    def fire(tag: str) -> tuple[float, int]:
        reqs = [
            Request(
                model="tpu:consensus-1b",
                prompt=f"{PROMPT} Occupancy stream {tag}-{i}.",
                max_tokens=256,
            )
            for i in range(64)
        ]
        t0 = time.monotonic()
        with ThreadPoolExecutor(64) as ex:
            results = list(
                ex.map(lambda r: provider.query(Context.background(), r), reqs)
            )
        return time.monotonic() - t0, sum(r.tokens or 0 for r in results)

    fire("warmup")
    batcher = next(iter(provider._batchers.values()))[1]
    best = None
    for i in range(2):
        stats0 = batcher.stats
        fire(f"run{i}")
        stats1 = batcher.stats
        ds = stats1["decode_s"] - stats0["decode_s"]
        if ds > 0:
            rate = (stats1["decode_tokens"] - stats0["decode_tokens"]) / ds
            best = rate if best is None else max(best, rate)
    return {
        "occupancy_streams": 64,
        "occupancy_pool_slots": 256,
        "bucket_enabled": batcher._rows_bucket_enabled,
        "rows_cap_end": batcher._rows_cap,
        "decode_phase_tokens_per_sec": round(best, 2) if best else None,
    }


def _prefix_sharing_phase(quant: str, preset: str = "consensus-1b") -> dict:
    """Paged-KV-pool prefix-sharing point (ISSUE 7, kv/): N requests
    sharing a long system prompt, measured at the engine prefill layer.

    Three numbers, all driver-visible fields:

      * warm-vs-cold prefill tok/s with the pool ON — a warm request's
        shared prefix arrives by block gather (copy bandwidth), so only
        the distinct tail runs through the model;
      * alternating two DIFFERENT system prompts, classic vs pooled —
        the classic single-slot snapshot thrashes (every request evicts
        the other prefix and pays a cold prefill), the radix holds both
        (this is the cross-REQUEST part of the claim, not reachable by
        the single-slot design at any size);
      * max resident decode streams at equal KV HBM
        (BENCH_KV_HBM_GB, default 8): row-bucketed streams each own a
        full prompt+output window; pooled streams store the shared
        prefix ONCE in the arena and own only suffix+output windows.
        Model-computed from the measured bytes/token, same budget both
        sides.
    """
    import gc

    import jax

    from llm_consensus_tpu.engine.engine import Engine, _bucket
    from llm_consensus_tpu.models.config import get_config

    on_cpu = jax.devices()[0].platform == "cpu"
    if on_cpu:
        preset = "tiny-llama"
        sys_chars, n_req, max_seq, chunk = 512, 4, 2048, 64
    else:
        sys_chars, n_req, max_seq, chunk = 2048, 8, 8192, 512
    seed_a = "You are panel member A in a production consensus fleet. "
    seed_b = "Operate as service tier B with strict latency budgets now. "
    sys_a = (seed_a * (sys_chars // len(seed_a) + 1))[:sys_chars]
    sys_b = (seed_b * (sys_chars // len(seed_b) + 1))[:sys_chars]
    tails = [f"User request {i}: summarize the key tradeoffs. " for i in range(n_req)]
    out_tokens = 128  # capacity model: decode budget per resident stream

    def build(pool: bool) -> Engine:
        os.environ["LLMC_KV_POOL"] = "1" if pool else "0"
        cfg = get_config(preset)
        return Engine(
            cfg, quant=quant if quant != "bf16" else None, kv_quant="int8",
            max_seq=max_seq, prefill_chunk=chunk, stream_interval=64,
        )

    def timed_prefill(eng: Engine, prompt: str) -> float:
        """Seconds for one full prefill of ``prompt`` (publish included —
        the serving path retains every finished cache)."""
        ids = eng.tokenizer.encode(prompt)
        t0 = time.monotonic()
        logits, cache = eng._prefill_ids(ids)
        jax.block_until_ready(logits)
        eng._retain_prefix(ids, cache)
        wall = time.monotonic() - t0
        return wall, len(ids)

    saved_env = os.environ.get("LLMC_KV_POOL")
    try:
        # -- warm vs cold, pool on ------------------------------------------
        eng = build(pool=True)
        cold_s, cold_tok = timed_prefill(eng, sys_a + tails[0])
        warm = [timed_prefill(eng, sys_a + t) for t in tails[1:]]
        warm_s = sum(w for w, _ in warm)
        warm_tok = sum(n for _, n in warm)
        kv = eng._kv_pool.stats() if eng._kv_pool is not None else {}
        hit_frac = (
            kv["hit_tokens"] / (kv["hit_tokens"] + kv["miss_tokens"])
            if kv.get("hit_tokens") or kv.get("miss_tokens") else None
        )
        # -- alternating prefixes, pooled side (same engine, warm) ----------
        alt = [sys_a + tails[0], sys_b + tails[0]] * 2
        for p in alt:  # seed both prefixes
            timed_prefill(eng, p)
        alt_pool_s = alt_pool_tok = 0
        for p in alt:
            w, n = timed_prefill(eng, p)
            alt_pool_s += w
            alt_pool_tok += n
        bytes_per_token = kv.get("bytes_per_token")
        del eng
        gc.collect()

        # -- alternating prefixes, classic single slot ----------------------
        eng0 = build(pool=False)
        for p in alt:
            timed_prefill(eng0, p)
        alt_cls_s = alt_cls_tok = 0
        for p in alt:
            w, n = timed_prefill(eng0, p)
            alt_cls_s += w
            alt_cls_tok += n
        del eng0
        gc.collect()
    finally:
        if saved_env is None:
            os.environ.pop("LLMC_KV_POOL", None)
        else:
            os.environ["LLMC_KV_POOL"] = saved_env

    # -- capacity at equal KV HBM (model, measured bytes/token) -------------
    hbm = float(os.environ.get("BENCH_KV_HBM_GB", "8")) * (1 << 30)
    caps = {}
    if bytes_per_token:
        full_window = _bucket(min(cold_tok + out_tokens, max_seq), max_seq)
        tail_tok = cold_tok - sys_chars  # byte tokenizer: ≈1 tok/char
        suffix_window = _bucket(min(tail_tok + out_tokens, max_seq), max_seq)
        classic = int(hbm // (bytes_per_token * full_window))
        bs = kv.get("block_size", 64)
        prefix_once = bytes_per_token * (-(-sys_chars // bs) * bs)
        pooled = int((hbm - prefix_once) // (bytes_per_token * suffix_window))
        caps = {
            "prefix_max_streams_classic": classic,
            "prefix_max_streams_pooled": pooled,
            "prefix_capacity_gain": (
                round(pooled / classic, 2) if classic else None
            ),
        }

    cold_tps = cold_tok / cold_s if cold_s > 0 else None
    warm_tps = warm_tok / warm_s if warm_s > 0 else None
    alt_cls_tps = alt_cls_tok / alt_cls_s if alt_cls_s > 0 else None
    alt_pool_tps = alt_pool_tok / alt_pool_s if alt_pool_s > 0 else None
    return {
        "prefix_streams": n_req,
        "prefix_system_tokens": sys_chars,
        "prefix_hit_token_fraction": (
            round(hit_frac, 4) if hit_frac is not None else None
        ),
        "prefix_cold_prefill_tok_s": round(cold_tps, 1) if cold_tps else None,
        "prefix_warm_prefill_tok_s": round(warm_tps, 1) if warm_tps else None,
        "prefix_warm_speedup": (
            round(warm_tps / cold_tps, 2) if warm_tps and cold_tps else None
        ),
        "prefix_alt_classic_tok_s": (
            round(alt_cls_tps, 1) if alt_cls_tps else None
        ),
        "prefix_alt_pooled_tok_s": (
            round(alt_pool_tps, 1) if alt_pool_tps else None
        ),
        "prefix_alt_speedup": (
            round(alt_pool_tps / alt_cls_tps, 2)
            if alt_pool_tps and alt_cls_tps else None
        ),
        **caps,
        "prefix_kv": kv,
    }


def _obs_overhead_phase(quant: str, preset: str = "consensus-1b") -> dict:
    """Live-observability overhead point (ISSUE 11, obs/live + blackbox):
    pooled decode tokens/s with the live plane ON (per-token latency
    histograms + aggressive window rotation + the always-on flight
    recorder ring) vs OFF, same engine, same workload.

    Regression-gates the "cheap when idle, bounded when hot" claim the
    way PR 2 gated zero-cost-when-disabled: ``obs_overhead_pct`` must
    stay ≤ 2% of pooled decode throughput. CPU-runnable (tiny models) so
    every driver round carries the number.
    """
    import threading

    import jax

    from llm_consensus_tpu.providers.base import Request
    from llm_consensus_tpu.providers.tpu import TPUProvider
    from llm_consensus_tpu.utils.context import Context

    on_cpu = jax.devices()[0].platform == "cpu"
    if on_cpu:
        preset, n_streams, max_tokens, fires = "tiny-llama", 8, 48, 3
    else:
        n_streams, max_tokens, fires = 16, 128, 3
    model = f"tpu:{preset}"
    q = quant if (quant != "bf16" and not on_cpu) else None

    def leg(live_on: bool) -> float:
        from llm_consensus_tpu.obs import attrib as attrib_mod
        from llm_consensus_tpu.obs import blackbox as bb_mod
        from llm_consensus_tpu.obs import live as live_mod
        from llm_consensus_tpu.obs import roofline as roofline_mod

        if live_on:
            # Worst-case live plane: fast window rotation (production
            # default is 10 s; 0.25 s makes the rotator's cost visible
            # if it has one) + a full-size flight recorder ring + the
            # chip-time attribution ledger (per-token goodput bumps,
            # interval attribution, the jax compile listener — the
            # whole ISSUE-12 plane is inside the 2% budget too) + the
            # roofline ledger's per-dispatch booking (installed
            # explicitly: module resolution is cached, so the OFF leg
            # running first would otherwise pin it disabled here).
            lm = live_mod.LiveMetrics(window_s=0.25)
            live_mod.install(lm)
            lm.start()
            bb_mod.install(bb_mod.FlightRecorder(capacity=4096))
            attrib_mod.install(attrib_mod.ChipTimeLedger())
            roofline_mod.install(roofline_mod.RooflineLedger())
        else:
            live_mod.install(None)
            bb_mod.install(None)
            attrib_mod.install(None)
            roofline_mod.install(None)
        prov = TPUProvider(
            ignore_eos=True, stream_interval=16, batch_streams=n_streams,
            quant=q,
        )
        try:
            prov.prepare([model], None)

            def fire() -> float:
                results = [None] * n_streams

                def one(i: int) -> None:
                    results[i] = prov.query_stream(
                        Context.background(),
                        Request(model=model,
                                prompt=f"obs overhead stream {i} body",
                                max_tokens=max_tokens),
                        None,
                    )

                threads = [
                    threading.Thread(target=one, args=(i,))
                    for i in range(n_streams)
                ]
                t0 = time.monotonic()
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                wall = time.monotonic() - t0
                toks = sum(r.tokens or 0 for r in results if r is not None)
                assert toks == n_streams * max_tokens, results
                return toks / wall
            fire()  # warm: compiles + first-admission walls
            return max(fire() for _ in range(fires))
        finally:
            prov.release()
            live_mod.reset()
            bb_mod.reset()
            attrib_mod.reset()
            roofline_mod.reset()

    tps_off = leg(False)
    tps_on = leg(True)
    overhead_pct = (tps_off - tps_on) / tps_off * 100.0 if tps_off else 0.0
    return {
        "obs_overhead_model": preset,
        "obs_overhead_streams": n_streams,
        "obs_overhead_tok_s_off": round(tps_off, 2),
        "obs_overhead_tok_s_on": round(tps_on, 2),
        # Negative = measurement noise in the live plane's favor; the
        # gate is one-sided (≤ 2% cost).
        "obs_overhead_pct": round(overhead_pct, 2),
        "obs_overhead_gate_pct": 2.0,
        "obs_overhead_ok": overhead_pct <= 2.0,
    }


def _integrity_phase(quant: str, preset: str = "consensus-1b") -> dict:
    """Integrity-plane overhead point (ISSUE 20, integrity/): pooled
    decode tokens/s with the plane ON (fused finite-logit sentinel on
    every decode fetch + sampled radix-gather verification at the
    default LLMC_INTEGRITY_SAMPLE) vs OFF, same engine, same workload.

    Regression-gates the plane's "byte-identical and ≤ 2% at default
    sampling" claim the way obs-overhead gates the live plane: a clean
    run pays one fused ``jnp.isfinite`` reduce per step and a sampled
    digest per gather, never a second fetch. CPU-runnable (tiny models)
    so every driver round carries the number.
    """
    import threading

    import jax

    from llm_consensus_tpu import integrity
    from llm_consensus_tpu.providers.base import Request
    from llm_consensus_tpu.providers.tpu import TPUProvider
    from llm_consensus_tpu.utils.context import Context

    on_cpu = jax.devices()[0].platform == "cpu"
    if on_cpu:
        preset, n_streams, max_tokens, fires = "tiny-llama", 8, 48, 3
    else:
        n_streams, max_tokens, fires = 16, 128, 3
    model = f"tpu:{preset}"
    q = quant if (quant != "bf16" and not on_cpu) else None
    saved = {
        k: os.environ.get(k) for k in ("LLMC_INTEGRITY", "LLMC_KV_POOL")
    }
    os.environ["LLMC_KV_POOL"] = "1"
    sample = None
    checks_on = 0

    def leg(plane_on: bool) -> float:
        nonlocal sample, checks_on
        os.environ["LLMC_INTEGRITY"] = "1" if plane_on else "0"
        integrity.reset()
        prov = TPUProvider(
            ignore_eos=True, stream_interval=16, batch_streams=n_streams,
            quant=q,
        )
        try:
            prov.prepare([model], None)

            def fire() -> float:
                results = [None] * n_streams

                def one(i: int) -> None:
                    results[i] = prov.query_stream(
                        Context.background(),
                        Request(model=model,
                                prompt=f"integrity overhead stream {i} body",
                                max_tokens=max_tokens),
                        None,
                    )

                threads = [
                    threading.Thread(target=one, args=(i,))
                    for i in range(n_streams)
                ]
                t0 = time.monotonic()
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                wall = time.monotonic() - t0
                toks = sum(r.tokens or 0 for r in results if r is not None)
                assert toks == n_streams * max_tokens, results
                return toks / wall
            fire()  # warm: compiles + first-admission walls
            best = max(fire() for _ in range(fires))
            if plane_on:
                plane = integrity.plane()
                assert plane is not None
                snap = plane.stats()
                sample = snap["sample"]
                checks_on = int(snap["checks_total"])
                # The plane really ran: the sentinel checked every
                # fetched decode chunk, and nothing fired on clean data.
                assert snap["checks"].get("logits", 0) > 0, snap
                assert snap["failures_total"] == 0, snap
            return best
        finally:
            prov.release()
            integrity.reset()

    try:
        tps_off = leg(False)
        tps_on = leg(True)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        integrity.reset()
    overhead_pct = (tps_off - tps_on) / tps_off * 100.0 if tps_off else 0.0
    return {
        "integrity_model": preset,
        "integrity_streams": n_streams,
        "integrity_sample": sample,
        "integrity_checks_on": checks_on,
        "integrity_tok_s_off": round(tps_off, 2),
        "integrity_tok_s_on": round(tps_on, 2),
        # Negative = measurement noise in the plane's favor; the gate is
        # one-sided (≤ 2% cost at the default sampling rate).
        "integrity_overhead_pct": round(overhead_pct, 2),
        "integrity_gate_pct": 2.0,
        "integrity_ok": overhead_pct <= 2.0,
    }


def _disagg_phase(quant: str, preset: str = "consensus-1b") -> dict:
    """Disaggregated prefill/decode point (ISSUE 13, engine/handoff.py):
    staggered serving traffic with admission prefill moved OFF the
    decode chips — dedicated prefill workers on their own sub-mesh hand
    finished prefix KV into the decode pool cross-mesh — vs the PR 4
    interleaved-admission baseline on the SAME device budget.

    Driver-visible fields: ``disagg_e2e_over_decode_phase`` (the
    acceptance gate, >= 0.95: with admission off-chip, end-to-end
    throughput approaches the pure decode-phase rate) next to the
    baseline's ratio, the measured cross-mesh ``handoff_bytes_per_s``,
    and each leg's decode-chip admission wall (the seconds that left).
    Skipped (with a marker field) when fewer than 2 devices are
    visible — the role split needs disjoint sub-meshes. CPU-runnable on
    tiny models so every driver round carries the numbers.
    """
    import threading

    import jax

    from llm_consensus_tpu.providers.base import Request
    from llm_consensus_tpu.providers.tpu import TPUProvider
    from llm_consensus_tpu.utils.context import Context

    devices = jax.devices()
    if len(devices) < 2:
        return {"disagg_skipped": f"needs >= 2 devices, have {len(devices)}"}
    on_cpu = devices[0].platform == "cpu"
    if on_cpu:
        preset, n_res, max_tokens, rounds_n = "tiny-llama", 4, 160, 2
        join_delay, chunk = 0.25, "64"
    else:
        n_res, max_tokens, rounds_n = 8, 192, 3
        join_delay, chunk = 0.1, "256"
    model = f"tpu:{preset}"
    q = quant if (quant != "bf16" and not on_cpu) else None

    def leg(disagg_on: bool) -> dict:
        # Both legs: paged pool on, interleaved admission on (the PR 4/7
        # serving defaults) — the ONLY difference is where admission
        # prefill compute runs. The workload is the shape interleaving
        # still pays for: a resident pool mid-decode when late joiners
        # arrive, so the baseline spends decode-chip dispatch slots on
        # the joiners' prefill chunks while the disagg leg's joiners
        # establish on the prefill mesh.
        env = {
            "LLMC_KV_POOL": "1",
            "LLMC_PREFILL_CHUNK": chunk,
            "LLMC_PREFILL_BUDGET": (
                os.environ.get("BENCH_PREFILL_BUDGET", "2048") or "2048"
            ),
        }
        saved = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        prov = TPUProvider(
            ignore_eos=True, stream_interval=16, batch_streams=2 * n_res,
            quant=q, disagg=disagg_on,
        )
        try:
            prov.prepare([model], None)

            def fire(tag: str) -> tuple:
                results = [None] * (2 * n_res)

                def one(i: int) -> None:
                    if i >= n_res:
                        # Late joiners: land while the residents decode.
                        time.sleep(join_delay + (i - n_res) * 0.05)
                    # Distinct prompts (no shared prefix): every
                    # admission pays its own full-prompt establishment
                    # somewhere — the question the phase answers is on
                    # WHICH mesh.
                    body = f"stream {tag}-{i} body segment distinct " * 18
                    results[i] = prov.query_stream(
                        Context.background(),
                        Request(model=model, prompt=body,
                                max_tokens=max_tokens),
                        None,
                    )

                threads = [
                    threading.Thread(target=one, args=(i,))
                    for i in range(2 * n_res)
                ]
                t0 = time.monotonic()
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                wall = time.monotonic() - t0
                return wall, sum(
                    r.tokens or 0 for r in results if r is not None
                )

            fire("warm0")  # compiles + first-admission walls
            fire("warm1")  # padded-wave variants
            batcher = next(iter(prov._batchers.values()))[1]
            stats0 = batcher.stats
            total_w = total_t = 0.0
            for r in range(rounds_n):
                w, tk = fire(f"run{r}")
                total_w += w
                total_t += tk
            stats1 = batcher.stats
            d_tok = stats1["decode_tokens"] - stats0["decode_tokens"]
            d_s = stats1["decode_s"] - stats0["decode_s"]
            e2e = total_t / total_w if total_w else 0.0
            decode_phase = d_tok / d_s if d_s > 0 else None
            out = {
                "e2e_tokens_per_sec": round(e2e, 2),
                "decode_phase_tokens_per_sec": (
                    round(decode_phase, 2) if decode_phase else None
                ),
                "e2e_over_decode_phase": (
                    round(e2e / decode_phase, 3) if decode_phase else None
                ),
                # The decode chip's admission wall: establishment +
                # admit prefill host walls plus the impure (admission-
                # carrying) arrival intervals — the seconds
                # disaggregation exists to remove.
                "decode_admission_s": round(
                    (stats1["admit_s"] - stats0["admit_s"])
                    + (stats1["establish_s"] - stats0["establish_s"])
                    + (stats1["impure_s"] - stats0["impure_s"]),
                    3,
                ),
            }
            if disagg_on:
                snap = prov.disagg_stats().get(preset) or {}
                out["handoff_bytes_per_s"] = snap.get("handoff_bytes_per_s")
                out["handoff_tokens"] = snap.get("handoff_tokens", 0)
                out["handoff_fallbacks"] = snap.get("fallbacks", 0)
                out["prefill_mesh_devices"] = snap.get("prefill_devices")
            return out
        finally:
            prov.release()
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    base = leg(False)
    dis = leg(True)
    ratio = dis.get("e2e_over_decode_phase")
    out = {
        "disagg_model": preset,
        "disagg_streams": 2 * n_res,
        "disagg_baseline": base,
        "disagg_on": dis,
        "disagg_e2e_over_decode_phase": ratio,
        "disagg_baseline_e2e_over_decode_phase": base.get(
            "e2e_over_decode_phase"
        ),
        "disagg_handoff_bytes_per_s": dis.get("handoff_bytes_per_s"),
        "disagg_gate": 0.95,
    }
    if on_cpu:
        # Forced-host "devices" share ONE physical CPU: moving prefill
        # compute between them cannot win, and the tiny model's
        # per-chunk decode rate makes the ratio denominator
        # meaningless — the CPU run proves the MACHINERY (handoff
        # bytes moved, zero fallbacks, both legs complete) and leaves
        # the throughput gate to real-chip rounds.
        out["disagg_ok"] = None
        out["disagg_cpu_note"] = (
            "machinery-only on CPU (virtual devices share one host); "
            "the >= 0.95 gate applies on real chips"
        )
        out["disagg_machinery_ok"] = bool(
            dis.get("handoff_tokens", 0) > 0
            and dis.get("handoff_fallbacks", 0) == 0
        )
    else:
        out["disagg_ok"] = ratio is not None and ratio >= 0.95
    return out


def _pressure_phase(quant: str, preset: str = "consensus-1b") -> dict:
    """Pressure-governor point (ISSUE 9, pressure/): HIGH-priority
    latency under a 4× LOW-priority overload, priority stack ON vs OFF,
    plus the preempt-resume cost model.

    Three result families, all driver-visible fields:

      * ``pressure_high_p50/p99_ms`` vs the ``_fifo`` twins — HIGH
        probes fired into a gateway whose queue a LOW flood saturates.
        With the stack on, HIGH requests bump/preempt/outrank the flood
        (the acceptance gate: zero HIGH 429s while LOW sheds); with it
        off (LLMC_PRESSURE=0, no priority fields) the same probes eat
        FIFO queueing and 429s.
      * ``pressure_preemptions`` / ``pressure_governor`` — the engine
        and governor really acted, not just the admission queue.
      * ``pressure_resume_gather_ms`` vs ``_recompute_ms`` — the cost of
        re-establishing a preempted stream's context (prompt + emitted
        prefix) with the radix pool resident vs a cold re-prefill: the
        number that says resume is near-free when the prefix survived.
    """
    import http.client
    import threading

    import jax

    from llm_consensus_tpu.engine.engine import Engine
    from llm_consensus_tpu.models.config import get_config
    from llm_consensus_tpu.providers.registry import Registry
    from llm_consensus_tpu.providers.tpu import TPUProvider

    on_cpu = jax.devices()[0].platform == "cpu"
    if on_cpu:
        preset = "tiny-llama"
        low_tokens, hi_tokens, n_probe, resume_chars = 48, 8, 10, 512
    else:
        low_tokens, hi_tokens, n_probe, resume_chars = 128, 16, 16, 2048
    model = f"tpu:{preset}"
    q = quant if (quant != "bf16" and not on_cpu) else None

    def post(port: int, body: dict):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=600)
        try:
            conn.request(
                "POST", "/v1/consensus", json.dumps(body),
                {"Content-Type": "application/json"},
            )
            r = conn.getresponse()
            return r.status, r.read()
        finally:
            conn.close()

    def leg(stack_on: bool) -> dict:
        """One gateway under the 4× LOW flood; HIGH probe latencies."""
        from llm_consensus_tpu import serve

        env = {
            "LLMC_PRESSURE": "1" if stack_on else "0",
            "LLMC_PRESSURE_PREEMPT": "1" if stack_on else "0",
            "LLMC_PRESSURE_POLL_S": "0.1",
            "LLMC_PRESSURE_UP_PATIENCE": "1",
        }
        saved = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        try:
            prov = TPUProvider(
                ignore_eos=True, stream_interval=8, batch_streams=4,
                quant=q,
            )
            prov.prepare([model], model)
            registry = Registry()
            registry.register(model, prov)
            # Oversubscribed on purpose (5 runs over a 4-slot pool):
            # admitted streams contend for batcher slots, so a HIGH
            # panel stream lands in the batcher queue behind resident
            # LOWs — exactly the shape the preemption path exists for.
            gw = serve.build_gateway(
                registry, [model], model, max_tokens=low_tokens,
                timeout=600.0, max_concurrency=5, max_queue=4,
                cache_size=0, save=False, port=0,
            )
            _, port = gw.start()
            stop = threading.Event()
            flood_codes: list = []

            def flood(i: int) -> None:
                r = 0
                while not stop.is_set():
                    body = {
                        "prompt": f"low flood lane {i} round {r} filler",
                        "max_tokens": low_tokens,
                    }
                    if stack_on:
                        body["priority"] = "low"
                    try:
                        flood_codes.append(post(port, body)[0])
                    except OSError:
                        pass
                    r += 1

            floods = [
                threading.Thread(target=flood, args=(i,)) for i in range(8)
            ]
            for t in floods:
                t.start()
            time.sleep(1.0)  # let the flood saturate slots + queue
            lat: list = []
            codes: list = []
            for i in range(n_probe):
                body = {
                    "prompt": f"high probe {i} distinct",
                    "max_tokens": hi_tokens,
                }
                if stack_on:
                    body["priority"] = "high"
                t0 = time.monotonic()
                try:
                    status, _ = post(port, body)
                except OSError:
                    status = -1
                codes.append(status)
                if status == 200:
                    lat.append((time.monotonic() - t0) * 1000)
            stop.set()
            for t in floods:
                t.join(timeout=600)
            lat.sort()
            stats = {
                "p50_ms": round(lat[len(lat) // 2], 1) if lat else None,
                "p99_ms": (
                    round(lat[min(len(lat) - 1, int(len(lat) * 0.99))], 1)
                    if lat else None
                ),
                "high_429": sum(1 for c in codes if c == 429),
                "high_ok": sum(1 for c in codes if c == 200),
                "low_shed": sum(1 for c in flood_codes if c in (429, 503)),
                "low_ok": sum(1 for c in flood_codes if c == 200),
            }
            if stack_on:
                stats["preemptions"] = sum(
                    snap.get("preemptions", 0)
                    for snap in prov.pressure_stats().values()
                )
                if gw.governor is not None:
                    gsnap = gw.governor.snapshot()
                    gsnap.pop("signals", None)
                    stats["governor"] = gsnap
            gw.close(drain=False, timeout=10.0)
            prov.release()
            return stats
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    def resume_cost() -> dict:
        """ms to re-establish a preempted stream's context: radix-pool
        gather vs cold recompute prefill of prompt + emitted prefix."""
        seed = "You are a resident stream about to be preempted. "
        prompt = (seed * (resume_chars // len(seed) + 1))[:resume_chars]
        out = {}
        saved = os.environ.get("LLMC_KV_POOL")
        try:
            for tag, pool in (("recompute", False), ("gather", True)):
                os.environ["LLMC_KV_POOL"] = "1" if pool else "0"
                eng = Engine(
                    get_config(preset), quant=q, max_seq=2048,
                    prefill_chunk=64, stream_interval=32,
                )
                ids = eng.tokenizer.encode(prompt)
                # Simulate the victim: prefill + publish, like a stream
                # that decoded ``low_tokens`` before preemption.
                logits, cache = eng._prefill_ids(ids)
                jax.block_until_ready(logits)
                eng._retain_prefix(ids, cache)
                # The resume: prefill prompt + prefix again. Pool on →
                # radix gather covers the published span; pool off →
                # full recompute (the classic snapshot matches too, so
                # clear it to model a cross-request eviction).
                def clear_snapshot():
                    if not pool:
                        eng._prefix_ids = None
                        eng._prefix_cache = None

                # Warm-up resume first: the gather/prefill programs
                # compile on their first hit, and the cost model must
                # compare steady-state paths, not one-off XLA walls.
                clear_snapshot()
                logits, _cache = eng._prefill_ids(list(ids))
                jax.block_until_ready(logits)
                clear_snapshot()
                t0 = time.monotonic()
                logits, _cache = eng._prefill_ids(list(ids))
                jax.block_until_ready(logits)
                out[tag] = round((time.monotonic() - t0) * 1000, 1)
        finally:
            if saved is None:
                os.environ.pop("LLMC_KV_POOL", None)
            else:
                os.environ["LLMC_KV_POOL"] = saved
        if out.get("gather") and out.get("recompute"):
            out["speedup"] = round(out["recompute"] / out["gather"], 2)
        return out

    governed = leg(stack_on=True)
    fifo = leg(stack_on=False)
    resume = resume_cost()
    return {
        "pressure_model": preset,
        "pressure_overload_x": 4,
        "pressure_high_p50_ms": governed["p50_ms"],
        "pressure_high_p99_ms": governed["p99_ms"],
        "pressure_high_429": governed["high_429"],
        "pressure_high_ok": governed["high_ok"],
        "pressure_low_shed": governed["low_shed"],
        "pressure_preemptions": governed.get("preemptions", 0),
        "pressure_governor": governed.get("governor"),
        "pressure_high_p50_ms_fifo": fifo["p50_ms"],
        "pressure_high_p99_ms_fifo": fifo["p99_ms"],
        "pressure_high_429_fifo": fifo["high_429"],
        "pressure_resume_gather_ms": resume.get("gather"),
        "pressure_resume_recompute_ms": resume.get("recompute"),
        "pressure_resume_speedup": resume.get("speedup"),
    }


def _elastic_phase(quant: str, preset: str = "consensus-1b") -> dict:
    """Elastic scale-down point (ISSUE 16, serve/elastic): HIGH-class
    streaming latency across a replica scale-down, journal-backed live
    migration ON vs drain-and-wait OFF.

    Two legs, each a fresh 2-replica fleet behind the router with HIGH
    streaming probes running while one replica retires mid-probe:

      * ``elastic_high_p50/p99_ms`` vs the ``_drain`` twins — probe
        latency through the seam. The migrated stream pays a failover +
        re-execution on the survivor; the drained stream finishes
        locally. Either way every probe must terminate ``done`` (the
        correctness half lives in the elastic dryrun lane; this phase
        prices it).
      * ``elastic_vacate_ms`` vs ``_drain`` — retire() to zero resident
        streams on the retiring replica: the number that says migration
        frees the device NOW while drain-and-wait holds it hostage for
        the slowest resident's full decode.
    """
    import http.client
    import threading

    import jax

    from llm_consensus_tpu import serve
    from llm_consensus_tpu.providers.registry import Registry
    from llm_consensus_tpu.providers.tpu import TPUProvider

    on_cpu = jax.devices()[0].platform == "cpu"
    if on_cpu:
        preset = "tiny-llama"
        probe_tokens, n_probe = 24, 8
    else:
        probe_tokens, n_probe = 48, 12
    model = f"tpu:{preset}"
    q = quant if (quant != "bf16" and not on_cpu) else None

    def post_sse(port: int, body: dict) -> str:
        """Stream one request; returns the terminal event name."""
        body = dict(body)
        body["stream"] = True
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=600)
        try:
            conn.request(
                "POST", "/v1/consensus", json.dumps(body),
                {"Content-Type": "application/json",
                 "Accept": "text/event-stream"},
            )
            resp = conn.getresponse()
            if resp.status != 200:
                return f"http-{resp.status}"
            event = None
            for raw in resp:
                line = raw.decode("utf-8").rstrip("\r\n")
                if line.startswith("event: "):
                    event = line[len("event: "):]
                    if event in ("done", "error"):
                        return event
            return event or "eof"
        finally:
            conn.close()

    # Engines are shared across both legs (gateways are cheap, compiles
    # are not): leg 1's warmup pays the only compile walls.
    provs = []
    for _ in range(2):
        prov = TPUProvider(ignore_eos=True, stream_interval=4, quant=q)
        prov.prepare([model], model)
        provs.append(prov)

    def leg(migrate: bool) -> dict:
        gws = []
        for prov in provs:
            reg = Registry()
            reg.register(model, prov)
            gw = serve.build_gateway(
                reg, [model], model, max_tokens=probe_tokens,
                timeout=600.0, max_concurrency=2, cache_size=0,
                save=False, port=0,
            )
            gw.start()
            gws.append(gw)
        urls = [f"http://{h}:{p}" for h, p in (g.address for g in gws)]
        router = serve.build_router(urls, poll_s=1.0)
        router.start()
        _, rport = router.address
        try:
            for g in gws:  # warm both engines outside the timed window
                post_sse(g.address[1], {"prompt": "elastic warm probe"})

            info = {"migrated": 0, "fallback": 0, "hit": False,
                    "vacate_ms": None}

            def scale_down() -> None:
                """Retire the replica holding the first resident probe —
                the seam lands mid-stream, like the controller's hook."""
                deadline = time.monotonic() + 60
                src = None
                while time.monotonic() < deadline and src is None:
                    src = next((g for g in gws if g._residents), None)
                    time.sleep(0.002)
                if src is None:
                    src = gws[0]  # all probes raced past: plain drain
                else:
                    info["hit"] = True
                dst = next(g for g in gws if g is not src)
                h, p = dst.address
                t0 = time.monotonic()
                doc = src.retire(
                    to=f"http://{h}:{p}" if migrate else None
                )
                while src._residents and time.monotonic() < t0 + 300:
                    time.sleep(0.002)
                info["vacate_ms"] = round((time.monotonic() - t0) * 1000, 1)
                info["migrated"] = doc["migrated"]
                info["fallback"] = doc["fallback"]

            trigger = threading.Thread(target=scale_down)
            trigger.start()
            lat: list = []
            outcomes: list = []
            for i in range(n_probe):
                body = {
                    "prompt": f"elastic high probe {i} distinct",
                    "max_tokens": probe_tokens,
                    "priority": "high",
                }
                t0 = time.monotonic()
                try:
                    outcomes.append(post_sse(rport, body))
                except OSError as err:
                    outcomes.append(f"oserror: {err}")
                    continue
                if outcomes[-1] == "done":
                    lat.append((time.monotonic() - t0) * 1000)
            trigger.join(timeout=600)
            lat.sort()
            return {
                "p50_ms": round(lat[len(lat) // 2], 1) if lat else None,
                "p99_ms": (
                    round(lat[min(len(lat) - 1, int(len(lat) * 0.99))], 1)
                    if lat else None
                ),
                "ok": sum(1 for o in outcomes if o == "done"),
                **info,
            }
        finally:
            router.close()
            for g in gws:
                g.close(drain=False, timeout=10.0)

    try:
        mig = leg(migrate=True)
        drain = leg(migrate=False)
    finally:
        for prov in provs:
            prov.release()
    return {
        "elastic_model": preset,
        "elastic_probe_n": n_probe,
        "elastic_high_p50_ms": mig["p50_ms"],
        "elastic_high_p99_ms": mig["p99_ms"],
        "elastic_high_ok": mig["ok"],
        "elastic_migrations": mig["migrated"],
        "elastic_vacate_ms": mig["vacate_ms"],
        "elastic_seam_hit": mig["hit"],
        "elastic_high_p50_ms_drain": drain["p50_ms"],
        "elastic_high_p99_ms_drain": drain["p99_ms"],
        "elastic_high_ok_drain": drain["ok"],
        "elastic_vacate_ms_drain": drain["vacate_ms"],
        "elastic_seam_hit_drain": drain["hit"],
    }


def _flywheel_phase(quant: str, preset: str = "consensus-1b") -> dict:
    """Flywheel hot-swap point (ISSUE 18, flywheel/): streaming latency
    across a live checkpoint hot-swap vs the drain-and-restart cycle it
    replaces.

    One provider + gateway serving streaming probes, three measurements:

      * ``flywheel_high_p50/p99_ms_noswap`` — undisturbed baseline.
      * ``flywheel_high_p50/p99_ms`` — the same probes with a trigger
        thread hot-swapping fresh weights mid-probe: it waits until a
        resident stream pins the engine (the seam the double-buffer
        discipline exists for), then swaps. The pinned stream finishes
        on its buffer; the flip parks until the last unpin.
        ``flywheel_swap_vacate_ms`` (request -> flip, the park included)
        and ``flywheel_swap_prep_ms`` (shard/quantize OUTSIDE the swap
        lock) come from the engine's own swap stats.
      * ``flywheel_restart_ms`` — the outage being avoided: drain the
        gateway, release the provider (compiles dropped), rebuild both,
        first probe done. Hot-swap keeps serving through what restart
        spends here.
    """
    import http.client
    import threading

    import jax

    from llm_consensus_tpu import serve
    from llm_consensus_tpu.providers.registry import Registry
    from llm_consensus_tpu.providers.tpu import TPUProvider

    on_cpu = jax.devices()[0].platform == "cpu"
    if on_cpu:
        preset = "tiny-llama"
        probe_tokens, n_probe = 24, 8
    else:
        probe_tokens, n_probe = 48, 12
    model = f"tpu:{preset}"
    q = quant if (quant != "bf16" and not on_cpu) else None

    def post_sse(port: int, body: dict) -> str:
        """Stream one request; returns the terminal event name."""
        body = dict(body)
        body["stream"] = True
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=600)
        try:
            conn.request(
                "POST", "/v1/consensus", json.dumps(body),
                {"Content-Type": "application/json",
                 "Accept": "text/event-stream"},
            )
            resp = conn.getresponse()
            if resp.status != 200:
                return f"http-{resp.status}"
            event = None
            for raw in resp:
                line = raw.decode("utf-8").rstrip("\r\n")
                if line.startswith("event: "):
                    event = line[len("event: "):]
                    if event in ("done", "error"):
                        return event
            return event or "eof"
        finally:
            conn.close()

    def build(prov) -> "tuple":
        reg = Registry()
        reg.register(model, prov)
        gw = serve.build_gateway(
            reg, [model], model, max_tokens=probe_tokens, timeout=600.0,
            max_concurrency=2, cache_size=0, save=False, port=0,
        )
        gw.start()
        return gw, gw.address[1]

    def probes(port: int, tag: str) -> "tuple[list, int]":
        lat: list = []
        ok = 0
        for i in range(n_probe):
            body = {
                "prompt": f"flywheel {tag} probe {i} distinct",
                "max_tokens": probe_tokens,
                "priority": "high",
            }
            t0 = time.monotonic()
            try:
                outcome = post_sse(port, body)
            except OSError:
                continue
            if outcome == "done":
                ok += 1
                lat.append((time.monotonic() - t0) * 1000)
        lat.sort()
        return lat, ok

    def pctl(lat: list, f: float):
        if not lat:
            return None
        return round(lat[min(len(lat) - 1, int(len(lat) * f))], 1)

    prov = TPUProvider(ignore_eos=True, stream_interval=4, quant=q)
    prov.prepare([model], model)
    gw = None
    try:
        gw, port = build(prov)
        # Warm with the probes' exact shape so the noswap baseline never
        # carries a prefill-bucket compile wall.
        post_sse(port, {
            "prompt": "flywheel warm probe 0 distinct",
            "max_tokens": probe_tokens, "priority": "high",
        })
        base_lat, _base_ok = probes(port, "noswap")

        info = {"hit": False, "stats": {}}

        def trigger() -> None:
            """Swap once a resident stream has pinned the engine — the
            flip must park behind the pin, like a canary rollout landing
            under live traffic."""
            from llm_consensus_tpu.models import get_config, init_params

            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if prov.swap_stats().get(preset, {}).get("pins", 0) > 0:
                    info["hit"] = True
                    break
                time.sleep(0.002)
            eng = prov._engine_for(model)
            fresh = init_params(
                get_config(preset), jax.random.PRNGKey(9), dtype=eng._dtype
            )
            info["stats"] = prov.swap_weights(
                model, fresh, eng.weight_version + 1, wait=True,
                meta={"source": "bench"},
            )

        th = threading.Thread(target=trigger)
        th.start()
        swap_lat, swap_ok = probes(port, "swap")
        th.join(timeout=600)
        st = info["stats"]

        # The outage hot-swap avoids: full drain + teardown (compiles
        # dropped with the provider) + rebuild + first probe served.
        t0 = time.monotonic()
        gw.close(drain=True, timeout=60.0)
        gw = None
        prov.release()
        prov = TPUProvider(ignore_eos=True, stream_interval=4, quant=q)
        prov.prepare([model], model)
        gw, port = build(prov)
        outcome = post_sse(port, {"prompt": "flywheel restart probe"})
        restart_ms = (
            round((time.monotonic() - t0) * 1000, 1)
            if outcome == "done" else None
        )
    finally:
        if gw is not None:
            gw.close(drain=False, timeout=10.0)
        prov.release()
    return {
        "flywheel_model": preset,
        "flywheel_probe_n": n_probe,
        "flywheel_high_p50_ms_noswap": pctl(base_lat, 0.5),
        "flywheel_high_p99_ms_noswap": pctl(base_lat, 0.99),
        "flywheel_high_p50_ms": pctl(swap_lat, 0.5),
        "flywheel_high_p99_ms": pctl(swap_lat, 0.99),
        "flywheel_high_ok": swap_ok,
        "flywheel_swaps": st.get("swaps", 0),
        "flywheel_seam_hit": info["hit"],
        "flywheel_swap_vacate_ms": st.get("last_vacate_ms"),
        "flywheel_swap_prep_ms": st.get("last_prep_ms"),
        "flywheel_restart_ms": restart_ms,
    }


def _judge_answers(n_answers: int = 5, answer_tokens: int = 512) -> list:
    """Synthetic panel answers for the judge phases (byte tokenizer ≈
    1 tok/char), worded differently per model so no cross-answer prefix
    collapses the work."""
    from llm_consensus_tpu.providers.base import Response

    base = (
        "The recommended strategy balances tensor parallel groups within "
        "a chip pod against pipeline stages across pods, weighing HBM "
        "capacity per device, collective bandwidth, and decode latency. "
    )
    return [
        Response(
            model=f"model-{i}", provider="tpu",
            content=(f"Answer variant {i}: " + base * 8)[:answer_tokens],
        )
        for i in range(n_answers)
    ]


def _judge_prompt(n_answers: int = 5, answer_tokens: int = 512) -> str:
    """The bench's standard judge prompt: the REAL render path
    (consensus/judge.py render_judge_prompt, the analog of reference
    judge.go:21-25) over n × synthetic answers."""
    from llm_consensus_tpu.consensus.judge import render_judge_prompt

    return render_judge_prompt(
        PROMPT, _judge_answers(n_answers, answer_tokens)
    )


def _judge_phase(quant: str, preset: str = "consensus-1b") -> dict:
    """Judge-phase measurement (VERDICT r3 #6, r4 #2): the consensus
    workload's long pole at realistic panel sizes is judge PREFILL over
    N concatenated panel answers. Measures prefill tok/s + MFU (chunked
    prefill, batch 1), steady decode at that depth, and the round-2
    prefix-reuse speedup (VERDICT r4 #8) on ``preset``.
    """
    import jax

    from llm_consensus_tpu.engine import Engine, SamplingParams
    from llm_consensus_tpu.models.config import get_config
    from llm_consensus_tpu.utils.flops import (
        decode_mfu, device_peak_flops, flops_per_token)

    cfg = get_config(preset)
    n_answers, answer_tokens = 5, 512
    prompt = _judge_prompt()
    eng = Engine(
        cfg, quant=quant if quant != "bf16" else None, kv_quant="int8",
        max_seq=8192, stream_interval=64,
    )
    ids = eng.tokenizer.encode(prompt)
    t = len(ids)
    device = jax.devices()[0]

    def prefill_once() -> float:
        t0 = time.monotonic()
        last_logits, _ = eng._prefill_ids(ids)
        # Force real completion: through the relay, dispatch returns long
        # before the device finishes (block_until_ready is unreliable).
        float(jax.device_get(last_logits)[0, 0])
        return time.monotonic() - t0
    prefill_once()  # compile
    # _prefill_ids never retains a snapshot itself (only generate_ids
    # does, later), so each timed pass re-prefills the full prompt.
    dt = min(prefill_once() for _ in range(2))
    prefill_tps = t / dt
    # Prefill FLOPs: per-token weight matmuls + the causal attention
    # quadratic at average depth t/2.
    peak = device_peak_flops(device.device_kind)
    prefill_flops = flops_per_token(cfg, context_len=t // 2) * t
    prefill_mfu = prefill_flops / dt / peak if peak else None
    # Decode at judge-context depth: steady-state rate from the engine's
    # own fetch-boundary clock (prefix snapshot now reused — that IS the
    # serving path for --rounds refinements).
    s = SamplingParams(max_new_tokens=min(MAX_TOKENS, 128), ignore_eos=True)
    res = eng.generate(prompt, s)
    decode_tps = (
        res.decode_tokens / res.decode_s if res.decode_s > 0 else None
    )
    # Round-2 prefix reuse (VERDICT r4 #8): --rounds re-renders the next
    # judge prompt on top of the previous round's; the engine snapshot
    # retained by generate() above makes round-2 prefill pay only the
    # appended tail (reference judge.go:96-99 re-prefills from scratch
    # every round). Measured as the full-prompt-equivalent rate: tokens
    # of the round-2 prompt over its (reuse-path) prefill wall.
    ids2 = eng.tokenizer.encode(
        prompt + "\nRefine the synthesis, addressing any disagreement."
    )

    def prefill_round2() -> float:
        t0 = time.monotonic()
        ll2, _ = eng._prefill_ids(ids2)
        float(jax.device_get(ll2)[0, 0])
        return time.monotonic() - t0

    prefill_round2()  # compiles the restore + tail-chunk programs
    dt2 = min(prefill_round2() for _ in range(2))
    round2_tps = len(ids2) / dt2
    return {
        "judge_phase_model": preset,
        "judge_prompt_tokens": t,
        "judge_answers": n_answers,
        "judge_answer_tokens": answer_tokens,
        "judge_prefill_tokens_per_sec": round(prefill_tps, 1),
        "judge_prefill_mfu": round(prefill_mfu, 4) if prefill_mfu else None,
        "judge_decode_tokens_per_sec": (
            round(decode_tps, 2) if decode_tps else None
        ),
        "judge_decode_mfu": (
            round(
                decode_mfu(cfg, decode_tps, device.device_kind,
                           context_len=t), 4
            ) if decode_tps else None
        ),
        "judge_round2_prefill_tokens_per_sec": round(round2_tps, 1),
        "judge_round2_prefill_speedup": round(round2_tps / prefill_tps, 2),
    }


def _judge_draft_phase(quant: str, preset: str, draft: str) -> dict:
    """Judge-DECODE via the speculative latency tier (VERDICT r4 #2 +
    ISSUE 8): the judge is a batch-1 stream — exactly the case the
    architecture's two-tier split prescribes speculative decoding for
    (docs/architecture.md §"Speculative decoding").

    Random-init weights make every REAL drafter's acceptance collapse to
    ~1 (uncorrelated argmaxes), so the phase separates the MACHINERY
    from the drafter:

      * **oracle ceiling** — an OracleDrafter replaying the target's own
        greedy output forces a=k+1 every round; its speedup over plain
        proves the k+1-token verify dispatch costs ~1 plain step (the
        ISSUE-8 >=2x acceptance gate), independent of any drafter.
      * **acceptance sweep** — forced a=1..k+1 maps the break-even
        curve: the a where drafted tok/s crosses plain is what a real
        drafter must beat at this model size.
      * **adversarial governor point** — a=1 WITH the governor on: the
        A/B must lock plain, pinning "drafted is never slower than plain
        at steady state" with a worst-case drafter.
      * **model-draft + prompt-lookup points** — the real drafters'
        overhead floor on random weights (real-checkpoint wins are the
        roadmap's serving numbers, not measurable here).
    """
    import jax

    from llm_consensus_tpu.engine import (
        Engine, OracleDrafter, PromptLookupDrafter, SamplingParams,
        SpeculativeEngine)
    from llm_consensus_tpu.models import get_config, init_params

    prompt = _judge_prompt()
    tokens_out = min(MAX_TOKENS, 128)
    k = 4
    cfg = get_config(preset)
    # stream_interval 32 (not the serving 128): every point must span
    # several fetch drains so the steady-state decode clock (tokens
    # after the first drain) actually measures — one-chunk generations
    # report decode_s == 0.
    eng = Engine(
        cfg, init_params(cfg, jax.random.PRNGKey(0)),
        max_seq=8192, stream_interval=32, quant=quant, kv_quant="int8",
    )
    s = SamplingParams(max_new_tokens=tokens_out, ignore_eos=True)

    def timed(genfn) -> tuple:
        # Uniform WALL-clock rate across every point: the engine's
        # steady-state decode clock (tokens after the first drain) spans
        # different fractions of the run for plain chunks vs spec round
        # groups, which would make the drafted-vs-plain ratios
        # incomparable. All points share one engine, so the warm prefix
        # snapshot makes each call's prefill a cheap masked restore and
        # wall ≈ decode wall. Best of two runs drops one-off jitter.
        best = None
        r = None
        for _ in range(2):
            t0 = time.monotonic()
            r = genfn()
            wall = time.monotonic() - t0
            rate = len(r.token_ids) / max(wall, 1e-9)
            best = rate if best is None else max(best, rate)
        return r, best

    # Plain baseline — its token_ids are also the oracle's continuation.
    eng.generate(prompt, s)  # warmup/compile + prefix snapshot
    ref, plain_tps = timed(lambda: eng.generate(prompt, s))

    def spec_point(drafter, adaptive=False, governor=False,
                   probe_tokens=None) -> tuple:
        spec = SpeculativeEngine(
            eng, drafter, k=k, adaptive=adaptive, governor=governor,
            probe_tokens=probe_tokens,
        )
        spec.generate(prompt, s)  # warmup/compile this k's programs
        r, rate = timed(lambda: spec.generate(prompt, s))
        assert r.token_ids == ref.token_ids, "spec output diverged"
        return rate, spec

    oracle_tps, ospec = spec_point(OracleDrafter(ref.token_ids))
    sweep = {}
    for a in range(1, k + 2):
        a_tps, _ = spec_point(OracleDrafter(ref.token_ids, accept=a))
        sweep[a] = round(a_tps, 2)
    # Adversarial point: a worst-case drafter (forced a=1) with the
    # governor ON — steady state must lock plain. Probe windows sized so
    # both probes AND a locked steady-state segment fit the run.
    adv_tps, adv_spec = spec_point(
        OracleDrafter(ref.token_ids, accept=1), governor=True,
        probe_tokens=max(8, tokens_out // 4),
    )
    lookup_tps, _ = spec_point(
        PromptLookupDrafter(), adaptive=True, governor=True,
    )
    out = {
        "judge_draft": draft,
        "judge_plain_decode_tokens_per_sec": round(plain_tps, 2),
        "judge_oracle_decode_tokens_per_sec": round(oracle_tps, 2),
        "judge_oracle_speedup": (
            round(oracle_tps / plain_tps, 2) if plain_tps else None
        ),
        "judge_spec_k": k,
        "judge_spec_accept_sweep_tokens_per_sec": sweep,
        "judge_spec_adversarial_tokens_per_sec": round(adv_tps, 2),
        "judge_spec_adversarial_vs_plain": (
            round(adv_tps / plain_tps, 2) if plain_tps else None
        ),
        "judge_spec_governor_locked": adv_spec.stats["governor_disables"],
        "judge_lookup_decode_tokens_per_sec": round(lookup_tps, 2),
        "judge_oracle_mean_accepted": round(ospec.mean_accepted, 2),
    }
    # Model-drafted point (the classic second-model tier), kept for
    # trajectory comparability with earlier rounds.
    try:
        dcfg = get_config(draft)
        drf = Engine(
            dcfg, init_params(dcfg, jax.random.PRNGKey(1)),
            max_seq=8192, stream_interval=128, quant=quant,
            kv_quant="int8",
        )
        drafted_tps, _ = spec_point(drf, adaptive=True, governor=True)
        out["judge_drafted_decode_tokens_per_sec"] = round(drafted_tps, 2)
    except Exception as err:  # noqa: BLE001 — the draft build is optional
        out["judge_drafted_error"] = f"{type(err).__name__}: {err}"[:200]
    return out


def _judge_serving_phase(quant: str, preset: str = "consensus-1b") -> dict:
    """Judge-scale (~4k-context) point on the SERVING path + the judge
    prefill-overlap A/B (ISSUE 4).

    (a) N concurrent ~4k-token judge-shaped prompts fire through the
    stream-batching provider (interleaved admission on) — the pooled
    judge tier at realistic context depth, with the same
    e2e-over-decode-phase decomposition the 1B ladder reports.

    (b) Judge TTFT, classic vs overlap, one engine: classic renders the
    full prompt after the last panel answer "arrives" and prefills it
    serially; overlap already holds header + answers in an
    Engine.PrefillSession (synced — the work ran while the panel was
    still decoding), so only the footer and the final partial chunk
    remain. ``judge_overlap_hidden_s`` is the prefill wall the overlap
    hid behind panel time (session open → sync complete); prefix-cache
    reuse is disabled for the A/B so neither side rides a snapshot.
    """
    from concurrent.futures import ThreadPoolExecutor

    import jax

    from llm_consensus_tpu.consensus.judge import (
        JUDGE_PROMPT_FOOTER, JUDGE_PROMPT_HEADER, render_response_block)
    from llm_consensus_tpu.engine import Engine, SamplingParams
    from llm_consensus_tpu.models.config import get_config
    from llm_consensus_tpu.providers.base import Request
    from llm_consensus_tpu.providers.tpu import TPUProvider
    from llm_consensus_tpu.utils.context import Context

    n_streams = 4
    n_answers, answer_tokens = 7, 512  # ≈ 4.3k-token judge prompt
    answers = _judge_answers(n_answers, answer_tokens)
    prompt = _judge_prompt(n_answers, answer_tokens)
    tokens_out = min(MAX_TOKENS, 128)
    prefill_budget = int(os.environ.get("BENCH_PREFILL_BUDGET", "2048") or 0)
    provider = TPUProvider(
        ignore_eos=True, stream_interval=64, quant=quant, kv_quant="int8",
        batch_streams=n_streams, max_seq=8192, prefill_budget=prefill_budget,
    )
    model = f"tpu:{preset}"
    provider.prepare([model], None, devices=jax.devices()[:1])

    def fire(tag: str) -> tuple[float, int]:
        reqs = [
            Request(
                model=model,
                prompt=f"{prompt}\nServing stream {tag}-{i}.",
                max_tokens=tokens_out,
            )
            for i in range(n_streams)
        ]
        t0 = time.monotonic()
        with ThreadPoolExecutor(n_streams) as ex:
            results = list(
                ex.map(lambda r: provider.query(Context.background(), r), reqs)
            )
        return time.monotonic() - t0, sum(r.tokens or 0 for r in results)

    fire("warmup")
    batcher = next(iter(provider._batchers.values()))[1]
    stats0 = batcher.stats
    wall, toks = fire("run")
    stats1 = batcher.stats
    delta = {k: stats1[k] - stats0[k] for k in stats0}
    agg_tps = toks / wall
    dp_tps = (
        delta["decode_tokens"] / delta["decode_s"]
        if delta["decode_s"] > 0 else None
    )
    n_prompt_tokens = len(prompt)  # byte tokenizer ≈ 1 tok/char
    provider.release()
    import gc

    gc.collect()

    cfg = get_config(preset)
    eng = Engine(
        cfg, quant=quant if quant != "bf16" else None, kv_quant="int8",
        max_seq=8192, stream_interval=64,
    )
    eng.prefix_cache_enabled = False  # neither A/B side rides a snapshot
    s = SamplingParams(max_new_tokens=32, ignore_eos=True)
    header = JUDGE_PROMPT_HEADER.format(prompt=PROMPT)

    def run_classic() -> float:
        first = [None]

        def cb(_chunk):
            if first[0] is None:
                first[0] = time.monotonic()

        t0 = time.monotonic()
        eng.generate(prompt, s, on_text=cb)
        return (first[0] or time.monotonic()) - t0

    def run_overlap() -> tuple[float, float]:
        sess = eng.prefill_session()
        t_open = time.monotonic()
        sess.append_text(header)
        for r in answers:
            sess.append_text(render_response_block(r))
        sess.sync()
        hidden = time.monotonic() - t_open
        first = [None]

        def cb(_chunk):
            if first[0] is None:
                first[0] = time.monotonic()

        t0 = time.monotonic()
        sess.append_text(JUDGE_PROMPT_FOOTER)
        sess.generate(s, on_text=cb)
        return (first[0] or time.monotonic()) - t0, hidden

    run_classic()  # compile
    run_overlap()  # compiles the growing-bucket chunk programs
    ttft_classic = min(run_classic() for _ in range(2))
    pairs = [run_overlap() for _ in range(2)]
    ttft_overlap = min(p[0] for p in pairs)
    hidden_s = max(p[1] for p in pairs)
    return {
        "judge_serving_model": preset,
        "judge_serving_prompt_tokens": n_prompt_tokens,
        "judge_serving_streams": n_streams,
        "judge_serving_prefill_budget": prefill_budget,
        "judge_serving_tokens_per_sec_chip": round(agg_tps, 2),
        "judge_serving_decode_phase_tokens_per_sec": (
            round(dp_tps, 2) if dp_tps else None
        ),
        "judge_serving_e2e_over_decode_phase": (
            round(agg_tps / dp_tps, 3) if dp_tps else None
        ),
        "judge_ttft_ms": round(ttft_overlap * 1000, 1),
        "judge_ttft_classic_ms": round(ttft_classic * 1000, 1),
        "judge_ttft_speedup": (
            round(ttft_classic / ttft_overlap, 2) if ttft_overlap > 0 else None
        ),
        "judge_overlap_hidden_s": round(hidden_s, 3),
    }


def _big_ladder(quant: str) -> dict:
    """Capacity ladder on models bigger than 1B (VERDICT r3 #3): every
    round-3 perf claim was consensus-1b; the north-star config is an
    8B-class panel. Runs a short serving ladder per model at batch
    sizes its int8 weights + int8 KV leave HBM for on one v5e
    (weights: ~3.3 GB consensus-3b, ~8 GB llama-3-8b; KV ≈ 40-50 MB
    per stream at the bench shapes). Points degrade to recorded errors
    when a neighbor's HBM pressure evicts them (shared relay chip).
    BENCH_BIG overrides, format "model[@variant]:b1,b2;model2:b3"
    ("0" disables). Variants (VERDICT r4 #1/#5): ``@w8a8`` = int8
    weights + int8 activations (the MXU double-rate lane, LLMC_W8A8=1);
    ``@int4`` = int4 weights (the single-chip capacity lane — ~4 GB for
    8B leaves room for a B=192+ KV pool on 16 GB).
    """
    spec = os.environ.get(
        "BENCH_BIG",
        "consensus-3b:64,128,256;consensus-3b@w8a8:256;"
        "llama-3-8b:16,32,64,128;"
        "llama-3-8b@w8a8:128;llama-3-8b@int4:192",
    )
    out: dict = {"big_ladder": []}
    for part in spec.split(";"):
        if ":" not in part:
            continue
        preset, blist = part.split(":", 1)
        preset = preset.strip()
        variant = None
        if "@" in preset:
            preset, variant = preset.split("@", 1)
        pt_quant, pt_env = quant, None
        if variant == "w8a8":
            pt_env = {**os.environ, "LLMC_W8A8": "1"}
        elif variant == "int4":
            pt_quant = "int4"
        for b in blist.split(","):
            b = int(b)
            try:
                point = _run_phase_subprocess(
                    ["--phase", "ladder-point", "--streams", str(b),
                     "--quant", pt_quant, "--model", preset],
                    timeout=1800, env=pt_env,
                )
            except Exception as err:  # noqa: BLE001
                point = {
                    "model": preset, "streams": b,
                    "error": f"{type(err).__name__}: {err}"[:200],
                }
            if variant:
                point["variant"] = variant
                if variant == "w8a8" and "decode_phase_mfu" in point:
                    # Both normalizations, as the round-4 verdict asks:
                    # bf16-peak (comparable across lanes) + int8-peak
                    # (the MXU's actual double rate).
                    point["decode_phase_mfu_int8peak"] = _int8peak_mfu(
                        point.get("decode_phase_mfu"),
                        point.get("device_kind", ""),
                    )
            out["big_ladder"].append(point)
    # Headline big_* fields: the best point of the LARGEST model that
    # produced one (the point of this phase is the big-model story).
    order = [
        p.strip().split(":")[0].split("@")[0]
        for p in spec.split(";") if ":" in p
    ]
    for preset in reversed(order):
        # Variant points (w8a8/int4) are excluded from the flat big_*
        # headline: it must stay round-over-round comparable on the
        # default int8 lane. Variants live fully labeled in big_ladder.
        pts = [
            p for p in out["big_ladder"]
            if p.get("model") == preset and "tokens_per_sec_chip" in p
            and not p.get("variant")
        ]
        if pts:
            best = max(pts, key=lambda p: p["tokens_per_sec_chip"])
            out.update({
                "big_model": preset,
                "big_streams": best["streams"],
                "big_tokens_per_sec_chip": best["tokens_per_sec_chip"],
                "big_decode_mfu": best["decode_mfu"],
                "big_decode_phase_tokens_per_sec": best.get(
                    "decode_phase_tokens_per_sec"
                ),
            })
            break
    return out


def _w8a8_divergence() -> dict:
    """Quantify the W8A8 lane's output divergence vs the bf16-activation
    lane on IDENTICAL int8 weights (VERDICT r3 weak #4: the opt-in needs
    an evidence-based error budget, not just a 'token outputs differ'
    disclaimer). Greedy decode over N prompts: elementwise token flip
    rate, first-divergence step, and relative RMS of the prefill logits.
    Caveat recorded with the numbers: random-init weights produce
    near-flat logit distributions, so greedy flips here UPPER-bound what
    a real checkpoint (peaked logits) would show.
    """
    import numpy as np

    from llm_consensus_tpu.engine import Engine, SamplingParams
    from llm_consensus_tpu.models.config import get_config

    cfg = get_config("consensus-1b")
    tokens = min(MAX_TOKENS, 64)
    s = SamplingParams(max_new_tokens=tokens, ignore_eos=True)
    prompts = [f"{PROMPT} Divergence probe {i}." for i in range(6)]
    saved = os.environ.pop("LLMC_W8A8", None)
    try:
        eng_a = Engine(cfg, quant="int8", kv_quant="int8", max_seq=1024,
                       stream_interval=64, seed=0)
        os.environ["LLMC_W8A8"] = "1"
        eng_b = Engine(cfg, quant="int8", kv_quant="int8", max_seq=1024,
                       stream_interval=64, seed=0)
    finally:
        os.environ.pop("LLMC_W8A8", None)
        if saved is not None:
            os.environ["LLMC_W8A8"] = saved
    assert eng_a.w8a8 is False and eng_b.w8a8 is True
    flips, first_div, rms = [], [], []
    for p in prompts:
        ids = eng_a.tokenizer.encode(p)
        la = np.asarray(eng_a._prefill_ids(ids)[0], np.float32)
        lb = np.asarray(eng_b._prefill_ids(ids)[0], np.float32)
        rms.append(float(
            np.sqrt(np.mean((la - lb) ** 2))
            / (np.sqrt(np.mean(la ** 2)) + 1e-9)
        ))
        ra = eng_a.generate(p, s)
        rb = eng_b.generate(p, s)
        n = min(len(ra.token_ids), len(rb.token_ids))
        diff = [i for i in range(n) if ra.token_ids[i] != rb.token_ids[i]]
        flips.append(len(diff) / max(n, 1))
        first_div.append(diff[0] if diff else n)
    return {
        "w8a8_token_flip_rate": round(sum(flips) / len(flips), 4),
        "w8a8_first_divergence_step_median": statistics.median(first_div),
        "w8a8_prefill_logit_rms_rel": round(sum(rms) / len(rms), 5),
        "w8a8_divergence_tokens_per_prompt": tokens,
        "w8a8_divergence_prompts": len(prompts),
        "w8a8_divergence_note": (
            "random-init weights: flat logits make greedy flips an "
            "upper bound vs a real peaked-logit checkpoint"
        ),
    }


def _quant_matrix() -> dict:
    """Pin the quantization matrix in the driver artifact (VERDICT r2 #6):
    {bf16, int8, int8+int8KV} × {B=1, B=32} aggregate decode tok/s via
    ``generate_batch`` on fresh engines, plus int4 as the capacity-only
    point with its measured penalty. One subprocess per config row (fresh
    HBM). The matrix exists to make relative claims ("int8 KV wins at
    batch") reproducible, not to re-measure the headline.
    """
    points = []
    for name in ("bf16", "int8", "int8+int8kv", "int4"):
        try:
            points.append(
                _run_phase_subprocess(["--phase", "quant-point", "--config", name])
            )
        except Exception as err:  # noqa: BLE001
            points.append({
                "config": name, "error": f"{type(err).__name__}: {err}"[:160],
            })
    return {"quant_matrix": points}


def _quant_point(name: str) -> dict:
    """One quant-matrix row (runs inside its own process)."""
    from llm_consensus_tpu.engine import Engine, SamplingParams
    from llm_consensus_tpu.models.config import get_config

    quant, kv_quant = {
        "bf16": (None, None),
        "int8": ("int8", None),
        "int8+int8kv": ("int8", "int8"),
        "int4": ("int4", None),
    }[name]
    cfg = get_config("consensus-1b")
    tokens = min(MAX_TOKENS, 64)
    s = SamplingParams(max_new_tokens=tokens, ignore_eos=True)
    eng = Engine(
        cfg, quant=quant, kv_quant=kv_quant, max_seq=1024, stream_interval=128,
    )
    entry = {"config": name}
    for b in (1, 32):
        prompts = [f"{PROMPT} Quant {name}-{i}." for i in range(b)]
        eng.generate_batch(prompts, s)  # warmup/compile
        best = 0.0
        # Best-of-2: one timed run occasionally absorbs a straggler
        # compile or neighbor burst on the shared relay chip (a bf16 b=1
        # row once recorded 13 tok/s against a ~200 steady state).
        for _ in range(2):
            t0 = time.monotonic()
            results = eng.generate_batch(prompts, s)
            tps = sum(len(r.token_ids) for r in results) / (
                time.monotonic() - t0
            )
            best = max(best, tps)
        entry[f"b{b}_tokens_per_sec"] = round(best, 2)
    return entry


if __name__ == "__main__":
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--phase", default="")
    parser.add_argument("--streams", type=int, default=8)
    parser.add_argument("--quant", default="int8")
    parser.add_argument("--config", default="int8")
    parser.add_argument("--model", default="consensus-1b")
    parser.add_argument("--draft", default="consensus-1b")
    args = parser.parse_args()
    if args.phase == "headline":
        print(json.dumps(_headline()))
    elif args.phase == "headline-big":
        print(json.dumps(_headline_big()))
    elif args.phase == "ladder-point":
        print(json.dumps(_ladder_point(args.streams, args.quant, args.model)))
    elif args.phase == "quant-point":
        print(json.dumps(_quant_point(args.config)))
    elif args.phase == "w8a8-divergence":
        print(json.dumps(_w8a8_divergence()))
    elif args.phase == "occupancy-point":
        print(json.dumps(_occupancy_point()))
    elif args.phase == "prefix-sharing":
        print(json.dumps(_prefix_sharing_phase(args.quant, args.model)))
    elif args.phase == "pressure":
        print(json.dumps(_pressure_phase(args.quant, args.model)))
    elif args.phase == "disagg":
        print(json.dumps(_disagg_phase(args.quant, args.model)))
    elif args.phase == "elastic":
        print(json.dumps(_elastic_phase(args.quant, args.model)))
    elif args.phase == "flywheel":
        print(json.dumps(_flywheel_phase(args.quant, args.model)))
    elif args.phase == "obs-overhead":
        print(json.dumps(_obs_overhead_phase(args.quant, args.model)))
    elif args.phase == "integrity":
        print(json.dumps(_integrity_phase(args.quant, args.model)))
    elif args.phase == "judge":
        print(json.dumps(_judge_phase(args.quant, args.model)))
    elif args.phase == "judge-serving":
        print(json.dumps(_judge_serving_phase(args.quant, args.model)))
    elif args.phase == "judge-draft":
        print(json.dumps(_judge_draft_phase(
            args.quant, args.model, args.draft
        )))
    else:
        main()
