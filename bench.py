"""Consensus benchmark: panel + judge fully on-device, one JSON line out.

Measures the BASELINE.json headline metric — consensus tokens/sec/chip —
by running the framework's REAL path end-to-end: tpu-provider engines
behind the registry, best-effort runner fan-out, judge synthesis. Nothing
is mocked; the only bench-specific knob is TPUProvider(ignore_eos=True) so
random-init weights decode a controlled number of tokens per phase.

Output: {"metric", "value", "unit", "vs_baseline"} plus supporting fields
(p50 end-to-end latency, device kind, token counts).

vs_baseline: the reference publishes no benchmark numbers (BASELINE.md) —
its compute is remote HTTP APIs, so on-device throughput has no reference
analog. Baseline resolution order: BASELINE.json "published" value if one
ever lands, else the previous round's BENCH_r*.json (so the ratio tracks
round-over-round progress), else 1.0.
"""

from __future__ import annotations

import glob
import json
import os
import re
import statistics
import time

REPO = os.path.dirname(os.path.abspath(__file__))
MAX_TOKENS = int(os.environ.get("BENCH_MAX_TOKENS", "128"))
RUNS = int(os.environ.get("BENCH_RUNS", "3"))

PROMPT = (
    "Compare the tradeoffs between tensor parallelism and pipeline "
    "parallelism for serving large language models, and recommend a "
    "strategy for a 70B parameter model on a 16-chip accelerator pod. "
    "Consider memory capacity, interconnect bandwidth, and latency."
)


def _resolve_baseline() -> float | None:
    try:
        with open(os.path.join(REPO, "BASELINE.json")) as f:
            published = json.load(f).get("published", {})
        for v in published.values():
            if isinstance(v, (int, float)):
                return float(v)
    except (OSError, json.JSONDecodeError):
        pass
    rounds = []
    for path in glob.glob(os.path.join(REPO, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                data = json.load(f)
            rounds.append((int(m.group(1)), float(data["value"])))
        except (OSError, json.JSONDecodeError, KeyError, ValueError):
            continue
    if rounds:
        return max(rounds)[1]
    return None


def main() -> None:
    import jax

    from llm_consensus_tpu.consensus import Judge
    from llm_consensus_tpu.providers.registry import Registry
    from llm_consensus_tpu.providers.tpu import TPUProvider
    from llm_consensus_tpu.runner import Runner
    from llm_consensus_tpu.utils.context import Context

    device = jax.devices()[0]
    on_cpu = device.platform == "cpu"
    # CPU fallback (driver runs this on a real chip): tiny shapes so the
    # harness stays runnable anywhere.
    panel = ["tpu:tiny-llama", "tpu:tiny-mistral"] if on_cpu else [
        "tpu:consensus-1b", "tpu:consensus-3b"
    ]
    judge_model = "tpu:tiny-llama" if on_cpu else "tpu:consensus-1b"

    # Serving config: weight-only int8 (ops/quant.py) — decode is
    # HBM-bound, so int8 weight streaming is the production-sensible
    # default for throughput. BENCH_QUANT=bf16 reverts; the value is
    # passed explicitly so ambient LLMC_QUANT can't skew the record.
    quant = os.environ.get("BENCH_QUANT", "int8")
    quant = "bf16" if quant in ("none", "") else quant
    # stream_interval=64: a chunk's decode compute fully covers the
    # device->host fetch RTT (65 ms through the relay), so the pipelined
    # lookahead hides it; at 32 the fastest models stall on the transfer.
    provider = TPUProvider(ignore_eos=True, stream_interval=64, quant=quant)
    # Panel + judge placed on mesh slices exactly as the CLI does it; the
    # metric divides by the chips the placement actually occupies, so it
    # stays honest whether the run lands on 1 real chip or an 8-slice.
    provider.prepare(panel, judge_model)
    used_devices: set = set()
    for m in set(panel + [judge_model]):
        mesh = provider.placement(m)
        if mesh is not None:
            used_devices.update(d.id for d in mesh.devices.flat)
    n_chips_used = max(1, len(used_devices))
    registry = Registry()
    for m in set(panel + [judge_model]):
        registry.register(m, provider)
    runner = Runner(registry, timeout=600.0, max_tokens=MAX_TOKENS)
    judge = Judge(provider, judge_model, max_tokens=MAX_TOKENS)

    mfu_samples: list[tuple[int, float]] = []  # (tokens, mfu) per response
    mbu_samples: list[tuple[int, float]] = []  # (tokens, mbu) per response

    run_no = [0]

    def one_run() -> tuple[float, int]:
        # Vary the tail of the prompt per run: identical prompts would let
        # the engines' prefix cache absorb the whole prefill, overstating
        # steady-state throughput; a fresh suffix keeps prefill honest
        # while still exercising shared-prefix reuse like real traffic.
        run_no[0] += 1
        prompt = f"{PROMPT} Consider scenario variant number {run_no[0]}."
        t0 = time.monotonic()
        tokens0 = provider.stats["tokens"]
        result = runner.run(Context.background(), panel, prompt)
        assert len(result.responses) == len(panel), result.failed_models
        for r in result.responses:
            if r.mfu is not None and r.tokens:
                mfu_samples.append((r.tokens, r.mfu))
            if r.mbu is not None and r.tokens:
                mbu_samples.append((r.tokens, r.mbu))
        consensus = judge.synthesize(Context.background(), prompt, result.responses)
        assert consensus
        return time.monotonic() - t0, provider.stats["tokens"] - tokens0

    one_run()  # warmup: compiles prefill/decode for every engine
    wall, toks = zip(*(one_run() for _ in range(RUNS)))

    total_tokens = sum(toks)
    total_time = sum(wall)
    tok_per_sec_chip = total_tokens / total_time / n_chips_used
    p50_ms = statistics.median(wall) * 1000

    def weighted(samples):
        return (
            round(sum(t * m for t, m in samples) / sum(t for t, _ in samples), 4)
            if samples
            else None
        )

    decode_mfu = weighted(mfu_samples)
    decode_mbu = weighted(mbu_samples)

    # -- batched serving phase (VERDICT r1 #3): aggregate throughput of N
    # concurrent same-model streams through the ContinuousBatcher. Decode
    # is HBM-bound at batch 1, so MFU only moves with batch size — this is
    # the measured route toward the >=50% decode-MFU north star.
    # Optional speculative-decoding variant (BENCH_DRAFT=<preset>): a
    # drafted single-stream generate on the big panel model, reported
    # next to the plain number. Off by default: the bench's random-init
    # weights give ~1 accepted token/round, so this measures the
    # plumbing's overhead floor, not the real-checkpoint win.
    # Optional phases are best-effort: the headline metric is the round's
    # one non-negotiable artifact, and a transient failure in a secondary
    # measurement (e.g. HBM pressure from a neighbor on a shared relay
    # chip) must degrade to a missing field, never rc=1.
    spec_fields = {}
    batched = None
    draft = os.environ.get("BENCH_DRAFT", "")
    batch_streams = int(os.environ.get("BENCH_BATCH_STREAMS", "8") or 0)
    if not on_cpu and (draft or batch_streams > 1):
        # Free the panel/judge engines first: every auxiliary phase
        # builds its own engines, and measuring them under the main
        # provider's pinned HBM would shrink the headroom they exist to
        # measure (or OOM outright).
        provider.release()
        import gc

        gc.collect()  # drop released device buffers before reallocating
    if draft and not on_cpu:
        try:
            spec_fields = _draft_phase(draft, quant, "consensus-3b")
        except Exception as err:  # noqa: BLE001
            spec_fields = {"draft_error": f"{type(err).__name__}: {err}"[:200]}
    if batch_streams > 1 and not on_cpu:
        try:
            batched = _batched_phase(batch_streams, quant, device)
        except Exception as err:  # noqa: BLE001
            batched = {"batched_error": f"{type(err).__name__}: {err}"[:200]}

    baseline = _resolve_baseline()
    print(json.dumps({
        "metric": "consensus tokens/sec/chip (panel+judge, on-device)",
        "value": round(tok_per_sec_chip, 2),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(tok_per_sec_chip / baseline, 3) if baseline else 1.0,
        "p50_latency_ms": round(p50_ms, 1),
        "runs": RUNS,
        "tokens_per_run": total_tokens // RUNS,
        "panel": panel,
        "judge": judge_model,
        "device": device.device_kind,
        "n_chips": n_chips_used,
        "panel_decode_mfu": decode_mfu,
        "panel_decode_mbu": decode_mbu,
        "quant": quant,
        **spec_fields,
        **(batched or {}),
    }))


def _draft_phase(draft: str, quant: str, target: str) -> dict:
    """Single-stream decode tok/s with and without a draft attached."""
    from llm_consensus_tpu.providers.base import Request
    from llm_consensus_tpu.providers.tpu import TPUProvider
    from llm_consensus_tpu.utils.context import Context

    def measure(provider) -> float:
        # Engines released in the finally AFTER the timestamp: teardown
        # time must not skew the drafted-vs-plain comparison, and a
        # mid-phase failure must not leak HBM into the next phase.
        try:
            req = Request(
                model=f"tpu:{target}", prompt=PROMPT, max_tokens=MAX_TOKENS
            )
            provider.query(Context.background(), req)  # warmup
            t0 = time.monotonic()
            resp = provider.query(Context.background(), req)
            dt = time.monotonic() - t0
            return (resp.tokens or 0) / dt
        finally:
            provider.release()

    plain = TPUProvider(ignore_eos=True, stream_interval=64, quant=quant)
    drafted = TPUProvider(
        ignore_eos=True, stream_interval=64, quant=quant, draft=draft,
    )
    plain_tps = measure(plain)
    drafted_tps = measure(drafted)
    return {
        "draft": draft,
        "draft_target": target,
        "draft_tokens_per_sec": round(drafted_tps, 2),
        "draft_plain_tokens_per_sec": round(plain_tps, 2),
    }


def _batched_phase(batch_streams: int, quant: str, device) -> dict:
    """Aggregate tokens/sec/chip + decode MFU/MBU at batch N.

    Fires ``batch_streams`` concurrent requests for one model through a
    stream-batching provider (they co-reside in the ContinuousBatcher's
    shared-frontier decode program) and measures wall-clock aggregate
    throughput — the serving configuration, not a kernel microbenchmark.
    """
    from concurrent.futures import ThreadPoolExecutor

    from llm_consensus_tpu.models.config import get_config
    from llm_consensus_tpu.providers.base import Request
    from llm_consensus_tpu.providers.tpu import TPUProvider
    from llm_consensus_tpu.utils.context import Context
    from llm_consensus_tpu.utils.flops import batched_decode_mbu, decode_mfu

    preset = "consensus-1b"
    model = f"tpu:{preset}"
    # Cap context capacity to what the phase actually needs (prompt +
    # suffix + decode, next power of two, floor 1024): the B-slot cache's
    # HBM is capacity × slots, and a tight cap keeps the phase alive even
    # when a shared chip is under neighbor pressure — derived from
    # MAX_TOKENS so a BENCH_MAX_TOKENS override can't silently truncate
    # streams.
    need = len(PROMPT) + 32 + MAX_TOKENS
    max_seq = max(1024, 1 << (need - 1).bit_length())
    provider = TPUProvider(
        ignore_eos=True, stream_interval=64, quant=quant,
        batch_streams=batch_streams, max_seq=max_seq,
    )
    # Pin to ONE device: on a multi-chip host the planner would hand the
    # model a TP mesh and the provider's multi-device gate would silently
    # de-batch every stream — the phase must measure per-chip batching.
    import jax

    provider.prepare([model], None, devices=jax.devices()[:1])

    def fire(tag: str) -> tuple[float, int]:
        reqs = [
            Request(
                model=model,
                prompt=f"{PROMPT} Stream {tag}-{i}.",
                max_tokens=MAX_TOKENS,
            )
            for i in range(batch_streams)
        ]
        t0 = time.monotonic()
        with ThreadPoolExecutor(batch_streams) as ex:
            results = list(
                ex.map(lambda r: provider.query(Context.background(), r), reqs)
            )
        return time.monotonic() - t0, sum(r.tokens or 0 for r in results)

    fire("warmup")  # compiles the batched prefill/decode programs
    walls, tokens = zip(*(fire(f"run{i}") for i in range(2)))
    agg_tps = sum(tokens) / sum(walls)
    cfg = get_config(preset)
    # Storage widths from the engine actually serving the phase, so an
    # ambient LLMC_KV_QUANT can't skew the recorded MBU.
    engine = provider._engine_for(model)
    ctx_len = len(PROMPT) + MAX_TOKENS // 2  # byte tokenizer ≈ 1 tok/char
    mfu = decode_mfu(cfg, agg_tps, device.device_kind, context_len=ctx_len)
    mbu = batched_decode_mbu(
        cfg, agg_tps, batch_streams, device.device_kind, context_len=ctx_len,
        weight_bytes={"int8": 1, "int4": 0.5}.get(engine.quant, 2),
        kv_bytes=1 if engine.kv_quant == "int8" else 2,
    )
    return {
        "batched_streams": batch_streams,
        "batched_model": model,
        "batched_tokens_per_sec_chip": round(agg_tps, 2),
        "batched_decode_mfu": round(mfu, 4) if mfu else None,
        "batched_decode_mbu": round(mbu, 4) if mbu else None,
    }


if __name__ == "__main__":
    main()
