"""Engine tests: tokenizer, streaming generate, cancellation, consistency."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.engine import ByteTokenizer, Engine, SamplingParams, StreamDecoder
from llm_consensus_tpu.models import forward, get_config, init_params
from llm_consensus_tpu.utils import Context


@pytest.fixture(scope="module")
def tiny_engine():
    cfg = get_config("tiny-llama")
    return Engine(cfg, dtype=jnp.float32, max_seq=128, seed=0)


# -- tokenizer ---------------------------------------------------------------


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer()
    for text in ["hello world", "naïve café — 中文 🚀", ""]:
        ids = tok.encode(text)
        assert ids[0] == tok.bos_id
        assert tok.decode(ids[1:]) == text


def test_stream_decoder_holds_partial_utf8():
    tok = ByteTokenizer()
    decoder = StreamDecoder(tok)
    emitted = []
    for b in "héllo".encode("utf-8"):
        text = decoder.push(b)
        if text:
            emitted.append(text)
    assert "".join(emitted) == "héllo"
    # no replacement chars ever surfaced mid-sequence
    assert all("�" not in e for e in emitted)


def test_stream_decoder_flush_replaces_dangling_bytes():
    decoder = StreamDecoder(ByteTokenizer())
    decoder.push(0xC3)  # first byte of a 2-byte sequence, never completed
    assert decoder.flush() == "�"


# -- generate ----------------------------------------------------------------


def test_generate_greedy_deterministic(tiny_engine):
    sp = SamplingParams(max_new_tokens=12)
    a = tiny_engine.generate("hello", sp)
    b = tiny_engine.generate("hello", sp)
    assert a.token_ids == b.token_ids
    assert a.finish_reason in ("length", "eos")
    assert a.prompt_tokens == len("hello") + 1  # +BOS
    assert a.latency_ms > 0


def test_generate_matches_manual_forward(tiny_engine):
    # The engine's prefill+decode must equal a hand-rolled full-forward
    # greedy loop — end-to-end consistency of bucketing, cache, sampling.
    eng = tiny_engine
    cfg = eng.cfg
    prompt_ids = eng.tokenizer.encode("abc")
    result = eng.generate_ids(prompt_ids, SamplingParams(max_new_tokens=8))

    ids = list(prompt_ids)
    manual = []
    for _ in range(8):
        logits, _ = forward(eng.params, cfg, jnp.asarray([ids], jnp.int32))
        nxt = int(jnp.argmax(logits[0, -1]))
        if nxt == eng.tokenizer.eos_id:
            break
        manual.append(nxt)
        ids.append(nxt)
    assert result.token_ids == manual


def test_stream_callback_receives_all_tokens(tiny_engine):
    streamed = []
    result = tiny_engine.generate_ids(
        tiny_engine.tokenizer.encode("xyz"),
        SamplingParams(max_new_tokens=10),
        on_token=streamed.append,
    )
    assert streamed == result.token_ids


def test_stream_interval_one_equivalent():
    cfg = get_config("tiny-llama")
    e1 = Engine(cfg, dtype=jnp.float32, max_seq=64, stream_interval=1)
    e4 = Engine(cfg, params=e1.params, dtype=jnp.float32, max_seq=64, stream_interval=4)
    sp = SamplingParams(max_new_tokens=9)
    assert e1.generate("q", sp).token_ids == e4.generate("q", sp).token_ids


def test_cancelled_context_returns_partial(tiny_engine):
    ctx = Context.background().with_cancel()
    seen = []

    def on_token(t):
        seen.append(t)
        if len(seen) == 4:
            ctx.cancel()

    result = tiny_engine.generate_ids(
        tiny_engine.tokenizer.encode("hello"),
        SamplingParams(max_new_tokens=64),
        ctx=ctx,
        on_token=on_token,
    )
    assert result.finish_reason == "cancelled"
    assert 4 <= len(result.token_ids) < 64


def test_deadline_finish_reason(tiny_engine):
    ctx = Context.background().with_timeout(0.0001)
    import time

    time.sleep(0.01)
    result = tiny_engine.generate_ids(
        tiny_engine.tokenizer.encode("hello"),
        SamplingParams(max_new_tokens=64),
        ctx=ctx,
    )
    assert result.finish_reason == "deadline"


def test_prompt_too_long_raises(tiny_engine):
    with pytest.raises(ValueError, match="exceeds max sequence length"):
        tiny_engine.generate_ids(list(range(200)), SamplingParams())


def test_empty_prompt_raises(tiny_engine):
    with pytest.raises(ValueError, match="empty prompt"):
        tiny_engine.generate_ids([], SamplingParams())


def test_max_new_tokens_respected(tiny_engine):
    result = tiny_engine.generate_ids(
        tiny_engine.tokenizer.encode("a"), SamplingParams(max_new_tokens=5)
    )
    assert len(result.token_ids) <= 5


def test_temperature_sampling_runs(tiny_engine):
    result = tiny_engine.generate_ids(
        tiny_engine.tokenizer.encode("a"),
        SamplingParams(max_new_tokens=6, temperature=0.8, top_k=50, seed=7),
    )
    assert len(result.token_ids) >= 1


def test_generate_text_streaming_matches_result(tiny_engine):
    chunks = []
    result = tiny_engine.generate(
        "hi", SamplingParams(max_new_tokens=10), on_text=chunks.append
    )
    assert "".join(chunks) == result.text


def test_long_prompt_truncated_middle_out(tiny_engine):
    # Judge prompts can exceed max_seq (reference has no cap either —
    # judge.go:21-25); the engine keeps head + tail and flags it.
    prompt = "start-marker " + "filler words here " * 40 + " end-marker"
    result = tiny_engine.generate(prompt, SamplingParams(max_new_tokens=8))
    assert result.truncated_prompt
    assert result.prompt_tokens < 128
    assert len(result.token_ids) >= 1


def test_short_prompt_not_truncated(tiny_engine):
    result = tiny_engine.generate("hi", SamplingParams(max_new_tokens=4))
    assert not result.truncated_prompt


def test_ignore_eos_decodes_fixed_length(tiny_engine):
    sampling = SamplingParams(max_new_tokens=8, ignore_eos=True)
    result = tiny_engine.generate_ids(
        tiny_engine.tokenizer.encode("a"), sampling
    )
    assert result.finish_reason == "length"
    # prefill samples token 1, then max_new-1 decode steps
    assert len(result.token_ids) == 8


# -- chunked prefill ---------------------------------------------------------


def test_chunked_prefill_matches_one_shot():
    """Long-prompt chunked prefill (one program, dynamic start) must be a
    pure execution-strategy change: greedy continuation identical to the
    bucketed one-shot path on the same fp32 weights."""
    cfg = get_config("tiny-llama")
    base = Engine(cfg, dtype=jnp.float32, max_seq=128, seed=0, prefill_chunk=0)
    chunked = Engine(
        cfg, params=base.params, dtype=jnp.float32, max_seq=128,
        prefill_chunk=16,
    )
    prompt = "the quick brown fox jumps over the lazy dog " * 2  # 88 ids
    s = SamplingParams(max_new_tokens=12, ignore_eos=True)
    assert chunked.generate(prompt, s).token_ids == base.generate(prompt, s).token_ids


def test_chunked_prefill_compiles_one_program():
    """Every chunk must reuse the same compiled program (the whole point:
    no per-bucket recompiles for long prompts)."""
    from llm_consensus_tpu.engine.engine import _prefill_chunk

    cfg = get_config("tiny-llama")
    e = Engine(cfg, dtype=jnp.float32, max_seq=128, prefill_chunk=16)
    before = _prefill_chunk._cache_size()
    e.generate("z" * 100, SamplingParams(max_new_tokens=4, ignore_eos=True))
    assert _prefill_chunk._cache_size() - before <= 1


def test_chunked_prefill_falls_back_when_chunks_exceed_cache():
    """n_chunks * chunk > max_seq would clamp the final chunk's cache write
    (dynamic_update_slice) onto real entries; the engine must take the
    bucketed path instead. chunk=48: 120 tokens → 3 chunks = 144 > 128."""
    cfg = get_config("tiny-llama")
    base = Engine(cfg, dtype=jnp.float32, max_seq=128, seed=0, prefill_chunk=0)
    e = Engine(
        cfg, params=base.params, dtype=jnp.float32, max_seq=128,
        prefill_chunk=48,
    )
    prompt = "y" * 120
    s = SamplingParams(max_new_tokens=4, ignore_eos=True)
    assert e.generate(prompt, s).token_ids == base.generate(prompt, s).token_ids


def test_chunked_prefill_width_bounded_by_prompt_bucket():
    """With max_seq far beyond the prompt, chunks attend a prompt-bucket
    prefix slice of the cache (kv_width), not the full capacity — and the
    result is still identical to the one-shot path."""
    cfg = get_config("tiny-llama")
    base = Engine(cfg, dtype=jnp.float32, max_seq=512, seed=0, prefill_chunk=0)
    chunked = Engine(
        cfg, params=base.params, dtype=jnp.float32, max_seq=512,
        prefill_chunk=16,
    )
    prompt = "a long prompt against a much longer cache " * 2  # 84 ids
    s = SamplingParams(max_new_tokens=12, ignore_eos=True)
    assert chunked.generate(prompt, s).token_ids == base.generate(prompt, s).token_ids


# -- prefix KV-cache reuse ---------------------------------------------------


def _fresh(cfg, params, **kw):
    return Engine(cfg, params=params, dtype=jnp.float32, max_seq=256, **kw)


def test_prefix_reuse_matches_fresh_engine():
    """Reusing the saved prompt KV must be invisible: same greedy tokens
    as a fresh engine for an extended prompt."""
    cfg = get_config("tiny-llama")
    base = Engine(cfg, dtype=jnp.float32, max_seq=256, seed=0,
                  prefill_chunk=16)
    shared = "the quick brown fox jumps over the lazy dog " * 2  # 88 ids
    s = SamplingParams(max_new_tokens=10, ignore_eos=True)
    base.generate(shared, s)  # snapshot the shared prefix
    extended = shared + "and then some more text."
    reused = base.generate(extended, s)
    fresh = _fresh(cfg, base.params, prefill_chunk=16).generate(extended, s)
    assert reused.token_ids == fresh.token_ids


def test_prefix_reuse_divergent_prompt_unaffected():
    """A prompt sharing no prefix must not be polluted by the snapshot."""
    cfg = get_config("tiny-llama")
    e = Engine(cfg, dtype=jnp.float32, max_seq=256, seed=0, prefill_chunk=16)
    s = SamplingParams(max_new_tokens=10, ignore_eos=True)
    e.generate("a" * 80, s)
    other = "completely different prompt with other words entirely " * 2
    reused = e.generate(other, s)
    fresh = _fresh(cfg, e.params, prefill_chunk=16).generate(other, s)
    assert reused.token_ids == fresh.token_ids


def test_prefix_reuse_repeated_prompt_exact():
    """Re-running the exact prompt (all but the final token restored) is
    identical to the first run."""
    cfg = get_config("tiny-llama")
    e = Engine(cfg, dtype=jnp.float32, max_seq=256, seed=0, prefill_chunk=16)
    s = SamplingParams(max_new_tokens=10, ignore_eos=True)
    prompt = "judge this panel of answers carefully " * 3
    first = e.generate(prompt, s)
    second = e.generate(prompt, s)
    assert second.token_ids == first.token_ids


def test_prefix_reuse_with_int8_kv_cache():
    cfg = get_config("tiny-llama")
    e = Engine(cfg, dtype=jnp.float32, max_seq=256, seed=0,
               prefill_chunk=16, kv_quant="int8")
    s = SamplingParams(max_new_tokens=8, ignore_eos=True)
    shared = "shared conversation context for every round " * 2
    e.generate(shared, s)
    extended = shared + "now critique the draft."
    reused = e.generate(extended, s)
    fresh = _fresh(cfg, e.params, prefill_chunk=16,
                   kv_quant="int8").generate(extended, s)
    assert reused.token_ids == fresh.token_ids


def test_prefix_cache_disabled_by_env(monkeypatch):
    monkeypatch.setenv("LLMC_PREFIX_CACHE", "0")
    cfg = get_config("tiny-llama")
    e = Engine(cfg, dtype=jnp.float32, max_seq=128)
    assert not e.prefix_cache_enabled
    e.generate("hello", SamplingParams(max_new_tokens=4, ignore_eos=True))
    assert e._prefix_cache is None


def test_prefix_snapshot_respects_size_cap(monkeypatch):
    monkeypatch.setenv("LLMC_PREFIX_CACHE_MAX_MB", "0.000001")
    cfg = get_config("tiny-llama")
    e = Engine(cfg, dtype=jnp.float32, max_seq=128)
    e.generate("hello", SamplingParams(max_new_tokens=4, ignore_eos=True))
    assert e._prefix_cache is None


def test_prefix_reuse_disabled_with_chunking_off():
    """prefill_chunk=0 documents 'chunking off'; prefix reuse rides the
    chunk program, so it must stay off too."""
    cfg = get_config("tiny-llama")
    e = Engine(cfg, dtype=jnp.float32, max_seq=256, seed=0, prefill_chunk=0)
    s = SamplingParams(max_new_tokens=6, ignore_eos=True)
    prompt = "one shot prefill only " * 4
    e.generate(prompt, s)
    reuse_len, _ = e._reusable_prefix(e.tokenizer.encode(prompt + "more"))
    assert reuse_len == 0 or e.prefill_chunk == 0  # gate holds in generate
    r = e.generate(prompt + "more", s)
    fresh = _fresh(cfg, e.params, prefill_chunk=0).generate(prompt + "more", s)
    assert r.token_ids == fresh.token_ids


def test_prefix_reuse_covers_generated_continuation():
    """The retained cache includes generated tokens, so a follow-up prompt
    that extends prompt+answer reuses past the old prompt boundary."""
    cfg = get_config("tiny-llama")
    e = Engine(cfg, dtype=jnp.float32, max_seq=256, seed=0, prefill_chunk=16)
    s = SamplingParams(max_new_tokens=12, ignore_eos=True)
    ids0 = e.tokenizer.encode("tell me a story " * 3)
    first = e.generate_ids(ids0, s)
    follow_ids = ids0 + first.token_ids + list(b" continue it.")
    lcp, _ = e._reusable_prefix(follow_ids)
    assert lcp == len(ids0) + len(first.token_ids)
    reused = e.generate_ids(follow_ids, s)
    fresh = _fresh(cfg, e.params, prefill_chunk=16).generate_ids(follow_ids, s)
    assert reused.token_ids == fresh.token_ids


def test_decode_kv_width_bucketing_matches_unbucketed(monkeypatch):
    """Width-bucketed decode attention (LLMC_DECODE_KV_MIN small enough to
    engage and cross buckets mid-generation) must emit identical tokens to
    full-capacity attention — single-stream, batched, and sliding-window."""
    s = SamplingParams(max_new_tokens=40, ignore_eos=True)
    for preset in ("tiny-llama", "tiny-mistral"):
        cfg = get_config(preset)
        params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
        monkeypatch.setenv("LLMC_DECODE_KV_MIN", "16")
        on = Engine(cfg, params=params, dtype=jnp.float32, max_seq=256)
        assert on._decode_width(20) == 32  # engaged, not full capacity
        monkeypatch.setenv("LLMC_DECODE_KV_MIN", "0")
        off = Engine(cfg, params=params, dtype=jnp.float32, max_seq=256)
        assert off._decode_width(20) is None
        prompt = "bucketed decode attention equivalence probe"
        assert on.generate(prompt, s).token_ids == off.generate(prompt, s).token_ids
        batch = ["short one", "a noticeably longer prompt for the batch"]
        assert [r.token_ids for r in on.generate_batch(batch, s)] == [
            r.token_ids for r in off.generate_batch(batch, s)
        ]


def test_decode_width_buckets():
    e = Engine(get_config("tiny-llama"), dtype=jnp.float32, max_seq=4096)
    assert e._decode_width(1) == 128        # floor (default 128, see engine.py)
    assert e._decode_width(257) == 384      # next 128-granule
    assert e._decode_width(616) == 640      # between pow2 boundaries
    assert e._decode_width(1024) == 1024    # exact boundary stays
    assert e._decode_width(4000) is None    # bucket reaches capacity


def test_prefill_loop_one_program_across_prompt_lengths():
    """The one-dispatch chunked prefill must key its program on the
    kv-width bucket alone — serving admission with varied prompt
    lengths must never pay a fresh full-model compile per length
    (round-5 review finding, fixed with a traced chunk count)."""
    from llm_consensus_tpu.engine.engine import _prefill_chunks_loop

    cfg = get_config("tiny-llama")
    e = Engine(cfg, dtype=jnp.float32, max_seq=128, prefill_chunk=16)
    s = SamplingParams(max_new_tokens=2, ignore_eos=True)
    e.generate("w" * 40, s)  # 3 chunks -> compiles the loop program
    before = _prefill_chunks_loop._cache_size()
    # Non-vacuous: the loop path must actually be in play (it would be
    # skipped entirely under LLMC_PREFILL_SCAN=0, making the == check
    # below trivially true).
    assert before > 0, "scan prefill not engaged (LLMC_PREFILL_SCAN=0?)"
    e.generate("x" * 55, s)  # 4 chunks, same 64-wide bucket
    e.generate("y" * 33, s)  # 3 chunks again (different content)
    assert _prefill_chunks_loop._cache_size() == before
