"""HF-import golden tests: an INDEPENDENT numpy forward over synthetic
HF-layout checkpoints (round-2 VERDICT #7).

Round 2's importer tests were round-trip self-consistent — they wrote
synthetic safetensors and checked the loaded tree's shapes/values, so a
systematic mapping bug (a missed transpose, a norm-offset shift, a
mis-stacked expert) would survive as long as it was applied consistently.
These tests close that hole: the reference forward below is written in
plain numpy DIRECTLY AGAINST the HF tensor layout and the model papers'
conventions ([out, in] linear weights, rotate-half RoPE, Mixtral top-k
softmax-over-selected gating, Gemma (1+w) norms and sqrt(d) embedding
scale, Qwen2 qkv bias), never touching the framework's model code. If
`load_hf_safetensors` + `models.forward` disagree with it, the import
mapping — not the test — is wrong.

Environment-constrained: zero egress means no published checkpoint to
golden against; an independent implementation over seeded random weights
is the strongest cross-check available (it cannot share a bug with the
import path short of both independently implementing the same wrong
convention).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.engine.checkpoint import load_hf_safetensors
from llm_consensus_tpu.models import forward, get_config
from llm_consensus_tpu.models.config import ModelConfig

# ---------------------------------------------------------------------------
# Independent numpy reference (HF conventions, HF tensor names/layouts)
# ---------------------------------------------------------------------------


def _np_rms_norm(x, w, eps, gemma):
    var = np.mean(x.astype(np.float64) ** 2, axis=-1, keepdims=True)
    normed = x / np.sqrt(var + eps)
    scale = (1.0 + w) if gemma else w
    return normed * scale


def _np_rope(x, positions, theta):
    # rotate_half convention: pairs are (i, i + d/2)
    *_, h, d = x.shape
    inv_freq = 1.0 / (theta ** (np.arange(0, d, 2) / d))
    ang = positions[:, None].astype(np.float64) * inv_freq  # [T, d/2]
    c, s = np.cos(ang), np.sin(ang)
    x1, x2 = x[..., : d // 2], x[..., d // 2 :]
    return np.concatenate(
        [x1 * c[None, :, None, :] - x2 * s[None, :, None, :],
         x2 * c[None, :, None, :] + x1 * s[None, :, None, :]],
        axis=-1,
    )


def _np_attention(q, k, v, scale, window=None):
    b, t, hq, d = q.shape
    hkv = k.shape[2]
    g = hq // hkv
    k = np.repeat(k, g, axis=2)
    v = np.repeat(v, g, axis=2)
    scores = np.einsum("bthd,bshd->bhts", q, k) * scale
    mask = np.tril(np.ones((t, t), bool))
    if window is not None:
        mask &= ~np.tril(np.ones((t, t), bool), -window)
    scores = np.where(mask[None, None], scores, -np.inf)
    scores -= scores.max(axis=-1, keepdims=True)
    p = np.exp(scores)
    p /= p.sum(axis=-1, keepdims=True)
    return np.einsum("bhts,bshd->bthd", p, v)


def _np_act(x, kind):
    if kind == "silu":
        return x / (1.0 + np.exp(-x))
    # gelu tanh approximation (HF/gemma convention)
    return 0.5 * x * (
        1.0 + np.tanh(np.sqrt(2.0 / np.pi) * (x + 0.044715 * x**3))
    )


def _np_mlp(h, t, i, act):
    gate = _np_act(h @ t[f"model.layers.{i}.mlp.gate_proj.weight"].T, act)
    up = h @ t[f"model.layers.{i}.mlp.up_proj.weight"].T
    return (gate * up) @ t[f"model.layers.{i}.mlp.down_proj.weight"].T


def _np_moe(h, t, i, cfg: ModelConfig):
    # Mixtral: softmax over the selected top-k router logits only.
    b, s, d = h.shape
    flat = h.reshape(-1, d)
    logits = flat @ t[f"model.layers.{i}.block_sparse_moe.gate.weight"].T
    order = np.argsort(-logits, axis=-1)[:, : cfg.experts_per_token]
    out = np.zeros_like(flat)
    for n in range(flat.shape[0]):
        top = logits[n, order[n]]
        gates = np.exp(top - top.max())
        gates /= gates.sum()
        for gate_w, e in zip(gates, order[n]):
            w1 = t[f"model.layers.{i}.block_sparse_moe.experts.{e}.w1.weight"]
            w2 = t[f"model.layers.{i}.block_sparse_moe.experts.{e}.w2.weight"]
            w3 = t[f"model.layers.{i}.block_sparse_moe.experts.{e}.w3.weight"]
            y = (_np_act(flat[n] @ w1.T, "silu") * (flat[n] @ w3.T)) @ w2.T
            out[n] += gate_w * y
    return out.reshape(b, s, d)


def _np_forward(tensors: dict, cfg: ModelConfig, token_ids) -> np.ndarray:
    """Logits [B, T, V] from HF-layout ``tensors`` — the golden path."""
    t = {k: v.astype(np.float64) for k, v in tensors.items()}
    gemma = cfg.norm_offset != 0.0
    x = t["model.embed_tokens.weight"][np.asarray(token_ids)]
    if cfg.embed_scale:
        x = x * np.sqrt(cfg.d_model)
    b, seq = np.asarray(token_ids).shape
    positions = np.arange(seq)
    for i in range(cfg.n_layers):
        h = _np_rms_norm(
            x, t[f"model.layers.{i}.input_layernorm.weight"], cfg.rms_eps, gemma
        )
        q = h @ t[f"model.layers.{i}.self_attn.q_proj.weight"].T
        k = h @ t[f"model.layers.{i}.self_attn.k_proj.weight"].T
        v = h @ t[f"model.layers.{i}.self_attn.v_proj.weight"].T
        if cfg.qkv_bias:
            q = q + t[f"model.layers.{i}.self_attn.q_proj.bias"]
            k = k + t[f"model.layers.{i}.self_attn.k_proj.bias"]
            v = v + t[f"model.layers.{i}.self_attn.v_proj.bias"]
        dh = cfg.head_dim
        q = q.reshape(b, seq, cfg.n_heads, dh)
        k = k.reshape(b, seq, cfg.n_kv_heads, dh)
        v = v.reshape(b, seq, cfg.n_kv_heads, dh)
        q = _np_rope(q, positions, cfg.rope_theta)
        k = _np_rope(k, positions, cfg.rope_theta)
        attn = _np_attention(q, k, v, dh**-0.5, cfg.sliding_window)
        x = x + attn.reshape(b, seq, cfg.n_heads * dh) @ (
            t[f"model.layers.{i}.self_attn.o_proj.weight"].T
        )
        h = _np_rms_norm(
            x, t[f"model.layers.{i}.post_attention_layernorm.weight"],
            cfg.rms_eps, gemma,
        )
        if cfg.is_moe:
            x = x + _np_moe(h, t, i, cfg)
        else:
            x = x + _np_mlp(h, t, i, cfg.activation)
    x = _np_rms_norm(x, t["model.norm.weight"], cfg.rms_eps, gemma)
    head = (
        t["model.embed_tokens.weight"]
        if cfg.tie_embeddings
        else t["lm_head.weight"]
    )
    return x @ head.T


# ---------------------------------------------------------------------------
# Synthetic HF checkpoints (seeded random, written as real safetensors)
# ---------------------------------------------------------------------------


def _make_hf_checkpoint(cfg: ModelConfig, path: str, seed: int = 0) -> dict:
    from safetensors.numpy import save_file

    rng = np.random.default_rng(seed)

    def w(*shape, scale=0.05):
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    d, dh = cfg.d_model, cfg.head_dim
    t: dict = {"model.embed_tokens.weight": w(cfg.vocab_size, d, scale=0.2)}
    # Norm weights near their neutral value, jittered so a dropped (1+w)
    # offset or a swapped norm cannot cancel out.
    neutral = 0.0 if cfg.norm_offset else 1.0
    t["model.norm.weight"] = (neutral + 0.1 * rng.standard_normal(d)).astype(
        np.float32
    )
    if not cfg.tie_embeddings:
        t["lm_head.weight"] = w(cfg.vocab_size, d, scale=0.2)
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}"
        t[f"{p}.input_layernorm.weight"] = (
            neutral + 0.1 * rng.standard_normal(d)
        ).astype(np.float32)
        t[f"{p}.post_attention_layernorm.weight"] = (
            neutral + 0.1 * rng.standard_normal(d)
        ).astype(np.float32)
        t[f"{p}.self_attn.q_proj.weight"] = w(cfg.n_heads * dh, d)
        t[f"{p}.self_attn.k_proj.weight"] = w(cfg.n_kv_heads * dh, d)
        t[f"{p}.self_attn.v_proj.weight"] = w(cfg.n_kv_heads * dh, d)
        t[f"{p}.self_attn.o_proj.weight"] = w(d, cfg.n_heads * dh)
        if cfg.qkv_bias:
            t[f"{p}.self_attn.q_proj.bias"] = w(cfg.n_heads * dh)
            t[f"{p}.self_attn.k_proj.bias"] = w(cfg.n_kv_heads * dh)
            t[f"{p}.self_attn.v_proj.bias"] = w(cfg.n_kv_heads * dh)
        if cfg.is_moe:
            t[f"{p}.block_sparse_moe.gate.weight"] = w(cfg.n_experts, d)
            for e in range(cfg.n_experts):
                ep = f"{p}.block_sparse_moe.experts.{e}"
                t[f"{ep}.w1.weight"] = w(cfg.d_ff, d)
                t[f"{ep}.w2.weight"] = w(d, cfg.d_ff)
                t[f"{ep}.w3.weight"] = w(cfg.d_ff, d)
        else:
            t[f"{p}.mlp.gate_proj.weight"] = w(cfg.d_ff, d)
            t[f"{p}.mlp.up_proj.weight"] = w(cfg.d_ff, d)
            t[f"{p}.mlp.down_proj.weight"] = w(d, cfg.d_ff)
    os.makedirs(path, exist_ok=True)
    save_file(t, os.path.join(path, "model.safetensors"))
    return t


PRESETS = [
    "tiny-llama",    # baseline llama conventions (GQA, SwiGLU, untied head)
    "tiny-gemma",    # norm offset (1+w), sqrt(d) embed scale, gelu, tied
    "tiny-qwen2",    # qkv bias
    "tiny-mistral",  # sliding window
    "tiny-mixtral",  # expert stacking + top-k gating
]


@pytest.mark.parametrize("preset", PRESETS)
def test_hf_import_matches_numpy_reference(preset, tmp_path):
    cfg = get_config(preset)
    tensors = _make_hf_checkpoint(cfg, str(tmp_path / preset), seed=7)
    params = load_hf_safetensors(cfg, str(tmp_path / preset), dtype=jnp.float32)
    tokens = np.asarray(
        np.random.default_rng(3).integers(0, cfg.vocab_size, (1, 6)), np.int32
    )
    golden = _np_forward(tensors, cfg, tokens)
    with jax.default_matmul_precision("highest"):
        logits, _ = forward(params, cfg, jnp.asarray(tokens))
    got = np.asarray(logits, np.float64)
    err = np.abs(got - golden).max() / max(1e-9, np.abs(golden).max())
    assert err < 2e-4, f"{preset}: relative logit error {err}"


def test_hf_import_detects_transpose_bug(tmp_path):
    """Meta-test: the golden actually has teeth — a deliberately
    transposed projection must blow the tolerance."""
    cfg = get_config("tiny-llama")
    tensors = _make_hf_checkpoint(cfg, str(tmp_path / "ok"), seed=7)
    params = load_hf_safetensors(cfg, str(tmp_path / "ok"), dtype=jnp.float32)
    bad = dict(params)
    bad["layers"] = dict(params["layers"])
    bad["layers"]["wq"] = jnp.swapaxes(params["layers"]["wq"], -1, -2)
    tokens = np.asarray([[5, 9, 2, 7, 1, 3]], np.int32)
    golden = _np_forward(tensors, cfg, tokens)
    with jax.default_matmul_precision("highest"):
        logits, _ = forward(bad, cfg, jnp.asarray(tokens))
    err = np.abs(np.asarray(logits, np.float64) - golden).max() / np.abs(
        golden
    ).max()
    assert err > 1e-2, "transposed wq went undetected — golden has no teeth"


def test_hf_sharded_import_matches_unsharded(tmp_path):
    """The lazy get_slice sharded importer must produce the same tree as
    the full importer — per shard, against TP NamedShardings."""
    import numpy as np
    from jax.sharding import Mesh

    from llm_consensus_tpu.engine.checkpoint import load_hf_safetensors_sharded

    cfg = get_config("tiny-llama", head_dim=128)  # tp-divisible heads
    _make_hf_checkpoint(cfg, str(tmp_path / "ck"), seed=11)
    full = load_hf_safetensors(cfg, str(tmp_path / "ck"), dtype=jnp.float32)
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(1, 2), ("dp", "tp"))
    sharded = load_hf_safetensors_sharded(
        cfg, str(tmp_path / "ck"), mesh, dtype=jnp.float32
    )
    flat_f = jax.tree_util.tree_leaves_with_path(full)
    flat_s = dict(jax.tree_util.tree_leaves_with_path(sharded))
    assert len(flat_f) == len(flat_s)
    for path, leaf in flat_f:
        got = np.asarray(flat_s[path])
        assert got.shape == leaf.shape, path
        assert np.array_equal(got, np.asarray(leaf)), path
