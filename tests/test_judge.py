"""Judge tests — ports judge_test.go:13-136 scenarios plus extras."""

import pytest

from llm_consensus_tpu.consensus import Judge, NoResponsesError, render_judge_prompt
from llm_consensus_tpu.providers import ProviderFunc, Request, Response
from llm_consensus_tpu.utils import Context


def resp(model, content, provider="test"):
    return Response(model=model, content=content, provider=provider)


def test_empty_responses_error():
    judge = Judge(ProviderFunc(lambda c, r: resp("j", "x")), "j")
    with pytest.raises(NoResponsesError):
        judge.synthesize(Context.background(), "p", [])


def test_single_response_passthrough_no_judge_call():
    # judge.go:74-79 — verbatim passthrough, callback still fired, provider untouched.
    calls = []

    def fn(ctx, req):
        calls.append(req)
        return resp("j", "judged")

    judge = Judge(ProviderFunc(fn), "j")
    chunks = []
    out = judge.synthesize_stream(
        Context.background(), "p", [resp("only", "the one answer")], chunks.append
    )
    assert out == "the one answer"
    assert chunks == ["the one answer"]
    assert calls == []


def test_multi_response_invokes_judge_with_embedded_answers():
    captured = {}

    def fn(ctx, req):
        captured["req"] = req
        return resp(req.model, "the consensus")

    judge = Judge(ProviderFunc(fn), "judge-model")
    out = judge.synthesize(
        Context.background(),
        "original question",
        [resp("m1", "answer one", "prov1"), resp("m2", "answer two", "prov2")],
    )
    assert out == "the consensus"
    req = captured["req"]
    assert req.model == "judge-model"
    for needle in ["original question", "answer one", "answer two"]:
        assert needle in req.prompt


def test_judge_error_propagates():
    def fn(ctx, req):
        raise RuntimeError("api down")

    judge = Judge(ProviderFunc(fn), "j")
    with pytest.raises(RuntimeError, match="judge query failed"):
        judge.synthesize(
            Context.background(), "p", [resp("a", "1"), resp("b", "2")]
        )


def test_template_expansion():
    # Parity with judge_test.go:101-136: the rendered prompt contains the
    # user prompt, every model name, provider name, content, and the exact
    # separator format (judge.go:21-25).
    rendered = render_judge_prompt(
        "what is 2+2?",
        [resp("alpha", "it is 4", "openai"), resp("beta", "four", "anthropic")],
    )
    assert "what is 2+2?" in rendered
    assert "--- Model: alpha | Provider: openai ---" in rendered
    assert "--- Model: beta | Provider: anthropic ---" in rendered
    assert "it is 4" in rendered
    assert "four" in rendered
    # instruction text wraps the responses
    assert rendered.index("what is 2+2?") < rendered.index("--- Model: alpha")


def test_streaming_chunks_forwarded():
    class StreamingProvider(ProviderFunc):
        def __init__(self):
            super().__init__(lambda c, r: resp("j", "abc"))

        def query_stream(self, ctx, req, callback):
            for ch in "abc":
                callback(ch)
            return resp("j", "abc")

    judge = Judge(StreamingProvider(), "j")
    chunks = []
    out = judge.synthesize_stream(
        Context.background(), "p", [resp("a", "1"), resp("b", "2")], chunks.append
    )
    assert out == "abc"
    assert chunks == ["a", "b", "c"]


def test_agreement_scoring_basics():
    from llm_consensus_tpu.consensus import score_agreement
    from llm_consensus_tpu.providers import Response

    same = [Response("a", "the sky is blue", "f", 1),
            Response("b", "the sky is blue", "f", 1)]
    ag = score_agreement(same)
    assert ag.score == 1.0 and ag.level == "high"
    assert ag.divergence == {"a": 0.0, "b": 0.0}

    mixed = [Response("a", "the sky is blue today", "f", 1),
             Response("b", "the sky is blue now", "f", 1),
             Response("c", "quantum flux capacitors rule", "f", 1)]
    ag = score_agreement(mixed)
    assert 0 < ag.score < 1
    # c is the outlier: largest divergence.
    assert max(ag.divergence, key=ag.divergence.get) == "c"

    assert score_agreement([Response("a", "x", "f", 1)]) is None
    assert score_agreement([]) is None


def test_agreement_in_result_json():
    import json

    from tests.test_cli import run_cli
    from llm_consensus_tpu.providers import ProviderFunc, Response

    def factory(model):
        content = "identical answer" if model != "j" else "synth"
        return ProviderFunc(
            lambda ctx, req, c=content: Response(req.model, c, "fake", 1.0))

    code, out, _ = run_cli(
        ["--models", "m1,m2", "--judge", "j", "--json", "q"], factory=factory)
    assert code == 0
    data = json.loads(out)
    assert data["agreement"]["score"] == 1.0
    assert data["agreement"]["level"] == "high"

    # Single model: no agreement key at all (omitempty).
    code, out, _ = run_cli(
        ["--models", "m1", "--judge", "j", "--json", "q"], factory=factory)
    assert "agreement" not in json.loads(out)
