"""Cancellation context tests (the Go-context analog, utils/context.py)."""

import time

import pytest

from llm_consensus_tpu.utils import Cancelled, Context, DeadlineExceeded


def test_background_never_done():
    ctx = Context.background()
    assert not ctx.done()
    assert ctx.err() is None
    assert ctx.remaining() is None


def test_cancel_sets_done():
    ctx = Context.background().with_cancel()
    ctx.cancel()
    assert ctx.done()
    with pytest.raises(Cancelled):
        ctx.raise_if_done()


def test_deadline_exceeded():
    ctx = Context.background().with_timeout(0.01)
    time.sleep(0.03)
    assert ctx.done()
    with pytest.raises(DeadlineExceeded):
        ctx.raise_if_done()


def test_child_inherits_parent_cancel():
    parent = Context.background().with_cancel()
    child = parent.with_timeout(100)
    grandchild = child.with_cancel()
    parent.cancel()
    assert child.done() and grandchild.done()
    assert isinstance(grandchild.err(), Cancelled)


def test_child_deadline_min_of_parent():
    parent = Context.background().with_timeout(0.01)
    child = parent.with_timeout(100)
    assert child.remaining() <= 0.01


def test_on_done_fires_on_cancel():
    ctx = Context.background().with_cancel()
    fired = []
    ctx.on_done(lambda: fired.append(1))
    assert fired == []
    ctx.cancel()
    assert fired == [1]


def test_on_done_fires_immediately_if_already_done():
    ctx = Context.background().with_cancel()
    ctx.cancel()
    fired = []
    ctx.on_done(lambda: fired.append(1))
    assert fired == [1]


def test_on_done_unsubscribe():
    ctx = Context.background().with_cancel()
    fired = []
    unsub = ctx.on_done(lambda: fired.append(1))
    unsub()
    ctx.cancel()
    assert fired == []


def test_sleep_wakes_on_cancel():
    ctx = Context.background().with_timeout(0.05)
    start = time.monotonic()
    completed = ctx.sleep(10)
    assert time.monotonic() - start < 5
    assert not completed
