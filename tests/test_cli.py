"""CLI tests — flags, prompt precedence, output routing, run persistence.

Coverage the reference lacks entirely (SURVEY.md §4 lesson): golden tests of
cmd/llm-consensus/main.go behaviors through an injected provider factory.
"""

import io
import json
import os

import pytest

from llm_consensus_tpu.cli.main import (
    CLIError,
    create_provider,
    get_prompt,
    main,
)
from llm_consensus_tpu.providers import ProviderFunc, Response


def echo_factory(model: str):
    if model.startswith("bad"):
        def fail(ctx, req):
            raise RuntimeError("provider down")
        return ProviderFunc(fail)
    return ProviderFunc(
        lambda ctx, req: Response(req.model, f"echo({req.prompt[:20]})", "fake", 1.0)
    )


def run_cli(argv, stdin_text="", factory=echo_factory):
    stdin = io.StringIO(stdin_text)
    stdout, stderr = io.StringIO(), io.StringIO()
    code = main(
        argv,
        factory=factory,
        stdin=stdin,
        stdout=stdout,
        stderr=stderr,
        install_signal_handlers=False,
    )
    return code, stdout.getvalue(), stderr.getvalue()


def test_version_flag():
    code, out, _ = run_cli(["--version"])
    assert code == 0
    assert out.startswith("llm-consensus 0.")
    assert "commit:" in out and "built:" in out


def test_models_flag_required():
    code, _, err = run_cli(["hello"])
    assert code == 1
    assert "error: --models flag is required" in err


def test_empty_piped_stdin_accepted():
    # StringIO stdin is not a char device → the piped-stdin branch runs;
    # empty piped input is an empty prompt and the run proceeds (parity:
    # the reference reads zero lines from an empty pipe).
    code, _, err = run_cli(["--models", "m1,m2", "--no-save"], stdin_text="")
    assert code == 0


def test_no_prompt_error_when_stdin_is_tty(monkeypatch):
    # With a TTY stdin and no arg/--file, the CLI must error (main.go:392).
    import importlib

    cli_main = importlib.import_module("llm_consensus_tpu.cli.main")
    monkeypatch.setattr(cli_main.ui, "is_terminal", lambda f: True)
    code, _, err = run_cli(["--models", "m1,m2"])
    assert code == 1
    assert "error: no prompt provided: use positional argument, --file, or pipe to stdin" in err


def test_json_output_to_stdout():
    code, out, err = run_cli(["--models", "m1,m2", "--judge", "j", "--json", "what is up"])
    assert code == 0
    d = json.loads(out)
    assert d["prompt"] == "what is up"
    assert d["judge"] == "j"
    assert len(d["responses"]) == 2
    assert d["consensus"].startswith("echo(")
    assert "warnings" not in d


def test_positional_args_joined():
    code, out, _ = run_cli(["--models", "m1", "--judge", "j", "--json", "a", "b", "c"])
    assert json.loads(out)["prompt"] == "a b c"


def test_prompt_from_file(tmp_path):
    f = tmp_path / "prompt.txt"
    f.write_text("  file prompt\n")
    code, out, _ = run_cli(["--models", "m1", "--judge", "j", "--json", "--file", str(f)])
    assert json.loads(out)["prompt"] == "file prompt"


def test_prompt_from_stdin():
    code, out, _ = run_cli(
        ["--models", "m1", "--judge", "j", "--json"], stdin_text="line1\nline2\n"
    )
    assert json.loads(out)["prompt"] == "line1\nline2"


def test_positional_beats_file(tmp_path):
    f = tmp_path / "p.txt"
    f.write_text("from file")
    code, out, _ = run_cli(
        ["--models", "m1", "--judge", "j", "--json", "--file", str(f), "from", "arg"]
    )
    assert json.loads(out)["prompt"] == "from arg"


def test_missing_prompt_file_error():
    code, _, err = run_cli(["--models", "m1", "--file", "/nonexistent/x.txt"])
    assert code == 1
    assert "error: reading prompt file" in err


def test_partial_failure_reported_in_json():
    code, out, _ = run_cli(["--models", "m1,bad1", "--judge", "j", "--json", "q"])
    assert code == 0
    d = json.loads(out)
    assert d["failed_models"] == ["bad1"]
    assert len(d["responses"]) == 1
    assert "bad1" in d["warnings"][0]


def test_all_models_fail_exits_1():
    code, _, err = run_cli(["--models", "bad1,bad2", "--judge", "j", "--json", "q"])
    assert code == 1
    assert "error: running queries" in err


def test_single_model_judge_passthrough():
    # Single response → judge passthrough (judge.go:74-79): consensus equals
    # the sole model answer even though the judge provider would fail.
    def factory(model):
        if model == "j":
            def fail(ctx, req):
                raise RuntimeError("judge must not be called")
            return ProviderFunc(fail)
        return echo_factory(model)

    code, out, _ = run_cli(["--models", "m1", "--judge", "j", "--json", "q"], factory=factory)
    assert code == 0
    d = json.loads(out)
    assert d["consensus"] == d["responses"][0]["content"]


def test_output_file_routing(tmp_path):
    path = tmp_path / "out.json"
    code, out, _ = run_cli(
        ["--models", "m1", "--judge", "j", "--output", str(path), "--no-save", "q"]
    )
    assert code == 0
    assert out == ""  # JSON went to the file, not stdout
    d = json.loads(path.read_text())
    assert d["judge"] == "j"


def test_auto_save_run_dir(tmp_path):
    data_dir = str(tmp_path / "data")
    code, out, _ = run_cli(
        ["--models", "m1,m2", "--judge", "j", "--data-dir", data_dir, "the question"]
    )
    assert code == 0
    runs = os.listdir(data_dir)
    assert len(runs) == 1
    run_dir = os.path.join(data_dir, runs[0])
    files = sorted(os.listdir(run_dir))
    # run.json (the resume manifest) and panel/ (per-model answer
    # journal) are written BEFORE the fan-out so a crashed run is
    # resumable; the classic artifacts land on success as before.
    assert files == [
        "consensus.md", "panel", "prompt.txt", "result.json", "run.json"
    ]
    panel = sorted(os.listdir(os.path.join(run_dir, "panel")))
    assert len(panel) == 2 and all(p.endswith(".json") for p in panel)
    assert open(os.path.join(run_dir, "prompt.txt")).read() == "the question"
    d = json.load(open(os.path.join(run_dir, "result.json")))
    assert d["prompt"] == "the question"
    # run-id format: YYYYmmdd-HHMMSS-xxxxxx (main.go:278-285)
    stem = runs[0]
    parts = stem.split("-")
    assert len(parts) == 3 and len(parts[0]) == 8 and len(parts[1]) == 6 and len(parts[2]) == 6


def test_json_flag_disables_auto_save(tmp_path):
    data_dir = str(tmp_path / "data")
    code, out, _ = run_cli(
        ["--models", "m1", "--judge", "j", "--json", "--data-dir", data_dir, "q"]
    )
    assert code == 0
    assert not os.path.exists(data_dir)


def test_no_save_flag(tmp_path):
    data_dir = str(tmp_path / "data")
    code, out, _ = run_cli(
        ["--models", "m1", "--judge", "j", "--no-save", "--data-dir", data_dir, "q"]
    )
    assert code == 0
    assert not os.path.exists(data_dir)
    json.loads(out)  # non-TTY stdout falls back to JSON


def test_unknown_model_lists_available():
    code, _, err = run_cli(["--models", "not-a-model", "q"], factory=create_provider)
    assert code == 1
    assert "error: unknown model 'not-a-model'" in err
    assert "tpu:<model>" in err


def test_judge_auto_added_to_registry():
    seen = []

    def factory(model):
        seen.append(model)
        return echo_factory(model)

    run_cli(["--models", "m1,m2", "--judge", "the-judge", "--json", "q"], factory=factory)
    assert "the-judge" in seen


def test_judge_not_duplicated_when_in_panel():
    seen = []

    def factory(model):
        seen.append(model)
        return echo_factory(model)

    run_cli(["--models", "m1,j", "--judge", "j", "--json", "q"], factory=factory)
    assert seen.count("j") == 1


def test_timeout_flag_parsed():
    # timeout is int seconds (main.go:317)
    code, out, _ = run_cli(["--models", "m1", "--judge", "j", "--json", "--timeout", "7", "q"])
    assert code == 0


def test_go_style_single_dash_flags():
    code, out, _ = run_cli(["-models", "m1", "-judge", "j", "-json", "q"])
    assert code == 0
    assert json.loads(out)["judge"] == "j"


# -- --continue (conversation history) ---------------------------------------


def test_continue_folds_history_into_prompts(tmp_path):
    """--continue loads the saved run, panel+judge see the conversation,
    and the new result records the accumulated history."""
    seen_prompts = []

    def factory(model):
        def fn(ctx, req):
            seen_prompts.append((model, req.prompt))
            return Response(req.model, f"ans-{model}", "fake", 1.0)
        return ProviderFunc(fn)

    data_dir = str(tmp_path / "data")
    # First run, auto-saved.
    code, _, err = run_cli(
        ["--models", "m1,m2", "--judge", "j", "--data-dir", data_dir,
         "--quiet", "first question"],
        factory=factory,
    )
    assert code == 0, err
    run_id = os.listdir(data_dir)[0]

    seen_prompts.clear()
    code, out, err = run_cli(
        ["--models", "m1,m2", "--judge", "j", "--data-dir", data_dir,
         "--continue", run_id, "--json", "follow up"],
        factory=factory,
    )
    assert code == 0, err
    data = json.loads(out)
    # Raw follow-up is the recorded prompt; history carries the exchange.
    assert data["prompt"] == "follow up"
    assert data["history"] == [
        {"prompt": "first question", "consensus": "ans-j"}
    ]
    # Panel and judge both saw the folded conversation.
    for model, prompt in seen_prompts:
        assert "first question" in prompt
        assert "ans-j" in prompt
        assert "follow up" in prompt


def test_continue_chains_history(tmp_path):
    """A continued run's save can itself be continued; history accumulates
    oldest-first."""
    data_dir = str(tmp_path / "data")
    code, _, _ = run_cli(
        ["--models", "m1", "--data-dir", data_dir, "--quiet", "q1"])
    assert code == 0
    first = os.listdir(data_dir)[0]
    code, _, _ = run_cli(
        ["--models", "m1", "--data-dir", data_dir, "--continue", first,
         "--quiet", "q2"])
    assert code == 0
    second = next(d for d in os.listdir(data_dir) if d != first)
    code, out, _ = run_cli(
        ["--models", "m1", "--data-dir", data_dir, "--continue", second,
         "--json", "q3"])
    assert code == 0
    hist = json.loads(out)["history"]
    assert [h["prompt"] for h in hist] == ["q1", "q2"]


def test_continue_unknown_run_errors(tmp_path):
    code, _, err = run_cli(
        ["--models", "m1", "--data-dir", str(tmp_path), "--continue",
         "nope", "q"])
    assert code == 1
    assert "loading run 'nope'" in err


# -- --system ----------------------------------------------------------------


def test_system_prompt_reaches_panel_not_judge():
    """--system flows to every panel request; the judge keeps its own role
    prompt (reference roadmap §3.2)."""
    seen = {}

    def factory(model):
        def fn(ctx, req):
            seen[model] = req.system
            return Response(req.model, "ans", "fake", 1.0)
        return ProviderFunc(fn)

    code, _, err = run_cli(
        ["--models", "m1,m2", "--judge", "j", "--system", "be terse",
         "--json", "q"],
        factory=factory,
    )
    assert code == 0, err
    assert seen["m1"] == "be terse" and seen["m2"] == "be terse"
    assert seen["j"] is None


def test_system_file(tmp_path):
    p = tmp_path / "sys.txt"
    p.write_text("from file\n")
    seen = {}

    def factory(model):
        def fn(ctx, req):
            seen[model] = req.system
            return Response(req.model, "ans", "fake", 1.0)
        return ProviderFunc(fn)

    code, _, _ = run_cli(
        ["--models", "m1", "--system-file", str(p), "--json", "q"],
        factory=factory,
    )
    assert code == 0
    assert seen["m1"] == "from file"


def test_system_and_system_file_exclusive(tmp_path):
    p = tmp_path / "sys.txt"
    p.write_text("x")
    code, _, err = run_cli(
        ["--models", "m1", "--system", "a", "--system-file", str(p), "q"])
    assert code == 1 and "mutually exclusive" in err


# -- config file + aliases ---------------------------------------------------


def test_config_file_defaults_and_aliases(tmp_path, monkeypatch):
    """Config supplies flag defaults and @aliases; CLI flags win."""
    cfgp = tmp_path / "cfg.json"
    cfgp.write_text(json.dumps({
        "models": "@panel",
        "judge": "j-from-config",
        "timeout": 7,
        "aliases": {"@panel": "m1, m2", "@solo": "m9"},
    }))
    monkeypatch.setenv("LLMC_CONFIG", str(cfgp))

    seen = []

    def factory(model):
        seen.append(model)
        return ProviderFunc(
            lambda ctx, req: Response(req.model, "ans", "fake", 1.0))

    # No --models flag: the config default (alias-expanded) applies.
    code, out, err = run_cli(["--json", "q"], factory=factory)
    assert code == 0, err
    data = json.loads(out)
    assert [r["model"] for r in data["responses"]] == ["m1", "m2"]
    assert data["judge"] == "j-from-config"

    # Explicit flags beat the config.
    seen.clear()
    code, out, _ = run_cli(
        ["--models", "@solo", "--judge", "j2", "--json", "q"], factory=factory)
    assert code == 0
    data = json.loads(out)
    assert [r["model"] for r in data["responses"]] == ["m9"]
    assert data["judge"] == "j2"


def test_unknown_alias_errors(tmp_path, monkeypatch):
    cfgp = tmp_path / "cfg.json"
    cfgp.write_text(json.dumps({"aliases": {"@a": "m1"}}))
    monkeypatch.setenv("LLMC_CONFIG", str(cfgp))
    code, _, err = run_cli(["--models", "@nope", "q"])
    assert code == 1 and "unknown model alias '@nope'" in err


def test_config_unknown_key_errors(tmp_path, monkeypatch):
    cfgp = tmp_path / "cfg.json"
    cfgp.write_text(json.dumps({"modles": "typo"}))
    monkeypatch.setenv("LLMC_CONFIG", str(cfgp))
    code, _, err = run_cli(["--models", "m1", "q"])
    assert code == 1 and "unknown keys" in err


def test_config_disabled_by_env(tmp_path, monkeypatch):
    cfgp = tmp_path / "cfg.json"
    cfgp.write_text("{not json")
    monkeypatch.setenv("LLMC_CONFIG", "0")
    code, _, err = run_cli(["--models", "m1", "--json", "q"])
    assert code == 0  # broken file never read


def test_alias_overlap_preserves_duplicates(tmp_path, monkeypatch):
    """Explicit duplicates have always meant two queries (reference
    semantics); alias overlap follows the same rule."""
    cfgp = tmp_path / "cfg.json"
    cfgp.write_text(json.dumps({"aliases": {"@a": "m1,m2", "@b": "m2,m3"}}))
    monkeypatch.setenv("LLMC_CONFIG", str(cfgp))
    code, out, _ = run_cli(["--models", "@a,@b", "--json", "q"])
    assert code == 0
    assert [r["model"] for r in json.loads(out)["responses"]] == [
        "m1", "m2", "m2", "m3"
    ]


def test_config_wrong_types_rejected(tmp_path, monkeypatch):
    cfgp = tmp_path / "cfg.json"
    cfgp.write_text(json.dumps({"rounds": "2"}))
    monkeypatch.setenv("LLMC_CONFIG", str(cfgp))
    code, _, err = run_cli(["--models", "m1", "q"])
    assert code == 1 and "'rounds' must be an integer" in err

    cfgp.write_text(json.dumps({"aliases": ["@a"]}))
    code, _, err = run_cli(["--models", "m1", "q"])
    assert code == 1 and "'aliases' must map" in err


def test_explicit_missing_config_path_errors(monkeypatch):
    monkeypatch.setenv("LLMC_CONFIG", "/nonexistent/typo.json")
    code, _, err = run_cli(["--models", "m1", "q"])
    assert code == 1 and "missing file" in err


def test_version_works_with_broken_config(tmp_path, monkeypatch):
    cfgp = tmp_path / "cfg.json"
    cfgp.write_text("{broken")
    monkeypatch.setenv("LLMC_CONFIG", str(cfgp))
    code, out, _ = run_cli(["--version"])
    assert code == 0 and out.startswith("llm-consensus")


# -- interactive mode --------------------------------------------------------


def test_interactive_queries_and_history(tmp_path):
    """Each line is a consensus query; the conversation folds into later
    queries; slash commands mutate the session."""
    seen = []

    def factory(model):
        def fn(ctx, req):
            seen.append((model, req.prompt))
            return Response(req.model, f"ans-{model}", "fake", 1.0)
        return ProviderFunc(fn)

    script = "\n".join([
        "first question",
        "/models +m2",
        "second question",
        "/reset",
        "/models -m2",
        "third question",
        "/exit",
        "never reached",
    ]) + "\n"
    code, out, err = run_cli(
        ["--models", "m1", "--judge", "j", "--interactive", "--no-save",
         "--quiet"],
        stdin_text=script, factory=factory,
    )
    assert code == 0, err
    # Query 1: m1 only, no history.
    q1 = [p for m, p in seen if m == "m1" and "first question" in p]
    assert q1 and "Earlier exchanges" not in q1[0]
    # Query 2: m1 AND m2, history folded in (query 1's consensus is the
    # single-response passthrough, i.e. ans-m1).
    q2 = [p for m, p in seen if m == "m2"]
    assert q2 and "first question" in q2[0] and "ans-m1" in q2[0]
    # Query 3 (after /reset and /models -m2): m1 only, no history.
    q3 = [p for m, p in seen if m == "m1" and "third question" in p]
    assert q3 and "Earlier exchanges" not in q3[0]
    assert not any(m == "m2" and "third" in p for m, p in seen)
    assert "never reached" not in " ".join(p for _, p in seen)


def test_interactive_query_error_keeps_session(tmp_path):
    """A failing query prints an error and the REPL continues."""
    def factory(model):
        def fn(ctx, req):
            if "boom" in req.prompt:
                raise RuntimeError("provider exploded")
            return Response(req.model, "ok", "fake", 1.0)
        return ProviderFunc(fn)

    code, out, err = run_cli(
        ["--models", "m1", "--judge", "m1", "--interactive", "--no-save",
         "--quiet"],
        stdin_text="boom\nworks\n", factory=factory,
    )
    assert code == 0
    assert "error:" in err
    # Second query still ran (non-TTY stdout → JSON line).
    assert '"consensus": "ok"' in out


def test_interactive_rejects_positional_prompt():
    code, _, err = run_cli(["--models", "m1", "--interactive", "hello"])
    assert code == 1 and "stdin" in err


def test_interactive_typod_command_rejected():
    code, out, err = run_cli(
        ["--models", "m1", "--interactive", "--no-save", "--quiet"],
        stdin_text="/judges j2\n/modelsx +m2\n/exit\n",
    )
    assert code == 0
    assert "unknown command '/judges'" in err
    assert "unknown command '/modelsx'" in err


def test_interactive_keeps_last_model():
    code, out, err = run_cli(
        ["--models", "m1", "--interactive", "--no-save", "--quiet"],
        stdin_text="/models -m1\n/exit\n",
    )
    assert code == 0
    assert "cannot remove the last panel model" in err
    assert "models: m1" in err


def test_interactive_rejects_output_and_file(tmp_path):
    code, _, err = run_cli(
        ["--models", "m1", "--interactive", "--output", "x.json"])
    assert code == 1 and "incompatible" in err
    p = tmp_path / "f.txt"
    p.write_text("x")
    code, _, err = run_cli(
        ["--models", "m1", "--interactive", "--file", str(p)])
    assert code == 1 and "stdin" in err


def test_sigint_cancels_run_gracefully():
    """Checklist item main.go:90-91: SIGINT → context cancel → the run
    winds down cooperatively (failed models, exit 1) instead of dying on
    a traceback."""
    import signal
    import threading

    def factory(model):
        def fn(ctx, req):
            ctx.sleep(10)  # cooperative: wakes on cancel
            ctx.raise_if_done()
            return Response(req.model, "never", "fake", 1.0)
        return ProviderFunc(fn)

    # Process-directed delivery (like a real Ctrl-C): the kernel hands the
    # signal to the main thread, interrupting its join so the handler runs
    # promptly. raise_signal from the timer thread would deliver to the
    # timer thread and the handler would wait for the join to finish.
    timer = threading.Timer(
        0.2, lambda: os.kill(os.getpid(), signal.SIGINT)
    )
    timer.start()
    stdin, stdout, stderr = io.StringIO(), io.StringIO(), io.StringIO()
    t0 = __import__("time").monotonic()
    code = main(
        ["--models", "m1,m2", "--judge", "j", "--json", "q"],
        factory=factory, stdin=stdin, stdout=stdout, stderr=stderr,
        install_signal_handlers=True,
    )
    timer.cancel()
    assert code == 1
    assert "error: running queries" in stderr.getvalue()
    assert __import__("time").monotonic() - t0 < 5  # not the 10s sleep


# ---------------------------------------------------------------------------
# the `serve` subcommand (cli/serve.py)


def test_serve_requires_models():
    stdout, stderr = io.StringIO(), io.StringIO()
    code = main(["serve"], stdout=stdout, stderr=stderr,
                install_signal_handlers=False)
    assert code == 1
    assert "error: --models flag is required" in stderr.getvalue()


def test_serve_flag_validation():
    from llm_consensus_tpu.cli.serve import parse_serve_args

    with pytest.raises(CLIError, match="--max-batch"):
        parse_serve_args(["--models", "m1", "--max-batch", "0"])
    with pytest.raises(CLIError, match="--max-concurrency"):
        parse_serve_args(["--models", "m1", "--max-concurrency", "0"])
    with pytest.raises(CLIError, match="--queue-depth"):
        parse_serve_args(["--models", "m1", "--queue-depth", "-1"])
    cfg = parse_serve_args(["--models", "m1,m2", "--max-batch", "16"])
    assert cfg.models == ["m1", "m2"]
    assert cfg.max_batch == 16


def test_serve_max_batch_env_alias(monkeypatch):
    from llm_consensus_tpu.cli.serve import parse_serve_args

    monkeypatch.setenv("LLMC_MAX_BATCH", "12")
    cfg = parse_serve_args(["--models", "m1"])
    assert cfg.max_batch == 12
    # The flag wins over the env.
    cfg = parse_serve_args(["--models", "m1", "--max-batch", "3"])
    assert cfg.max_batch == 3


def test_serve_concurrency_validated_against_max_batch():
    from llm_consensus_tpu.cli.serve import parse_serve_args, resolve_concurrency

    # tpu panel: the cap derives from batcher slots / streams-per-run.
    cfg = parse_serve_args([
        "--models", "tpu:tiny-llama,tpu:tiny-gemma",
        "--judge", "tpu:tiny-mistral", "--max-batch", "8",
    ])
    assert resolve_concurrency(cfg) == 8  # 1 stream per preset per run

    # The same preset twice in the panel doubles its per-run streams.
    cfg = parse_serve_args([
        "--models", "tpu:tiny-llama,tpu:tiny-llama",
        "--judge", "tpu:tiny-gemma", "--max-batch", "8",
    ])
    assert resolve_concurrency(cfg) == 4

    # An explicit cap that oversubscribes the batcher fails at startup.
    cfg = parse_serve_args([
        "--models", "tpu:tiny-llama", "--judge", "tpu:tiny-gemma",
        "--max-batch", "4", "--max-concurrency", "8",
    ])
    with pytest.raises(CLIError, match="oversubscribes"):
        resolve_concurrency(cfg)

    # HTTP-only panels have no device budget to validate against.
    cfg = parse_serve_args([
        "--models", "m1,m2", "--judge", "j",
        "--max-batch", "1", "--max-concurrency", "32",
    ])
    assert resolve_concurrency(cfg) == 32


def test_tpu_provider_reads_llmc_max_batch(monkeypatch):
    from llm_consensus_tpu.providers.tpu import TPUProvider

    monkeypatch.setenv("LLMC_MAX_BATCH", "5")
    assert TPUProvider().max_batch == 5
    monkeypatch.delenv("LLMC_MAX_BATCH")
    monkeypatch.setenv("LLMC_BATCH_STREAMS", "7")
    assert TPUProvider().max_batch == 7
    assert TPUProvider(batch_streams=3).max_batch == 3
