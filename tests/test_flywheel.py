"""Flywheel tests: corpus scanner, live weight hot-swap, canary rollout.

Covers the three halves of the data flywheel (flywheel/):

  * corpus — ``run.json`` is the SOLE authority for what counts as a run
    (artifact dirs beside runs are skipped, never guessed at by name),
    corrupt payloads are counted and survived, dedup and the train/
    holdout split are deterministic across rescans, and the injected
    ``corpus_corrupt`` fault exercises the torn-journal path;
  * hot-swap — Engine.swap_weights is monotone (stale versions are
    rejected and counted), parks under pins and applies on the last
    unpin, and rollback restores the double-buffered previous params
    under a NEW version; a pinned stream's bytes are identical across a
    live swap (the acceptance bar for zero-impact checkpoint flips), and
    the ``swap_mid_stream`` / ``canary_regress`` injections fire at
    their sites;
  * canary — the router's canary lane splits the keyspace
    deterministically by LLMC_CANARY_FRACTION (reorder within health
    tiers, never exclusion), and the CanaryWatcher's p99-ratio streak
    drives an automatic rollback end-to-end.
"""

from __future__ import annotations

import json
import os
import time

import pytest

from llm_consensus_tpu import faults, obs
from llm_consensus_tpu.faults import FaultPlan
from llm_consensus_tpu.flywheel.canary import CanaryWatcher
from llm_consensus_tpu.flywheel.corpus import (
    ARTIFACTS_DIRNAME,
    build_corpus,
    scan_run_dirs,
)


@pytest.fixture(autouse=True)
def _clean_plan(monkeypatch):
    monkeypatch.delenv("LLMC_FAULTS", raising=False)
    faults.reset()
    obs.reset()
    yield
    faults.reset()
    obs.reset()


# ---------------------------------------------------------------------------
# corpus scanner


def _write_run(data_dir, run_id, *, consensus="the verdict text",
               prompt="what is consensus?", n_responses=2, result=True,
               torn=False, salt=""):
    run_dir = os.path.join(str(data_dir), run_id)
    os.makedirs(run_dir, exist_ok=True)
    with open(os.path.join(run_dir, "run.json"), "w", encoding="utf-8") as f:
        json.dump({"run_id": run_id}, f)
    if not result:
        return run_dir
    path = os.path.join(run_dir, "result.json")
    if torn:
        with open(path, "w", encoding="utf-8") as f:
            f.write('{"consensus": "half a jso')  # torn mid-write
        return run_dir
    doc = {
        "prompt": prompt + salt,
        "consensus": consensus,
        "responses": [
            {"model": f"m{i}", "content": f"answer {i}{salt}",
             "provider": "fake"}
            for i in range(n_responses)
        ],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    return run_dir


def test_manifest_is_sole_authority(tmp_path):
    """Dirs without run.json — the artifacts namespace, profiler dumps,
    anything foreign — are skipped and counted, never parsed as runs."""
    _write_run(tmp_path, "r01", salt="1")
    _write_run(tmp_path, "r02", salt="2")
    for pollution in (ARTIFACTS_DIRNAME, "blackbox", "roofline-20260807"):
        os.makedirs(tmp_path / pollution / "nested", exist_ok=True)
        (tmp_path / pollution / "dump.bin").write_bytes(b"\x00\x01")
    (tmp_path / "stray-file.json").write_text("{}")  # files never scanned
    runs, skipped = scan_run_dirs(str(tmp_path))
    assert [r[0] for r in runs] == ["r01", "r02"]
    assert skipped == 3
    corpus = build_corpus(str(tmp_path), holdout=0.0)
    assert corpus.runs_scanned == 2 and corpus.runs_skipped == 3
    assert len(corpus.train) == 2 and corpus.runs_corrupt == 0


def test_corrupt_result_counted_never_fatal(tmp_path):
    _write_run(tmp_path, "r01", salt="ok")
    _write_run(tmp_path, "r02", torn=True)
    corpus = build_corpus(str(tmp_path), holdout=0.0)
    assert corpus.runs_corrupt == 1
    assert len(corpus.train) == 1  # the healthy run still contributes


def test_thin_runs_contribute_nothing(tmp_path):
    _write_run(tmp_path, "r01", result=False)  # in-flight: manifest only
    _write_run(tmp_path, "r02", n_responses=1)  # no judge ran (go parity)
    _write_run(tmp_path, "r03", consensus="")  # empty verdict
    corpus = build_corpus(str(tmp_path), holdout=0.0)
    assert corpus.runs_scanned == 3 and corpus.runs_corrupt == 0
    assert len(corpus.train) == 0 and len(corpus.holdout) == 0


def test_dedup_and_stable_split(tmp_path):
    """Identical pairs dedup to one example; the split side of every
    example and the corpus hash are reproducible across rescans, and an
    example keeps its side as unrelated runs accumulate."""
    for i in range(24):
        _write_run(tmp_path, f"r{i:02d}", salt=str(i))
    _write_run(tmp_path, "r90", salt="0")  # re-served: same content as r00
    corpus = build_corpus(str(tmp_path), holdout=0.25)
    assert corpus.deduped == 1
    assert len(corpus.train) + len(corpus.holdout) == 24
    assert len(corpus.holdout) > 0  # 24 draws at 0.25: starvation ≈ 0.1%
    again = build_corpus(str(tmp_path), holdout=0.25)
    assert again.corpus_hash == corpus.corpus_hash
    sides = {ex.key: "h" for ex in corpus.holdout}
    sides.update({ex.key: "t" for ex in corpus.train})
    for i in range(8):
        _write_run(tmp_path, f"s{i:02d}", salt=f"new-{i}")
    grown = build_corpus(str(tmp_path), holdout=0.25)
    assert grown.corpus_hash != corpus.corpus_hash
    for ex in grown.holdout:
        assert sides.get(ex.key, "h") == "h"  # no holdout→train leaks
    for ex in grown.train:
        assert sides.get(ex.key, "t") == "t"


def test_corpus_corrupt_injection(tmp_path):
    """The injected ``corpus_corrupt`` fault torches one manifested run
    mid-scan — the build counts it and keeps going (torn-journal
    survival without having to tear real bytes)."""
    _write_run(tmp_path, "r01", salt="1")
    _write_run(tmp_path, "r02", salt="2")
    _write_run(tmp_path, "r03", salt="3")
    faults.install(FaultPlan("corpus_corrupt@run=r02"))
    corpus = build_corpus(str(tmp_path), holdout=0.0)
    assert corpus.runs_corrupt == 1
    assert len(corpus.train) == 2
    assert {ex.run_id for ex in corpus.train} == {"r01", "r03"}


# ---------------------------------------------------------------------------
# hot-swap: Engine.swap_weights semantics on a swap-only stub

# The stub (analysis/protocols.py idiom) runs the REAL pin/swap/rollback
# methods with exactly the state the hot-swap section owns — no model, no
# mesh, so these stay fast and order-independent.


def _stub_engine():
    from llm_consensus_tpu.analysis import sanitizer
    from llm_consensus_tpu.engine.engine import Engine

    class _Cfg:
        name = "stub"

    eng = Engine.__new__(Engine)
    eng.cfg = _Cfg()
    eng._faults = None
    eng._shard_fn = None
    eng.quant = None
    eng._kv_pool = None
    eng.params = "A"
    eng._prefix_lock = sanitizer.make_lock("engine.prefix")
    eng._prefix_ids = None
    eng._prefix_cache = None
    eng._swap_lock = sanitizer.make_lock("engine.swap")
    eng._swap_cv = sanitizer.make_condition("engine.swap", eng._swap_lock)
    eng.weight_version = 0
    eng.weight_meta = {}
    eng._pins = 0
    eng._pending_swap = None
    eng._prev_weights = None
    eng._swap_requested = 0.0
    eng._swap_stats = {
        "swaps": 0, "swap_rejects": 0, "swap_queued": 0,
        "rollbacks": 0, "last_vacate_ms": 0.0, "last_prep_ms": 0.0,
    }
    return eng


def test_swap_versions_are_monotone():
    eng = _stub_engine()
    assert eng.swap_weights(0, "B") is False  # not newer than resident
    assert eng.swap_weights(3, "B") is True
    assert eng.weight_version == 3 and eng.params == "B"
    assert eng.swap_weights(3, "C") is False  # replays never double-apply
    assert eng.swap_weights(2, "C") is False
    stats = eng.swap_stats()
    assert stats["swaps"] == 1 and stats["swap_rejects"] == 3


def test_swap_parks_under_pin_applies_on_last_unpin():
    eng = _stub_engine()
    assert eng.pin_weights() == 0
    eng.pin_weights()  # refcount composes: generate + per-stream pins
    assert eng.swap_weights(1, "B", meta={"corpus": "abc"}) is True
    assert eng.weight_version == 0 and eng.params == "A"  # parked
    assert eng.swap_pending()
    eng.unpin_weights()
    assert eng.weight_version == 0  # one pin still resident
    eng.unpin_weights()  # LAST unpin applies the parked pair
    assert eng.weight_version == 1 and eng.params == "B"
    assert not eng.swap_pending()
    assert eng.weight_meta == {"corpus": "abc"}
    assert eng.swap_stats()["swap_queued"] == 1


def test_rollback_restores_previous_buffer_under_new_version():
    eng = _stub_engine()
    resident = eng.params
    assert eng.swap_weights(1, "B") is True
    rb = eng.rollback_weights({"reason": "canary"})
    assert rb == 2  # versions stay monotone: no number ever reappears
    assert eng.weight_version == 2 and eng.params is resident
    assert eng.weight_meta["rolled_back_to"] == 0
    assert eng.weight_meta["rolled_back_from"] == 1
    assert eng.weight_meta["reason"] == "canary"
    assert eng.swap_stats()["rollbacks"] == 1


def test_rollback_without_history_is_none():
    eng = _stub_engine()
    assert eng.rollback_weights() is None


def test_swap_mid_stream_injection_fires_at_apply():
    """The ``swap_mid_stream`` fault holds the apply so live streams are
    mid-decode when it lands (FC coverage for the swap site)."""
    eng = _stub_engine()
    plan = FaultPlan("swap_mid_stream@s=0.01@times=-1")
    eng._faults = plan
    t0 = time.monotonic()
    assert eng.swap_weights(1, "B") is True
    assert time.monotonic() - t0 >= 0.01
    assert eng.weight_version == 1
    assert any(t.endswith("->swap_mid_stream") for t in plan.trace)


# ---------------------------------------------------------------------------
# hot-swap: a REAL pinned stream's bytes across a live swap


@pytest.fixture(scope="module")
def swap_engine():
    import jax.numpy as jnp

    from llm_consensus_tpu.engine import Engine
    from llm_consensus_tpu.models import get_config

    cfg = get_config("tiny-llama")
    return Engine(cfg, dtype=jnp.float32, max_seq=128, seed=0)


def test_pinned_stream_bytes_identical_across_swap(swap_engine):
    """The flywheel acceptance bar: a stream admitted before the swap
    decodes to the LAST byte on the weights it started with — the swap
    parks in the double buffer and flips only when the pins drain."""
    import jax

    from llm_consensus_tpu.engine import ContinuousBatcher, SamplingParams
    from llm_consensus_tpu.models import get_config, init_params

    eng = swap_engine
    sp = SamplingParams(max_new_tokens=48, ignore_eos=True)
    prompt = "the judge weighs every panel answer before the verdict"
    ref = eng.generate(prompt, sp)
    base = eng.weight_version
    b = ContinuousBatcher(eng, max_batch=2)
    try:
        fut = b.submit(prompt, sp)
        deadline = time.time() + 120
        while time.time() < deadline and eng.swap_stats()["pins"] == 0:
            time.sleep(0.005)
        assert eng.swap_stats()["pins"] > 0, "stream never pinned"
        import jax.numpy as jnp

        fresh = init_params(
            get_config("tiny-llama"), jax.random.PRNGKey(3), dtype=jnp.float32
        )
        assert eng.swap_weights(base + 1, fresh) is True
        r = fut.result(timeout=600)
        assert r.token_ids == ref.token_ids
        assert r.text == ref.text
        deadline = time.time() + 120
        while time.time() < deadline and eng.weight_version <= base:
            time.sleep(0.005)
        assert eng.weight_version == base + 1  # applied once pins drained
    finally:
        b.close()


def test_canary_regress_injection_fires_on_swapped_decode(swap_engine):
    """``canary_regress`` slows decode ONLY after a swap landed (the
    regression a bad checkpoint would cause, without needing one)."""
    import jax

    from llm_consensus_tpu.engine import ContinuousBatcher, SamplingParams
    from llm_consensus_tpu.models import get_config, init_params
    import jax.numpy as jnp

    eng = swap_engine
    fresh = init_params(
        get_config("tiny-llama"), jax.random.PRNGKey(5), dtype=jnp.float32
    )
    assert eng.swap_weights(eng.weight_version + 1, fresh) is True
    plan = FaultPlan("canary_regress@s=0@times=-1")
    eng._faults = plan
    b = ContinuousBatcher(eng, max_batch=2)
    try:
        sp = SamplingParams(max_new_tokens=4, ignore_eos=True)
        b.submit("probe", sp).result(timeout=600)
        assert any(t.endswith("->canary_regress") for t in plan.trace)
    finally:
        eng._faults = None
        b.close()


# ---------------------------------------------------------------------------
# canary watcher → automatic rollback


def _feed(w, base_s, canary_s, n=10):
    for _ in range(n):
        w.record(0, base_s)
        w.record(1, canary_s)


def test_watcher_requires_consecutive_regressed_windows():
    w = CanaryWatcher(tol=1.5, windows=2, min_samples=5)
    _feed(w, 0.010, 0.050)
    assert w.tick() is False  # streak 1 of 2
    _feed(w, 0.010, 0.011)  # recovered: streak resets
    assert w.tick() is False
    _feed(w, 0.010, 0.050)
    assert w.tick() is False
    _feed(w, 0.010, 0.050)
    assert w.tick() is True  # 2 consecutive ⇒ fire
    assert w.stats()["regressions"] == 1


def test_watcher_ignores_starved_and_uniform_windows():
    w = CanaryWatcher(tol=1.5, windows=2, min_samples=5)
    _feed(w, 0.010, 0.050)
    assert w.tick() is False  # streak 1
    for _ in range(20):
        w.record(0, 0.010)  # canary lull: uniform traffic
    assert w.tick() is False
    _feed(w, 0.010, 0.050)
    assert w.tick() is True  # uniform window did NOT erase the streak
    _feed(w, 0.010, 0.050)
    assert w.tick() is False  # re-armed after firing
    _feed(w, 0.010, 0.050, n=2)  # starved: below min_samples
    assert w.tick() is False
    assert w.stats()["streak"] == 0  # anecdotes reset the streak


def test_canary_regress_triggers_auto_rollback_end_to_end():
    """Watcher verdict ⇒ rollback hook ⇒ engine back on baseline params
    under a new version — zero manual intervention, the flywheel's
    failure mode is 'a few slow canary windows', never an incident."""
    eng = _stub_engine()
    resident = eng.params
    assert eng.swap_weights(1, "B-regressed") is True

    fired = []

    def on_regress(info):
        fired.append(info)
        eng.rollback_weights({"reason": "canary_regress", **info})

    w = CanaryWatcher(tol=1.5, windows=2, min_samples=5,
                      on_regress=on_regress)
    for _ in range(3):
        _feed(w, 0.010, 0.080)
        if w.tick():
            break
    assert len(fired) == 1
    assert fired[0]["canary_version"] == 1
    assert fired[0]["ratio"] > 1.5
    assert eng.params is resident  # baseline buffer restored ...
    assert eng.weight_version == 2  # ... under a NEW monotone version
    assert eng.weight_meta["rolled_back_to"] == 0
    assert eng.weight_meta["reason"] == "canary_regress"


# ---------------------------------------------------------------------------
# router canary lane


def test_router_canary_lane_splits_keyspace(monkeypatch):
    from llm_consensus_tpu.serve.fleet import FleetState
    from llm_consensus_tpu.serve.router import ConsensusRouter

    monkeypatch.setenv("LLMC_CANARY_FRACTION", "0.3")
    fleet = FleetState()
    urls = [f"http://127.0.0.1:91{i:02d}" for i in range(4)]
    new = set(urls[2:])  # two replicas already swapped to version 1
    for i, u in enumerate(urls):
        fleet.heartbeat(u, load_score=0.0, weight_version=1 if u in new else 0)
    router = ConsensusRouter(fleet)
    canary_hits = 0
    for k in range(200):
        key = f"prompt-{k}"
        order = router.candidates(key)
        assert sorted(order) == sorted(urls)  # reorder, never exclusion
        head = {order[0], order[1]}
        assert head in (new, set(urls[:2]))  # whole cohort leads the lane
        if head == new:
            canary_hits += 1
        assert order == router.candidates(key)  # deterministic per key
    assert 0.15 < canary_hits / 200.0 < 0.45  # ≈ LLMC_CANARY_FRACTION
    assert router.counters["canary_requests"] > 0
    snap = fleet.snapshot()
    assert snap["by_weight_version"] == {"0": 2, "1": 2}


def test_router_canary_lane_inert_on_uniform_fleet(monkeypatch):
    from llm_consensus_tpu.serve.fleet import FleetState
    from llm_consensus_tpu.serve.router import ConsensusRouter

    monkeypatch.setenv("LLMC_CANARY_FRACTION", "0.5")
    fleet = FleetState()
    urls = [f"http://127.0.0.1:92{i:02d}" for i in range(3)]
    for u in urls:
        fleet.heartbeat(u, load_score=0.0, weight_version=7)
    router = ConsensusRouter(fleet)
    for k in range(32):
        assert sorted(router.candidates(f"k{k}")) == sorted(urls)
    assert router.counters["canary_requests"] == 0


def test_fleet_heartbeat_version_change_is_a_transition():
    from llm_consensus_tpu.serve.fleet import FleetState

    fleet = FleetState()
    replica = fleet.heartbeat("http://127.0.0.1:9300", weight_version=0)
    assert replica.weight_version == 0
    fleet.heartbeat("http://127.0.0.1:9300", weight_version=2)
    assert replica.weight_version == 2
    snap = fleet.snapshot()
    assert snap["by_weight_version"] == {"2": 1}
