"""tpu provider tests: config[1] behavior — on-device model through the
unchanged runner/judge/CLI path, on the CPU backend with tiny models."""

import io
import json

import jax
import pytest

from llm_consensus_tpu.cli.main import create_provider, main
from llm_consensus_tpu.providers import Request
from llm_consensus_tpu.providers.tpu import TPUProvider, parse_model_name
from llm_consensus_tpu.utils import Context


@pytest.fixture(scope="module")
def provider():
    return TPUProvider(stream_interval=2)


def test_parse_model_name():
    assert parse_model_name("tpu:tiny-llama") == "tiny-llama"
    with pytest.raises(ValueError, match="available"):
        parse_model_name("tpu:not-a-model")


def test_query_stream_real_tokens(provider):
    chunks = []
    resp = provider.query_stream(
        Context.background(),
        Request(model="tpu:tiny-llama", prompt="hello", max_tokens=12),
        chunks.append,
    )
    assert resp.provider == "tpu"
    assert resp.model == "tpu:tiny-llama"
    assert resp.content == "".join(chunks)
    assert resp.latency_ms > 0


def test_query_deterministic_greedy(provider):
    req = Request(model="tpu:tiny-llama", prompt="abc", max_tokens=10)
    a = provider.query(Context.background(), req)
    b = provider.query(Context.background(), req)
    assert a.content == b.content


def test_engine_shared_across_calls(provider):
    provider.query(Context.background(), Request("tpu:tiny-llama", "x", max_tokens=2))
    e1 = provider._engines["tiny-llama"]
    provider.query(Context.background(), Request("tpu:tiny-llama", "y", max_tokens=2))
    assert provider._engines["tiny-llama"] is e1


def test_deadline_raises_failed_model(provider):
    import time

    ctx = Context.background().with_timeout(0.0001)
    time.sleep(0.01)
    with pytest.raises(Exception, match="deadline"):
        provider.query(ctx, Request(model="tpu:tiny-llama", prompt="q", max_tokens=50))


def test_full_cli_run_with_tpu_models(tmp_path):
    """config[1]-shaped run: tpu panel + tpu judge through the real CLI."""
    stdout, stderr = io.StringIO(), io.StringIO()
    code = main(
        [
            "--models", "tpu:tiny-llama,tpu:tiny-qwen2",
            "--judge", "tpu:tiny-llama",
            "--json",
            "--max-tokens", "32",
            "what is the answer?",
        ],
        stdin=io.StringIO(""),
        stdout=stdout,
        stderr=stderr,
        install_signal_handlers=False,
    )
    assert code == 0, stderr.getvalue()
    d = json.loads(stdout.getvalue())
    assert {r["model"] for r in d["responses"]} == {"tpu:tiny-llama", "tpu:tiny-qwen2"}
    assert all(r["provider"] == "tpu" for r in d["responses"])
    assert d["judge"] == "tpu:tiny-llama"
    assert isinstance(d["consensus"], str)


def test_create_provider_routes_tpu_scheme():
    p = create_provider("tpu:tiny-llama")
    assert isinstance(p, TPUProvider)


def test_engine_crash_is_contained_as_warning(monkeypatch):
    """Failure isolation (SURVEY §5): an engine blowing up on-device (XLA
    OOM, compile failure, ...) must become a warning + failed model while
    panel siblings keep decoding — reference best-effort semantics
    (runner.go:100-107) applied to the TPU path."""
    from llm_consensus_tpu.providers.registry import Registry
    from llm_consensus_tpu.providers.tpu import TPUProvider
    from llm_consensus_tpu.runner import Runner
    from llm_consensus_tpu.utils.context import Context

    provider = TPUProvider(ignore_eos=True, stream_interval=4)
    panel = ["tpu:tiny-llama", "tpu:tiny-mistral"]
    provider.prepare(panel, None)

    real_engine_for = provider._engine_for

    class Boom:
        def generate(self, *a, **k):
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory on slice")

    def engine_for(model):
        if model == "tpu:tiny-mistral":
            return Boom()
        return real_engine_for(model)

    monkeypatch.setattr(provider, "_engine_for", engine_for)

    registry = Registry()
    for m in panel:
        registry.register(m, provider)
    result = Runner(registry, timeout=300.0, max_tokens=6).run(
        Context.background(), panel, "isolation probe"
    )
    assert [r.model for r in result.responses] == ["tpu:tiny-llama"]
    assert result.failed_models == ["tpu:tiny-mistral"]
    assert any("RESOURCE_EXHAUSTED" in w for w in result.warnings)


def test_transient_engine_failure_recovers_with_fresh_engine(monkeypatch):
    """Elastic recovery: a transient on-device blowup rebuilds the engine
    once and the query succeeds; a second failure (or any failure after
    streaming began) surfaces as the model's failure."""
    from llm_consensus_tpu.providers.tpu import TPUProvider
    from llm_consensus_tpu.providers.base import Request
    from llm_consensus_tpu.utils.context import Context

    provider = TPUProvider(ignore_eos=True, stream_interval=4)
    provider.prepare(["tpu:tiny-llama"], None)
    real = provider._engine_for("tpu:tiny-llama")

    class Flaky:
        mesh = real.mesh

        def generate(self, *a, **k):
            raise RuntimeError("RESOURCE_EXHAUSTED: transient")

    flaky = Flaky()
    provider._engines["tiny-llama"] = flaky
    req = Request(model="tpu:tiny-llama", prompt="recover", max_tokens=4)
    resp = provider.query(Context.background(), req)
    assert resp.tokens == 4  # rebuilt engine served the query
    assert provider._engines["tiny-llama"] is not flaky

    # Failure after streaming began must NOT retry (text already shown).
    class StreamThenDie:
        mesh = real.mesh

        def generate(self, prompt, sampling, ctx, on_text=None):
            if on_text is not None:
                on_text("partial ")
            raise RuntimeError("died mid-stream")

    provider._engines["tiny-llama"] = StreamThenDie()
    chunks = []
    with pytest.raises(RuntimeError, match="died mid-stream"):
        provider.query_stream(Context.background(), req, chunks.append)
    assert chunks == ["partial "]


def test_engine_failure_retries_exactly_once(monkeypatch):
    """The retry cap is ONE: when the rebuilt engine also fails, the
    second error propagates after exactly two generate calls."""
    from llm_consensus_tpu.providers.tpu import TPUProvider
    from llm_consensus_tpu.providers.base import Request
    from llm_consensus_tpu.utils.context import Context

    provider = TPUProvider(ignore_eos=True, stream_interval=4)
    provider.prepare(["tpu:tiny-llama"], None)
    calls = {"n": 0}

    class AlwaysDies:
        mesh = None

        def generate(self, *a, **k):
            calls["n"] += 1
            raise RuntimeError(f"persistent failure #{calls['n']}")

    provider._engines["tiny-llama"] = AlwaysDies()
    monkeypatch.setattr(
        provider, "_build_engine", lambda preset, mesh=None: AlwaysDies()
    )
    req = Request(model="tpu:tiny-llama", prompt="q", max_tokens=4)
    with pytest.raises(RuntimeError, match="persistent failure #2"):
        provider.query(Context.background(), req)
    assert calls["n"] == 2


# -- stream batching (batch_streams > 1 routes through ContinuousBatcher) ---


def test_batch_streams_concurrent_requests_exact():
    """Two concurrent requests for the SAME model share a batcher and
    produce exactly what the direct path produces."""
    import threading

    from llm_consensus_tpu.providers.tpu import TPUProvider

    direct = TPUProvider(ignore_eos=True, stream_interval=4)
    batched = TPUProvider(ignore_eos=True, stream_interval=4, batch_streams=4)
    reqs = [
        Request(model="tpu:tiny-llama", prompt=f"concurrent stream {i}",
                max_tokens=8)
        for i in range(3)
    ]
    want = [direct.query(Context.background(), r).content for r in reqs]
    got = [None] * len(reqs)

    def run(i):
        got[i] = batched.query(Context.background(), reqs[i]).content

    threads = [threading.Thread(target=run, args=(i,)) for i in range(len(reqs))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert got == want
    # The batcher was actually engaged (and is reused across requests).
    assert "tiny-llama" in batched._batchers


def test_batch_streams_streaming_callbacks():
    from llm_consensus_tpu.providers.tpu import TPUProvider

    provider = TPUProvider(ignore_eos=True, stream_interval=4, batch_streams=2)
    chunks = []
    resp = provider.query_stream(
        Context.background(),
        Request(model="tpu:tiny-llama", prompt="stream batching text", max_tokens=6),
        chunks.append,
    )
    assert "".join(chunks) == resp.content


def test_batch_streams_eviction_closes_batcher():
    """A re-plan that drops a model's engine also closes its batcher (the
    scheduler thread must not keep a stale engine's cache alive)."""
    from llm_consensus_tpu.providers.tpu import TPUProvider

    provider = TPUProvider(ignore_eos=True, stream_interval=4, batch_streams=2)
    # No prepare: unsharded engine -> the query creates a live batcher.
    provider.query(
        Context.background(),
        Request(model="tpu:tiny-llama", prompt="warm", max_tokens=4),
    )
    assert "tiny-llama" in provider._batchers
    batcher = provider._batchers["tiny-llama"][1]
    # Re-plan without tiny-llama: engine + batcher evicted and closed.
    provider.prepare(["tpu:tiny-mistral"], None)
    assert "tiny-llama" not in provider._batchers
    assert batcher._closed
    assert not batcher._thread.is_alive()


def test_batch_streams_engaged_on_single_device_mesh():
    """A planned single-device placement must still batch: the mesh is
    pure placement, and round 1's `mesh is not None` gate silently ran
    "batched" streams as contending single-stream generates."""
    from llm_consensus_tpu.providers.tpu import TPUProvider

    provider = TPUProvider(ignore_eos=True, stream_interval=4, batch_streams=2)
    # Pin to one device so the placement is single-device even on the
    # 8-virtual-device test mesh (otherwise this test would silently skip
    # the very gate it exists to cover).
    provider.prepare(["tpu:tiny-llama"], None, devices=jax.devices()[:1])
    mesh = provider.placement("tpu:tiny-llama")
    assert mesh is not None and mesh.devices.size == 1
    provider.query(
        Context.background(),
        Request(model="tpu:tiny-llama", prompt="placed batch", max_tokens=4),
    )
    assert "tiny-llama" in provider._batchers


def test_release_frees_engines_and_batchers():
    """release() drops engines/batchers/placements and closes scheduler
    threads; the provider stays usable (lazy rebuild on next query)."""
    from llm_consensus_tpu.providers.tpu import TPUProvider

    provider = TPUProvider(ignore_eos=True, stream_interval=4, batch_streams=2)
    # No prepare: unsharded engine, so the query builds a live batcher.
    first = provider.query(
        Context.background(),
        Request(model="tpu:tiny-llama", prompt="before release", max_tokens=4),
    )
    batcher = provider._batchers["tiny-llama"][1]
    provider.release()
    assert not provider._engines and not provider._batchers and not provider._meshes
    assert batcher._closed
    again = provider.query(
        Context.background(),
        Request(model="tpu:tiny-llama", prompt="before release", max_tokens=4),
    )
    assert again.content == first.content


def test_elastic_replacement_moves_model_off_dead_slice(monkeypatch):
    """A slice that fails twice (original engine + same-mesh rebuild) gets
    re-placed on healthy chips and the request succeeds — the device-level
    analog of runner.go:100-107's failure isolation."""
    provider = TPUProvider(ignore_eos=True, stream_interval=4)
    panel = ["tpu:tiny-llama", "tpu:tiny-mistral"]
    provider.prepare(panel, None)
    bad = {d.id for d in provider.placement("tpu:tiny-llama").devices.flat}
    healthy = {d.id for d in provider.placement("tpu:tiny-mistral").devices.flat}
    assert not bad & healthy  # disjoint slices, as planned

    orig_build = provider._build_engine

    def build(preset, mesh=None):
        eng = orig_build(preset, mesh)
        if mesh is not None and {d.id for d in mesh.devices.flat} & bad:
            def boom(*a, **k):
                raise RuntimeError("DATA_LOSS: slice wedged")

            eng.generate = boom
        return eng

    monkeypatch.setattr(provider, "_build_engine", build)

    import warnings

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        resp = provider.query(
            Context.background(),
            Request(model="tpu:tiny-llama", prompt="elastic probe", max_tokens=6),
        )
    assert resp.content
    moved = {d.id for d in provider.placement("tpu:tiny-llama").devices.flat}
    assert not moved & bad, f"still on dead devices: {moved}"
    assert any("re-placing tiny-llama" in str(w.message) for w in caught)

    # The healthy sibling's placement is untouched.
    assert {
        d.id for d in provider.placement("tpu:tiny-mistral").devices.flat
    } == healthy

    # The dead slice is remembered: a later re-plan routes around it
    # instead of handing the model back its wedged chips.
    provider.prepare(panel, None)
    replanned = {d.id for d in provider.placement("tpu:tiny-llama").devices.flat}
    assert not replanned & bad, f"re-plan returned to dead devices: {replanned}"


def test_elastic_replacement_covers_build_failures(monkeypatch):
    """The rebuild itself dying on the dead slice (param allocation on a
    wedged chip) must also trigger re-placement, not propagate."""
    provider = TPUProvider(ignore_eos=True, stream_interval=4)
    panel = ["tpu:tiny-llama", "tpu:tiny-mistral"]
    provider.prepare(panel, None)
    bad = {d.id for d in provider.placement("tpu:tiny-llama").devices.flat}

    orig_build = provider._build_engine

    def build(preset, mesh=None):
        if mesh is not None and {d.id for d in mesh.devices.flat} & bad:
            raise RuntimeError("DATA_LOSS: allocation failed on dead chip")
        return orig_build(preset, mesh)

    # Seed a cached engine that fails at generate so the retry path runs;
    # its rebuild then dies in _build_engine on the same dead slice.
    first = orig_build("tiny-llama", provider.placement("tpu:tiny-llama"))

    def boom(*a, **k):
        raise RuntimeError("DATA_LOSS: slice wedged")

    first.generate = boom
    provider._engines["tiny-llama"] = first
    monkeypatch.setattr(provider, "_build_engine", build)

    resp = provider.query(
        Context.background(),
        Request(model="tpu:tiny-llama", prompt="elastic build probe", max_tokens=6),
    )
    assert resp.content
    moved = {d.id for d in provider.placement("tpu:tiny-llama").devices.flat}
    assert not moved & bad


def test_provider_max_seq_caps_engine_capacity(monkeypatch):
    """max_seq (arg or LLMC_MAX_SEQ) caps every engine's context window
    below the preset's full size — KV HBM is proportional to capacity."""
    provider = TPUProvider(ignore_eos=True, stream_interval=4, max_seq=128)
    provider.query(
        Context.background(),
        Request(model="tpu:tiny-llama", prompt="capped", max_tokens=4),
    )
    assert provider._engines["tiny-llama"].max_seq == 128

    monkeypatch.setenv("LLMC_MAX_SEQ", "256")
    via_env = TPUProvider(ignore_eos=True, stream_interval=4)
    via_env.query(
        Context.background(),
        Request(model="tpu:tiny-llama", prompt="capped", max_tokens=4),
    )
    assert via_env._engines["tiny-llama"].max_seq == 256


def test_draft_plus_batching_warns_and_batches():
    """MODEL-drafted speculation and stream batching are mutually
    exclusive: a provider configured with both warns ONCE and routes
    through the batcher — a drafted request must never silently bypass
    stream batching (round-2 VERDICT #4). Buffer drafters (`lookup`)
    compose instead: the pool itself runs batched spec rounds."""
    import warnings

    from llm_consensus_tpu.providers.base import Request
    from llm_consensus_tpu.providers.tpu import TPUProvider
    from llm_consensus_tpu.utils.context import Context

    provider = TPUProvider(
        ignore_eos=True, stream_interval=4, batch_streams=2,
        draft="tiny-llama",
    )
    try:
        req = Request(model="tpu:tiny-mistral", prompt="spec vs batch",
                      max_tokens=4)
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            provider.query(Context.background(), req)
            provider.query(Context.background(), req)
        msgs = [
            str(c.message) for c in caught
            if "model draft" in str(c.message)
            and "ignored" in str(c.message)
        ]
        assert len(msgs) == 1, msgs  # warned exactly once
        assert "tiny-mistral" in provider._batchers, "request bypassed batching"
        assert not provider._specs, "draft engine built despite batching"
        # Model drafts never put the pool in spec mode.
        assert provider._batchers["tiny-mistral"][1]._spec is None
    finally:
        provider.release()


def test_lookup_draft_composes_with_batching():
    """`--draft lookup` + batch_streams>1: the pool runs batched spec
    rounds (no warning, no bypass) and greedy output matches the plain
    batched provider byte for byte."""
    import warnings

    from llm_consensus_tpu.providers.base import Request
    from llm_consensus_tpu.providers.tpu import TPUProvider
    from llm_consensus_tpu.utils.context import Context

    req = Request(model="tpu:tiny-llama", prompt="lookup composes",
                  max_tokens=8)
    plain = TPUProvider(ignore_eos=True, stream_interval=4,
                        batch_streams=2)
    spec = TPUProvider(ignore_eos=True, stream_interval=4,
                       batch_streams=2, draft="lookup")
    try:
        want = plain.query(Context.background(), req)
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            got = spec.query(Context.background(), req)
        assert got.content == want.content
        assert not [
            c for c in caught if "ignored" in str(c.message)
        ], [str(c.message) for c in caught]
        entry = spec._batchers.get("tiny-llama")
        assert entry is not None and entry[1]._spec is not None
        assert entry[1].spec_snapshot()["rounds"] > 0
        stats = spec.spec_stats()
        assert stats and stats["tiny-llama"]["rounds"] > 0
    finally:
        plain.release()
        spec.release()
