"""Elastic fleet tests (serve/elastic.py): lifecycle, hysteresis, migration.

Covers the three halves of the elastic tier:

  * **lifecycle** — the joining → serving → draining → retiring state
    machine on real gateways: illegal transitions raise, a ``joining``
    replica advertises full load and refuses migrations, ``/healthz``
    carries the state, and the router never places onto a non-serving
    replica;
  * **scale hysteresis** — the ElasticController's two-sided patience:
    sustained evidence scales, mid-band samples reset both streaks,
    min/max clamp, a refused hook retries instead of booking, and the
    ``replica_flap`` fault (plus a plain oscillating signal) never flaps
    the pool size;
  * **live migration** — a retiring gateway ships a resident mid-flight
    SSE stream to a destination over ``POST /v1/migrate``; the router's
    failover + StreamLedger splice the seam so the client's stream is
    byte-identical to an undisturbed run. The ``migrate_stall`` fault
    degrades migration to drain-and-wait (the stream finishes locally),
    never a drop.
"""

from __future__ import annotations

import http.client
import json
import os
import threading
import time

import pytest

from llm_consensus_tpu import faults, obs, serve
from llm_consensus_tpu.faults import FaultPlan
from llm_consensus_tpu.providers.base import Provider, Request, Response
from llm_consensus_tpu.providers.registry import Registry
from llm_consensus_tpu.serve.elastic import (
    DRAINING,
    JOINING,
    RETIRING,
    SERVING,
    ElasticController,
    MigrationRecord,
    MigrationTable,
    can_transition,
    placeable,
)
from llm_consensus_tpu.serve.fleet import ring_order
from llm_consensus_tpu.utils.context import Context

pytestmark = pytest.mark.faults

PANEL = ["alpha", "beta"]
JUDGE = "gamma"
CHUNK = 6   # characters per streamed chunk
HOLD = 2    # chunks each panel stream emits BEFORE blocking on the gate


@pytest.fixture(autouse=True)
def _clean_plan(monkeypatch):
    monkeypatch.delenv("LLMC_FAULTS", raising=False)
    faults.reset()
    obs.reset()
    yield
    faults.reset()
    obs.reset()


def expected_content(model: str, prompt: str) -> str:
    return f"{model} answers {prompt} at some length for chunking"


class MidStreamProvider(Provider):
    """Deterministic streaming fake that can freeze panel streams
    MID-flight: each panel query emits ``HOLD`` chunks, releases
    ``arrivals``, then blocks on ``gate`` before emitting the rest — so
    a migration fired at the gate point must splice a non-empty
    already-delivered prefix."""

    def __init__(self, gate: "threading.Event | None" = None,
                 arrivals: "threading.Semaphore | None" = None):
        self._lock = threading.Lock()
        self.calls: list[tuple[str, str]] = []
        self._gate = gate
        self._arrivals = arrivals

    def query(self, ctx: Context, req: Request) -> Response:
        return self.query_stream(ctx, req, None)

    def query_stream(self, ctx, req, callback):
        with self._lock:
            self.calls.append((req.model, req.prompt))
        content = expected_content(req.model, req.prompt[:16])
        chunks = [content[i:i + CHUNK] for i in range(0, len(content), CHUNK)]
        gated = req.model in PANEL and self._gate is not None
        for i, chunk in enumerate(chunks):
            if gated and i == HOLD:
                if self._arrivals is not None:
                    self._arrivals.release()
                assert self._gate.wait(30.0), "test gate never released"
                ctx.raise_if_done()
            if callback is not None:
                callback(chunk)
        ctx.raise_if_done()
        return Response(model=req.model, content=content, provider="fake")


def make_replica(tmp_path, provider, name: str, **kw):
    registry = Registry()
    for m in PANEL + [JUDGE]:
        registry.register(m, provider)
    kw.setdefault("timeout", 30.0)
    kw.setdefault("max_concurrency", 4)
    kw.setdefault("cache_size", 0)  # migration re-executes, never replays
    gw = serve.build_gateway(
        registry, list(PANEL), JUDGE,
        data_dir=os.path.join(str(tmp_path), "data", name), **kw,
    )
    gw.start()
    return gw


def gw_url(gw) -> str:
    host, port = gw.address
    return f"http://{host}:{port}"


def make_router(replicas, **kw):
    kw.setdefault("poll_s", 60.0)  # tests drive polls explicitly
    router = serve.build_router([gw_url(g) for g in replicas], **kw)
    router.start()
    return router


def post(port: int, body: dict, path: str = "/v1/consensus", timeout=60):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request(
            "POST", path, json.dumps(body),
            {"Content-Type": "application/json"},
        )
        r = conn.getresponse()
        headers = dict(r.getheaders())
        data = r.read()
    finally:
        conn.close()
    return r.status, headers, data


def get(port: int, path: str):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        data = r.read()
    finally:
        conn.close()
    return r.status, json.loads(data)


def post_sse(port: int, body: dict, timeout=60):
    body = dict(body)
    body["stream"] = True
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    events: list[tuple[str, dict]] = []
    try:
        conn.request(
            "POST", "/v1/consensus", json.dumps(body),
            {"Content-Type": "application/json",
             "Accept": "text/event-stream"},
        )
        resp = conn.getresponse()
        assert resp.status == 200, resp.read()
        event, data_lines = None, []
        for raw in resp:
            line = raw.decode("utf-8").rstrip("\r\n")
            if line.startswith("event: "):
                event = line[len("event: "):]
            elif line.startswith("data: "):
                data_lines.append(line[len("data: "):])
            elif not line and (event or data_lines):
                events.append((event, json.loads("\n".join(data_lines))))
                if event in ("done", "error"):
                    break
                event, data_lines = None, []
    finally:
        conn.close()
    return events


def sse_text(events) -> dict:
    out: dict = {}
    for name, doc in events:
        if name == "chunk":
            key = (doc["kind"], doc["model"])
            out[key] = out.get(key, "") + doc["text"]
    return out


def baseline_sse_text(tmp_path, prompt: str) -> dict:
    gw = make_replica(tmp_path, MidStreamProvider(), "baseline")
    try:
        _, port = gw.address
        return sse_text(post_sse(port, {"prompt": prompt}))
    finally:
        gw.close(timeout=5.0)


# ---------------------------------------------------------------------------
# lifecycle state machine


def test_lifecycle_is_a_forward_state_machine():
    assert placeable(SERVING)
    assert not any(placeable(s) for s in (JOINING, DRAINING, RETIRING))
    assert can_transition(JOINING, SERVING)
    assert can_transition(SERVING, DRAINING)
    assert can_transition(DRAINING, RETIRING)
    assert can_transition(DRAINING, SERVING)  # a drain can be cancelled
    assert not can_transition(SERVING, JOINING)
    assert not can_transition(RETIRING, SERVING)
    assert not can_transition(JOINING, DRAINING)


def test_gateway_lifecycle_transitions_and_illegal_moves(tmp_path):
    gw = make_replica(tmp_path, MidStreamProvider(), "lc")
    try:
        assert gw.lifecycle == SERVING
        gw.set_lifecycle(DRAINING)
        gw.set_lifecycle(SERVING)   # cancel the drain
        gw.set_lifecycle(DRAINING)
        gw.set_lifecycle(RETIRING)
        with pytest.raises(ValueError):
            gw.set_lifecycle(SERVING)  # retiring is terminal
    finally:
        gw.close(timeout=5.0)


def test_joining_replica_is_fully_loaded_and_refuses_migrations(tmp_path):
    gw = make_replica(tmp_path, MidStreamProvider(), "cold",
                      lifecycle=JOINING)
    try:
        assert gw.lifecycle == JOINING
        # A cold engine has no capacity worth advertising.
        assert gw.load_score() == 1.0
        _, port = gw.address
        status, doc = get(port, "/healthz")
        assert status == 200
        assert doc["lifecycle"] == JOINING and doc["placeable"] is False
        # A non-placeable destination must refuse a migration offer so
        # the source falls back to finishing the stream locally.
        record = MigrationRecord(key="k-cold", resume={"alpha": {"text": ""}})
        st, resp = gw.accept_migration(json.dumps(record.to_doc()).encode())
        assert st == 200 and resp["accepted"] is False
        gw.mark_serving()
        assert gw.lifecycle == SERVING
        assert gw.load_score() < 1.0
        _, doc = get(port, "/healthz")
        assert doc["placeable"] is True
    finally:
        gw.close(timeout=5.0)


def test_healthz_reflects_draining_lifecycle(tmp_path):
    gw = make_replica(tmp_path, MidStreamProvider(), "drainz")
    try:
        _, port = gw.address
        gw.set_lifecycle(DRAINING)
        # Drain answers 503 — what balancers key on — but the body still
        # carries the full lifecycle so the elastic tier can tell a
        # policy drain from an unhealthy replica.
        status, doc = get(port, "/healthz")
        assert status == 503
        assert doc["status"] == "draining"
        assert doc["lifecycle"] == DRAINING
        assert doc["draining"] is True and doc["placeable"] is False
    finally:
        gw.close(timeout=5.0)


# ---------------------------------------------------------------------------
# migration records + table


def test_migration_record_roundtrip_and_validation():
    rec = MigrationRecord(
        key="k1",
        resume={"m": {"prompt_ids": [1, 2], "sampling": {}, "tokens": [9]}},
        emitted={"model_chunk:m": "partial"},
        priority=2,
        trace_id="t-1",
        flags={"kv_pool": True},
        source="http://127.0.0.1:1",
    )
    again = MigrationRecord.from_doc(json.loads(json.dumps(rec.to_doc())))
    assert again.key == rec.key
    assert again.resume == rec.resume
    assert again.emitted == rec.emitted
    assert again.priority == 2 and again.trace_id == "t-1"
    with pytest.raises(ValueError):
        MigrationRecord.from_doc({"resume": {}})  # key is mandatory


def test_migration_table_claims_once_and_expires():
    now = [0.0]
    table = MigrationTable(ttl_s=1.0, clock=lambda: now[0])
    table.offer(MigrationRecord(key="k1"))
    assert table.depth() == 1
    assert table.claim("k1") is not None
    assert table.claim("k1") is None  # exactly once
    table.offer(MigrationRecord(key="k2"))
    now[0] = 2.0  # past the TTL: the record must not leak
    assert table.claim("k2") is None
    stats = table.stats()
    assert stats == {"depth": 0, "offered": 2, "claimed": 1, "expired": 1}


# ---------------------------------------------------------------------------
# scale hysteresis


def make_controller(loads, count, **kw):
    """Controller over a scripted load signal and an in-test replica
    count; hooks mutate the count like a real fleet would."""
    calls = {"up": 0, "down": 0}

    def scale_up():
        calls["up"] += 1
        count[0] += 1
        return True

    def scale_down():
        calls["down"] += 1
        count[0] -= 1
        return True

    kw.setdefault("scale_up", scale_up)
    kw.setdefault("scale_down", scale_down)
    kw.setdefault("high_water", 0.8)
    kw.setdefault("low_water", 0.2)
    kw.setdefault("up_patience", 3)
    kw.setdefault("down_patience", 3)
    kw.setdefault("tick_s", 60.0)
    ctl = ElasticController(
        signal=lambda: loads[0],
        replica_count=lambda: count[0],
        **kw,
    )
    return ctl, calls


def test_scale_up_needs_sustained_high_and_mid_band_resets():
    loads, count = [1.0], [1]
    ctl, calls = make_controller(loads, count, min_replicas=1, max_replicas=4)
    assert ctl.tick() is None
    assert ctl.tick() is None
    loads[0] = 0.5            # mid-band: resets the up-streak
    assert ctl.tick() is None
    loads[0] = 1.0
    assert ctl.tick() is None
    assert ctl.tick() is None
    assert ctl.tick() == "up"  # 3 CONSECUTIVE highs
    assert calls == {"up": 1, "down": 0} and count[0] == 2
    assert ctl.scale_ups == 1 and ctl.scale_downs == 0


def test_scale_down_needs_sustained_low_and_min_clamp_denies():
    loads, count = [0.0], [2]
    ctl, calls = make_controller(loads, count, min_replicas=1, max_replicas=4)
    assert [ctl.tick() for _ in range(3)] == [None, None, "down"]
    assert count[0] == 1 and calls["down"] == 1
    # At min_replicas: sustained low evidence is DENIED, never booked.
    assert [ctl.tick() for _ in range(3)] == [None, None, None]
    assert count[0] == 1 and calls["down"] == 1
    assert ctl.denied == 1


def test_max_clamp_denies_scale_up():
    loads, count = [1.0], [4]
    ctl, calls = make_controller(loads, count, min_replicas=1, max_replicas=4)
    assert [ctl.tick() for _ in range(3)] == [None, None, None]
    assert count[0] == 4 and calls["up"] == 0
    assert ctl.denied == 1


def test_refused_hook_is_denied_then_retries():
    loads, count = [1.0], [1]
    verdict = [False]
    ctl, _ = make_controller(
        loads, count, min_replicas=1, max_replicas=4,
        scale_up=lambda: verdict[0],
    )
    assert [ctl.tick() for _ in range(3)] == [None, None, None]
    assert ctl.denied == 1 and ctl.scale_ups == 0
    verdict[0] = True  # the hook can now satisfy the decision
    assert [ctl.tick() for _ in range(3)] == [None, None, "up"]
    assert ctl.scale_ups == 1


def test_oscillating_signal_never_flaps_the_pool():
    loads, count = [1.0], [2]
    ctl, calls = make_controller(loads, count, min_replicas=1, max_replicas=4)
    for i in range(20):  # join/leave oscillation: extremes every tick
        loads[0] = 1.0 if i % 2 else 0.0
        assert ctl.tick() is None
    assert calls == {"up": 0, "down": 0}
    assert ctl.scale_ups == 0 and ctl.scale_downs == 0 and count[0] == 2


def test_replica_flap_fault_is_absorbed_by_hysteresis():
    faults.install(FaultPlan("replica_flap@phase=elastic@s=5", seed=11))
    now = [0.0]
    loads, count = [0.5], [2]
    ctl, calls = make_controller(
        loads, count, min_replicas=1, max_replicas=4, clock=lambda: now[0],
    )
    for _ in range(10):  # the whole flap window: load reads 1.0/0.0/1.0...
        assert ctl.tick() is None
        now[0] += 0.5
    assert ctl.flaps == 1
    assert calls == {"up": 0, "down": 0}
    snap = ctl.snapshot()
    assert snap["scale_ups"] == 0 and snap["scale_downs"] == 0
    assert snap["flaps"] == 1
    # Past the window the scripted signal rules again.
    now[0] = 10.0
    loads[0] = 1.0
    assert [ctl.tick() for _ in range(3)] == [None, None, "up"]


def test_forced_request_bypasses_patience_not_clamps():
    loads, count = [0.5], [1]
    ctl, calls = make_controller(loads, count, min_replicas=1, max_replicas=2)
    assert ctl.request("down")["reason"] == "at min_replicas"
    doc = ctl.request("up")
    assert doc["scaled"] == "up" and doc["replicas"] == 2
    assert ctl.request("up")["reason"] == "at max_replicas"
    doc = ctl.request("down")
    assert doc["scaled"] == "down" and doc["replicas"] == 1
    assert calls == {"up": 1, "down": 1}
    with pytest.raises(ValueError):
        ctl.request("sideways")


def test_router_scale_endpoint(tmp_path):
    provider = MidStreamProvider()
    gws = [make_replica(tmp_path, provider, f"r{i}") for i in range(2)]
    router = make_router(gws, min_replicas=1, max_replicas=4)
    try:
        _, port = router.address
        status, _, data = post(port, {"direction": "up"}, path="/v1/scale")
        assert status == 200, data
        doc = json.loads(data)
        # Default hooks are inert successes: the decision books.
        assert doc["scaled"] == "up"
        status, _, data = post(port, {"direction": "left"}, path="/v1/scale")
        assert status == 400
        _, stats = get(port, "/statsz")
        assert stats["elastic"]["scale_ups"] == 1
        assert stats["elastic"]["max_replicas"] == 4
    finally:
        router.close()
        for g in gws:
            g.close(timeout=5.0)


# ---------------------------------------------------------------------------
# lifecycle-aware placement


def test_draining_replica_is_excluded_from_placement(tmp_path):
    provider = MidStreamProvider()
    gws = [make_replica(tmp_path, provider, f"r{i}") for i in range(2)]
    router = make_router(gws)
    try:
        _, port = router.address
        body = {"prompt": "drain placement probe"}
        from llm_consensus_tpu.serve.router import RouteRequest

        key = RouteRequest(b"", dict(body), False).key()
        urls = [gw_url(g) for g in gws]
        home = ring_order(key, urls, vnodes=router.vnodes)[0]
        other = next(u for u in urls if u != home)
        # The home replica advertises a draining lifecycle via its poll:
        # placement must route around it with no failover needed.
        for replica in router.fleet.replicas():
            if replica.url == home:
                router.fleet.record_poll(replica, True, lifecycle=DRAINING)
        status, _, data = post(port, body)
        assert status == 200
        assert json.loads(data)["replica"] == other
        _, stats = get(port, "/statsz")
        assert stats["fleet"]["by_lifecycle"] == {DRAINING: 1, SERVING: 1}
    finally:
        router.close()
        for g in gws:
            g.close(timeout=5.0)


# ---------------------------------------------------------------------------
# live stream migration


def test_migrate_endpoint_parks_record(tmp_path):
    gw = make_replica(tmp_path, MidStreamProvider(), "park")
    try:
        _, port = gw.address
        rec = MigrationRecord(key="k-park", resume={"alpha": {"text": "hi"}})
        status, _, data = post(port, rec.to_doc(), path="/v1/migrate")
        assert status == 200
        assert json.loads(data) == {"accepted": True, "key": "k-park"}
        _, stats = get(port, "/statsz")
        assert stats["elastic"]["migrations_in"] == 1
        assert stats["elastic"]["table"]["depth"] == 1
        status, _, data = post(port, {"resume": {}}, path="/v1/migrate")
        assert status == 400  # a record without a key is unparseable
    finally:
        gw.close(timeout=5.0)


def test_retire_with_no_residents_is_a_plain_drain(tmp_path):
    gw = make_replica(tmp_path, MidStreamProvider(), "idle")
    try:
        doc = gw.retire()
        assert doc == {"residents": 0, "migrated": 0, "fallback": 0,
                       "lifecycle": RETIRING}
        assert gw.admission.draining
    finally:
        gw.close(timeout=5.0)


def test_retire_migrates_live_stream_byte_identical(tmp_path):
    prompt = "live migration probe"
    expected = baseline_sse_text(tmp_path, prompt)
    gate = threading.Event()
    arrivals = threading.Semaphore(0)
    provider = MidStreamProvider(gate=gate, arrivals=arrivals)
    gws = [make_replica(tmp_path, provider, f"r{i}") for i in range(2)]
    router = make_router(gws)
    try:
        _, port = router.address
        box: dict = {}

        def client():
            box["events"] = post_sse(port, {"prompt": prompt})

        t = threading.Thread(target=client)
        t.start()
        # The panel streams emitted HOLD chunks and froze: the client
        # already holds a prefix the migration seam must splice.
        assert arrivals.acquire(timeout=10)
        source = next(g for g in gws if g._residents)
        dest = next(g for g in gws if g is not source)
        doc = source.retire(to=gw_url(dest))
        gate.set()
        t.join(timeout=30)
        assert not t.is_alive(), "client never finished across the seam"
        assert doc["residents"] == 1 and doc["migrated"] == 1
        assert doc["fallback"] == 0 and doc["lifecycle"] == RETIRING
        events = box["events"]
        assert events[-1][0] == "done", events[-1]
        # Byte-identity across the migration seam: nothing dropped,
        # nothing duplicated — the stream reads like nothing happened.
        assert sse_text(events) == expected
        assert events[-1][1]["failovers"] == 1
        # The destination parked, claimed and resumed the record.
        _, dstats = get(dest.address[1], "/statsz")
        assert dstats["elastic"]["migrations_in"] == 1
        assert dstats["elastic"]["migrations_resumed"] == 1
        assert dstats["elastic"]["table"]["depth"] == 0
        _, sstats = get(source.address[1], "/statsz")
        assert sstats["elastic"]["migrations_out"] == 1
        assert sstats["elastic"]["lifecycle"] == RETIRING
    finally:
        gate.set()
        router.close()
        for g in gws:
            g.close(timeout=5.0)


def test_migrate_stall_falls_back_to_local_finish(tmp_path):
    prompt = "stall fallback probe"
    expected = baseline_sse_text(tmp_path, prompt)
    gate = threading.Event()
    arrivals = threading.Semaphore(0)
    provider = MidStreamProvider(gate=gate, arrivals=arrivals)
    # Install BEFORE the gateways exist: the retire loop consults the
    # plan its constructor captured.
    faults.install(FaultPlan("migrate_stall@phase=migrate@stream=1", seed=3))
    source = make_replica(tmp_path, provider, "stall-src")
    dest = make_replica(tmp_path, MidStreamProvider(), "stall-dst")
    try:
        _, port = source.address
        box: dict = {}

        def client():
            box["events"] = post_sse(port, {"prompt": prompt})

        t = threading.Thread(target=client)
        t.start()
        assert arrivals.acquire(timeout=10)
        # The (injected) stalled destination never acknowledges: the
        # source must NOT cancel the stream — it finishes locally.
        doc = source.retire(to=gw_url(dest))
        assert doc["residents"] == 1 and doc["migrated"] == 0
        assert doc["fallback"] == 1
        gate.set()
        t.join(timeout=30)
        assert not t.is_alive()
        events = box["events"]
        assert events[-1][0] == "done", events[-1]
        assert sse_text(events) == expected  # finished in place, intact
        _, dstats = get(dest.address[1], "/statsz")
        assert dstats["elastic"]["migrations_in"] == 0
        _, sstats = get(source.address[1], "/statsz")
        assert sstats["elastic"]["migrate_fallbacks"] == 1
    finally:
        gate.set()
        source.close(timeout=5.0)
        dest.close(timeout=5.0)
