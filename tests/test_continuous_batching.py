"""Continuous batching (engine/batcher.py).

TPU-build extension — the reference's only concurrency is goroutine
fan-out over HTTP calls (SURVEY.md §2 #2); on-device serving adds slot
admission/eviction mid-flight. The load-bearing property: a stream's
tokens are EXACTLY what the single-stream engine would produce (greedy),
no matter what its slot neighbors are doing.
"""

import threading
import time

import jax
import jax.numpy as jnp
import pytest

from llm_consensus_tpu.engine import ContinuousBatcher, Engine, SamplingParams
from llm_consensus_tpu.models import get_config, init_params
from llm_consensus_tpu.utils import Context


@pytest.fixture(scope="module")
def engine():
    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    return Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                  stream_interval=8)


@pytest.fixture()
def batcher(engine):
    b = ContinuousBatcher(engine, max_batch=2)
    yield b
    b.close()


def _single(engine, prompt, s):
    return engine.generate(prompt, s)


def test_single_submission_matches_single_stream(engine, batcher):
    s = SamplingParams(max_new_tokens=24, ignore_eos=True)
    got = batcher.submit("continuous batching probe", s).result(timeout=300)
    ref = _single(engine, "continuous batching probe", s)
    assert got.token_ids == ref.token_ids
    assert got.text == ref.text
    assert got.finish_reason == ref.finish_reason
    assert got.prompt_tokens == ref.prompt_tokens


def test_concurrent_streams_match_single_stream(engine, batcher):
    s = SamplingParams(max_new_tokens=20, ignore_eos=True)
    prompts = ["first stream", "the second, rather longer, stream prompt"]
    futs = [batcher.submit(p, s) for p in prompts]
    results = [f.result(timeout=300) for f in futs]
    for p, r in zip(prompts, results):
        assert r.token_ids == _single(engine, p, s).token_ids, p


def test_oversubscription_queues_and_completes(engine, batcher):
    """5 streams through 2 slots: later submissions are admitted as
    earlier ones retire, every result still exact."""
    s = SamplingParams(max_new_tokens=12, ignore_eos=True)
    prompts = [f"queued stream number {i}" for i in range(5)]
    futs = [batcher.submit(p, s) for p in prompts]
    for p, f in zip(prompts, futs):
        assert f.result(timeout=300).token_ids == _single(engine, p, s).token_ids


def test_admission_mid_flight(engine, batcher):
    """A stream admitted while another decodes must not perturb it."""
    s_long = SamplingParams(max_new_tokens=48, ignore_eos=True)
    s_short = SamplingParams(max_new_tokens=8, ignore_eos=True)
    f1 = batcher.submit("long running stream", s_long)
    time.sleep(0.3)  # let it start decoding
    f2 = batcher.submit("late arrival", s_short)
    r1, r2 = f1.result(timeout=300), f2.result(timeout=300)
    assert r1.token_ids == _single(engine, "long running stream", s_long).token_ids
    assert r2.token_ids == _single(engine, "late arrival", s_short).token_ids


def test_per_stream_max_new(engine, batcher):
    s8 = SamplingParams(max_new_tokens=8, ignore_eos=True)
    s16 = SamplingParams(max_new_tokens=16, ignore_eos=True)
    f8 = batcher.submit("alpha", s8)
    f16 = batcher.submit("beta", s16)
    assert len(f8.result(timeout=300).token_ids) == 8
    assert len(f16.result(timeout=300).token_ids) == 16


def test_streaming_callback_order(engine, batcher):
    s = SamplingParams(max_new_tokens=10, ignore_eos=True)
    chunks: list[str] = []
    got = batcher.submit(
        "stream text callback", s, on_text=chunks.append
    ).result(timeout=300)
    assert "".join(chunks) == got.text
    assert got.text  # byte tokenizer always yields text


def test_cancellation_does_not_kill_neighbors(engine, batcher):
    s_doomed = SamplingParams(max_new_tokens=220, ignore_eos=True)
    s_live = SamplingParams(max_new_tokens=30, ignore_eos=True)
    ctx = Context.background().with_cancel()
    started = threading.Event()
    f_cancel = batcher.submit(
        "doomed", s_doomed, ctx=ctx, on_text=lambda _t: started.set()
    )
    f_live = batcher.submit("survivor stream", s_live)
    assert started.wait(timeout=120)  # doomed stream is mid-decode
    ctx.cancel()
    r_cancel = f_cancel.result(timeout=300)
    r_live = f_live.result(timeout=300)
    assert r_cancel.finish_reason == "cancelled"
    assert len(r_cancel.token_ids) < 220
    assert r_live.finish_reason == "length"
    assert r_live.token_ids == _single(
        engine, "survivor stream", s_live
    ).token_ids


def test_mismatched_sampling_shape_rejected(engine):
    b = ContinuousBatcher(engine, max_batch=2)
    try:
        b.submit("greedy", SamplingParams(max_new_tokens=4, ignore_eos=True))
        with pytest.raises(ValueError, match="sampling shape"):
            b.submit(
                "sampled",
                SamplingParams(max_new_tokens=4, temperature=0.7),
            )
    finally:
        b.close()


def test_submit_after_close_raises(engine):
    b = ContinuousBatcher(engine, max_batch=1)
    b.close()
    with pytest.raises(RuntimeError, match="closed"):
        b.submit("too late", SamplingParams(max_new_tokens=4))


def test_eos_retires_slot(engine):
    """A stream hitting EOS frees its slot for the queue; ignore_eos=False
    path (tiny models emit eos id 0 quickly from random logits... force it
    by decoding until the byte tokenizer's eos shows up or length caps)."""
    b = ContinuousBatcher(engine, max_batch=1)
    try:
        s = SamplingParams(max_new_tokens=6)  # respects EOS
        r = b.submit("eos probe", s).result(timeout=300)
        ref = engine.generate("eos probe", s)
        assert r.finish_reason == ref.finish_reason
        assert r.token_ids == ref.token_ids
    finally:
        b.close()


def test_many_streams_stress(engine):
    """Submissions from several threads, max_batch=2: all complete, all
    exact. Exercises admission/retire/reuse churn under contention."""
    b = ContinuousBatcher(engine, max_batch=2)
    try:
        s = SamplingParams(max_new_tokens=6, ignore_eos=True)
        prompts = [f"stress prompt {i}" for i in range(8)]
        futs = {}
        lock = threading.Lock()

        def submit(p):
            f = b.submit(p, s)
            with lock:
                futs[p] = f

        threads = [threading.Thread(target=submit, args=(p,)) for p in prompts]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for p, f in futs.items():
            assert f.result(timeout=300).token_ids == engine.generate(p, s).token_ids, p
    finally:
        b.close()


def test_waterline_compaction_gives_fresh_runway(engine):
    """Streams outliving the shared frontier survive via compaction: a
    max_seq-256 engine decoding 3 sequential waves of streams must keep
    every wave exact — without compaction the shared frontier would hit
    capacity and truncate later waves."""
    b = ContinuousBatcher(engine, max_batch=2)
    try:
        s = SamplingParams(max_new_tokens=60, ignore_eos=True)
        # 6 streams x (prompt ~20 + 60 new) >> 256 slots of shared frontier.
        prompts = [f"compaction wave stream {i}" for i in range(6)]
        futs = [b.submit(p, s) for p in prompts]
        for p, f in zip(prompts, futs):
            r = f.result(timeout=300)
            assert r.finish_reason == "length"
            assert r.token_ids == engine.generate(p, s).token_ids, p
    finally:
        b.close()


def test_long_prompt_waits_for_frontier(engine):
    """A prompt longer than the live frontier queues until it fits (or the
    pool idles); it must still come out exact."""
    b = ContinuousBatcher(engine, max_batch=2)
    try:
        s = SamplingParams(max_new_tokens=10, ignore_eos=True)
        short = b.submit("tiny", s)
        long_prompt = "a deliberately much longer prompt " * 4
        longf = b.submit(long_prompt, s)
        assert short.result(timeout=300).token_ids == engine.generate("tiny", s).token_ids
        assert longf.result(timeout=300).token_ids == engine.generate(long_prompt, s).token_ids
    finally:
        b.close()


def test_cache_tail_exact_parity(engine):
    """A stream whose window reaches cache capacity must emit every token
    the single-stream engine would (1-step tail dispatches), not retire a
    chunk early."""
    b = ContinuousBatcher(engine, max_batch=1)
    try:
        prompt = "tail parity " * 16  # ~190 tokens of a 256-slot cache
        s = SamplingParams(max_new_tokens=500, ignore_eos=True)  # capacity-capped
        r = b.submit(prompt, s).result(timeout=300)
        ref = engine.generate(prompt, s)
        assert r.finish_reason == ref.finish_reason == "length"
        assert r.token_ids == ref.token_ids
    finally:
        b.close()


def test_queued_stream_deadline_resolves_without_admission(engine):
    """A stream whose deadline expires while still queued resolves
    promptly (empty, finish=deadline) instead of hanging until a slot
    frees and paying prefill."""
    b = ContinuousBatcher(engine, max_batch=1)
    try:
        blocker = b.submit(
            "occupies the only slot",
            SamplingParams(max_new_tokens=200, ignore_eos=True),
        )
        ctx = Context.background().with_timeout(0.05)
        time.sleep(0.1)  # expire before any slot frees
        doomed = b.submit(
            "never admitted", SamplingParams(max_new_tokens=50), ctx=ctx
        )
        r = doomed.result(timeout=120)
        assert r.finish_reason == "deadline"
        assert r.token_ids == []
        blocker.result(timeout=300)
    finally:
        b.close()


def test_admission_failure_fails_one_stream_not_the_pool(engine, monkeypatch):
    """A prefill exception fails that stream's Future; the pool keeps
    serving other streams. Both admission prefill forms are poisoned:
    the batched wave falls back to singles, whose failure must land on
    the one bad stream only."""
    b = ContinuousBatcher(engine, max_batch=1)
    try:
        real = type(b.engine)._prefill_ids
        real_rows = type(b.engine)._prefill_rows

        def boom(self, ids):
            if len(ids) < 12:
                raise RuntimeError("injected prefill failure")
            return real(self, ids)

        def boom_rows(self, rows):
            if any(len(r) < 12 for r in rows):
                raise RuntimeError("injected prefill failure")
            return real_rows(self, rows)

        monkeypatch.setattr(type(b.engine), "_prefill_ids", boom)
        monkeypatch.setattr(type(b.engine), "_prefill_rows", boom_rows)
        doomed = b.submit("short", SamplingParams(max_new_tokens=4))
        with pytest.raises(RuntimeError, match="injected prefill failure"):
            doomed.result(timeout=120)
        s = SamplingParams(max_new_tokens=6, ignore_eos=True)
        survivor = b.submit("a long enough healthy prompt", s)
        monkeypatch.undo()
        assert survivor.result(timeout=300).token_ids == engine.generate(
            "a long enough healthy prompt", s
        ).token_ids
    finally:
        monkeypatch.undo()
        b.close()


def test_close_cancels_queued_streams(engine):
    """close() while streams wait in the queue must not leave any Future
    unresolved (a cancelled Future raises CancelledError, never hangs)."""
    from concurrent.futures import CancelledError

    b = ContinuousBatcher(engine, max_batch=1)
    s_long = SamplingParams(max_new_tokens=120, ignore_eos=True)
    running = b.submit("occupies the slot", s_long)
    queued = b.submit("never admitted before close", s_long)
    time.sleep(0.2)
    b.close()
    running.result(timeout=300)  # in-flight stream finishes
    try:
        r = queued.result(timeout=10)  # either cancelled or cleanly run
        assert r.token_ids is not None
    except CancelledError:
        pass


def test_fifo_fairness_no_leapfrog(engine):
    """Once a stream is requeued (frontier/capacity), later arrivals must
    not be admitted ahead of it — under sustained short-prompt load a
    long prompt would otherwise starve until the pool drained."""
    b = ContinuousBatcher(engine, max_batch=2)
    try:
        s = SamplingParams(max_new_tokens=40, ignore_eos=True)
        first_text_at: dict = {}

        def mark(name):
            def cb(_chunk):
                first_text_at.setdefault(name, time.monotonic())
            return cb

        # Occupy one slot; its decode advances the shared frontier.
        a = b.submit("x", s, on_text=mark("a"))
        # B's prompt exceeds the young frontier -> requeued for a while.
        long_prompt = "deliberately long prompt " * 2
        bb = b.submit(long_prompt, s, on_text=mark("b"))
        # C arrives later; a free slot exists, but admitting C before B
        # would be the starvation bug.
        cc = b.submit("y", s, on_text=mark("c"))

        ra, rb, rc = (f.result(timeout=300) for f in (a, bb, cc))
        assert ra.token_ids == engine.generate("x", s).token_ids
        assert rb.token_ids == engine.generate(long_prompt, s).token_ids
        assert rc.token_ids == engine.generate("y", s).token_ids
        assert first_text_at["b"] <= first_text_at["c"], (
            "later short prompt leapfrogged a requeued long prompt"
        )
    finally:
        b.close()


def test_tp_sharded_batcher_token_exact():
    """Continuous batching under a TP mesh (the sharded judge's serving
    path): splice/compact touch only slot/position axes, which TP never
    shards, so GSPMD partitions the whole pool — output must be
    token-exact vs the same sharded engine single-stream, including
    through waterline compactions (sequential waves push the shared
    frontier past max_seq=96 with live rows whose row_start > 0, so
    _compact_cache's traced roll actually executes on the sharded
    cache — two equal streams alone would compute shift = 0 and never
    compact)."""
    import numpy as np
    from jax.sharding import Mesh

    from llm_consensus_tpu.models import get_config, init_params

    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(1, 2), ("dp", "tp"))
    eng = Engine(cfg, params=params, dtype=jnp.float32, max_seq=96,
                 stream_interval=4, mesh=mesh)
    b = ContinuousBatcher(eng, max_batch=2)
    try:
        s = SamplingParams(max_new_tokens=24, ignore_eos=True)
        # 6 staggered streams × (~24 prompt + 24 new) >> 96 shared slots.
        prompts = [f"tp sharded wave stream {i}" for i in range(6)]
        futs = [b.submit(p, s, Context.background()) for p in prompts]
        for p, f in zip(prompts, futs):
            ref = eng.generate(p, s)
            assert f.result(timeout=300).token_ids == ref.token_ids, p
    finally:
        b.close()


def test_provider_batching_engages_on_tp_placement():
    """A planned multi-device tp placement routes through the batcher
    (round 2 initially gated this to single-device meshes)."""
    from llm_consensus_tpu.providers.base import Request
    from llm_consensus_tpu.providers.tpu import TPUProvider
    import jax as _jax

    provider = TPUProvider(ignore_eos=True, stream_interval=4, batch_streams=2)
    provider.prepare(["tpu:tiny-llama"], None, devices=_jax.devices()[:2])
    mesh = provider.placement("tpu:tiny-llama")
    assert mesh is not None and mesh.devices.size == 2
    provider.query(
        Context.background(),
        Request(model="tpu:tiny-llama", prompt="tp batched", max_tokens=4),
    )
    assert "tiny-llama" in provider._batchers
    provider.release()


def _gated_batcher(engine, max_batch):
    """Batcher whose scheduler waits on a gate: submissions queued before
    the gate opens form one deterministic admission wave."""
    gate = threading.Event()
    real_loop = ContinuousBatcher._loop

    def gated(self):
        gate.wait(timeout=300)
        real_loop(self)

    ContinuousBatcher._loop = gated
    try:
        b = ContinuousBatcher(engine, max_batch=max_batch)
    finally:
        ContinuousBatcher._loop = real_loop
    return b, gate


def test_burst_batched_admission_exact(engine):
    """A same-instant burst takes the batched-admission path (ONE
    Engine._prefill_rows call for the wave) and every stream is still
    token-exact vs the single-stream engine — including heterogeneous
    prompt lengths that span prefill buckets."""
    b, gate = _gated_batcher(engine, max_batch=4)
    calls = {"rows": 0, "single": 0}
    real_rows = type(engine)._prefill_rows
    real_ids = type(engine)._prefill_ids

    def count_rows(self, rows):
        calls["rows"] += 1
        return real_rows(self, rows)

    def count_ids(self, ids):
        calls["single"] += 1
        return real_ids(self, ids)

    s = SamplingParams(max_new_tokens=12, ignore_eos=True)
    prompts = [
        "a",
        "burst admission stream two",
        "a deliberately rather longer burst admission prompt " * 2,
        "stream four",
    ]
    try:
        type(engine)._prefill_rows = count_rows
        type(engine)._prefill_ids = count_ids
        futs = [b.submit(p, s) for p in prompts]
        gate.set()
        results = [f.result(timeout=300) for f in futs]
        assert calls["rows"] >= 1, "burst did not take batched admission"
        assert calls["single"] == 0, "burst fell back to per-stream prefill"
    finally:
        type(engine)._prefill_rows = real_rows
        type(engine)._prefill_ids = real_ids
        gate.set()
        b.close()
    for p, r in zip(prompts, results):
        assert r.token_ids == engine.generate(p, s).token_ids, p


def test_burst_admission_prefill_failure_falls_back_to_singles(engine):
    """A failing batched prefill degrades to one-by-one admission: the
    wave still completes exactly through the single-stream path."""
    b, gate = _gated_batcher(engine, max_batch=3)
    real_rows = type(engine)._prefill_rows

    def boom(self, rows):
        raise RuntimeError("injected batched prefill failure")

    s = SamplingParams(max_new_tokens=8, ignore_eos=True)
    prompts = [f"fallback wave {i}" for i in range(3)]
    try:
        type(engine)._prefill_rows = boom
        futs = [b.submit(p, s) for p in prompts]
        gate.set()
        for p, f in zip(prompts, futs):
            assert f.result(timeout=300).token_ids == engine.generate(
                p, s
            ).token_ids, p
    finally:
        type(engine)._prefill_rows = real_rows
        gate.set()
        b.close()


def test_burst_batched_admission_int8_kv_exact():
    """Batched admission splices quantized cache trees (codes + scales)
    correctly: int8-KV batcher output matches the same engine's
    single-stream output."""
    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    eng = Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                 stream_interval=8, kv_quant="int8")
    b, gate = _gated_batcher(eng, max_batch=3)
    s = SamplingParams(max_new_tokens=10, ignore_eos=True)
    prompts = [f"quantized burst stream {i}" for i in range(3)]
    try:
        futs = [b.submit(p, s) for p in prompts]
        gate.set()
        for p, f in zip(prompts, futs):
            assert f.result(timeout=300).token_ids == eng.generate(
                p, s
            ).token_ids, p
    finally:
        gate.set()
        b.close()


def test_wave_prefix_reuse_across_bursts():
    """Burst waves sharing a multi-chunk prompt prefix re-prefill only
    the tail chunks after the first wave (VERDICT r2 #3: panel prefill
    cost ~1x the shared prompt, not per admission), and stay token-exact
    vs the single-stream engine."""
    import llm_consensus_tpu.engine.engine as eng_mod

    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    eng = Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                 stream_interval=8, prefill_chunk=16)
    shared = "shared panel prompt prefix " * 5  # ~135 tokens, ~8 chunks
    s = SamplingParams(max_new_tokens=6, ignore_eos=True)
    chunk_calls = []
    real_chunk = eng_mod._prefill_chunk

    def spy(*a, **k):
        chunk_calls.append(1)
        return real_chunk(*a, **k)

    eng_mod._prefill_chunk = spy
    b, gate = _gated_batcher(eng, max_batch=2)
    try:
        w1 = [shared + f"wave one tail {i}" for i in range(2)]
        futs = [b.submit(p, s) for p in w1]
        gate.set()
        r1 = [f.result(timeout=300) for f in futs]
        wave1_chunks = len(chunk_calls)
        chunk_calls.clear()
        w2 = [shared + f"second wave tail {i}" for i in range(2)]
        futs = [b.submit(p, s) for p in w2]
        r2 = [f.result(timeout=300) for f in futs]
        wave2_chunks = len(chunk_calls)
    finally:
        eng_mod._prefill_chunk = real_chunk
        gate.set()
        b.close()
    assert wave2_chunks < wave1_chunks, (wave1_chunks, wave2_chunks)
    for p, r in zip(w1 + w2, r1 + r2):
        ref = Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                     stream_interval=8, prefill_chunk=16).generate(p, s)
        assert r.token_ids == ref.token_ids, p


def test_large_seed_admission_not_pool_fatal(engine):
    """Seeds >= 2**31 must admit through the batched path (uint32 key
    derivation) instead of killing the scheduler with an int32 overflow."""
    b, gate = _gated_batcher(engine, max_batch=2)
    s = [SamplingParams(max_new_tokens=4, ignore_eos=True, seed=2**31 + i)
         for i in range(2)]
    try:
        futs = [b.submit(f"big seed {i}", s[i]) for i in range(2)]
        gate.set()
        for f in futs:
            assert len(f.result(timeout=300).token_ids) == 4
    finally:
        gate.set()
        b.close()


def test_wave_admission_non_chunk_multiple_capacity():
    """A max_seq that is not a multiple of the prefill chunk forces the
    one-shot wave-prefill path (chunking would floor away tail tokens —
    the round-3 review regression); wave output stays exact."""
    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    eng = Engine(cfg, params=params, dtype=jnp.float32, max_seq=200,
                 stream_interval=8, prefill_chunk=16)
    assert eng._rows_bucket(150) % 16 != 0  # the hazard shape
    b, gate = _gated_batcher(eng, max_batch=2)
    s = SamplingParams(max_new_tokens=6, ignore_eos=True)
    prompts = ["x " * 70 + "one", "x " * 70 + "two"]  # ~140+ tokens each
    try:
        futs = [b.submit(p, s) for p in prompts]
        gate.set()
        for p, f in zip(prompts, futs):
            assert f.result(timeout=300).token_ids == eng.generate(
                p, s
            ).token_ids, p
    finally:
        gate.set()
        b.close()


def test_wave_admission_after_compaction_exact():
    """Burst waves keep arriving while earlier waves push the shared
    frontier past capacity: compaction and batched admission must
    compose (the wave splice offsets are computed against the
    post-compaction frontier)."""
    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    eng = Engine(cfg, params=params, dtype=jnp.float32, max_seq=128,
                 stream_interval=8)
    b = ContinuousBatcher(eng, max_batch=2)
    s = SamplingParams(max_new_tokens=40, ignore_eos=True)
    prompts = [f"compaction wave pair stream {i}" for i in range(6)]
    try:
        futs = [b.submit(p, s) for p in prompts]
        for p, f in zip(prompts, futs):
            assert f.result(timeout=300).token_ids == eng.generate(
                p, s
            ).token_ids, p
    finally:
        b.close()


def test_occupancy_bucket_shrinks_and_regrows(monkeypatch):
    """Dead-slot fix: when most of a pool retires, the decode row bucket
    shrinks (live rows compact into low slots) and regrows on the next
    burst — with every stream still exactly matching single-stream
    greedy output across the moves."""
    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    eng = Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                 stream_interval=8)
    b = ContinuousBatcher(eng, max_batch=16)
    try:
        assert b._rows_bucket_enabled and b._min_rows == 8
        s_short = SamplingParams(max_new_tokens=6, ignore_eos=True)
        s_long = SamplingParams(max_new_tokens=64, ignore_eos=True)
        prompts_short = [f"short stream number {i}" for i in range(12)]
        prompts_long = [f"long running stream {i}" for i in range(4)]
        futs_s = [b.submit(p, s_short) for p in prompts_short]
        futs_l = [b.submit(p, s_long) for p in prompts_long]
        for p, f in zip(prompts_short, futs_s):
            assert f.result(timeout=600).token_ids == eng.generate(
                p, s_short
            ).token_ids, p
        # Long streams keep decoding at low occupancy: the bucket should
        # shrink to the 8-row floor while they finish.
        results_l = [f.result(timeout=600) for f in futs_l]
        assert b._rows_cap == 8  # shrunk (hysteresis: 3 dispatches at <=50%)
        for p, r in zip(prompts_long, results_l):
            assert r.token_ids == eng.generate(p, s_long).token_ids, p
        # Regrowth: a fresh 12-wide burst needs more than 8 rows.
        prompts2 = [f"second burst stream {i}" for i in range(12)]
        futs2 = [b.submit(p, s_short) for p in prompts2]
        for p, f in zip(prompts2, futs2):
            assert f.result(timeout=600).token_ids == eng.generate(
                p, s_short
            ).token_ids, p
        assert b._rows_cap == 16
    finally:
        b.close()


def test_occupancy_bucket_disabled_by_env(monkeypatch):
    monkeypatch.setenv("LLMC_POOL_BUCKET", "0")
    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    eng = Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                 stream_interval=8)
    b = ContinuousBatcher(eng, max_batch=16)
    try:
        assert not b._rows_bucket_enabled
        s = SamplingParams(max_new_tokens=8, ignore_eos=True)
        futs = [b.submit(f"env off {i}", s) for i in range(4)]
        [f.result(timeout=600) for f in futs]
        assert b._rows_cap == 16
    finally:
        b.close()


def test_phase_stats_account_and_overshoot_gate(engine):
    """Per-phase wall accounting (VERDICT r4 #3) plus the overshoot
    gate / final-chunk clamp: a burst whose streams all need fewer
    steps than the in-flight pipeline would otherwise dispatch must
    retire with zero tail dead-stepping and exact token counts."""
    b = ContinuousBatcher(engine, max_batch=4)
    try:
        # max_new=9 with chunk=8: one full chunk (planned 1+8=9) covers
        # the need exactly; the gate must block a second chunk.
        s = SamplingParams(max_new_tokens=9, ignore_eos=True)
        futs = [b.submit(f"gate stream {i}", s) for i in range(4)]
        for i, f in enumerate(futs):
            r = f.result(timeout=300)
            assert len(r.token_ids) == 9
            assert r.token_ids == engine.generate(
                f"gate stream {i}", s
            ).token_ids
        st = b.stats
        for key in ("decode_tokens", "decode_s", "tail_s", "impure_s",
                    "impure_tokens", "establish_s", "admit_s",
                    "admit_tokens", "absorb_s"):
            assert key in st, key
        # Every prompt token admitted must be counted.
        assert st["admit_tokens"] == sum(
            len(engine.tokenizer.encode(f"gate stream {i}"))
            for i in range(4)
        )
        # All covered at the first dispatch: no zero-emit tail chunk.
        assert st["tail_s"] == 0.0
        # Tokens land in decode or impure intervals (plus the 4
        # prefill-sampled firsts, which ride the first chunk's fetch).
        assert st["decode_tokens"] + st["impure_tokens"] <= 9 * 4
    finally:
        b.close()


def test_final_chunk_clamp_non_multiple(engine):
    """max_new not a chunk multiple: the clamped final chunk must not
    cost tokens (exactness) and planned accounting must not stall."""
    b = ContinuousBatcher(engine, max_batch=2)
    try:
        s = SamplingParams(max_new_tokens=11, ignore_eos=True)  # 1+8+2
        f0 = b.submit("clamp alpha", s)
        f1 = b.submit("clamp beta", s)
        for prompt, f in (("clamp alpha", f0), ("clamp beta", f1)):
            r = f.result(timeout=300)
            assert len(r.token_ids) == 11
            assert r.token_ids == engine.generate(prompt, s).token_ids
    finally:
        b.close()
