"""Deterministic schedule exploration + happens-before race detection
(llm_consensus_tpu/analysis/schedule.py, race.py).

The explorer's contract, tested end to end:

  * both planted-bug fixtures (a check-then-act atomicity violation and
    an AB/BA deadlock) are FOUND within a bounded schedule budget far
    under the acceptance ceiling of 512;
  * the same seed produces the identical schedule trace and the
    identical finding (schedule index, replay token);
  * a failing schedule's replay token round-trips: replaying it
    reproduces the exact failure, and delta-debug minimization returns
    a token with no more preemptions that still fails;
  * the FastTrack-style race detector flags an off-lock read of a
    guarded field with both access sites, stays silent for
    lock-protected access, honors the notify⇒wake happens-before edge
    (no false positive on a condition-variable handoff), and respects
    inline ``race-ok`` / ``lint-ok: GS01`` suppressions;
  * the REAL protocol fixtures (admission preempt-vs-drain,
    handoff-crash-fallback, supervisor-restart-vs-submit) model-check
    clean — run here via the ``@pytest.mark.schedules`` integration the
    conftest provides, the same bodies the CI ``model-check`` lane
    explores over a bigger seed matrix.
"""

from __future__ import annotations

import threading

import pytest

from llm_consensus_tpu.analysis import race, sanitizer, schedule
from llm_consensus_tpu.analysis.protocols import (
    admission_preempt_vs_drain, handoff_crash_fallback, planted_atomicity,
    planted_deadlock, quarantine_vs_resident_stream,
    scale_down_vs_resident_stream, supervisor_restart_vs_submit,
    swap_vs_resident_stream,
)

BUDGET = 512  # the acceptance ceiling; findings land far under it


# ---------------------------------------------------------------------------
# planted bugs: detection within budget

def test_atomicity_violation_found_within_budget():
    res = schedule.explore(planted_atomicity, schedules=BUDGET, seed=0,
                           race=False)
    assert res.failed, "explorer missed the planted atomicity violation"
    assert res.schedules_run <= 64, (
        f"took {res.schedules_run} schedules — budget regression"
    )
    assert isinstance(res.failure.exc, AssertionError)
    assert "lost update" in str(res.failure.exc)


def test_deadlock_found_within_budget():
    res = schedule.explore(planted_deadlock, schedules=BUDGET, seed=0,
                           race=False)
    assert res.failed, "explorer missed the planted deadlock"
    assert res.schedules_run <= 64
    assert isinstance(res.failure.exc, schedule.DeadlockError)
    # The report names each blocked thread's resource.
    assert res.failure.exc.threads
    for _name, (status, what, _stack) in res.failure.exc.threads.items():
        assert status in ("blocked", "timed", "runnable")
        assert what is None or what[0] in ("lock", "cond", "event", "join")


# ---------------------------------------------------------------------------
# determinism + replay + minimization

def test_same_seed_same_trace_same_finding():
    a = schedule.explore(planted_atomicity, schedules=BUDGET, seed=0,
                         race=False)
    b = schedule.explore(planted_atomicity, schedules=BUDGET, seed=0,
                         race=False)
    assert a.failed and b.failed
    assert a.failure.token == b.failure.token
    assert a.failure.index == b.failure.index
    assert a.failure.seed == b.failure.seed
    # Different seed base explores a different prefix (usually a
    # different token) but still finds the bug within budget.
    c = schedule.explore(planted_atomicity, schedules=BUDGET, seed=1000,
                         race=False)
    assert c.failed


def test_passing_body_traces_are_deterministic():
    def body():
        lock = sanitizer.make_lock("fixture.t")
        out = []

        def worker():
            with lock:
                out.append(1)

        t = threading.Thread(target=worker)
        t.start()
        with lock:
            out.append(2)
        t.join()
        assert sorted(out) == [1, 2]

    a = schedule.explore(body, schedules=8, seed=3, race=False,
                         keep_traces=True)
    b = schedule.explore(body, schedules=8, seed=3, race=False,
                         keep_traces=True)
    assert not a.failed and not b.failed
    assert a.traces == b.traces
    assert len(a.traces) == 8


def test_replay_token_reproduces_failure():
    res = schedule.explore(planted_deadlock, schedules=BUDGET, seed=0,
                           race=False)
    assert res.failed
    with pytest.raises(schedule.DeadlockError):
        schedule.replay(planted_deadlock, res.failure.token, race=False)
    res2 = schedule.explore(planted_atomicity, schedules=BUDGET, seed=0,
                            race=False)
    with pytest.raises(AssertionError, match="lost update"):
        schedule.replay(planted_atomicity, res2.failure.token, race=False)


def test_token_encode_decode_round_trip():
    for trace in ([], [0, 1, 2, 15], [0] * 40, [3, 17, 0, 255], [16]):
        tok = schedule.encode_token(trace)
        assert schedule.decode_token(tok) == trace
    with pytest.raises(ValueError):
        schedule.decode_token("notatoken!")
    with pytest.raises(ValueError):
        schedule.decode_token("")


def test_minimize_reduces_preemptions_and_still_fails():
    res = schedule.explore(planted_atomicity, schedules=BUDGET, seed=0,
                           race=False)
    assert res.failed
    tok = schedule.minimize(planted_atomicity, res.failure.token,
                            race=False)
    orig_nz = sum(1 for c in schedule.decode_token(res.failure.token) if c)
    min_nz = sum(1 for c in schedule.decode_token(tok) if c)
    assert min_nz <= orig_nz
    assert len(tok) <= len(res.failure.token)
    with pytest.raises(AssertionError, match="lost update"):
        schedule.replay(planted_atomicity, tok, race=False)


def test_from_env_parsing(monkeypatch):
    monkeypatch.setenv("LLMC_SCHED", "")
    assert schedule.from_env() is None
    monkeypatch.setenv("LLMC_SCHED", "7")
    assert schedule.from_env() == ("seed", 7)
    monkeypatch.setenv("LLMC_SCHED", "replay:x012")
    assert schedule.from_env() == ("replay", [0, 1, 2])
    monkeypatch.setenv("LLMC_SCHED", "bogus")
    with pytest.raises(ValueError):
        schedule.from_env()


def test_check_raises_assertion_with_replay_token():
    with pytest.raises(AssertionError) as ei:
        schedule.check(planted_atomicity, schedules=BUDGET)
    assert "LLMC_SCHED=replay:" in str(ei.value)


def test_check_honors_replay_env(monkeypatch):
    res = schedule.explore(planted_deadlock, schedules=BUDGET, seed=0,
                           race=False)
    monkeypatch.setenv("LLMC_SCHED", f"replay:{res.failure.token}")
    with pytest.raises(schedule.DeadlockError):
        schedule.check(planted_deadlock, schedules=1)


# ---------------------------------------------------------------------------
# race detector

class _Gauge:
    """Planted race: write under lock, read without."""

    def __init__(self):
        self._lock = sanitizer.make_lock("fixture.gauge")
        self._v = 0  # guarded by: _lock

    def set(self, v):
        with self._lock:
            self._v = v

    def peek(self):
        return self._v  # off-lock read — the planted bug

    def peek_locked_properly(self):
        with self._lock:
            return self._v


def _gauge_writer_body(reader):
    def body():
        g = _Gauge()

        def w():
            g.set(7)

        t = threading.Thread(target=w)
        t.start()
        reader(g)
        t.join()

    return body


def test_race_detector_flags_off_lock_read():
    res = schedule.explore(
        _gauge_writer_body(lambda g: g.peek()), schedules=16, seed=0,
        race=True, instrument=[(_Gauge, {"_v"})],
    )
    assert res.failed
    assert isinstance(res.failure.exc, race.RaceError)
    r = res.failure.exc.races[0]
    assert r["label"] == "_Gauge._v"
    assert r["kind"] in ("write-read", "read-write", "write-write")
    # Both access sites land in THIS file.
    assert "test_schedule" in r["site"][0]
    assert "test_schedule" in r["prev_site"][0]


def test_minimize_and_replay_accept_instrument():
    """A failure found with ``explore(..., instrument=...)`` must carry
    the instrumentation through minimize/replay, or the ddmin oracle
    never reproduces and minimization silently no-ops."""
    body = _gauge_writer_body(lambda g: g.peek())
    inst = [(_Gauge, {"_v"})]
    res = schedule.explore(body, schedules=16, seed=0, race=True,
                           instrument=inst)
    assert res.failed
    mint = schedule.minimize(body, res.failure.token, race=True,
                             instrument=inst)
    with pytest.raises(race.RaceError):
        schedule.replay(body, mint, race=True, instrument=inst)


def test_race_detector_forgets_collected_objects():
    """``id()`` recycles: a collected object's stale write epoch must
    not alias onto a new object allocated at the same address (the
    new object's first access would false-positive)."""
    import gc

    tids = {"cur": 1}
    det = race.RaceDetector(tid_fn=lambda: tids["cur"])

    class Obj:
        pass

    o = Obj()
    oid = id(o)
    tids["cur"] = 2  # a second thread writes with no later HB edge
    det.on_write(o, "_v", ("f.py", 10), "Obj._v")
    assert (oid, "_v") in det._vars
    del o
    gc.collect()
    o2 = None
    hold = []  # keep misses alive so the allocator must reach o's slot
    for _ in range(10000):
        cand = Obj()
        if id(cand) == oid:
            o2 = cand
            break
        hold.append(cand)
    if o2 is None:
        pytest.skip("allocator did not recycle the id")
    tids["cur"] = 1
    det.on_read(o2, "_v", ("f.py", 20), "Obj._v")
    assert det.races == [], det.races


def test_race_detector_lock_protected_access_is_clean():
    res = schedule.explore(
        _gauge_writer_body(lambda g: g.peek_locked_properly()),
        schedules=32, seed=0, race=True, instrument=[(_Gauge, {"_v"})],
    )
    assert not res.failed, repr(res.failure)


def test_race_detector_notify_wake_edge_is_sound():
    """Condition handoff: consumer reads fields the producer wrote,
    ordered only by notify⇒wake + lock edges — must NOT be a race."""

    class Box:
        def __init__(self):
            self._lock = sanitizer.make_lock("fixture.box")
            self._cond = sanitizer.make_condition("fixture.box", self._lock)
            self._full = False  # guarded by: _lock
            self._item = None   # guarded by: _lock

        def put(self, v):
            with self._cond:
                self._item = v
                self._full = True
                self._cond.notify()

        def take(self):
            with self._cond:
                while not self._full:
                    self._cond.wait()
                v = self._item
                self._full = False
            return v

    def body():
        b = Box()
        out = []

        def consumer():
            out.append(b.take())

        t = threading.Thread(target=consumer)
        t.start()
        b.put(42)
        t.join()
        assert out == [42], out

    res = schedule.explore(body, schedules=64, seed=0, race=True,
                           instrument=[(Box, {"_full", "_item"})])
    assert not res.failed, repr(res.failure)


def test_race_detector_inline_suppression():
    class Suppressed:
        def __init__(self):
            self._lock = sanitizer.make_lock("fixture.sup")
            self._v = 0  # guarded by: _lock

        def set(self, v):
            with self._lock:
                self._v = v

        def peek(self):
            return self._v  # race-ok deliberate monotone read

    def body():
        s = Suppressed()

        def w():
            s.set(1)

        t = threading.Thread(target=w)
        t.start()
        s.peek()
        t.join()

    res = schedule.explore(body, schedules=16, seed=0, race=True,
                           instrument=[(Suppressed, {"_v"})])
    assert not res.failed, repr(res.failure)


def test_race_inventory_covers_guarded_classes():
    inv = race.inventory()
    fields = inv[
        ("llm_consensus_tpu.serve.admission", "AdmissionController")
    ]
    assert "_queue" in fields and "_draining" in fields
    assert ("llm_consensus_tpu.engine.handoff", "KVHandoff") in inv


def test_live_race_detector_on_sanitizer_locks():
    """Live (non-scheduler) mode: SanLock acquire/release feed the
    detector, so an off-lock read after a real thread join (no HB edge
    in live mode) is flagged deterministically, while a lock-protected
    read is not."""
    prev = sanitizer.monitor()
    sanitizer.install(sanitizer.LockMonitor())
    det = race.RaceDetector()
    try:
        race.attach(det, extra=[(_Gauge, {"_v"})])
        # Live mode has no fork/join edges (no Thread interception), so
        # publish the __init__ writes through the lock before spawning.
        g = _Gauge()
        with g._lock:
            pass
        t = threading.Thread(target=lambda: g.set(5))
        t.start()
        t.join()
        g.peek()  # off-lock, never joined the worker's clock — racy
        assert len(det.races) == 1
        assert det.races[0]["label"] == "_Gauge._v"
        g2 = _Gauge()
        with g2._lock:
            pass
        t2 = threading.Thread(target=lambda: g2.set(6))
        t2.start()
        t2.join()
        g2.peek_locked_properly()  # joins the lock clock — ordered
        assert len(det.races) == 1  # no new race
    finally:
        race.detach()
        sanitizer.install(prev)


# ---------------------------------------------------------------------------
# cooperative primitives: modeled timeouts, events, budget

def test_event_polling_loop_explores_without_sleeping():
    def body():
        stop = sanitizer.make_event("fixture.stop")
        ticks = [0]

        def looper():
            while not stop.wait(0.25):
                ticks[0] += 1
                if ticks[0] > 100:
                    raise AssertionError("stop never observed")

        t = threading.Thread(target=looper)
        t.start()
        stop.set()
        t.join()

    res = schedule.explore(body, schedules=16, seed=0, race=False)
    assert not res.failed, repr(res.failure)


def test_timed_lock_acquire_models_both_outcomes():
    def body():
        lock = sanitizer.make_lock("fixture.timed")
        got = []

        def contender():
            got.append(lock.acquire(timeout=0.5))
            if got[-1]:
                lock.release()

        with lock:
            t = threading.Thread(target=contender)
            t.start()
            # hold while the contender races its timed acquire
        t.join()
        assert got[0] in (True, False)

    res = schedule.explore(body, schedules=24, seed=0, race=False)
    assert not res.failed, repr(res.failure)


def test_step_budget_catches_unbounded_loops():
    def body():
        stop = sanitizer.make_event("fixture.never")

        def looper():
            while not stop.wait(0.1):
                pass  # never stopped — livelock by construction

        t = threading.Thread(target=looper)
        t.start()
        t.join()  # untimed: the looper spins forever on modeled timeouts

    res = schedule.explore(body, schedules=1, seed=0, race=False,
                           max_steps=500)
    assert res.failed
    assert isinstance(res.failure.exc, schedule.ScheduleBudget)


def test_non_reentrant_self_acquire_is_a_deadlock():
    """Re-acquiring a non-reentrant lock you own is a guaranteed wedge
    on the real threading.Lock — the model checker must report it, not
    silently grant the lock."""

    def body():
        lock = sanitizer.make_lock("fixture.self")
        with lock:
            with lock:  # self-deadlock on a non-reentrant lock
                pass

    res = schedule.explore(body, schedules=4, seed=0, race=False)
    assert res.failed
    assert isinstance(res.failure.exc, schedule.DeadlockError)
    # Non-blocking and timed forms model the real semantics instead.
    def body2():
        lock = sanitizer.make_lock("fixture.self2")
        with lock:
            assert lock.acquire(blocking=False) is False
            assert lock.acquire(timeout=0.1) is False

    res2 = schedule.explore(body2, schedules=4, seed=0, race=False)
    assert not res2.failed, repr(res2.failure)


def test_live_rlock_feeds_race_detector_hb_edges():
    """SanRLock acquire/release must carry the lock-clock join, or
    every happens-before edge through an RLock is lost and correctly
    locked accesses false-positive."""
    prev = sanitizer.monitor()
    sanitizer.install(sanitizer.LockMonitor())
    det = race.RaceDetector()
    sanitizer.set_race_detector(det)
    try:
        class RGauge:
            def __init__(self):
                self._lock = sanitizer.make_rlock("fixture.rgauge")
                self._v = 0  # guarded by: _lock

        race.attach(det, extra=[(RGauge, {"_v"})])
        g = RGauge()
        with g._lock:
            pass  # publish init writes through the rlock clock
        def w():
            with g._lock:
                with g._lock:  # reentrant: outermost pair only
                    g._v = 5
        t = threading.Thread(target=w)
        t.start()
        t.join()
        with g._lock:
            _ = g._v  # joins the rlock clock — ordered, no race
        assert det.races == [], det.races
    finally:
        race.detach()
        sanitizer.set_race_detector(None)
        sanitizer.install(prev)


def test_rlock_reentrancy_under_scheduler():
    def body():
        rl = sanitizer.make_rlock("fixture.rl")
        out = []

        def worker():
            with rl:
                with rl:  # reentrant
                    out.append(1)

        t = threading.Thread(target=worker)
        t.start()
        with rl:
            out.append(2)
        t.join()
        assert sorted(out) == [1, 2]

    res = schedule.explore(body, schedules=16, seed=0, race=False)
    assert not res.failed, repr(res.failure)


def test_scheduler_mode_assert_held_still_works():
    """assert_held integrates with the session's monitor: *_locked
    helpers keep their runtime guard under the model checker."""
    violations = []

    def body():
        lock = sanitizer.make_lock("fixture.ah")
        with lock:
            assert sanitizer.assert_held(lock)
        sanitizer.assert_held(lock)  # off-lock: records a violation
        mon = sanitizer.monitor()
        violations.append(len(mon.report()["violations"]))

    res = schedule.explore(body, schedules=1, seed=0, race=False)
    assert not res.failed, repr(res.failure)
    assert violations == [1]


# ---------------------------------------------------------------------------
# live SanCondition bookkeeping (the PR-15 wait/notify fix)

def test_san_condition_wait_mints_no_fresh_edges():
    """The wait-reacquire re-enters the held stack without recording
    (held → acquired) edges: across a notify/wake cycle under an outer
    lock, the edge set is exactly what the FIRST acquisition recorded,
    and the held stack stays exact (release after wait works)."""
    prev = sanitizer.monitor()
    mon = sanitizer.LockMonitor()
    sanitizer.install(mon)
    try:
        outer = sanitizer.make_lock("test.outer")
        inner = sanitizer.make_lock("test.inner")
        cond = sanitizer.make_condition("test.inner", inner)
        assert isinstance(cond, sanitizer.SanCondition)
        state = {"go": False}

        def waiter():
            with outer:
                with cond:
                    edges_before = len(mon.report()["edges"])
                    while not state["go"]:
                        cond.wait(timeout=5)
                    # Reacquire happened; no new ordering edges minted.
                    assert len(mon.report()["edges"]) == edges_before
                    assert mon.holds(inner)
                assert mon.holds(outer)

        t = threading.Thread(target=waiter)
        t.start()
        import time

        time.sleep(0.05)
        with cond:
            state["go"] = True
            cond.notify()
        t.join(timeout=5)
        assert not t.is_alive()
        rep = mon.report()
        # Exactly the one programmer-chosen ordering: outer → inner.
        assert ("test.outer", "test.inner") in [tuple(e) for e in rep["edges"]]
        assert not rep["cycles"]
        assert not rep["violations"]
    finally:
        sanitizer.install(prev)


def test_san_condition_notify_wake_feeds_live_detector():
    prev = sanitizer.monitor()
    sanitizer.install(sanitizer.LockMonitor())
    det = race.RaceDetector()
    sanitizer.set_race_detector(det)
    try:
        lock = sanitizer.make_lock("test.pc")
        cond = sanitizer.make_condition("test.pc", lock)
        ready = []

        def waiter():
            with cond:
                got = cond.wait(timeout=5)
                ready.append(got)

        t = threading.Thread(target=waiter)
        t.start()
        import time

        time.sleep(0.05)
        with cond:
            cond.notify()
        t.join(timeout=5)
        assert ready == [True]
        # The notify recorded a sync clock for this condition; the wake
        # joined it (observable: the sync entry exists).
        assert id(cond) in det._sync
    finally:
        sanitizer.set_race_detector(None)
        sanitizer.install(prev)


# ---------------------------------------------------------------------------
# real protocol fixtures, via the pytest marker integration

@pytest.mark.schedules(20)
def test_admission_protocol_model_checked():
    admission_preempt_vs_drain()


@pytest.mark.schedules(20)
def test_handoff_protocol_model_checked():
    handoff_crash_fallback()


@pytest.mark.schedules(10)
def test_supervisor_protocol_model_checked():
    supervisor_restart_vs_submit()


@pytest.mark.schedules(20)
def test_scale_down_protocol_model_checked():
    scale_down_vs_resident_stream()


@pytest.mark.schedules(20)
def test_swap_protocol_model_checked():
    swap_vs_resident_stream()


@pytest.mark.schedules(20)
def test_quarantine_protocol_model_checked():
    quarantine_vs_resident_stream()
