"""Static analysis framework + checkers + runtime sanitizer
(llm_consensus_tpu/analysis/).

Golden-finding tests drive each checker over small fixture projects
written to tmp_path — one clean module and one seeded with each
violation class — then assert the exact finding codes and details.
Baseline behavior (grandfathering, staleness, update) and the
``lint-ok`` inline suppression are covered against the same fixtures.
The sanitizer half proves the lock-order monitor reports a deliberately
constructed A→B / B→A cycle, that ``assert_held`` records off-lock
guarded access, and that everything is pass-through when disabled.

The last test runs the full checker suite over THIS repository with the
checked-in baseline — the same gate CI runs — so a tree change that
introduces a finding fails here before it fails the analysis job.
"""

from __future__ import annotations

import textwrap
import threading
from pathlib import Path

import pytest

from llm_consensus_tpu.analysis import core, sanitizer
from llm_consensus_tpu.analysis.core import (
    Project, apply_baseline, load_baseline, run_checkers, save_baseline,
)

REPO_ROOT = Path(__file__).resolve().parents[1]


def _mini_project(
    tmp_path: Path,
    files: dict,
    readme: str = "",
    obs_doc: str = "",
) -> Project:
    """A throwaway project tree: ``files`` maps package-relative paths
    to source text; README/docs are optional."""
    pkg = tmp_path / "llm_consensus_tpu"
    for rel, src in files.items():
        p = pkg / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    # Package markers so rglob mirrors the real layout.
    for d in set(p.parent for p in pkg.rglob("*.py")) | {pkg}:
        init = d / "__init__.py"
        if not init.exists():
            init.write_text("")
    (tmp_path / "README.md").write_text(readme)
    docs = tmp_path / "docs"
    docs.mkdir(exist_ok=True)
    (docs / "observability.md").write_text(obs_doc)
    return Project(tmp_path)


def _codes(findings) -> list:
    return sorted(f.code for f in findings)


def _only(findings, code) -> list:
    return [f for f in findings if f.code == code]


# ---------------------------------------------------------------------------
# guarded-state (GS)

CLEAN_GUARDED = """
    import threading

    class Pool:
        def __init__(self):
            self._lock = threading.Lock()
            self._work = threading.Condition(self._lock)
            self._free = []  # guarded by: _lock
            self._stats = {}  # guarded by: _lock

        def take(self):
            with self._lock:
                return self._free.pop()

        def via_alias(self):
            with self._work:
                self._stats["x"] = 1

        def _drain_locked(self):
            return list(self._free)
"""

DIRTY_GUARDED = """
    import threading

    class Pool:
        def __init__(self):
            self._lock = threading.Lock()
            self._free = []  # guarded by: _lock
            self._stats = {}  # guarded by: _missing

        def bad_read(self):
            return len(self._free)

        def bad_write(self):
            self._free.append(1)

        def excused(self):
            return bool(self._free)  # lint-ok: GS01 watchdog read
"""


def test_guarded_state_clean_and_dirty(tmp_path):
    proj = _mini_project(tmp_path, {"mod.py": CLEAN_GUARDED})
    assert run_checkers(proj, only={"guarded-state"}) == []
    proj = _mini_project(tmp_path / "d", {"mod.py": DIRTY_GUARDED})
    found = run_checkers(proj, only={"guarded-state"})
    gs01 = _only(found, "GS01")
    assert sorted(f.detail for f in gs01) == [
        "Pool.bad_read :: _free",
        "Pool.bad_write :: _free",
    ]
    # The annotation naming a nonexistent lock is its own finding.
    assert [f.detail for f in _only(found, "GS02")] == [
        "Pool :: _stats :: _missing"
    ]


def test_guarded_state_sanitizer_factories_count_as_locks(tmp_path):
    src = """
    from llm_consensus_tpu.analysis import sanitizer

    class C:
        def __init__(self):
            self._cond = sanitizer.make_condition("c")
            self._n = 0  # guarded by: _cond

        def ok(self):
            with self._cond:
                self._n += 1

        def bad(self):
            return self._n
    """
    proj = _mini_project(tmp_path, {"mod.py": src})
    found = run_checkers(proj, only={"guarded-state"})
    assert [f.detail for f in found] == ["C.bad :: _n"]


# ---------------------------------------------------------------------------
# tracer hygiene (TH)

TRACER_FIXTURE = """
    import os
    import random
    import threading
    import time
    from functools import partial

    import jax

    def _helper(x):
        time.sleep(0.1)
        return x

    @partial(jax.jit, static_argnames=("k",))
    def seeded(x, k):
        t = time.monotonic()
        r = random.random()
        e = os.environ.get("HOME", "")
        lock = threading.Lock()
        v = x.item()
        f = float(x)
        return _helper(x)

    def host_only(x):
        # Host code may do all of this freely — not jit-reachable.
        time.sleep(0.0)
        return random.random()

    def wrapped(x):
        return x * 2

    _prog = jax.jit(wrapped)
"""


def test_tracer_hygiene_codes_and_reachability(tmp_path):
    proj = _mini_project(tmp_path, {"mod.py": TRACER_FIXTURE})
    found = run_checkers(proj, only={"tracer-hygiene"})
    by_fn: dict = {}
    for f in found:
        by_fn.setdefault(f.detail.split(" :: ")[0], set()).add(f.code)
    # The decorated root carries every violation class.
    assert by_fn["seeded"] == {"TH01", "TH02", "TH03", "TH04", "TH05"}
    # Reachability: the helper called FROM the jitted root is flagged.
    assert by_fn["_helper"] == {"TH01"}
    # jax.jit(fn) call-site roots are tracked; clean, so absent.
    assert "wrapped" not in by_fn
    # Host-only functions are never flagged.
    assert "host_only" not in by_fn


def test_tracer_hygiene_knob_reads_flagged(tmp_path):
    src = """
    import jax
    from llm_consensus_tpu.utils import knobs

    @jax.jit
    def prog(x):
        if knobs.get_bool("LLMC_W8A8"):
            return x * 2
        return x
    """
    proj = _mini_project(tmp_path, {"mod.py": src})
    found = run_checkers(proj, only={"tracer-hygiene"})
    assert _codes(found) == ["TH03"]


# ---------------------------------------------------------------------------
# knob registry (KR)

KNOBS_FIXTURE = """
    REGISTRY = {}
    def _k(name, kind, default, subsystem, doc):
        REGISTRY[name] = (kind, default, subsystem, doc)
    _k("LLMC_ALPHA", "int", 4, "engine", "documented and used")
    _k("LLMC_ORPHAN", "str", "", "engine", "declared but undocumented")
"""


def test_knob_registry_drift_directions(tmp_path):
    proj = _mini_project(
        tmp_path,
        {
            "utils/knobs.py": KNOBS_FIXTURE,
            "mod.py": """
            import os
            from llm_consensus_tpu.utils import knobs

            RAW = os.environ.get("LLMC_ALPHA", "")
            TYPO = knobs.get_int("LLMC_TPYO")
            OK = knobs.get_int("LLMC_ALPHA")
            """,
        },
        readme="Knobs: `LLMC_ALPHA` and the stale `LLMC_GHOST`.\n",
    )
    found = run_checkers(proj, only={"knob-registry"})
    assert [f.detail for f in _only(found, "KR01")] == [
        "LLMC_ALPHA :: raw-read"
    ]
    assert [f.detail for f in _only(found, "KR02")] == [
        "LLMC_TPYO :: undeclared"
    ]
    assert [f.detail for f in _only(found, "KR03")] == [
        "LLMC_ORPHAN :: undocumented"
    ]
    # Doc-only names: the typo'd getter name never reaches docs, but the
    # stale README mention does.
    kr04 = {f.detail for f in _only(found, "KR04")}
    assert kr04 == {"LLMC_GHOST :: doc-only"}


def test_knob_registry_env_writes_need_declaration_only(tmp_path):
    proj = _mini_project(
        tmp_path,
        {
            "utils/knobs.py": KNOBS_FIXTURE,
            "mod.py": """
            import os

            os.environ["LLMC_ALPHA"] = "1"       # write: legal
            os.environ["LLMC_UNKNOWN"] = "1"     # write of undeclared
            """,
        },
        readme="`LLMC_ALPHA` `LLMC_ORPHAN`\n",
    )
    found = run_checkers(proj, only={"knob-registry"})
    assert _codes(found) == ["KR02"]
    assert found[0].detail == "LLMC_UNKNOWN :: undeclared"


# ---------------------------------------------------------------------------
# fault coverage (FC)

PLAN_FIXTURE = """
    SITE_KINDS = {
        "prefill": ("prefill_oom",),
        "serve": ("queue_full", "slow_admit"),
    }
"""


def test_fault_coverage_gap_detection(tmp_path):
    proj = _mini_project(tmp_path, {"faults/plan.py": PLAN_FIXTURE})
    tests = tmp_path / "tests"
    tests.mkdir()
    (tests / "test_x.py").write_text(
        'PLAN = "prefill_oom@step=1,queue_full"\n'
    )
    found = run_checkers(proj, only={"fault-coverage"})
    assert [f.detail for f in found] == ["serve :: slow_admit"]
    # Cover it via a dryrun lane instead of a test: also accepted.
    (tmp_path / "__graft_entry__.py").write_text('X = "slow_admit@s=1"\n')
    proj = Project(tmp_path)
    assert run_checkers(proj, only={"fault-coverage"}) == []


def test_fault_coverage_unparsable_is_a_finding(tmp_path):
    proj = _mini_project(
        tmp_path, {"faults/plan.py": "SITE_KINDS = make()\n"}
    )
    found = run_checkers(proj, only={"fault-coverage"})
    assert _codes(found) == ["FC02"]


# ---------------------------------------------------------------------------
# metrics docs (MD)

PROM_FIXTURE = """
    FAMILIES = {
        "llmc_ttft_seconds": "histogram",
        "llmc_declared_unused_total": "counter",
        "llmc_stat": "gauge",
    }
"""

GATEWAY_FIXTURE = """
    class GW:
        def metricsz(self):
            gauges = {"rogue_gauge": 1.0}
            self.live.observe("ttft", 0.1, outcome="ok")
            return gauges
"""


def test_metrics_docs_three_way_crosscheck(tmp_path):
    proj = _mini_project(
        tmp_path,
        {"obs/prom.py": PROM_FIXTURE, "serve/gateway.py": GATEWAY_FIXTURE},
        obs_doc="| `llmc_ttft_seconds` | ... |\n| `llmc_stat` | ... |\n"
                "| `llmc_phantom_total` | stale row |\n",
    )
    found = run_checkers(proj, only={"metrics-docs"})
    assert [f.detail for f in _only(found, "MD01")] == [
        "llmc_rogue_gauge :: undeclared"
    ]
    assert [f.detail for f in _only(found, "MD02")] == [
        "llmc_declared_unused_total :: undocumented"
    ]
    assert [f.detail for f in _only(found, "MD03")] == [
        "llmc_phantom_total :: doc-only"
    ]


# ---------------------------------------------------------------------------
# baseline + fingerprints

def test_baseline_grandfathers_and_reports_stale(tmp_path):
    proj = _mini_project(tmp_path, {"mod.py": DIRTY_GUARDED})
    found = run_checkers(proj, only={"guarded-state"})
    assert found
    bl = tmp_path / "baseline.txt"
    save_baseline(bl, found)
    # Every finding suppressed: the gate is green.
    rep = apply_baseline(found, load_baseline(bl))
    assert rep.ok and len(rep.grandfathered) == len(found)
    # A NEW finding still fails even with the old ones grandfathered.
    extra = core.Finding("GS01", "llm_consensus_tpu/mod.py", 1,
                         "new", "Pool.newer :: _free")
    rep = apply_baseline(found + [extra], load_baseline(bl))
    assert not rep.ok and [f.detail for f in rep.new] == [
        "Pool.newer :: _free"
    ]
    # Fixing a finding leaves its entry stale — reported for removal.
    rep = apply_baseline(found[1:], load_baseline(bl))
    assert rep.ok and len(rep.stale) == 1


def test_fingerprints_are_line_independent(tmp_path):
    proj = _mini_project(tmp_path, {"mod.py": DIRTY_GUARDED})
    fp1 = {f.fingerprint for f in run_checkers(proj, only={"guarded-state"})}
    shifted = "\n\n\n# shifted by a comment block\n" + textwrap.dedent(
        DIRTY_GUARDED
    )
    (tmp_path / "llm_consensus_tpu" / "mod.py").write_text(shifted)
    proj = Project(tmp_path)
    fp2 = {f.fingerprint for f in run_checkers(proj, only={"guarded-state"})}
    assert fp1 == fp2


def test_cli_exit_codes(tmp_path):
    from llm_consensus_tpu.analysis.__main__ import main

    _mini_project(tmp_path, {"mod.py": DIRTY_GUARDED})
    bl = tmp_path / "bl.txt"
    args = ["--root", str(tmp_path), "--baseline", str(bl),
            "--checks", "guarded-state"]
    assert main(args) == 1  # findings, no baseline
    assert main(args + ["--update-baseline"]) == 0
    assert main(args) == 0  # grandfathered
    assert main(args + ["--no-baseline"]) == 1
    assert main(["--root", str(tmp_path / "nope")]) == 2
    assert main(args[:2] + ["--checks", "bogus"]) == 2


# ---------------------------------------------------------------------------
# runtime sanitizer

@pytest.fixture()
def monitor():
    m = sanitizer.LockMonitor()
    sanitizer.install(m)
    yield m
    sanitizer.reset()


def test_factories_plain_when_disabled(monkeypatch):
    monkeypatch.delenv("LLMC_SANITIZE", raising=False)
    sanitizer.reset()
    try:
        assert not sanitizer.enabled()
        assert isinstance(sanitizer.make_lock("x"), type(threading.Lock()))
        assert sanitizer.assert_held(threading.Lock())  # no-op, True
        assert sanitizer.report() is None
    finally:
        sanitizer.reset()


def test_lock_order_cycle_detected(monitor):
    a = sanitizer.make_lock("A")
    b = sanitizer.make_lock("B")
    assert isinstance(a, sanitizer.SanLock)

    def ab():
        with a:
            with b:
                pass

    t = threading.Thread(target=ab)
    t.start()
    t.join()
    # Sequentially (no real deadlock), the opposite order on this thread.
    with b:
        with a:
            pass
    cycles = monitor.cycles()
    assert cycles and set(cycles[0]) >= {"A", "B"}, cycles
    rep = monitor.report()
    assert ("A", "B") in rep["edges"] and ("B", "A") in rep["edges"]
    assert rep["cycles"]


def test_consistent_order_reports_no_cycle(monitor):
    a = sanitizer.make_lock("A")
    b = sanitizer.make_lock("B")
    for _ in range(3):
        with a:
            with b:
                pass
    assert monitor.cycles() == []
    assert monitor.violations == []


def test_assert_held_records_violation(monitor):
    lock = sanitizer.make_lock("guarded")
    with lock:
        assert sanitizer.assert_held(lock)
    assert not sanitizer.assert_held(lock)
    assert len(monitor.violations) == 1
    assert "guarded" in monitor.violations[0]["what"]


def test_condition_wait_keeps_bookkeeping_exact(monitor):
    cond = sanitizer.make_condition("C")
    inner = cond._lock
    assert isinstance(inner, sanitizer.SanLock)
    released_during_wait = []

    def waiter():
        with cond:
            cond.wait(timeout=5)
            released_during_wait.append(monitor.holds(inner))

    t = threading.Thread(target=waiter)
    t.start()
    # Let the waiter release the lock inside wait(), then notify.
    deadline = threading.Event()
    for _ in range(200):
        if cond._lock.locked():
            deadline.wait(0.01)
        else:
            break
    with cond:
        assert sanitizer.assert_held(cond)
        cond.notify_all()
    t.join(5)
    assert not t.is_alive()
    # Re-acquired through the instrumented path on wakeup.
    assert released_during_wait == [True]
    assert monitor.violations == []


def test_rlock_reentrancy_no_self_edges(monitor):
    r = sanitizer.make_rlock("R")
    with r:
        with r:
            pass
    rep = monitor.report()
    assert ("R", "R") not in rep["edges"]
    assert monitor.cycles() == []


# ---------------------------------------------------------------------------
# raw-primitives (SA)

def test_raw_primitives_flagged_across_import_forms(tmp_path):
    dirty = """
    import threading
    import threading as _t
    from threading import Condition

    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self._rl = _t.RLock()
            self._cond = Condition()
            self._ev = threading.Event()
            self._tls = threading.local()   # not restricted
            self._sem = threading.Semaphore()  # not restricted
    """
    proj = _mini_project(tmp_path, {"mod.py": dirty})
    found = _only(run_checkers(proj, only={"raw-primitives"}), "SA01")
    assert len(found) == 4
    assert {f.detail.split(" :: ")[0] for f in found} == {
        "threading.Lock", "threading.RLock", "threading.Condition",
        "threading.Event",
    }
    for f in found:
        assert "sanitizer.make_" in f.message


def test_raw_primitives_factories_allowlist_and_suppression(tmp_path):
    clean = """
    from llm_consensus_tpu.analysis import sanitizer

    class C:
        def __init__(self):
            self._lock = sanitizer.make_lock("c")
            self._cond = sanitizer.make_condition("c", self._lock)
            self._ev = sanitizer.make_event("c")
    """
    proj = _mini_project(tmp_path, {"mod.py": clean})
    assert run_checkers(proj, only={"raw-primitives"}) == []
    # The instrumentation substrate itself is allowlisted …
    proj = _mini_project(
        tmp_path / "a",
        {"analysis/impl.py": "import threading\nL = threading.Lock()\n"},
    )
    assert run_checkers(proj, only={"raw-primitives"}) == []
    # … and an inline lint-ok suppresses a deliberate site.
    proj = _mini_project(
        tmp_path / "s",
        {"mod.py": (
            "import threading\n"
            "L = threading.Lock()  # lint-ok: SA01 bootstrap\n"
        )},
    )
    assert run_checkers(proj, only={"raw-primitives"}) == []


def test_raw_primitives_repo_grep_is_empty():
    """The acceptance-criterion grep, as a test: no raw primitive
    construction outside analysis/ anywhere in the package."""
    import re

    pat = re.compile(r"threading\.(Lock|RLock|Condition|Event)\(")
    offenders = []
    pkg = REPO_ROOT / "llm_consensus_tpu"
    for p in pkg.rglob("*.py"):
        rel = p.relative_to(REPO_ROOT).as_posix()
        if rel.startswith("llm_consensus_tpu/analysis/"):
            continue
        for i, line in enumerate(p.read_text().splitlines(), 1):
            if pat.search(line) and "lint-ok: SA01" not in line:
                offenders.append(f"{rel}:{i}: {line.strip()}")
    assert offenders == []


def test_render_report_carries_cycle_edge_stacks(monitor):
    a = sanitizer.make_lock("A")
    b = sanitizer.make_lock("B")

    def ab():
        with a:
            with b:
                pass

    t = threading.Thread(target=ab)
    t.start()
    t.join()
    with b:
        with a:
            pass
    rep = monitor.report()
    text = sanitizer.render_report(rep)
    assert "lock-order cycle" in text
    assert "edge A -> B first acquired at:" in text
    assert "edge B -> A first acquired at:" in text
    # The first-observed stacks point at THIS test, not wait internals.
    assert "test_analysis" in text


# ---------------------------------------------------------------------------
# the real tree, under the real baseline — the CI gate, as a test

def test_repository_is_analysis_clean():
    proj = Project(REPO_ROOT)
    findings = run_checkers(proj)
    rep = apply_baseline(findings, load_baseline(core.BASELINE_DEFAULT))
    assert rep.ok, "new analysis findings:\n" + "\n".join(
        f.render() for f in rep.new
    )
