"""Registry tests (reference: registry.go:10-53)."""

import threading

import pytest

from llm_consensus_tpu.providers import ProviderFunc, Registry, Response, UnknownModelError


def fake(name="p"):
    return ProviderFunc(lambda ctx, req: Response(req.model, "ok", name))


def test_register_and_get():
    r = Registry()
    p = fake()
    r.register("m1", p)
    assert r.get("m1") is p
    assert "m1" in r


def test_get_unknown_model_lists_available():
    r = Registry()
    r.register("m1", fake())
    r.register("m2", fake())
    with pytest.raises(UnknownModelError) as exc:
        r.get("nope")
    assert "nope" in str(exc.value)
    assert "m1" in str(exc.value) and "m2" in str(exc.value)


def test_models_sorted():
    r = Registry()
    for m in ["b", "a", "c"]:
        r.register(m, fake())
    assert r.models() == ["a", "b", "c"]


def test_concurrent_register_and_get():
    # The reference guards the map with an RWMutex (registry.go:11); stress
    # the same guarantee.
    r = Registry()
    errors = []

    def writer(i):
        for j in range(100):
            r.register(f"m{i}-{j}", fake())

    def reader():
        for _ in range(200):
            try:
                r.models()
            except Exception as e:  # pragma: no cover
                errors.append(e)

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(4)]
    threads += [threading.Thread(target=reader) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert len(r.models()) == 400
