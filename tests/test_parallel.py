"""Parallel layer tests on the 8-device virtual CPU mesh.

Correctness bar: a TP/EP-sharded forward must produce the same numbers as
the unsharded single-device forward (GSPMD only changes placement), and a
sharded Engine must stream the same tokens as an unsharded one.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.engine import Engine, SamplingParams
from llm_consensus_tpu.models import forward, init_kv_cache, init_params
from llm_consensus_tpu.models.config import get_config
from llm_consensus_tpu.parallel import (
    best_tp,
    cache_specs,
    carve_slices,
    make_mesh,
    make_shard_fn,
    param_specs,
    plan_panel,
    shard_pytree,
)


def _forward_logits(cfg, params, tokens):
    logits, _ = forward(params, cfg, tokens)
    return np.asarray(jax.device_get(logits), np.float32)


# -- mesh topology -----------------------------------------------------------


def test_make_mesh_shapes():
    mesh = make_mesh({"dp": 2, "tp": 4})
    assert mesh.shape == {"dp": 2, "tp": 4}
    mesh = make_mesh({"dp": -1, "tp": 2})
    assert mesh.shape == {"dp": 4, "tp": 2}
    with pytest.raises(ValueError):
        make_mesh({"dp": 3, "tp": 4})


def test_carve_slices_disjoint():
    devs = jax.devices()
    slices = carve_slices(devs, [4, 2, 2])
    assert [len(s) for s in slices] == [4, 2, 2]
    seen = {d.id for s in slices for d in s}
    assert len(seen) == 8
    with pytest.raises(ValueError):
        carve_slices(devs, [8, 1])


def test_best_tp_respects_gqa():
    cfg = get_config("tiny-llama")  # n_kv_heads=2
    assert best_tp(cfg, 8) == 2
    assert best_tp(cfg, 1) == 1
    cfg = get_config("tiny-gemma")  # n_kv_heads=4 (MHA)
    assert best_tp(cfg, 8) == 4


def test_plan_panel_disjoint_slices():
    panel = [(n, get_config("tiny-llama")) for n in ("a", "b", "c")]
    judge = ("j", get_config("tiny-gemma"))
    plan = plan_panel(panel, judge)
    assert [p.role for p in plan.placements] == ["panel"] * 3 + ["judge"]
    judge_devs = {d.id for d in plan.for_model("j").mesh.devices.flat}
    for name in ("a", "b", "c"):
        panel_devs = {d.id for d in plan.for_model(name).mesh.devices.flat}
        assert not (judge_devs & panel_devs), "judge and panel slices overlap"


# -- TP / EP numerical equivalence ------------------------------------------


@pytest.mark.parametrize("preset", ["tiny-llama", "tiny-qwen2", "tiny-gemma"])
def test_tp_forward_matches_unsharded(preset):
    cfg = get_config(preset)
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)

    want = _forward_logits(cfg, params, tokens)

    tp = best_tp(cfg, 4)
    mesh = make_mesh({"dp": 2, "tp": tp}, jax.devices()[: 2 * tp])
    sharded = shard_pytree(params, param_specs(cfg, mesh), mesh)
    got = _forward_logits(cfg, sharded, tokens)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_ep_moe_forward_matches_unsharded():
    cfg = get_config("tiny-mixtral")  # 4 experts
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    want = _forward_logits(cfg, params, tokens)

    mesh = make_mesh({"dp": 1, "ep": 4, "tp": 2})
    sharded = shard_pytree(params, param_specs(cfg, mesh), mesh)
    got = _forward_logits(cfg, sharded, tokens)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_param_specs_degrade_when_indivisible():
    cfg = get_config("tiny-llama")  # n_kv_heads=2, so kv dim = 64
    mesh = make_mesh({"dp": 1, "tp": 8})
    specs = param_specs(cfg, mesh)
    # kv projection (2 heads * 32 = 64) is divisible by 8; d_ff=256 too —
    # but a 3-kv-head config would not be. Check sanitizer via vocab:
    tiny = get_config("tiny-llama", vocab_size=510)  # not divisible by 8
    specs = param_specs(tiny, mesh)
    assert specs["embed"] == jax.sharding.PartitionSpec(None, None)


# -- sharded decode through the Engine --------------------------------------


def test_sharded_engine_matches_unsharded_tokens():
    cfg = get_config("tiny-llama")
    base = Engine(cfg, seed=3, dtype=jnp.float32)
    want = base.generate("consensus", SamplingParams(max_new_tokens=12))

    mesh = make_mesh({"dp": 1, "tp": 2}, jax.devices()[:2])
    sharded = Engine(
        cfg, seed=3, dtype=jnp.float32, shard_fn=make_shard_fn(cfg, mesh)
    )
    got = sharded.generate("consensus", SamplingParams(max_new_tokens=12))
    assert got.token_ids == want.token_ids
    assert got.text == want.text


def test_cache_specs_match_cache_tree():
    cfg = get_config("tiny-llama")
    mesh = make_mesh({"dp": 1, "tp": 2}, jax.devices()[:2])
    cache = init_kv_cache(cfg, batch=1)
    sharded = shard_pytree(cache, cache_specs(cfg, mesh), mesh)
    assert sharded["k"].shape == cache["k"].shape


def test_two_engines_on_disjoint_slices():
    """Panel semantics: two sharded engines coexist and agree with baselines."""
    slices = carve_slices(jax.devices(), [2, 2])
    cfg_a, cfg_b = get_config("tiny-llama"), get_config("tiny-qwen2")
    mesh_a = make_mesh({"dp": 1, "tp": 2}, slices[0])
    mesh_b = make_mesh({"dp": 1, "tp": 2}, slices[1])
    eng_a = Engine(cfg_a, seed=1, dtype=jnp.float32, shard_fn=make_shard_fn(cfg_a, mesh_a))
    eng_b = Engine(cfg_b, seed=2, dtype=jnp.float32, shard_fn=make_shard_fn(cfg_b, mesh_b))
    ra = eng_a.generate("hello", SamplingParams(max_new_tokens=8))
    rb = eng_b.generate("hello", SamplingParams(max_new_tokens=8))
    base_a = Engine(cfg_a, seed=1, dtype=jnp.float32).generate(
        "hello", SamplingParams(max_new_tokens=8)
    )
    base_b = Engine(cfg_b, seed=2, dtype=jnp.float32).generate(
        "hello", SamplingParams(max_new_tokens=8)
    )
    assert ra.token_ids == base_a.token_ids
    assert rb.token_ids == base_b.token_ids
