"""HTTP provider tests against a local fake SSE server.

Coverage the reference lacks (SURVEY.md §4): its WithXBaseURL options exist
precisely for pointing providers at a test server but are never used. Here
each provider is exercised for auth headers, request bodies, streaming
parsing, non-stream extraction, and error paths.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from llm_consensus_tpu.providers import Request
from llm_consensus_tpu.providers.anthropic import AnthropicProvider
from llm_consensus_tpu.providers.google import GoogleProvider
from llm_consensus_tpu.providers.http_sse import HTTPError
from llm_consensus_tpu.providers.openai import OpenAIProvider
from llm_consensus_tpu.utils import Context


class FakeAPI(BaseHTTPRequestHandler):
    """Scriptable endpoint: the test sets handler.respond(path, body) -> (status, headers, payload)."""

    respond = None  # set per-test
    requests: list = []

    def do_POST(self):
        length = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(length) or b"{}")
        FakeAPI.requests.append(
            {"path": self.path, "headers": {k.lower(): v for k, v in self.headers.items()}, "body": body}
        )
        status, payload = FakeAPI.respond(self.path, body)
        self.send_response(status)
        is_sse = isinstance(payload, list)
        self.send_header(
            "Content-Type", "text/event-stream" if is_sse else "application/json"
        )
        self.end_headers()
        if is_sse:
            for line in payload:
                self.wfile.write((line + "\n").encode())
        else:
            self.wfile.write(json.dumps(payload).encode())

    def log_message(self, *args):
        pass


@pytest.fixture()
def fake_api():
    server = HTTPServer(("127.0.0.1", 0), FakeAPI)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    FakeAPI.requests = []
    yield f"http://127.0.0.1:{server.server_address[1]}"
    server.shutdown()
    thread.join()


CTX = Context.background


# -- OpenAI ------------------------------------------------------------------


def test_openai_requires_api_key(monkeypatch):
    monkeypatch.delenv("OPENAI_API_KEY", raising=False)
    with pytest.raises(RuntimeError, match="OPENAI_API_KEY"):
        OpenAIProvider()


def test_openai_query(fake_api):
    FakeAPI.respond = lambda path, body: (
        200,
        {
            "output": [
                {"content": [{"type": "output_text", "text": "four"}]},
                {"content": [{"type": "reasoning", "text": "skip"},
                             {"type": "output_text", "text": "!"}]},
            ]
        },
    )
    p = OpenAIProvider(api_key="sk-test", base_url=fake_api)
    resp = p.query(CTX(), Request(model="gpt-x", prompt="2+2?"))
    assert resp.content == "four!"
    assert resp.provider == "openai"
    assert resp.latency_ms >= 0
    req = FakeAPI.requests[0]
    assert req["path"] == "/responses"
    assert req["headers"]["authorization"] == "Bearer sk-test"
    assert req["body"] == {"model": "gpt-x", "input": "2+2?"}


def test_openai_stream(fake_api):
    FakeAPI.respond = lambda path, body: (
        200,
        [
            'data: {"type":"response.created"}',
            'data: {"type":"response.output_text.delta","delta":"fo"}',
            ": comment to skip",
            'data: {"type":"response.output_text.delta","delta":"ur"}',
            "data: not-json-is-skipped",
            "data: [DONE]",
            'data: {"type":"response.output_text.delta","delta":"IGNORED"}',
        ],
    )
    p = OpenAIProvider(api_key="sk-test", base_url=fake_api)
    chunks = []
    resp = p.query_stream(CTX(), Request(model="gpt-x", prompt="2+2?"), chunks.append)
    assert chunks == ["fo", "ur"]
    assert resp.content == "four"
    assert FakeAPI.requests[0]["body"]["stream"] is True


def test_openai_http_error_includes_body(fake_api):
    FakeAPI.respond = lambda path, body: (401, {"error": "bad key"})
    p = OpenAIProvider(api_key="sk-bad", base_url=fake_api)
    with pytest.raises(HTTPError, match="status 401"):
        p.query(CTX(), Request(model="m", prompt="p"))


# -- Anthropic ---------------------------------------------------------------


def test_anthropic_requires_api_key(monkeypatch):
    monkeypatch.delenv("ANTHROPIC_API_KEY", raising=False)
    with pytest.raises(RuntimeError, match="ANTHROPIC_API_KEY"):
        AnthropicProvider()


def test_anthropic_query(fake_api):
    FakeAPI.respond = lambda path, body: (
        200,
        {"content": [{"type": "text", "text": "hello"}, {"type": "text", "text": " there"}]},
    )
    p = AnthropicProvider(api_key="ak-test", base_url=fake_api)
    resp = p.query(CTX(), Request(model="claude-x", prompt="hi"))
    assert resp.content == "hello there"
    assert resp.provider == "anthropic"
    req = FakeAPI.requests[0]
    assert req["path"] == "/messages"
    assert req["headers"]["x-api-key"] == "ak-test"
    assert req["headers"]["anthropic-version"] == "2023-06-01"
    assert req["body"]["max_tokens"] == 4096
    assert req["body"]["messages"] == [{"role": "user", "content": "hi"}]


def test_anthropic_stream(fake_api):
    FakeAPI.respond = lambda path, body: (
        200,
        [
            'data: {"type":"message_start"}',
            'data: {"type":"content_block_delta","delta":{"type":"text_delta","text":"he"}}',
            'data: {"type":"content_block_delta","delta":{"type":"input_json_delta","partial_json":"x"}}',
            'data: {"type":"content_block_delta","delta":{"type":"text_delta","text":"llo"}}',
            'data: {"type":"message_stop"}',
        ],
    )
    p = AnthropicProvider(api_key="ak", base_url=fake_api)
    chunks = []
    resp = p.query_stream(CTX(), Request(model="claude-x", prompt="hi"), chunks.append)
    assert chunks == ["he", "llo"]
    assert resp.content == "hello"


# -- Google ------------------------------------------------------------------


def test_google_requires_api_key(monkeypatch):
    monkeypatch.delenv("GOOGLE_API_KEY", raising=False)
    with pytest.raises(RuntimeError, match="GOOGLE_API_KEY"):
        GoogleProvider()


def test_google_query_key_in_url_model_in_path(fake_api):
    FakeAPI.respond = lambda path, body: (
        200,
        {"candidates": [{"content": {"parts": [{"text": "bonjour"}]}}]},
    )
    p = GoogleProvider(api_key="gk-test", base_url=fake_api)
    resp = p.query(CTX(), Request(model="gemini-x", prompt="hi"))
    assert resp.content == "bonjour"
    assert resp.provider == "google"
    req = FakeAPI.requests[0]
    assert req["path"] == "/models/gemini-x:generateContent?key=gk-test"
    assert req["body"] == {"contents": [{"parts": [{"text": "hi"}]}]}


def test_google_stream_full_response_chunks(fake_api):
    FakeAPI.respond = lambda path, body: (
        200,
        [
            'data: {"candidates":[{"content":{"parts":[{"text":"bon"}]}}]}',
            'data: {"candidates":[]}',
            'data: {"candidates":[{"content":{"parts":[{"text":"jour"}]}}]}',
        ],
    )
    p = GoogleProvider(api_key="gk", base_url=fake_api)
    chunks = []
    resp = p.query_stream(CTX(), Request(model="gemini-x", prompt="hi"), chunks.append)
    assert chunks == ["bon", "jour"]
    assert resp.content == "bonjour"
    assert FakeAPI.requests[0]["path"].endswith(":streamGenerateContent?key=gk&alt=sse")


# -- shared behavior ---------------------------------------------------------


def test_cancelled_context_aborts_before_request(fake_api):
    FakeAPI.respond = lambda path, body: (200, {"content": []})
    p = AnthropicProvider(api_key="ak", base_url=fake_api)
    ctx = Context.background().with_cancel()
    ctx.cancel()
    with pytest.raises(Exception, match="context canceled"):
        p.query(ctx, Request(model="m", prompt="p"))
    assert FakeAPI.requests == []


def test_deadline_bounds_stream(fake_api):
    # Server stalls between events; an expired deadline must abort the loop.
    import time as _time

    def slow_respond(path, body):
        return 200, ['data: {"type":"content_block_delta","delta":{"type":"text_delta","text":"x"}}'] * 3

    FakeAPI.respond = slow_respond
    p = AnthropicProvider(api_key="ak", base_url=fake_api)
    ctx = Context.background().with_timeout(0.0001)
    _time.sleep(0.01)
    with pytest.raises(Exception, match="deadline"):
        p.query_stream(ctx, Request(model="m", prompt="p"), None)


# -- retry with backoff ------------------------------------------------------


def test_post_json_retries_transient_5xx(fake_api, monkeypatch):
    """A 503 then a 200 must transparently succeed (reference roadmap
    retry feature; LLMC_HTTP_BACKOFF=0 keeps the test instant)."""
    monkeypatch.setenv("LLMC_HTTP_BACKOFF", "0")
    calls = {"n": 0}

    def respond(path, body):
        calls["n"] += 1
        if calls["n"] == 1:
            return 503, {"error": "overloaded"}
        return 200, {"ok": True}

    FakeAPI.respond = respond
    from llm_consensus_tpu.providers.http_sse import post_json

    out = post_json(CTX(), f"{fake_api}/x", {}, {})
    assert out == {"ok": True}
    assert calls["n"] == 2


def test_post_json_does_not_retry_4xx(fake_api, monkeypatch):
    monkeypatch.setenv("LLMC_HTTP_BACKOFF", "0")
    calls = {"n": 0}

    def respond(path, body):
        calls["n"] += 1
        return 401, {"error": "bad key"}

    FakeAPI.respond = respond
    from llm_consensus_tpu.providers.http_sse import post_json

    with pytest.raises(HTTPError):
        post_json(CTX(), f"{fake_api}/x", {}, {})
    assert calls["n"] == 1


def test_post_json_gives_up_after_max_retries(fake_api, monkeypatch):
    monkeypatch.setenv("LLMC_HTTP_BACKOFF", "0")
    monkeypatch.setenv("LLMC_HTTP_RETRIES", "1")
    calls = {"n": 0}

    def respond(path, body):
        calls["n"] += 1
        return 503, {"error": "down"}

    FakeAPI.respond = respond
    from llm_consensus_tpu.providers.http_sse import post_json

    with pytest.raises(HTTPError):
        post_json(CTX(), f"{fake_api}/x", {}, {})
    assert calls["n"] == 2  # initial + 1 retry


def test_stream_retries_only_before_first_chunk(fake_api, monkeypatch):
    """A transient failure before any chunk retries; content is never
    delivered twice."""
    monkeypatch.setenv("LLMC_HTTP_BACKOFF", "0")
    calls = {"n": 0}

    def respond(path, body):
        calls["n"] += 1
        if calls["n"] == 1:
            return 429, {"error": "rate limited"}
        return 200, ['data: {"text": "hello"}', "data: [DONE]"]

    FakeAPI.respond = respond
    from llm_consensus_tpu.providers.http_sse import stream_json_events

    chunks = []
    out = stream_json_events(
        CTX(), f"{fake_api}/x", {}, {},
        extract=lambda e: e.get("text"), callback=chunks.append,
    )
    assert out == "hello"
    assert chunks == ["hello"]
    assert calls["n"] == 2


def test_stream_retries_reset_after_headers(fake_api, monkeypatch):
    """A connection that dies AFTER 200 + SSE headers but before any data
    line is still transient and must retry (IncompleteRead/reset path)."""
    monkeypatch.setenv("LLMC_HTTP_BACKOFF", "0")
    calls = {"n": 0}

    class DyingAPI(FakeAPI):
        pass

    def respond(path, body):
        calls["n"] += 1
        if calls["n"] == 1:
            return 200, "DIE"  # sentinel: close mid-stream
        return 200, ['data: {"text": "ok"}', "data: [DONE]"]

    def do_POST(self):
        length = int(self.headers.get("Content-Length", 0))
        self.rfile.read(length)
        status, payload = respond(self.path, None)
        self.send_response(status)
        self.send_header("Content-Type", "text/event-stream")
        if payload == "DIE":
            self.send_header("Content-Length", "1000")
            self.end_headers()
            self.wfile.flush()
            self.connection.close()  # reset before any data arrives
        else:
            self.end_headers()
            for line in payload:
                self.wfile.write((line + "\n").encode())

    monkeypatch.setattr(FakeAPI, "do_POST", do_POST)
    from llm_consensus_tpu.providers.http_sse import stream_json_events

    out = stream_json_events(
        CTX(), f"{fake_api}/x", {}, {},
        extract=lambda e: e.get("text"), callback=None,
    )
    assert out == "ok"
    assert calls["n"] == 2


def test_malformed_retry_env_falls_back_to_defaults(monkeypatch):
    from llm_consensus_tpu.providers.http_sse import _backoff_s, _max_attempts

    monkeypatch.setenv("LLMC_HTTP_RETRIES", "two")
    monkeypatch.setenv("LLMC_HTTP_BACKOFF", "0,5")
    assert _max_attempts() == 3  # default 2 retries
    assert _backoff_s(0) == 0.5


def test_system_prompt_maps_to_native_fields(fake_api, monkeypatch):
    """Each provider carries Request.system in its native mechanism."""
    monkeypatch.setenv("OPENAI_API_KEY", "k")
    monkeypatch.setenv("ANTHROPIC_API_KEY", "k")
    monkeypatch.setenv("GOOGLE_API_KEY", "k")
    FakeAPI.respond = lambda path, body: (200, {
        "output": [{"content": [{"type": "output_text", "text": "ok"}]}],
        "content": [{"type": "text", "text": "ok"}],
        "candidates": [{"content": {"parts": [{"text": "ok"}]}}],
    })
    req = Request(model="m", prompt="p", system="sys!")

    OpenAIProvider(base_url=fake_api).query(CTX(), req)
    assert FakeAPI.requests[-1]["body"]["instructions"] == "sys!"

    AnthropicProvider(base_url=fake_api).query(CTX(), req)
    assert FakeAPI.requests[-1]["body"]["system"] == "sys!"

    GoogleProvider(base_url=fake_api).query(CTX(), req)
    assert FakeAPI.requests[-1]["body"]["systemInstruction"] == {
        "parts": [{"text": "sys!"}]
    }
