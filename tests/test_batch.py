"""Batched generation (Engine.generate_batch) — the serving-throughput
API. No reference analog (its concurrency is goroutines over HTTP)."""

import jax
import jax.numpy as jnp
import pytest

from llm_consensus_tpu.engine import Engine, SamplingParams
from llm_consensus_tpu.models import get_config, init_params

PROMPTS = [
    "short prompt",
    "a somewhat longer prompt about tensor parallelism on TPU pods",
    "mid-length prompt about consensus",
]


@pytest.fixture(scope="module")
def engine():
    cfg = get_config("tiny-llama")
    return Engine(cfg, dtype=jnp.float32, max_seq=128, seed=0)


def test_batch_matches_solo_runs(engine):
    """Right-aligned batching with row offsets is an execution-strategy
    change only: each row's greedy tokens equal its solo run."""
    s = SamplingParams(max_new_tokens=10, ignore_eos=True)
    batch = engine.generate_batch(PROMPTS, s)
    for prompt, r in zip(PROMPTS, batch):
        solo = engine.generate(prompt, s)
        assert r.token_ids == solo.token_ids, prompt
        assert r.prompt_tokens == solo.prompt_tokens


def test_batch_of_one_matches_generate(engine):
    s = SamplingParams(max_new_tokens=8, ignore_eos=True)
    [r] = engine.generate_batch([PROMPTS[0]], s)
    assert r.token_ids == engine.generate(PROMPTS[0], s).token_ids


def test_batch_with_int8_kv_cache():
    cfg = get_config("tiny-llama")
    e = Engine(cfg, dtype=jnp.float32, max_seq=128, kv_quant="int8")
    s = SamplingParams(max_new_tokens=6, ignore_eos=True)
    results = e.generate_batch(PROMPTS[:2], s)
    assert all(len(r.token_ids) == 6 for r in results)


def test_batch_with_weight_quant_and_sharding():
    from llm_consensus_tpu.parallel.mesh import make_mesh

    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(3), dtype=jnp.float32)
    mesh = make_mesh({"dp": 1, "tp": 2}, jax.devices()[:2])
    e = Engine(cfg, params, dtype=jnp.float32, max_seq=128, mesh=mesh)
    base = Engine(cfg, params, dtype=jnp.float32, max_seq=128)
    s = SamplingParams(max_new_tokens=8, ignore_eos=True)
    sharded = e.generate_batch(PROMPTS[:2], s)
    solo = base.generate_batch(PROMPTS[:2], s)
    assert [r.token_ids for r in sharded] == [r.token_ids for r in solo]


def test_batch_empty_list_and_bos_only_prompt(engine):
    assert engine.generate_batch([]) == []
    # "" encodes to [BOS], a valid 1-token prompt — same contract as
    # generate(); the ValueError guard is for raw empty id lists.
    s = SamplingParams(max_new_tokens=4, ignore_eos=True)
    [r] = engine.generate_batch([""], s)
    assert r.token_ids == engine.generate("", s).token_ids


def test_batch_respects_max_new(engine):
    s = SamplingParams(max_new_tokens=3, ignore_eos=True)
    for r in engine.generate_batch(PROMPTS, s):
        assert len(r.token_ids) == 3
        assert r.finish_reason == "length"


def test_batch_chunked_prefill_matches_one_shot():
    """Long buckets prefill in chunks (row-aligned); results identical to
    the one-shot path and to solo runs."""
    cfg = get_config("tiny-llama")
    e_chunk = Engine(cfg, dtype=jnp.float32, max_seq=256, seed=0,
                     prefill_chunk=16)
    e_shot = Engine(cfg, params=e_chunk.params, dtype=jnp.float32,
                    max_seq=256, prefill_chunk=0)
    long_prompts = [
        "a " * 40,                       # ~81 ids
        "the quick brown fox " * 6,      # ~121 ids
    ]
    s = SamplingParams(max_new_tokens=8, ignore_eos=True)
    chunked = e_chunk.generate_batch(long_prompts, s)
    oneshot = e_shot.generate_batch(long_prompts, s)
    assert [r.token_ids for r in chunked] == [r.token_ids for r in oneshot]
    for p, r in zip(long_prompts, chunked):
        assert r.token_ids == e_shot.generate(p, s).token_ids
