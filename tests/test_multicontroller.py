"""Multi-controller execution (parallel/multicontroller.py,
runner/multihost.py).

Two layers of proof:
  * single-process unit tests — the exchange primitives short-circuit to
    identity, so the full merge/broadcast control flow runs without real
    processes;
  * a REAL two-process CPU cluster (subprocesses joined via
    jax.distributed, 4 virtual devices each) driving the whole CLI: each
    controller queries the panel models its host owns, results exchange
    over the cluster, process 0 alone emits the JSON.
"""

import io
import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

from llm_consensus_tpu.parallel import multicontroller as mc
from llm_consensus_tpu.providers.base import ProviderFunc, Request, Response
from llm_consensus_tpu.providers.registry import Registry
from llm_consensus_tpu.runner.multihost import MultiControllerRunner
from llm_consensus_tpu.runner.runner import AllModelsFailed
from llm_consensus_tpu.utils.context import Context

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ok(name):
    return ProviderFunc(
        lambda ctx, req: Response(
            model=req.model, content=f"answer from {name}", provider="fake"
        )
    )


def test_single_process_primitives_are_identity():
    assert mc.allgather_bytes(b"abc") == [b"abc"]
    assert mc.broadcast_bytes(b"xyz", owner=0) == b"xyz"
    assert mc.allgather_json({"a": 1}) == [{"a": 1}]
    assert mc.broadcast_json([1, 2], owner=0) == [1, 2]
    assert not mc.is_multicontroller()


def test_model_owner_defaults():
    reg = Registry()
    reg.register("m", _ok("m"))
    assert mc.model_owner(reg, "m") == 0       # no placement → process 0
    assert mc.model_owner(reg, "unknown") == 0


def test_multicontroller_runner_single_process_merge():
    """With one process owning everything, the merged result matches the
    plain runner's semantics — responses ordered by the request list
    (the deterministic order every controller must agree on)."""
    reg = Registry()
    reg.register("a", _ok("a"))
    reg.register("b", _ok("b"))

    def boom(ctx, req):
        raise RuntimeError("boom")

    reg.register("evil", ProviderFunc(boom))
    runner = MultiControllerRunner(reg, timeout=5.0, owner_fn=lambda m: 0)
    result = runner.run(Context.background(), ["b", "evil", "a"], "q")
    assert [r.model for r in result.responses] == ["b", "a"]
    assert result.failed_models == ["evil"]
    assert any("boom" in w for w in result.warnings)


def test_multicontroller_runner_all_fail():
    reg = Registry()
    reg.register("evil", ProviderFunc(
        lambda ctx, req: (_ for _ in ()).throw(RuntimeError("dead"))
    ))
    runner = MultiControllerRunner(reg, timeout=5.0, owner_fn=lambda m: 0)
    with pytest.raises(AllModelsFailed, match="dead"):
        runner.run(Context.background(), ["evil"], "q")


def test_multicontroller_runner_unowned_models_not_queried():
    """Models owned by another process are skipped locally; with the
    single-process identity exchange they simply never answer."""
    calls = []

    def track(ctx, req):
        calls.append(req.model)
        return Response(model=req.model, content="x", provider="fake")

    reg = Registry()
    reg.register("mine", ProviderFunc(track))
    reg.register("theirs", ProviderFunc(track))
    owner = {"mine": 0, "theirs": 1}.__getitem__
    runner = MultiControllerRunner(reg, timeout=5.0, owner_fn=owner)
    result = runner.run(Context.background(), ["mine", "theirs"], "q")
    assert calls == ["mine"]
    assert [r.model for r in result.responses] == ["mine"]


def test_broadcast_provider_single_process_passthrough():
    provider = mc.BroadcastProvider(_ok("judge"), owner=0)
    chunks = []
    resp = provider.query_stream(
        Context.background(), Request(model="j", prompt="p"), chunks.append
    )
    assert resp.content == "answer from judge"

    def boom(ctx, req):
        raise RuntimeError("judge exploded")

    failing = mc.BroadcastProvider(ProviderFunc(boom), owner=0)
    with pytest.raises(RuntimeError, match="judge exploded"):
        failing.query(Context.background(), Request(model="j", prompt="p"))


_WORKER = textwrap.dedent("""
    import io, json, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    from llm_consensus_tpu.cli.main import main

    code = main(
        ["--models", "tpu:tiny-llama,tpu:tiny-mistral",
         "--judge", "tpu:tiny-llama", "--json", "--no-save",
         "--max-tokens", "8", "multi controller probe"],
        stdin=io.StringIO(""), stdout=sys.stdout, stderr=sys.stderr,
        install_signal_handlers=False,
    )
    sys.exit(code)
""")


@pytest.mark.slow
def test_two_process_cpu_cluster_end_to_end(tmp_path):
    """Two controller processes, 4 virtual CPU devices each, full CLI:
    host-aware planning gives each host its models, each process drives
    only its own, the exchange merges, and process 0 alone prints."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    procs = []
    for pid in range(2):
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            LLMC_COORDINATOR=f"localhost:{port}",
            LLMC_NUM_PROCESSES="2",
            LLMC_PROCESS_ID=str(pid),
            LLMC_CONFIG="0",
            PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        )
        procs.append(subprocess.Popen(
            [sys.executable, str(worker)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
            text=True,
        ))
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-controller run timed out")
        outs.append((p.returncode, out, err))

    def sans_gloo(text: str) -> str:
        # The CPU distributed backend's Gloo transport chats on stdout;
        # drop its lines before judging what the CLI itself printed.
        return "\n".join(
            ln for ln in text.splitlines() if not ln.startswith("[Gloo]")
        ).strip()

    (rc0, out0, err0), (rc1, out1, err1) = outs
    assert rc0 == 0, err0[-2000:]
    assert rc1 == 0, err1[-2000:]
    d = json.loads(sans_gloo(out0))
    assert {r["model"] for r in d["responses"]} == {
        "tpu:tiny-llama", "tpu:tiny-mistral"
    }
    assert d["consensus"]
    assert sans_gloo(out1) == ""  # secondary controller owns no output


def test_multicontroller_runner_duplicate_models():
    """A model requested N times yields N responses (reference parity:
    the plain runner also queries duplicates — runner.go:62-63)."""
    reg = Registry()
    reg.register("m", _ok("m"))
    runner = MultiControllerRunner(reg, timeout=5.0, owner_fn=lambda m: 0)
    result = runner.run(Context.background(), ["m", "m"], "q")
    assert [r.model for r in result.responses] == ["m", "m"]


# -- degraded mode (bounded allgather + survivor merge) -----------------------


@pytest.fixture()
def faults_env():
    """Install-and-clean a fault plan + degraded-peer state per test."""
    from llm_consensus_tpu import faults

    faults.reset()
    mc.reset_degraded()
    yield faults
    faults.reset()
    mc.reset_degraded()


def test_bounded_allgather_identity_without_faults(faults_env):
    assert mc.allgather_json_bounded({"a": 1}, timeout=5.0) == ([{"a": 1}], [])
    assert mc.allgather_bytes_bounded(b"xy", timeout=5.0) == ([b"xy"], [])
    assert mc.degraded_peers() == frozenset()


@pytest.mark.faults
def test_degraded_merge_dead_controller(faults_env):
    """A dropped controller costs its models, not the run: survivors
    merge, the dead host's models land in failed_models with a warning,
    and the peer is remembered as degraded."""
    faults_env.install(faults_env.FaultPlan("controller_drop@host=1"))
    reg = Registry()
    reg.register("mine", _ok("mine"))
    reg.register("theirs", _ok("theirs"))
    owner = {"mine": 0, "theirs": 1}.__getitem__
    runner = MultiControllerRunner(
        reg, timeout=5.0, owner_fn=owner, allgather_timeout=2.0
    )
    with pytest.warns(RuntimeWarning, match="missed the allgather deadline"):
        result = runner.run(Context.background(), ["mine", "theirs"], "q")
    assert [r.model for r in result.responses] == ["mine"]
    assert result.failed_models == ["theirs"]
    assert any("controller 1 missed" in w for w in result.warnings)
    assert mc.degraded_peers() == frozenset({1})


@pytest.mark.faults
def test_degraded_merge_all_owned_models_failed(faults_env):
    """Every model on the dead host: the merged result is a total
    wipeout, which stays an error (runner.go:122-124 across hosts)."""
    faults_env.install(faults_env.FaultPlan("controller_drop@host=1"))
    reg = Registry()
    reg.register("a", _ok("a"))
    reg.register("b", _ok("b"))
    runner = MultiControllerRunner(
        reg, timeout=5.0, owner_fn=lambda m: 1, allgather_timeout=2.0
    )
    with pytest.warns(RuntimeWarning):
        with pytest.raises(AllModelsFailed, match="missed the allgather"):
            runner.run(Context.background(), ["a", "b"], "q")


@pytest.mark.faults
def test_late_controller_within_deadline_merges_normally(faults_env):
    """A slow peer that still makes the deadline is a normal merge — no
    failed models, no degraded state."""
    faults_env.install(
        faults_env.FaultPlan("controller_late@host=1@s=0.01")
    )
    reg = Registry()
    reg.register("mine", _ok("mine"))
    runner = MultiControllerRunner(
        reg, timeout=5.0, owner_fn=lambda m: 0, allgather_timeout=2.0
    )
    result = runner.run(Context.background(), ["mine"], "q")
    assert [r.model for r in result.responses] == ["mine"]
    assert result.failed_models == []
    assert mc.degraded_peers() == frozenset()


@pytest.mark.faults
def test_late_controller_past_deadline_is_dropped(faults_env):
    """A peer later than the deadline is indistinguishable from a dead
    one: bounded wait, then survivor merge."""
    faults_env.install(
        faults_env.FaultPlan("controller_late@host=1@s=5")
    )
    reg = Registry()
    reg.register("mine", _ok("mine"))
    reg.register("theirs", _ok("theirs"))
    owner = {"mine": 0, "theirs": 1}.__getitem__
    runner = MultiControllerRunner(
        reg, timeout=5.0, owner_fn=owner, allgather_timeout=0.05
    )
    t0 = __import__("time").monotonic()
    with pytest.warns(RuntimeWarning):
        result = runner.run(Context.background(), ["mine", "theirs"], "q")
    wall = __import__("time").monotonic() - t0
    assert wall < 3.0, f"blocked past the allgather deadline ({wall:.1f}s)"
    assert [r.model for r in result.responses] == ["mine"]
    assert result.failed_models == ["theirs"]
    assert mc.degraded_peers() == frozenset({1})


@pytest.mark.faults
def test_broadcast_provider_degrades_to_local_judge(faults_env):
    """Once any peer is degraded the broadcast is skipped entirely: the
    survivor serves the judge from its local provider instead of hanging
    on a collective a dead (or unknown-liveness) peer must join."""
    mc.mark_degraded([1])
    calls = []

    def judge_fn(ctx, req):
        calls.append(req.model)
        return Response(model=req.model, content="verdict", provider="fake")

    provider = mc.BroadcastProvider(ProviderFunc(judge_fn), owner=1)
    resp = provider.query(Context.background(), Request(model="j", prompt="p"))
    assert resp.content == "verdict"
    assert calls == ["j"]  # this (surviving) process ran the judge locally


def test_allgather_timeout_respects_context_deadline():
    ctx = Context.background().with_timeout(0.5)
    assert mc.allgather_timeout(ctx) <= 0.5
    assert mc.allgather_timeout(None) == mc.DEFAULT_ALLGATHER_TIMEOUT_S
