"""Checkpoint tests: Orbax roundtrip and HF safetensors import parity.

The HF import test builds a real tiny LlamaForCausalLM with transformers
(CPU torch), saves safetensors, imports into the stacked pytree layout, and
checks logits parity against transformers — end-to-end numerical proof that
the weight mapping (incl. [out,in]→[in,out] transposes and layer stacking)
is correct.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.engine.checkpoint import (
    load_hf_safetensors,
    load_params,
    save_params,
    try_load_params,
)
from llm_consensus_tpu.models import forward, get_config, init_params
from llm_consensus_tpu.models.config import ModelConfig


def test_orbax_roundtrip(tmp_path):
    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(0))
    path = str(tmp_path / "ckpt")
    save_params(params, path)
    restored = load_params(path)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_try_load_missing_returns_none(tmp_path):
    assert try_load_params(get_config("tiny-llama"), str(tmp_path / "nope")) is None


@pytest.mark.slow
def test_hf_llama_import_logits_parity(tmp_path):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_cfg = LlamaConfig(
        vocab_size=512,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=256,
        rope_theta=10000.0,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(hf_cfg).eval()
    ckpt_dir = str(tmp_path / "hf")
    model.save_pretrained(ckpt_dir, safe_serialization=True)

    cfg = ModelConfig(
        name="hf-tiny", family="llama", vocab_size=512, d_model=64, n_layers=2,
        n_heads=4, n_kv_heads=2, head_dim=16, d_ff=128, rope_theta=10000.0,
        max_seq_len=256,
    )
    params = load_hf_safetensors(cfg, ckpt_dir, dtype=jnp.float32)

    tokens = np.array([[1, 42, 7, 100, 3, 255, 17, 9]], dtype=np.int32)
    with torch.no_grad():
        hf_logits = model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    jx_logits, _ = forward(params, cfg, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(jx_logits), hf_logits, rtol=2e-3, atol=2e-3)


@pytest.mark.slow
def test_hf_import_via_try_load(tmp_path):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_cfg = LlamaConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        tie_word_embeddings=False,
    )
    model = LlamaForCausalLM(hf_cfg)
    ckpt_dir = str(tmp_path / "hf2")
    model.save_pretrained(ckpt_dir, safe_serialization=True)
    cfg = ModelConfig(
        name="hf-tiny2", family="llama", vocab_size=512, d_model=64, n_layers=2,
        n_heads=4, n_kv_heads=2, head_dim=16, d_ff=128,
    )
    params = try_load_params(cfg, ckpt_dir)
    assert params is not None
    assert params["layers"]["wq"].shape == (2, 64, 64)


# -- sharded loading (VERDICT r1 #4: no full-param materialization) ----------


def _tp_mesh(tp=8):
    import numpy as _np
    from jax.sharding import Mesh

    return Mesh(_np.array(jax.devices()[:tp]).reshape(tp), ("tp",))


def _tp_friendly_cfg():
    # Dims divisible by tp=8 so every projection actually shards.
    # head_dim = d_model // n_heads, matching what transformers derives
    # for the HF-parity tests.
    return ModelConfig(
        name="tp-tiny", family="llama", vocab_size=512, d_model=64,
        n_layers=2, n_heads=8, n_kv_heads=8, head_dim=8, d_ff=256,
        max_seq_len=256,
    )


def _assert_tp_sharded(params, cfg, mesh):
    """Sharded leaves carry 1/tp of their bytes per device; per-device
    total ≈ full/tp + the (small) replicated leaves."""
    from llm_consensus_tpu.parallel.sharding import param_specs

    tp = mesh.shape["tp"]
    specs = param_specs(cfg, mesh)
    total = sharded_total = per_dev_sharded = 0
    for leaf, spec in zip(jax.tree.leaves(params), jax.tree.leaves(specs)):
        nbytes = leaf.size * leaf.dtype.itemsize
        total += nbytes
        if any(ax is not None for ax in spec):
            shard = leaf.addressable_shards[0].data
            assert shard.size == leaf.size // tp, (spec, leaf.shape, shard.shape)
            sharded_total += nbytes
            per_dev_sharded += nbytes // tp
    assert sharded_total / total > 0.75  # the big leaves all shard
    assert per_dev_sharded == sharded_total // tp


def test_orbax_sharded_restore(tmp_path):
    from llm_consensus_tpu.engine.checkpoint import load_params_sharded

    cfg = _tp_friendly_cfg()
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    path = str(tmp_path / "ckpt")
    save_params(params, path)

    mesh = _tp_mesh()
    restored = load_params_sharded(cfg, path, mesh)
    _assert_tp_sharded(restored, cfg, mesh)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_hf_sharded_restore_matches_full_import(tmp_path):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    from llm_consensus_tpu.engine.checkpoint import load_hf_safetensors_sharded

    hf_cfg = LlamaConfig(
        vocab_size=512, hidden_size=64, intermediate_size=256,
        num_hidden_layers=2, num_attention_heads=8, num_key_value_heads=8,
        max_position_embeddings=256, rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    LlamaForCausalLM(hf_cfg).eval().save_pretrained(
        str(tmp_path / "hf"), safe_serialization=True
    )
    cfg = _tp_friendly_cfg()
    mesh = _tp_mesh()
    full = load_hf_safetensors(cfg, str(tmp_path / "hf"), dtype=jnp.float32)
    sharded = load_hf_safetensors_sharded(
        cfg, str(tmp_path / "hf"), mesh, dtype=jnp.float32
    )
    _assert_tp_sharded(sharded, cfg, mesh)
    key = lambda kv: str(kv[0])
    for (ka, a), (kb, b) in zip(
        sorted(jax.tree_util.tree_leaves_with_path(full), key=key),
        sorted(jax.tree_util.tree_leaves_with_path(sharded), key=key),
    ):
        assert str(ka) == str(kb)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=str(ka))


@pytest.mark.slow
def test_try_load_routes_to_sharded_on_mesh(tmp_path):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_cfg = LlamaConfig(
        vocab_size=512, hidden_size=64, intermediate_size=256,
        num_hidden_layers=2, num_attention_heads=8, num_key_value_heads=8,
        max_position_embeddings=256, tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    LlamaForCausalLM(hf_cfg).eval().save_pretrained(
        str(tmp_path / "hf"), safe_serialization=True
    )
    cfg = _tp_friendly_cfg()
    mesh = _tp_mesh()
    params = try_load_params(cfg, str(tmp_path / "hf"), mesh=mesh)
    _assert_tp_sharded(params, cfg, mesh)


def test_hf_sharded_restore_moe_and_bias(tmp_path):
    """The sliced importer covers the qwen2 bias and mixtral MoE layouts
    (synthetic HF-named safetensors; the full importer is the reference)."""
    from safetensors.numpy import save_file

    from llm_consensus_tpu.engine.checkpoint import (
        _HF_LAYER_MAP, _HF_MOE_MAP, load_hf_safetensors_sharded)

    rng = np.random.default_rng(0)
    for family, cfg in (
        ("qwen2", ModelConfig(
            name="tp-qwen", family="qwen2", vocab_size=512, d_model=64,
            n_layers=2, n_heads=8, n_kv_heads=8, head_dim=8, d_ff=256,
            qkv_bias=True, max_seq_len=256,
        )),
        ("mixtral", ModelConfig(
            name="tp-mix", family="mixtral", vocab_size=512, d_model=64,
            n_layers=2, n_heads=8, n_kv_heads=8, head_dim=8, d_ff=256,
            n_experts=8, experts_per_token=2, max_seq_len=256,
        )),
    ):
        d, dh, hq, hkv = cfg.d_model, cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
        tensors = {
            "model.embed_tokens.weight": rng.standard_normal(
                (cfg.vocab_size, d), dtype=np.float32),
            "model.norm.weight": rng.standard_normal((d,), dtype=np.float32),
            "lm_head.weight": rng.standard_normal(
                (cfg.vocab_size, d), dtype=np.float32),
        }
        for i in range(cfg.n_layers):
            shapes = {
                "attn_norm": (d,), "mlp_norm": (d,),
                "wq": (hq * dh, d), "wk": (hkv * dh, d), "wv": (hkv * dh, d),
                "wo": (d, hq * dh),
            }
            if cfg.qkv_bias:
                shapes.update(bq=(hq * dh,), bk=(hkv * dh,), bv=(hkv * dh,))
            if cfg.is_moe:
                tensors[_HF_MOE_MAP["w_router"].format(i=i)] = (
                    rng.standard_normal((cfg.n_experts, d), dtype=np.float32))
                for p, shape in (("w_gate", (cfg.d_ff, d)),
                                 ("w_up", (cfg.d_ff, d)),
                                 ("w_down", (d, cfg.d_ff))):
                    for e in range(cfg.n_experts):
                        tensors[_HF_MOE_MAP[p].format(i=i, e=e)] = (
                            rng.standard_normal(shape, dtype=np.float32))
            else:
                shapes.update(w_gate=(cfg.d_ff, d), w_up=(cfg.d_ff, d),
                              w_down=(d, cfg.d_ff))
            for p, shape in shapes.items():
                tensors[_HF_LAYER_MAP[p].format(i=i)] = rng.standard_normal(
                    shape, dtype=np.float32)
        ckpt = str(tmp_path / family)
        os.makedirs(ckpt)
        save_file(tensors, os.path.join(ckpt, "model.safetensors"))

        mesh = _tp_mesh()
        full = load_hf_safetensors(cfg, ckpt, dtype=jnp.float32)
        sharded = load_hf_safetensors_sharded(cfg, ckpt, mesh, dtype=jnp.float32)
        key = lambda kv: str(kv[0])
        for (ka, a), (kb, b) in zip(
            sorted(jax.tree_util.tree_leaves_with_path(full), key=key),
            sorted(jax.tree_util.tree_leaves_with_path(sharded), key=key),
        ):
            assert str(ka) == str(kb)
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=f"{family} {ka}")
