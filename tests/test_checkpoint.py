"""Checkpoint tests: Orbax roundtrip and HF safetensors import parity.

The HF import test builds a real tiny LlamaForCausalLM with transformers
(CPU torch), saves safetensors, imports into the stacked pytree layout, and
checks logits parity against transformers — end-to-end numerical proof that
the weight mapping (incl. [out,in]→[in,out] transposes and layer stacking)
is correct.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.engine.checkpoint import (
    load_hf_safetensors,
    load_params,
    save_params,
    try_load_params,
)
from llm_consensus_tpu.models import forward, get_config, init_params
from llm_consensus_tpu.models.config import ModelConfig


def test_orbax_roundtrip(tmp_path):
    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(0))
    path = str(tmp_path / "ckpt")
    save_params(params, path)
    restored = load_params(path)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_try_load_missing_returns_none(tmp_path):
    assert try_load_params(get_config("tiny-llama"), str(tmp_path / "nope")) is None


@pytest.mark.slow
def test_hf_llama_import_logits_parity(tmp_path):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_cfg = LlamaConfig(
        vocab_size=512,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=256,
        rope_theta=10000.0,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(hf_cfg).eval()
    ckpt_dir = str(tmp_path / "hf")
    model.save_pretrained(ckpt_dir, safe_serialization=True)

    cfg = ModelConfig(
        name="hf-tiny", family="llama", vocab_size=512, d_model=64, n_layers=2,
        n_heads=4, n_kv_heads=2, head_dim=16, d_ff=128, rope_theta=10000.0,
        max_seq_len=256,
    )
    params = load_hf_safetensors(cfg, ckpt_dir, dtype=jnp.float32)

    tokens = np.array([[1, 42, 7, 100, 3, 255, 17, 9]], dtype=np.int32)
    with torch.no_grad():
        hf_logits = model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    jx_logits, _ = forward(params, cfg, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(jx_logits), hf_logits, rtol=2e-3, atol=2e-3)


@pytest.mark.slow
def test_hf_import_via_try_load(tmp_path):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_cfg = LlamaConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        tie_word_embeddings=False,
    )
    model = LlamaForCausalLM(hf_cfg)
    ckpt_dir = str(tmp_path / "hf2")
    model.save_pretrained(ckpt_dir, safe_serialization=True)
    cfg = ModelConfig(
        name="hf-tiny2", family="llama", vocab_size=512, d_model=64, n_layers=2,
        n_heads=4, n_kv_heads=2, head_dim=16, d_ff=128,
    )
    params = try_load_params(cfg, ckpt_dir)
    assert params is not None
    assert params["layers"]["wq"].shape == (2, 64, 64)
