"""Fleet tier tests (serve/fleet.py + serve/router.py) over fake providers.

Covers the router's contracts end-to-end through real HTTP:

  * health hysteresis — one slow/failed poll demotes to suspect, never
    dead; death needs consecutive failures; revival needs consecutive
    good polls;
  * consistent-hash placement — identical concurrent requests share a
    home replica and coalesce to ONE execution fleet-wide;
  * cross-replica failover — a replica dying mid-SSE-stream (injected
    ``replica_down``, and a genuinely unreachable replica) costs the
    client a pause, never a dropped or duplicated character;
  * spillover — when no live replica can take an eligible request, it
    degrades to the remote registry and is tagged ``degraded: remote``;
    policy and deadline-class gating hold;
  * heartbeat registration — gateways announce themselves, registrations
    age out, and the router places onto announced replicas with no
    static config.
"""

from __future__ import annotations

import http.client
import json
import os
import threading
import time

import pytest

from llm_consensus_tpu import faults, obs, serve
from llm_consensus_tpu.faults import FaultPlan
from llm_consensus_tpu.providers.base import Provider, Request, Response
from llm_consensus_tpu.providers.registry import Registry
from llm_consensus_tpu.serve.fleet import (
    DEAD,
    HEALTHY,
    SUSPECT,
    FleetState,
    HealthMonitor,
    StreamLedger,
    ring_order,
)
from llm_consensus_tpu.utils.context import Context

pytestmark = pytest.mark.faults

PANEL = ["alpha", "beta"]
JUDGE = "gamma"
CHUNK = 6  # characters per streamed chunk


@pytest.fixture(autouse=True)
def _clean_plan(monkeypatch):
    monkeypatch.delenv("LLMC_FAULTS", raising=False)
    faults.reset()
    obs.reset()
    yield
    faults.reset()
    obs.reset()


def expected_content(model: str, prompt: str) -> str:
    return f"{model} answers {prompt} at some length for chunking"


class StreamingProvider(Provider):
    """Deterministic multi-chunk streaming fake; optionally gated."""

    def __init__(self, gate: "threading.Event | None" = None,
                 arrivals: "threading.Semaphore | None" = None):
        self._lock = threading.Lock()
        self.calls: list[tuple[str, str]] = []
        self._gate = gate          # panel queries block on this
        self._arrivals = arrivals  # released once per panel query start

    def query(self, ctx: Context, req: Request) -> Response:
        return self.query_stream(ctx, req, None)

    def query_stream(self, ctx, req, callback):
        with self._lock:
            self.calls.append((req.model, req.prompt))
        if req.model in PANEL:
            if self._arrivals is not None:
                self._arrivals.release()
            if self._gate is not None:
                assert self._gate.wait(30.0), "test gate never released"
        ctx.raise_if_done()
        content = expected_content(req.model, req.prompt[:16])
        if callback is not None:
            for i in range(0, len(content), CHUNK):
                callback(content[i:i + CHUNK])
        return Response(model=req.model, content=content, provider="fake")

    def panel_calls(self):
        with self._lock:
            return [c for c in self.calls if c[0] in PANEL]


def make_replica(tmp_path, provider, name: str, **kw):
    registry = Registry()
    for m in PANEL + [JUDGE]:
        registry.register(m, provider)
    kw.setdefault("timeout", 30.0)
    kw.setdefault("max_concurrency", 4)
    kw.setdefault("cache_size", 0)  # failover re-executes, never replays
    gw = serve.build_gateway(
        registry, list(PANEL), JUDGE,
        data_dir=os.path.join(str(tmp_path), "data", name), **kw,
    )
    gw.start()
    return gw


def gw_url(gw) -> str:
    host, port = gw.address
    return f"http://{host}:{port}"


def make_router(replicas, **kw):
    kw.setdefault("poll_s", 60.0)  # tests drive polls explicitly
    router = serve.build_router([gw_url(g) for g in replicas], **kw)
    router.start()
    return router


def post(port: int, body: dict, path: str = "/v1/consensus", timeout=60):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request(
            "POST", path, json.dumps(body),
            {"Content-Type": "application/json"},
        )
        r = conn.getresponse()
        headers = dict(r.getheaders())
        data = r.read()
    finally:
        conn.close()
    return r.status, headers, data


def get(port: int, path: str):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        data = r.read()
    finally:
        conn.close()
    return r.status, json.loads(data)


def post_sse(port: int, body: dict, timeout=60):
    """POST with SSE accept; returns the parsed event list."""
    body = dict(body)
    body["stream"] = True
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    events: list[tuple[str, dict]] = []
    try:
        conn.request(
            "POST", "/v1/consensus", json.dumps(body),
            {"Content-Type": "application/json",
             "Accept": "text/event-stream"},
        )
        resp = conn.getresponse()
        assert resp.status == 200, resp.read()
        event, data_lines = None, []
        for raw in resp:
            line = raw.decode("utf-8").rstrip("\r\n")
            if line.startswith("event: "):
                event = line[len("event: "):]
            elif line.startswith("data: "):
                data_lines.append(line[len("data: "):])
            elif not line and (event or data_lines):
                events.append((event, json.loads("\n".join(data_lines))))
                if event in ("done", "error"):
                    break
                event, data_lines = None, []
    finally:
        conn.close()
    return events


def sse_text(events) -> dict:
    """Per-(kind, model) concatenated chunk text."""
    out: dict = {}
    for name, doc in events:
        if name == "chunk":
            key = (doc["kind"], doc["model"])
            out[key] = out.get(key, "") + doc["text"]
    return out


def baseline_sse_text(tmp_path, prompt: str) -> dict:
    """The undisturbed stream: one fresh replica, queried directly (the
    judge streams a rendered judge-prompt, so expectations must come
    from a real run, not from the raw prompt)."""
    gw = make_replica(tmp_path, StreamingProvider(), "baseline")
    try:
        _, port = gw.address
        return sse_text(post_sse(port, {"prompt": prompt}))
    finally:
        gw.close(timeout=5.0)


def runs_executed(*gateways) -> int:
    return sum(g.scheduler.runs_executed for g in gateways)


# ---------------------------------------------------------------------------
# hysteresis state machine


def test_one_slow_poll_is_never_dead():
    fleet = FleetState(suspect_after=1, dead_after=3, revive_after=2)
    replica = fleet.add_static("http://127.0.0.1:1")
    faults.install(FaultPlan("slow_healthz@phase=poll@s=0.01", seed=3))
    polled = []
    monitor = HealthMonitor(
        fleet, poll_s=60.0,
        probe=lambda url: (polled.append(url) or (True, 0.1, False, None)),
    )
    monitor.poll_once()  # the injected slow poll: one failure
    assert replica.state == SUSPECT  # demoted, but NOT dead
    assert polled == []              # the slow poll never completed
    monitor.poll_once()              # next poll is clean
    assert replica.state == HEALTHY
    assert fleet.deaths == 0


def test_death_needs_consecutive_failures_and_revival_is_conservative():
    fleet = FleetState(suspect_after=1, dead_after=3, revive_after=2)
    replica = fleet.add_static("http://127.0.0.1:1")
    fleet.record_poll(replica, False)
    assert replica.state == SUSPECT
    fleet.record_poll(replica, True)   # one good poll heals suspect
    assert replica.state == HEALTHY
    for _ in range(4):                 # suspect_after + dead_after
        fleet.record_poll(replica, False)
    assert replica.state == DEAD
    fleet.record_poll(replica, True)   # one good poll does NOT revive
    assert replica.state == DEAD
    fleet.record_poll(replica, True)
    assert replica.state == HEALTHY
    assert fleet.deaths == 1 and fleet.revivals == 1


def test_proxy_failure_counts_as_failed_poll():
    fleet = FleetState(suspect_after=1, dead_after=3)
    replica = fleet.add_static("http://127.0.0.1:1")
    fleet.note_proxy_failure("http://127.0.0.1:1")
    assert replica.state == SUSPECT
    assert replica.fails == 1


# ---------------------------------------------------------------------------
# placement


def test_ring_order_is_stable_and_complete():
    urls = [f"http://127.0.0.1:{p}" for p in (9001, 9002, 9003)]
    order = ring_order("some-key", urls)
    assert sorted(order) == sorted(urls)
    assert order == ring_order("some-key", urls)
    # Removing a non-home replica keeps the home.
    home = order[0]
    shrunk = [u for u in urls if u != order[-1]]
    assert ring_order("some-key", shrunk)[0] == home


def test_routed_json_roundtrip_and_stats(tmp_path):
    provider = StreamingProvider()
    gws = [make_replica(tmp_path, provider, f"r{i}") for i in range(2)]
    router = make_router(gws)
    try:
        _, port = router.address
        status, _, data = post(port, {"prompt": "route me"})
        assert status == 200, data
        doc = json.loads(data)
        assert doc["consensus"]
        assert doc["replica"] in [gw_url(g) for g in gws]
        assert runs_executed(*gws) == 1
        status, stats = get(port, "/statsz")
        assert status == 200
        assert stats["counters"]["requests"] == 1
        assert stats["fleet"]["by_state"]["healthy"] == 2
        status, health = get(port, "/healthz")
        assert status == 200 and health["replicas"]["healthy"] == 2
    finally:
        router.close()
        for g in gws:
            g.close(timeout=5.0)


def test_identical_concurrent_requests_coalesce_fleet_wide(tmp_path):
    gate = threading.Event()
    arrivals = threading.Semaphore(0)
    provider = StreamingProvider(gate=gate, arrivals=arrivals)
    gws = [make_replica(tmp_path, provider, f"r{i}") for i in range(2)]
    router = make_router(gws)
    try:
        _, port = router.address
        results: list = [None, None]

        def fire(i):
            results[i] = post(port, {"prompt": "coalesce fleet-wide"})

        threads = [
            threading.Thread(target=fire, args=(i,)) for i in range(2)
        ]
        for t in threads:
            t.start()
        # The leader's panel queries started; both entry requests are
        # pinned to the same home by the hash ring, so the second is a
        # follower — release once the leader is mid-flight.
        assert arrivals.acquire(timeout=10)
        time.sleep(0.2)  # let the second request join the flight
        gate.set()
        for t in threads:
            t.join(timeout=30)
        docs = [json.loads(r[2]) for r in results]
        assert all(r[0] == 200 for r in results)
        # ONE execution fleet-wide: same home gateway, coalesced there.
        assert runs_executed(*gws) == 1
        assert sum(1 for d in docs if d["coalesced"]) == 1
        assert len(provider.panel_calls()) == len(PANEL)
    finally:
        gate.set()
        router.close()
        for g in gws:
            g.close(timeout=5.0)


def test_saturated_home_overflows_to_next_ring_replica(tmp_path):
    provider = StreamingProvider()
    gws = [make_replica(tmp_path, provider, f"r{i}") for i in range(2)]
    router = make_router(gws)
    try:
        _, port = router.address
        body = {"prompt": "overflow probe"}
        from llm_consensus_tpu.serve.router import RouteRequest

        key = RouteRequest(b"", dict(body), False).key()
        urls = [gw_url(g) for g in gws]
        home = ring_order(key, urls, vnodes=router.vnodes)[0]
        other = next(u for u in urls if u != home)
        # Mark the home replica saturated via a (simulated) poll.
        for replica in router.fleet.replicas():
            if replica.url == home:
                router.fleet.record_poll(replica, True, load_score=0.99)
        status, _, data = post(port, body)
        assert status == 200
        assert json.loads(data)["replica"] == other
    finally:
        router.close()
        for g in gws:
            g.close(timeout=5.0)


# ---------------------------------------------------------------------------
# failover


def test_replica_down_mid_stream_reroutes_byte_identical(tmp_path):
    prompt = "failover mid-stream probe"
    expected = baseline_sse_text(tmp_path, prompt)
    provider = StreamingProvider()
    gws = [make_replica(tmp_path, provider, f"r{i}") for i in range(2)]
    # The 3rd relayed frame of the first replica attempt dies: frame 1-2
    # are chunks the client already holds, so the failover replica's
    # replay must burn exactly that prefix.
    faults.install(FaultPlan("replica_down@phase=proxy@frame=3", seed=5))
    router = make_router(gws)
    try:
        _, port = router.address
        events = post_sse(port, {"prompt": prompt})
        assert events[-1][0] == "done", events[-1]
        # Byte-identity: every stream's concatenation equals the
        # undisturbed run's — nothing dropped, nothing duplicated at
        # the failover seam.
        assert sse_text(events) == expected
        # The envelope reports THIS request's seam count, not the
        # router-global counter.
        assert events[-1][1]["failovers"] == 1
        # Both replicas executed (home partially streamed, then died
        # from the router's perspective; the other re-ran in full).
        assert runs_executed(*gws) == 2
        _, stats = get(port, "/statsz")
        assert stats["counters"]["failovers"] == 1
        # The router's own evidence demoted the failed home replica.
        states = {r["url"]: r["state"] for r in stats["fleet"]["replicas"]}
        assert SUSPECT in states.values()
    finally:
        router.close()
        for g in gws:
            g.close(timeout=5.0)


def test_unreachable_replica_fails_over_on_connect(tmp_path):
    provider = StreamingProvider()
    gw = make_replica(tmp_path, provider, "live")
    # A genuinely dead replica: nothing listens on this port.
    import socket

    probe_sock = socket.socket()
    probe_sock.bind(("127.0.0.1", 0))
    dead_port = probe_sock.getsockname()[1]
    probe_sock.close()
    router = serve.build_router(
        [f"http://127.0.0.1:{dead_port}", gw_url(gw)], poll_s=60.0
    )
    router.start()
    try:
        _, port = router.address
        # Whichever home the ring picks, the request must land on the
        # live replica — possibly after one connect failover.
        status, _, data = post(port, {"prompt": "connect failover"})
        assert status == 200
        assert json.loads(data)["replica"] == gw_url(gw)
        assert runs_executed(gw) == 1
    finally:
        router.close()
        gw.close(timeout=5.0)


def test_injected_partition_forces_failover(tmp_path):
    provider = StreamingProvider()
    gws = [make_replica(tmp_path, provider, f"r{i}") for i in range(2)]
    faults.install(FaultPlan("partition@phase=connect", seed=9))
    router = make_router(gws)
    try:
        _, port = router.address
        status, _, data = post(port, {"prompt": "partition probe"})
        assert status == 200
        doc = json.loads(data)
        assert doc["consensus"]
        _, stats = get(port, "/statsz")
        assert stats["counters"]["failovers"] == 1
    finally:
        router.close()
        for g in gws:
            g.close(timeout=5.0)


# ---------------------------------------------------------------------------
# spillover


def remote_fake_registry():
    registry = Registry()
    provider = StreamingProvider()
    for m in ["remote-a", "remote-b", "remote-judge"]:
        registry.register(m, provider)
    return registry


def make_spill_router(tmp_path, replicas=(), **kw):
    kw.setdefault("poll_s", 60.0)
    kw.setdefault("spillover_registry", remote_fake_registry())
    kw.setdefault("spillover_models", ["remote-a", "remote-b"])
    kw.setdefault("spillover_judge", "remote-judge")
    kw.setdefault("data_dir", os.path.join(str(tmp_path), "spill"))
    router = serve.build_router([gw_url(g) for g in replicas], **kw)
    router.start()
    return router


def test_spillover_when_fleet_is_dead(tmp_path):
    router = make_spill_router(tmp_path)  # zero replicas ⇒ nothing live
    try:
        _, port = router.address
        status, _, data = post(port, {"prompt": "spill me", "timeout": 60})
        assert status == 200, data
        doc = json.loads(data)
        assert doc["degraded"] == "remote"
        assert doc["consensus"]
        assert [r["model"] for r in doc["responses"]] == ["remote-a",
                                                          "remote-b"]
        _, stats = get(port, "/statsz")
        assert stats["counters"]["spillover"] == 1
    finally:
        router.close()


def test_spillover_streams_sse(tmp_path):
    router = make_spill_router(tmp_path)
    try:
        _, port = router.address
        events = post_sse(port, {"prompt": "spill sse", "timeout": 60})
        assert events[-1][0] == "done"
        assert events[-1][1]["degraded"] == "remote"
        text = sse_text(events)
        assert ("model_chunk", "remote-a") in text
    finally:
        router.close()


def test_spillover_gated_by_deadline_class(tmp_path):
    from llm_consensus_tpu.serve.router import SpilloverPolicy

    router = make_spill_router(
        tmp_path,
        spillover_policy=SpilloverPolicy("saturated", min_timeout_s=30.0),
    )
    try:
        _, port = router.address
        # A tight deadline can't absorb a remote round trip: honest 503.
        status, _, data = post(port, {"prompt": "too tight", "timeout": 5})
        assert status == 503, data
        _, stats = get(port, "/statsz")
        assert stats["counters"]["spillover"] == 0
        assert stats["counters"]["rejected"] == 1
    finally:
        router.close()


def test_spillover_failure_mid_stream_ends_with_sse_error(tmp_path):
    """A remote-lane failure after the SSE stream began must terminate
    the stream with an ``error`` event — never a bare HTTP status line
    spliced into the open event stream (which parses as nothing and
    leaves the consumer hanging with no terminal event)."""

    class ExplodingProvider(Provider):
        def query(self, ctx, req):
            return self.query_stream(ctx, req, None)

        def query_stream(self, ctx, req, callback):
            if callback is not None:
                callback("partial ")
            raise RuntimeError("remote API fell over")

    registry = Registry()
    provider = ExplodingProvider()
    for m in ["remote-a", "remote-b", "remote-judge"]:
        registry.register(m, provider)
    router = make_spill_router(tmp_path, spillover_registry=registry)
    try:
        _, port = router.address
        events = post_sse(port, {"prompt": "boom", "timeout": 60})
        assert events[-1][0] == "error", events
        assert "routing failed" in events[-1][1]["error"]
    finally:
        router.close()


def test_bad_registration_returns_400():
    router = serve.build_router([], poll_s=60.0)
    router.start()
    try:
        _, port = router.address
        status, _, data = post(
            port, {"url": "http://x:1", "load_score": "high"},
            path="/v1/register",
        )
        assert status == 400, data
        assert b"bad registration" in data
        assert router.fleet.replicas() == []
    finally:
        router.close()


def test_spillover_gated_by_policy_off(tmp_path):
    from llm_consensus_tpu.serve.router import SpilloverPolicy

    router = make_spill_router(
        tmp_path, spillover_policy=SpilloverPolicy("off")
    )
    try:
        _, port = router.address
        status, _, _data = post(port, {"prompt": "policy off", "timeout": 60})
        assert status == 503
    finally:
        router.close()


# ---------------------------------------------------------------------------
# heartbeat registration


def test_register_heartbeat_and_expiry():
    clock = [100.0]
    fleet = FleetState(clock=lambda: clock[0])
    replica = fleet.heartbeat(
        "http://127.0.0.1:9009", load_score=0.2, interval_s=1.0
    )
    assert replica.state == HEALTHY and not fleet.expired(replica)
    clock[0] += 10.0  # missed every beat in the grace window
    assert fleet.expired(replica)
    fleet.heartbeat("http://127.0.0.1:9009", load_score=0.3)
    assert not fleet.expired(replica)  # a late beat re-admits it


def test_gateway_announce_end_to_end(tmp_path):
    provider = StreamingProvider()
    gw = make_replica(tmp_path, provider, "announced")
    router = serve.build_router([], poll_s=60.0)  # NO static replicas
    router.start()
    try:
        _, port = router.address
        gw.announce(f"http://127.0.0.1:{port}", interval_s=0.2)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            _, stats = get(port, "/statsz")
            if stats["fleet"]["replicas"]:
                break
            time.sleep(0.05)
        assert stats["fleet"]["replicas"], "gateway never registered"
        doc = stats["fleet"]["replicas"][0]
        assert doc["url"] == gw_url(gw)
        assert doc["source"] == "heartbeat"
        assert 0.0 <= doc["load_score"] <= 1.0
        # And the router can place onto the announced replica.
        status, _, data = post(port, {"prompt": "announced routing"})
        assert status == 200
        assert json.loads(data)["replica"] == gw_url(gw)
    finally:
        router.close()
        gw.close(timeout=5.0)


# ---------------------------------------------------------------------------
# ledger unit coverage


def test_stream_ledger_double_failover():
    ledger = StreamLedger()
    assert ledger.record("model_chunk", "m", "abcdef") == "abcdef"
    ledger.arm_replay()
    assert ledger.record("model_chunk", "m", "abc") is None
    assert ledger.record("model_chunk", "m", "defghi") == "ghi"
    ledger.arm_replay()  # second failover: 9 delivered chars burn first
    assert ledger.record("model_chunk", "m", "abcdefghi") is None
    assert ledger.record("model_chunk", "m", "jkl") == "jkl"
    assert ledger.delivered_any
