"""Test configuration.

JAX-based tests run on a virtual 8-device CPU mesh so all sharding /
parallelism logic is exercised without TPU hardware (the driver separately
dry-run-compiles the multi-chip path via __graft_entry__.dryrun_multichip).

Note: this environment ships a sitecustomize that forces JAX_PLATFORMS=axon
(the tunneled TPU), so the env var alone is not enough — the platform is
also pinned via jax.config, which takes precedence. XLA_FLAGS must be set
before the first backend initialization.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import tempfile

# Persistent XLA compilation cache: the suite's wall time is dominated
# by recompiles of tiny models; caching compiled programs across runs
# cuts repeat invocations ~3× (measured: 21s → 6.6s on a subset).
# Per-user path (shared /tmp on CI boxes), and LLMC_XLA_CACHE points the
# tpu provider's own cache mechanism at the SAME dir — otherwise the
# first TPUProvider test would redirect the process's cache to the
# developer's real serving cache (polluting it with CPU test programs).
# The dir is keyed by a host-CPU fingerprint as well as uid: XLA:CPU
# caches AOT executables compiled for the build host's exact CPU
# features, and loading one on a different host (container migrated
# between machines, shared /tmp) warns "could lead to execution errors
# such as SIGILL" — and did: a stale cache SEGFAULTED the suite inside
# compilation_cache.get_executable_and_time. A fingerprint change gets
# a fresh dir instead of a crash.
def _cpu_fingerprint() -> str:
    import hashlib

    try:
        with open("/proc/cpuinfo") as f:
            flags = next(
                (ln for ln in f if ln.startswith("flags")), "unknown"
            )
    except OSError:
        flags = "unknown"
    return hashlib.sha256(flags.encode()).hexdigest()[:12]


_cache_dir = os.path.join(
    tempfile.gettempdir(),
    f"llmc-test-xla-cache-{os.getuid()}-{_cpu_fingerprint()}",
)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache_dir)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.3")
os.environ.setdefault("LLMC_XLA_CACHE", _cache_dir)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running (checkpoint/e2e) tests")
    config.addinivalue_line(
        "markers",
        "faults: deterministic fault-injection / degraded-mode tests "
        "(the CI chaos lane runs exactly this marker)",
    )
    config.addinivalue_line(
        "markers",
        "schedules(n): run the test body under n deterministically "
        "explored thread schedules (analysis/schedule.py); a failing "
        "schedule raises with its LLMC_SCHED=replay:<token> repro",
    )


def pytest_sessionstart(session):
    devices = jax.devices()
    assert devices[0].platform == "cpu", f"tests must run on CPU, got {devices}"
    assert len(devices) == 8, f"expected 8 virtual CPU devices, got {len(devices)}"


import pytest as _pytest


@_pytest.hookimpl(tryfirst=True)
def pytest_pyfunc_call(pyfuncitem):
    """``@pytest.mark.schedules(n)``: replace the single call with n
    deterministically explored schedules (seeded ``0..n-1``, rebased by
    ``LLMC_SCHED=<seed>``; ``LLMC_SCHED=replay:<token>`` runs exactly
    one interleaving). Returning True suppresses the default call."""
    m = pyfuncitem.get_closest_marker("schedules")
    if m is None:
        return None
    from llm_consensus_tpu.analysis import schedule

    n = int(m.args[0]) if m.args else 16
    testfn = pyfuncitem.obj
    names = getattr(pyfuncitem, "_fixtureinfo").argnames
    kwargs = {name: pyfuncitem.funcargs[name] for name in names}
    schedule.check(lambda: testfn(**kwargs), schedules=n)
    return True


@_pytest.fixture(autouse=True)
def _no_ambient_config(monkeypatch):
    """Hermetic CLI tests: a developer's ~/.llm-consensus.json must never
    leak into test runs. Config-file tests set LLMC_CONFIG explicitly."""
    monkeypatch.setenv("LLMC_CONFIG", "0")
