"""Test configuration.

JAX-based tests run on a virtual 8-device CPU mesh so all sharding /
parallelism logic is exercised without TPU hardware (the driver separately
dry-run-compiles the multi-chip path via __graft_entry__.dryrun_multichip).
The env vars must be set before jax initializes any backend, hence here at
conftest import time.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
