"""LLM-graded confidence (consensus/confidence.py, --confidence).

Reference roadmap §2.4 (/root/reference/docs/proposed-features.md:77-83,
unimplemented there): the judge rates its confidence in the consensus
(0-100) and lists controversy points. Grading is best-effort — a garbled
judge reply degrades to a warning, never a failed run.
"""

import io
import json

from llm_consensus_tpu.cli.main import main
from llm_consensus_tpu.consensus import (
    grade_confidence,
    parse_confidence,
    render_confidence_prompt,
)
from llm_consensus_tpu.providers import ProviderFunc, Request, Response
from llm_consensus_tpu.utils.context import Context


def _resp(model, content):
    return Response(model, content, "fake", 1.0)


def test_render_prompt_embeds_everything():
    text = render_confidence_prompt(
        "the question",
        [_resp("m1", "answer one"), _resp("m2", "answer two")],
        "the consensus",
    )
    assert "the question" in text
    assert "--- Model: m1 | Provider: fake ---" in text
    assert "answer one" in text and "answer two" in text
    assert "the consensus" in text
    assert "CONFIDENCE:" in text  # format contract shown to the judge


def test_parse_well_formed():
    c = parse_confidence(
        "CONFIDENCE: 82\nCONTROVERSY:\n- models disagreed on X\n- and on Y\n"
    )
    assert c.score == 82
    assert c.controversy == ["models disagreed on X", "and on Y"]


def test_parse_none_controversy_and_clamping():
    c = parse_confidence("CONFIDENCE: 250\nCONTROVERSY: none\n")
    assert c.score == 100  # clamped
    assert c.controversy == []


def test_parse_tolerates_surrounding_prose_and_stops_list():
    c = parse_confidence(
        "Here is my grading.\nCONFIDENCE: 55\nCONTROVERSY:\n"
        "- point one\nSome trailing commentary.\n- not a controversy point\n"
    )
    assert c.score == 55
    assert c.controversy == ["point one"]  # list ends at first non-bullet


def test_parse_garbage_returns_none_score():
    c = parse_confidence("I feel pretty good about this one!")
    assert c.score is None
    assert c.controversy == []


def test_grade_confidence_queries_judge():
    seen = {}

    def judge(ctx, req: Request):
        seen["prompt"] = req.prompt
        return Response(req.model, "CONFIDENCE: 64\nCONTROVERSY: none", "fake", 1.0)

    c = grade_confidence(
        Context.background(), ProviderFunc(judge), "j", "q",
        [_resp("m1", "a"), _resp("m2", "b")], "the consensus",
    )
    assert c.score == 64
    assert "the consensus" in seen["prompt"]


def _factory(grade_reply):
    def factory(model):
        def fn(ctx, req: Request):
            if "CONFIDENCE" in req.prompt:  # the grading query
                return Response(req.model, grade_reply, "fake", 1.0)
            return Response(req.model, f"ans-{req.model}", "fake", 1.0)
        return ProviderFunc(fn)
    return factory


def _run(argv, grade_reply="CONFIDENCE: 77\nCONTROVERSY:\n- scope of X\n"):
    stdout, stderr = io.StringIO(), io.StringIO()
    code = main(
        argv, factory=_factory(grade_reply), stdin=io.StringIO(),
        stdout=stdout, stderr=stderr, install_signal_handlers=False,
    )
    return code, stdout.getvalue(), stderr.getvalue()


def test_cli_confidence_in_json_result():
    code, out, _ = _run(
        ["--models", "m1,m2", "--judge", "j", "--json", "--confidence", "q"]
    )
    assert code == 0
    result = json.loads(out)
    assert result["confidence"] == {"score": 77, "controversy": ["scope of X"]}


def test_cli_without_flag_omits_confidence():
    code, out, _ = _run(["--models", "m1,m2", "--judge", "j", "--json", "q"])
    assert code == 0
    assert "confidence" not in json.loads(out)


def test_cli_unparseable_grading_warns_not_fails():
    code, out, _ = _run(
        ["--models", "m1,m2", "--judge", "j", "--json", "--confidence", "q"],
        grade_reply="no structured grade here",
    )
    assert code == 0
    result = json.loads(out)
    assert "confidence" not in result
    assert any("unparseable" in w for w in result.get("warnings", []))


def test_cli_vote_and_confidence_exclusive():
    stdout, stderr = io.StringIO(), io.StringIO()
    code = main(
        ["--models", "m1,m2", "--vote", "--options", "a,b", "--confidence", "q"],
        factory=_factory(""), stdin=io.StringIO(),
        stdout=stdout, stderr=stderr, install_signal_handlers=False,
    )
    assert code == 1
    assert "mutually exclusive" in stderr.getvalue()


def test_config_file_confidence_default(tmp_path, monkeypatch):
    cfgp = tmp_path / "conf.json"
    cfgp.write_text(json.dumps({"confidence": True}))
    monkeypatch.setenv("LLMC_CONFIG", str(cfgp))
    code, out, _ = _run(["--models", "m1,m2", "--judge", "j", "--json", "q"])
    assert code == 0
    assert json.loads(out)["confidence"]["score"] == 77


def test_single_response_panel_still_grades():
    """With one panel model the judge passthrough skips synthesis, but a
    requested grading still runs against the passthrough consensus."""
    code, out, _ = _run(
        ["--models", "m1", "--judge", "j", "--json", "--confidence", "q"]
    )
    assert code == 0
    assert json.loads(out)["confidence"]["score"] == 77
