"""Pipeline forward == plain forward, plus gradient flow through the ring."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.models import forward, get_config, init_params
from llm_consensus_tpu.parallel.mesh import make_mesh
from llm_consensus_tpu.parallel.pipeline import dryrun_pipeline, pipeline_forward
from llm_consensus_tpu.train.loss import cross_entropy_loss


def _setup(n_layers=4, batch=8, seq=16, name="tiny-llama"):
    cfg = get_config(name, n_layers=n_layers)
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, seq), 0, cfg.vocab_size, jnp.int32
    )
    return cfg, params, tokens


class TestPipelineForward:
    @pytest.mark.parametrize("pp,microbatches", [(2, 2), (2, 4), (4, 4), (4, 8)])
    def test_matches_plain_forward(self, pp, microbatches):
        cfg, params, tokens = _setup()
        mesh = make_mesh({"pp": pp}, jax.devices()[:pp])
        out = pipeline_forward(params, cfg, tokens, mesh, microbatches=microbatches)
        ref, _ = forward(params, cfg, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    def test_gemma_family(self):
        cfg, params, tokens = _setup(name="tiny-gemma")
        mesh = make_mesh({"pp": 2}, jax.devices()[:2])
        out = pipeline_forward(params, cfg, tokens, mesh, microbatches=2)
        ref, _ = forward(params, cfg, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    def test_gradients_match(self):
        cfg, params, tokens = _setup()
        targets = jnp.roll(tokens, -1, axis=1)
        mesh = make_mesh({"pp": 2}, jax.devices()[:2])

        def loss_pipe(p):
            return cross_entropy_loss(
                pipeline_forward(p, cfg, tokens, mesh, microbatches=4), targets
            )

        def loss_ref(p):
            return cross_entropy_loss(forward(p, cfg, tokens)[0], targets)

        g_pipe = jax.grad(loss_pipe)(params)
        g_ref = jax.grad(loss_ref)(params)
        for a, b in zip(jax.tree.leaves(g_pipe), jax.tree.leaves(g_ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-4)

    def test_rejects_bad_divisibility(self):
        cfg, params, tokens = _setup(n_layers=4, batch=6)
        mesh = make_mesh({"pp": 2}, jax.devices()[:2])
        with pytest.raises(ValueError, match="microbatches"):
            pipeline_forward(params, cfg, tokens, mesh, microbatches=4)
        cfg3 = get_config("tiny-llama", n_layers=3)
        params3 = init_params(cfg3, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="divisible"):
            pipeline_forward(params3, cfg3, tokens[:8], mesh)
        # v2: the stage-resident queues need M % S == 0.
        cfg4, params4, tokens4 = _setup(n_layers=4, batch=6)
        with pytest.raises(ValueError, match="resident per stage"):
            pipeline_forward(params4, cfg4, tokens4, mesh, microbatches=3)

    def test_stage_sharded_boundary_queues(self):
        """v2's memory contract: the pipeline body's input arrives stage-
        sharded ([S, c, mb, T, D] over pp), so per-stage activation
        residency is 1/S of the batch — not the v1 full replication."""
        cfg, params, tokens = _setup()
        mesh = make_mesh({"pp": 4}, jax.devices()[:4])
        logits = pipeline_forward(params, cfg, tokens, mesh, microbatches=8)
        ref, _ = forward(params, cfg, tokens)
        # Per-microbatch parity is the real layout proof: a wrong
        # stage-sharded round-trip would permute whole microbatches, so
        # every row matching in order pins the [S, c] interleave exactly.
        np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    def test_dryrun(self, capsys):
        dryrun_pipeline(8)
        assert "pipeline pp=" in capsys.readouterr().out
