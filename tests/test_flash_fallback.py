"""Engine safety net: a Pallas kernel that fails to lower must degrade
the engine to the XLA attention path, never kill the run.

Round 1's decode kernel shipped with a Mosaic-invalid BlockSpec and was
on by default on TPU backends — every hardware run crashed at first
dispatch and the bench recorded rc=1. The runner's contract is
best-effort (reference runner.go:75-83: a model failure is a warning);
these tests pin the guard that makes a kernel bug a perf regression
instead of a crash.
"""

import warnings

import jax.numpy as jnp
import pytest

import llm_consensus_tpu.ops.pallas as pallas_pkg
from llm_consensus_tpu.engine import Engine, SamplingParams
from llm_consensus_tpu.engine.engine import _is_pallas_lowering_error
from llm_consensus_tpu.models import get_config

MOSAIC_MSG = (
    "The Pallas TPU lowering currently requires that the last two "
    "dimensions of your block shape are divisible by 8 and 128"
)


def _broken_kernel(*args, **kwargs):
    raise ValueError(MOSAIC_MSG)


def test_lowering_error_detector():
    assert _is_pallas_lowering_error(ValueError(MOSAIC_MSG))
    assert _is_pallas_lowering_error(RuntimeError("Mosaic failed to compile"))
    assert not _is_pallas_lowering_error(ValueError("empty prompt"))
    assert not _is_pallas_lowering_error(MemoryError("oom"))

    # XlaRuntimeError is retryable ONLY in its compile-time form (the
    # Mosaic compiler rejecting a kernel, before any executable runs);
    # a runtime fault means donated buffers may be consumed, so even a
    # Mosaic-flavored message must propagate.
    class XlaRuntimeError(Exception):
        pass

    assert _is_pallas_lowering_error(
        XlaRuntimeError("INTERNAL: Mosaic failed to compile TPU kernel")
    )
    assert not _is_pallas_lowering_error(
        XlaRuntimeError("Mosaic custom call faulted at runtime")
    )


def test_decode_kernel_failure_falls_back_to_xla(monkeypatch):
    """A broken decode kernel pins the engine to XLA mid-run and the
    generation still produces the exact greedy tokens."""
    cfg = get_config("tiny-llama", head_dim=128)  # decode_flash-eligible
    # max_seq distinct from every other dh=128 engine test: the cache
    # shape must force a fresh trace, or a jit-cache hit from an earlier
    # test would dispatch a cached good program and never reach the
    # patched kernel.
    ref = Engine(cfg, dtype=jnp.float32, max_seq=160, attn_impl="xla")
    eng = Engine(
        cfg, params=ref.params, dtype=jnp.float32, max_seq=160,
        attn_impl="flash",
    )
    monkeypatch.setattr(pallas_pkg, "decode_attention", _broken_kernel)
    sampling = SamplingParams(max_new_tokens=8, ignore_eos=True)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        out = eng.generate("hello world consensus", sampling)
    assert eng.attn_impl == "xla"
    assert any("falling back to XLA" in str(w.message) for w in caught)
    assert out.token_ids == ref.generate("hello world consensus", sampling).token_ids


def test_prefill_kernel_failure_falls_back_to_xla(monkeypatch):
    """Same guard on the one-shot prefill dispatch (flash prefill path)."""
    cfg = get_config("tiny-llama")
    # max_seq distinct from other tiny-llama engine tests (see decode
    # test above for why the shapes must force a fresh trace).
    ref = Engine(
        cfg, dtype=jnp.float32, max_seq=96, attn_impl="xla",
        prefill_chunk=0,  # force the one-shot per-bucket prefill program
    )
    eng = Engine(
        cfg, params=ref.params, dtype=jnp.float32, max_seq=96,
        attn_impl="flash", prefill_chunk=0,
    )
    monkeypatch.setattr(pallas_pkg, "flash_attention", _broken_kernel)
    sampling = SamplingParams(max_new_tokens=4, ignore_eos=True)
    # 320 bytes under the byte tokenizer; _budget_prompt middle-out
    # truncates to fit max_seq=96 and the result pads to bucket 96,
    # whose block sizes flash_supported admits.
    prompt = "word " * 64
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        out = eng.generate(prompt, sampling)
    assert eng.attn_impl == "xla"
    assert any("falling back to XLA" in str(w.message) for w in caught)
    assert out.token_ids == ref.generate(prompt, sampling).token_ids


def test_non_pallas_errors_propagate():
    """The guard must not swallow genuine errors (e.g. bad prompts)."""
    cfg = get_config("tiny-llama")
    eng = Engine(cfg, dtype=jnp.float32, max_seq=32, attn_impl="flash")
    with pytest.raises(ValueError, match="empty prompt"):
        eng.generate_ids([], SamplingParams(max_new_tokens=4))
    assert eng.attn_impl == "flash"  # untouched by unrelated failures
