"""Deterministic fault injection (llm_consensus_tpu/faults/).

Three layers of proof:
  * plan mechanics — spec parsing, counter/probability matching, and the
    determinism contract: same seed + same spec ⇒ byte-identical fault
    sequence (trace_bytes);
  * injector sites — each injector fires where the spec names, with the
    stack's real recovery machinery absorbing it (elastic engine rebuild,
    SSE retry veto, runner watchdog, batcher per-stream failure);
  * zero-cost-when-disabled — no plan resolves without LLMC_FAULTS, and
    engines bind None at construction.
"""

import time

import pytest

from llm_consensus_tpu import faults
from llm_consensus_tpu.faults import FaultPlan, InjectedFault, parse_spec
from llm_consensus_tpu.providers import Request
from llm_consensus_tpu.providers.base import ProviderFunc, Response
from llm_consensus_tpu.providers.registry import Registry
from llm_consensus_tpu.utils.context import Context

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean_plan(monkeypatch):
    """Every test starts and ends with no ambient plan."""
    monkeypatch.delenv("LLMC_FAULTS", raising=False)
    faults.reset()
    yield
    faults.reset()


# -- plan mechanics -----------------------------------------------------------


def test_parse_spec_grammar():
    specs = parse_spec(
        "prefill_oom@step=3,controller_drop@host=1,sse_reset@chunk=2"
    )
    assert [s.kind for s in specs] == [
        "prefill_oom", "controller_drop", "sse_reset"
    ]
    assert specs[0].args == {"step": "3"}
    assert specs[1].args == {"host": "1"}
    assert specs[2].args == {"chunk": "2"}
    assert all(s.times == 1 for s in specs)


def test_parse_spec_rejects_unknown_kind():
    with pytest.raises(ValueError, match="unknown fault kind"):
        parse_spec("meteor_strike@step=1")


def test_counter_matching_is_one_indexed():
    plan = FaultPlan("decode_fault@step=3")
    assert plan.fire("decode") is None
    assert plan.fire("decode") is None
    assert plan.fire("decode") is not None  # the 3rd dispatch
    assert plan.fire("decode") is None  # times=1 exhausted


def test_attribute_matching():
    plan = FaultPlan("worker_stall@model=slow")
    assert plan.fire("runner", model="fast") is None
    assert plan.fire("runner", model="slow") is not None


def test_kinds_only_fire_at_their_site():
    plan = FaultPlan("prefill_oom@step=1")
    assert plan.fire("decode") is None
    assert plan.fire("sse") is None
    assert plan.fire("prefill") is not None


def test_times_caps_fires():
    plan = FaultPlan("decode_fault@times=2")
    assert plan.fire("decode") is not None
    assert plan.fire("decode") is not None
    assert plan.fire("decode") is None


def test_same_seed_same_spec_byte_identical_sequence():
    spec = "decode_fault@p=0.5@times=-1,prefill_oom@step=2"

    def drive(plan: FaultPlan) -> bytes:
        for i in range(64):
            plan.fire("decode", step=i)
        plan.fire("prefill")
        plan.fire("prefill")
        plan.fire("runner", model="m")
        return plan.trace_bytes()

    a = drive(FaultPlan(spec, seed=1234))
    b = drive(FaultPlan(spec, seed=1234))
    assert a == b  # the acceptance contract: byte-identical
    c = drive(FaultPlan(spec, seed=4321))
    assert a != c  # the probabilistic draws actually depend on the seed


def test_plan_disabled_without_env():
    assert faults.plan() is None


def test_plan_resolves_from_env(monkeypatch):
    monkeypatch.setenv("LLMC_FAULTS", "decode_fault@step=1")
    monkeypatch.setenv("LLMC_FAULTS_SEED", "99")
    faults.reset()
    plan = faults.plan()
    assert plan is not None and plan.seed == 99
    assert faults.plan() is plan  # resolved once, cached


# -- engine sites -------------------------------------------------------------


def _tiny_engine():
    from llm_consensus_tpu.engine import Engine
    from llm_consensus_tpu.models.config import get_config

    return Engine(get_config("tiny-llama"), stream_interval=4, max_seq=128)


def test_engine_binds_no_plan_when_disabled():
    eng = _tiny_engine()
    assert eng._faults is None  # zero-cost: one None-check per dispatch


def test_injected_prefill_oom_fails_then_clears():
    from llm_consensus_tpu.engine import SamplingParams

    faults.install(FaultPlan("prefill_oom@step=1"))
    eng = _tiny_engine()
    with pytest.raises(InjectedFault, match="prefill_oom"):
        eng.generate("boom", SamplingParams(max_new_tokens=2, ignore_eos=True))
    # times=1: the very next generate prefilled cleanly.
    out = eng.generate(
        "fine now", SamplingParams(max_new_tokens=2, ignore_eos=True)
    )
    assert len(out.token_ids) == 2


def test_injected_decode_fault():
    from llm_consensus_tpu.engine import SamplingParams

    faults.install(FaultPlan("decode_fault@step=1"))
    eng = _tiny_engine()
    with pytest.raises(InjectedFault, match="decode_fault"):
        eng.generate("boom", SamplingParams(max_new_tokens=8, ignore_eos=True))


def test_tpu_provider_elastic_recovery_from_injected_oom():
    """The provider's evict→rebuild ladder absorbs one injected prefill
    OOM: the query still answers (best-effort semantics end-to-end)."""
    from llm_consensus_tpu.providers.tpu import TPUProvider

    faults.install(FaultPlan("prefill_oom@step=1"))
    provider = TPUProvider(ignore_eos=True, stream_interval=4)
    resp = provider.query(
        Context.background(),
        Request(model="tpu:tiny-llama", prompt="recover", max_tokens=2),
    )
    assert resp.tokens == 2
    plan = faults.plan()
    assert any(ln.endswith("->prefill_oom") for ln in plan.trace)


def test_injected_build_fail_rides_replacement_ladder():
    """build_fail on the first construction: the rebuild (2nd build)
    serves the query."""
    from llm_consensus_tpu.providers.tpu import TPUProvider

    faults.install(FaultPlan("build_fail@preset=tiny-llama"))
    provider = TPUProvider(ignore_eos=True, stream_interval=4)
    resp = provider.query(
        Context.background(),
        Request(model="tpu:tiny-llama", prompt="rebuild", max_tokens=2),
    )
    assert resp.tokens == 2


def test_batcher_books_admit_time_for_failed_prefill():
    """A failed admission prefill fails THAT stream and still counts its
    host wall toward admit_s (ADVICE r5 batcher.py:1326)."""
    from llm_consensus_tpu.engine import ContinuousBatcher, SamplingParams

    # times=2: both the batched-wave attempt and the single-stream
    # fallback die, so the stream's future carries the injected fault.
    faults.install(FaultPlan("prefill_oom@times=2"))
    eng = _tiny_engine()
    batcher = ContinuousBatcher(eng, max_batch=2)
    try:
        fut = batcher.submit(
            "doomed", SamplingParams(max_new_tokens=2, ignore_eos=True)
        )
        with pytest.raises(InjectedFault):
            fut.result(timeout=60)
        deadline = time.monotonic() + 10
        while batcher.stats["admit_s"] == 0.0:
            assert time.monotonic() < deadline, "admit_s never booked"
            time.sleep(0.01)
        assert batcher.stats["admit_s"] > 0.0
    finally:
        batcher.close()


# -- SSE site -----------------------------------------------------------------


def test_sse_reset_injector_unit():
    plan = FaultPlan("sse_reset@chunk=2")
    assert plan.fire("sse") is None
    assert plan.fire("sse") is not None


# -- runner site --------------------------------------------------------------


def _fake(name: str, content: str = "answer"):
    return ProviderFunc(
        lambda ctx, req: Response(
            model=req.model, content=content, provider="fake"
        )
    )


def test_worker_stall_watchdog_abandons_without_blocking_join():
    from llm_consensus_tpu.runner import Runner

    faults.install(FaultPlan("worker_stall@model=stuck@s=5"))
    reg = Registry()
    reg.register("alive", _fake("alive"))
    reg.register("stuck", _fake("stuck"))
    runner = Runner(reg, timeout=0.2, stall_grace=0.2)
    t0 = time.monotonic()
    result = runner.run(Context.background(), ["alive", "stuck"], "q")
    wall = time.monotonic() - t0
    assert wall < 4.0, f"join blocked on the stalled worker ({wall:.1f}s)"
    assert [r.model for r in result.responses] == ["alive"]
    assert result.failed_models == ["stuck"]
    assert any("abandoned" in w for w in result.warnings)


def test_duplicate_model_stall_does_not_conflate_workers():
    """Watchdog state is per-worker, not per-name: with the same model
    requested twice and ONE worker stalled (times=1), the other
    duplicate's genuine response survives — one failure, one response."""
    from llm_consensus_tpu.runner import Runner

    faults.install(FaultPlan("worker_stall@model=m@s=5"))
    reg = Registry()
    reg.register("m", _fake("m"))
    runner = Runner(reg, timeout=0.2, stall_grace=0.2)
    result = runner.run(Context.background(), ["m", "m"], "q")
    assert [r.model for r in result.responses] == ["m"]
    assert result.failed_models == ["m"]
    assert sum("abandoned" in w for w in result.warnings) == 1


def test_probability_draw_is_order_independent():
    """p= consumes an RNG draw only when every other qualifier matched,
    no matter where p= sits in the spec — so unrelated dispatches cannot
    shift later probabilistic decisions."""
    def drive(spec: str) -> list[str]:
        plan = FaultPlan(spec + ",decode_fault@p=0.5@times=-1", seed=5)
        fired = []
        for i in range(32):
            plan.fire("prefill", model="other")  # never matches model=x
            fs = plan.fire("decode")
            fired.append(fs.kind if fs else "-")
        return fired

    a = drive("prefill_oom@p=0.5@model=x")
    b = drive("prefill_oom@model=x@p=0.5")
    assert a == b


def test_streaming_worker_is_not_declared_stalled():
    """A worker past its deadline but still streaming gets grace from its
    last activity, not its deadline — slow-but-alive is not stalled."""
    from llm_consensus_tpu.providers.base import Provider
    from llm_consensus_tpu.runner import Runner

    class SlowStreamer(Provider):
        name = "slow"

        def query(self, ctx, req):
            return self.query_stream(ctx, req, None)

        def query_stream(self, ctx, req, callback):
            for _ in range(6):
                time.sleep(0.1)
                if callback is not None:
                    callback("chunk ")
            return Response(model=req.model, content="done", provider="fake")

    reg = Registry()
    reg.register("slow", SlowStreamer())
    runner = Runner(reg, timeout=0.2, stall_grace=0.3)
    result = runner.run(Context.background(), ["slow"], "q")
    assert [r.model for r in result.responses] == ["slow"]
    assert result.failed_models == []
