"""Fused decode-attention kernel vs the XLA reference path.

Round 1 shipped this kernel with a Mosaic-invalid K/V BlockSpec that only
surfaced on real TPU (interpret mode executes the kernel program without
the tiling checks), taking down the whole bench. This file closes both
gaps the advisor flagged:

  * interpret-mode parity at production head_dim=128 — covering row_start,
    sliding_window, logit_softcap, non-block-multiple widths, and pos=0 —
    against the exact mask semantics transformer.forward builds for the
    XLA decode path;
  * cross-platform **TPU lowering** smoke tests: ``jax.export`` with
    ``platforms=["tpu"]`` runs the Mosaic lowering (including BlockSpec
    tiling validation) on the CPU test mesh, so a kernel that cannot
    compile for TPU fails CI instead of failing the fleet.
"""

import functools

import jax
import jax.numpy as jnp
import pytest

from llm_consensus_tpu.ops.attention import attention, make_attention_mask
from llm_consensus_tpu.ops.pallas import decode_attention, decode_flash_supported


def _reference(q, k, v, pos, row_start=None, sliding_window=None,
               logit_softcap=None):
    """The XLA decode path: attention() under the T=1 cache mask that
    transformer.forward builds (row-relative positions, kv_valid frontier)."""
    b = q.shape[0]
    s = k.shape[1]
    pos = jnp.asarray(pos, jnp.int32)
    rs = jnp.zeros((b,), jnp.int32) if row_start is None else row_start
    q_pos = jnp.broadcast_to(pos[None, None], (b, 1)) - rs[:, None]
    kv_slots = jnp.arange(s, dtype=jnp.int32)[None, :]
    kv_valid = jnp.broadcast_to(kv_slots < pos + 1, (b, s))
    kv_valid = jnp.logical_and(kv_valid, kv_slots >= rs[:, None])
    kv_pos = jnp.broadcast_to(kv_slots, (b, s)) - rs[:, None]
    mask = make_attention_mask(q_pos, kv_pos, kv_valid, sliding_window)
    return attention(q, k, v, mask, logit_softcap=logit_softcap)


def _qkv(key, b, w, hq, hkv, dh, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    return (
        jax.random.normal(kq, (b, 1, hq, dh), dtype),
        jax.random.normal(kk, (b, w, hkv, dh), dtype),
        jax.random.normal(kv, (b, w, hkv, dh), dtype),
    )


def _stack(x):
    """Per-layer entry -> 1-layer stacked cache (the kernel's new operand
    form: [L, B, S, Hkv, dh] with layer selection via scalar prefetch)."""
    import jax as _jax
    return _jax.tree.map(lambda a: a[None], x)


CASES = [
    # (b, w, hq, hkv, pos, window, softcap, row_start)
    (1, 512, 8, 8, 300, None, None, None),    # MHA, mid-cache frontier
    (2, 512, 16, 8, 511, None, None, None),   # GQA g=2, full width
    (2, 300, 8, 2, 150, None, None, (0, 37)), # non-block-multiple width + pads
    (1, 512, 8, 1, 0, None, None, None),      # MQA, pos=0 (first decode step)
    (2, 512, 8, 8, 400, 128, 50.0, None),     # sliding window + softcap
    (4, 96, 8, 4, 95, None, None, (3, 0, 10, 90)),  # small ragged batch
    (1, 24, 4, 2, 20, 8, None, None),         # width below one kv block
]


@pytest.mark.parametrize("case", CASES)
def test_decode_matches_xla_reference_f32(case):
    b, w, hq, hkv, pos, window, cap, rs = case
    dh = 128  # production head_dim — the size the kernel auto-enables for
    q, k, v = _qkv(jax.random.PRNGKey(0), b, w, hq, hkv, dh)
    row_start = None if rs is None else jnp.asarray(rs, jnp.int32)
    with jax.default_matmul_precision("highest"):
        got = decode_attention(
            q, _stack(k), _stack(v), jnp.int32(pos), 0, row_start,
            sliding_window=window, logit_softcap=cap, interpret=True,
        )
        want = _reference(q, k, v, pos, row_start, window, cap)
    assert got.shape == want.shape
    assert jnp.allclose(got, want, atol=1e-5, rtol=1e-5), (
        float(jnp.abs(got - want).max())
    )


def test_decode_never_reads_beyond_frontier():
    """NaNs in unwritten cache slots must not leak into the output."""
    b, w, hq, hkv, dh, pos = 1, 512, 8, 4, 128, 100
    q, k, v = _qkv(jax.random.PRNGKey(1), b, w, hq, hkv, dh)
    k = k.at[:, pos + 1:].set(jnp.nan)
    v = v.at[:, pos + 1:].set(jnp.nan)
    got = decode_attention(
        q, _stack(k), _stack(v), jnp.int32(pos), interpret=True
    )
    assert not bool(jnp.isnan(got).any())


def test_decode_traced_pos_one_program():
    """pos is data, not shape: one jitted program serves every step."""
    b, w, hq, hkv, dh = 1, 256, 8, 4, 128
    q, k, v = _qkv(jax.random.PRNGKey(2), b, w, hq, hkv, dh)

    @jax.jit
    def f(q, k, v, pos):
        return decode_attention(q, _stack(k), _stack(v), pos, interpret=True)

    with jax.default_matmul_precision("highest"):
        for pos in (0, 17, 255):
            got = f(q, k, v, jnp.int32(pos))
            want = _reference(q, k, v, pos)
            assert jnp.allclose(got, want, atol=1e-5, rtol=1e-5)


def test_decode_flash_supported_gate():
    assert decode_flash_supported(16, 8, 128)    # consensus-1b
    assert decode_flash_supported(8, 1, 128)     # MQA
    assert decode_flash_supported(32, 8, 256)    # gemma-ish dh
    assert not decode_flash_supported(16, 8, 32)   # lane dim not 128-aligned
    assert not decode_flash_supported(15, 8, 128)  # ragged GQA
    # width legality: the grid must cover the span in Mosaic-legal blocks
    assert decode_flash_supported(16, 8, 128, width=4096)
    assert decode_flash_supported(16, 8, 128, width=96)       # 32-divisible
    assert not decode_flash_supported(16, 8, 128, width=300)  # pow2 divisor 4
    assert decode_flash_supported(16, 8, 128, width=24)       # full-ish bk=8
    assert not decode_flash_supported(16, 8, 128, width=24, quantized=True)
    assert decode_flash_supported(16, 8, 128, width=4096, quantized=True)


def test_decode_layer_selection():
    """layer_idx pages the right layer's K/V out of the stack."""
    b, w, hq, hkv, dh, pos = 2, 128, 8, 4, 128, 100
    key = jax.random.PRNGKey(7)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, 1, hq, dh), jnp.float32)
    k_stack = jax.random.normal(kk, (3, b, w, hkv, dh), jnp.float32)
    v_stack = jax.random.normal(kv, (3, b, w, hkv, dh), jnp.float32)
    with jax.default_matmul_precision("highest"):
        for li in range(3):
            got = decode_attention(
                q, k_stack, v_stack, jnp.int32(pos), jnp.int32(li),
                interpret=True,
            )
            want = _reference(q, k_stack[li], v_stack[li], pos)
            assert jnp.allclose(got, want, atol=1e-5, rtol=1e-5), li


def test_engine_decode_flash_same_tokens():
    """Engine with the fused decode kernel emits the identical greedy
    sequence as the XLA attention path at production head_dim."""
    from llm_consensus_tpu.engine import Engine, SamplingParams
    from llm_consensus_tpu.models import get_config

    cfg = get_config("tiny-llama", head_dim=128)
    base = Engine(cfg, dtype=jnp.float32, max_seq=128, attn_impl="xla")
    flash = Engine(
        cfg, params=base.params, dtype=jnp.float32, max_seq=128,
        attn_impl="flash",
    )
    sampling = SamplingParams(max_new_tokens=12, ignore_eos=True)
    prompt = "the quick brown fox jumps over the lazy dog"
    assert (
        base.generate(prompt, sampling).token_ids
        == flash.generate(prompt, sampling).token_ids
    )


# ---------------------------------------------------------------------------
# TPU lowering smoke tests (the round-1 escape: interpret mode cannot catch
# Mosaic tiling violations; cross-platform export runs the real lowering).
# ---------------------------------------------------------------------------

def _lower_for_tpu(fn, *args):
    if not hasattr(jax, "export"):
        # Older jax: the cross-platform export API isn't available, so
        # the real Mosaic lowering can't run off-TPU — skip rather than
        # fail the whole numerics file on an API gap.
        pytest.skip("jax.export unavailable in this jax version")
    jax.export.export(jax.jit(fn), platforms=["tpu"])(*args)


@pytest.mark.parametrize(
    "b,w,hq,hkv,dh",
    [
        (1, 512, 16, 8, 128),   # consensus-1b decode shape (round-1 crash)
        (1, 512, 24, 8, 128),   # consensus-3b
        (2, 64, 8, 8, 128),     # width below the default kv block
        (1, 1024, 16, 16, 256), # MHA, wide head
        (8, 512, 16, 8, 128),   # continuous-batching layout
    ],
)
def test_decode_kernel_lowers_for_tpu(b, w, hq, hkv, dh):
    q, k, v = _qkv(jax.random.PRNGKey(0), b, w, hq, hkv, dh, jnp.bfloat16)
    rs = jnp.zeros((b,), jnp.int32)
    _lower_for_tpu(
        functools.partial(
            decode_attention, interpret=False, sliding_window=None,
        ),
        q, _stack(k), _stack(v), jnp.int32(3), jnp.int32(0), rs,
    )


def test_prefill_kernel_lowers_for_tpu():
    from llm_consensus_tpu.ops.pallas import flash_attention

    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (1, 128, 16, 128), jnp.bfloat16)
    k = jax.random.normal(kk, (1, 512, 8, 128), jnp.bfloat16)
    v = jax.random.normal(kv, (1, 512, 8, 128), jnp.bfloat16)
    _lower_for_tpu(
        functools.partial(flash_attention, q_offset=0, interpret=False),
        q, k, v,
    )


# ---------------------------------------------------------------------------
# int8 KV entries consumed directly (dequant per block in VMEM)
# ---------------------------------------------------------------------------


def _quantize_entry(x):
    """[B, W, H, dh] → {"q8", "s"} with the engine's per-row scaling."""
    from llm_consensus_tpu.ops.quant import quantize_kv

    q8, s = quantize_kv(x)
    # seq-minor scale layout [B, H, W] (the cache's storage form)
    return {"q8": q8, "s": jnp.swapaxes(s[..., 0], 1, 2)}


@pytest.mark.parametrize(
    "b,w,hq,hkv,pos,window,rs",
    [
        (1, 512, 16, 8, 300, None, None),
        (2, 300, 8, 2, 150, None, (0, 37)),   # ragged width + row pads
        (2, 512, 8, 8, 400, 128, None),       # sliding window
        (1, 512, 8, 1, 0, None, None),        # MQA, first step
    ],
)
def test_decode_int8_kv_matches_dequantized(b, w, hq, hkv, pos, window, rs):
    """The kernel consuming int8 {"q8","s"} entries must equal the float
    kernel over the dequantized arrays — the quantization error itself is
    shared, so outputs match tightly."""
    from llm_consensus_tpu.ops.quant import kv_read

    dh = 128
    q, k, v = _qkv(jax.random.PRNGKey(3), b, w, hq, hkv, dh)
    kq, vq = _quantize_entry(k), _quantize_entry(v)
    k_deq, v_deq = kv_read(kq, jnp.float32), kv_read(vq, jnp.float32)
    row_start = None if rs is None else jnp.asarray(rs, jnp.int32)
    with jax.default_matmul_precision("highest"):
        got = decode_attention(
            q, _stack(kq), _stack(vq), jnp.int32(pos), 0, row_start,
            sliding_window=window, interpret=True,
        )
        want = decode_attention(
            q, _stack(k_deq), _stack(v_deq), jnp.int32(pos), 0, row_start,
            sliding_window=window, interpret=True,
        )
    assert jnp.allclose(got, want, atol=2e-4, rtol=2e-4), (
        float(jnp.abs(got - want).max())
    )


def test_engine_decode_flash_int8_kv_same_tokens():
    """Engine with int8 KV cache + the fused decode kernel (which reads
    codes directly) emits the identical greedy sequence to the XLA path
    over the same int8 cache."""
    from llm_consensus_tpu.engine import Engine, SamplingParams
    from llm_consensus_tpu.models import get_config

    cfg = get_config("tiny-llama", head_dim=128)
    base = Engine(cfg, dtype=jnp.float32, max_seq=192, attn_impl="xla",
                  kv_quant="int8")
    flash = Engine(
        cfg, params=base.params, dtype=jnp.float32, max_seq=192,
        attn_impl="flash", kv_quant="int8",
    )
    sampling = SamplingParams(max_new_tokens=12, ignore_eos=True)
    prompt = "int8 cache direct decode parity"
    assert (
        base.generate(prompt, sampling).token_ids
        == flash.generate(prompt, sampling).token_ids
    )


def test_decode_kernel_int8_lowers_for_tpu():
    q, k, v = _qkv(jax.random.PRNGKey(0), 2, 512, 16, 8, 128, jnp.bfloat16)
    kq, vq = _quantize_entry(k), _quantize_entry(v)
    rs = jnp.zeros((2,), jnp.int32)
    _lower_for_tpu(
        functools.partial(decode_attention, interpret=False),
        q, _stack(kq), _stack(vq), jnp.int32(3), jnp.int32(0), rs,
    )


def test_decode_kernel_b_block8_lowers_for_tpu():
    """The production large-batch serving shape (int8 KV, bucket 128,
    b >= 8) selects b_block=8 — the full batch-row-blocked kernel with
    unrolled row-start selects must pass Mosaic lowering, not just the
    b_block<=2 shapes the other smoke cases reach."""
    q, k, v = _qkv(jax.random.PRNGKey(0), 16, 128, 16, 8, 128, jnp.bfloat16)
    kq, vq = _quantize_entry(k), _quantize_entry(v)
    rs = jnp.arange(16, dtype=jnp.int32)
    _lower_for_tpu(
        functools.partial(decode_attention, interpret=False),
        q, _stack(kq), _stack(vq), jnp.int32(100), jnp.int32(0), rs,
    )


def test_decode_b_block8_parity_ragged_rows():
    """Interpret-mode parity at a shape that selects b_block=8 with
    ragged per-row frontiers (every row of a block having a different
    row_start exercises the unrolled scalar-select mask build). w=64
    keeps the f32 K/V blocks inside the VMEM budget at b_block=8 —
    wider f32 shapes would silently degrade to b_block=4."""
    b, w, hq, hkv, dh, pos = 16, 64, 16, 8, 128, 60
    q, k, v = _qkv(jax.random.PRNGKey(5), b, w, hq, hkv, dh)
    rs = jnp.asarray([i * 3 % 40 for i in range(b)], jnp.int32)
    with jax.default_matmul_precision("highest"):
        got = decode_attention(
            q, _stack(k), _stack(v), jnp.int32(pos), 0, rs, interpret=True
        )
        want = _reference(q, k, v, pos, rs)
    assert jnp.allclose(got, want, atol=1e-5, rtol=1e-5), (
        float(jnp.abs(got - want).max())
    )


def test_tp_sharded_decode_flash_int8_kv_same_tokens():
    """TP shard_map over the decode kernel with an int8 KV cache: the 4-D
    seq-minor scale leaves need a 4-axis spec (heads on axis 2) — a 5-axis
    spec crashes shard_map with a message _flash_guard cannot classify as
    a lowering failure, so this path must work, not fall back."""
    import numpy as np
    from jax.sharding import Mesh

    from llm_consensus_tpu.engine import Engine, SamplingParams
    from llm_consensus_tpu.models import get_config, init_params

    cfg = get_config("tiny-llama", head_dim=128)
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(1, 2), ("dp", "tp"))
    base = Engine(cfg, params=params, dtype=jnp.float32, max_seq=128,
                  attn_impl="xla", kv_quant="int8", mesh=mesh)
    flash = Engine(cfg, params=params, dtype=jnp.float32, max_seq=128,
                   attn_impl="flash", kv_quant="int8", mesh=mesh)
    s = SamplingParams(max_new_tokens=10, ignore_eos=True)
    prompt = "tp int8 kv decode flash parity"
    got = flash.generate(prompt, s)
    assert flash.attn_impl == "flash", "kernel fell back to XLA under tp"
    assert got.token_ids == base.generate(prompt, s).token_ids


@pytest.mark.parametrize(
    "window,cap,rs",
    [
        (None, None, None),            # plain
        (128, None, None),             # sliding window
        (None, 50.0, None),            # logit softcap
        (None, None, (0, 37, 5, 90)),  # ragged per-row frontiers
    ],
)
def test_w8a8_scores_close_to_float(monkeypatch, window, cap, rs):
    """Opt-in int8×int8 MXU scores: output stays within the combined
    int8-KV + q-rounding error envelope of the float kernel across the
    masking variants (window / softcap / row_start) so the w8a8 path's
    shared-tail wiring is actually executed, not just the default."""
    b, w, hq, hkv, dh, pos = 4, 256, 16, 8, 128, 200
    q, k, v = _qkv(jax.random.PRNGKey(9), b, w, hq, hkv, dh)
    kq, vq = _quantize_entry(k), _quantize_entry(v)
    row_start = None if rs is None else jnp.asarray(rs, jnp.int32)
    kwargs = dict(sliding_window=window, logit_softcap=cap, interpret=True)
    with jax.default_matmul_precision("highest"):
        monkeypatch.setenv("LLMC_DECODE_W8A8", "1")
        got = decode_attention(
            q, _stack(kq), _stack(vq), jnp.int32(pos), 0, row_start, **kwargs
        )
        monkeypatch.setenv("LLMC_DECODE_W8A8", "0")
        want = decode_attention(
            q, _stack(kq), _stack(vq), jnp.int32(pos), 0, row_start, **kwargs
        )
    err = float(jnp.abs(got.astype(jnp.float32) - want.astype(jnp.float32)).max())
    rel = err / float(jnp.abs(want).max())
    assert rel < 2e-2, rel


def test_w8a8_kernel_lowers_for_tpu(monkeypatch):
    monkeypatch.setenv("LLMC_DECODE_W8A8", "1")
    q, k, v = _qkv(jax.random.PRNGKey(0), 8, 512, 16, 8, 128, jnp.bfloat16)
    kq, vq = _quantize_entry(k), _quantize_entry(v)
    rs = jnp.zeros((8,), jnp.int32)
    _lower_for_tpu(
        functools.partial(decode_attention, interpret=False),
        q, _stack(kq), _stack(vq), jnp.int32(100), jnp.int32(0), rs,
    )
