"""Voting mode + multi-round consensus (reference roadmap §§2.2-2.3,
unimplemented there — TPU-build extensions)."""

import json

from llm_consensus_tpu.consensus.vote import (
    parse_vote,
    render_vote_prompt,
    tally_votes,
)
from llm_consensus_tpu.providers import ProviderFunc, Response

from tests.test_cli import run_cli


def _resp(model, content):
    return Response(model, content, "fake", 1.0)


def test_parse_vote_first_line_exact():
    assert parse_vote("B\nbecause reasons", ["A", "B", "C"]) == "B"
    assert parse_vote("- C.\nexplanation", ["A", "B", "C"]) == "C"


def test_parse_vote_last_mention_fallback():
    # Conclusions come last: the latest-mentioned option wins the fallback.
    assert parse_vote("While A is popular, B is the better fit.", ["A", "B"]) == "B"
    assert parse_vote("B is tempting, but in the end A wins.", ["A", "B"]) == "A"
    assert parse_vote("no option mentioned", ["A", "B"]) is None


def test_parse_vote_whole_word_only():
    # "A" inside "Apple" must not count as a vote for A.
    assert parse_vote("B it is. Apples are nice.", ["A", "B"]) == "B"


def test_tally_plurality_and_tie_break():
    r = tally_votes(
        [_resp("m1", "A"), _resp("m2", "B"), _resp("m3", "A")], ["A", "B"]
    )
    assert r.winner == "A" and r.counts == {"A": 2, "B": 1}
    tie = tally_votes([_resp("m1", "B"), _resp("m2", "A")], ["A", "B"])
    assert tie.winner == "A"  # option order breaks ties


def test_tally_unparsed_recorded():
    r = tally_votes([_resp("m1", "hmm"), _resp("m2", "B")], ["A", "B"])
    assert r.winner == "B"
    assert r.unparsed == ["m1"]
    assert "(no vote parsed): m1" in r.summary()


def test_render_vote_prompt_lists_options():
    p = render_vote_prompt("pick one", ["X", "Y"])
    assert "pick one" in p and "- X" in p and "- Y" in p


# -- CLI integration ---------------------------------------------------------


def _vote_factory(model: str):
    choice = {"m1": "A", "m2": "B", "m3": "A"}.get(model, "A")
    return ProviderFunc(
        lambda ctx, req, c=choice: Response(req.model, c, "fake", 1.0)
    )


def test_cli_vote_mode_tallies_without_judge():
    code, out, err = run_cli(
        ["--models", "m1,m2,m3", "--vote", "--options", "A,B",
         "--json", "ask"],
        factory=_vote_factory,
    )
    assert code == 0, err
    data = json.loads(out)
    assert data["judge"] == "vote"
    assert data["consensus"].startswith("A")
    assert "A: 2" in data["consensus"] and "B: 1" in data["consensus"]


def test_cli_vote_requires_options():
    code, _, err = run_cli(["--models", "m1", "--vote", "ask"])
    assert code == 1 and "--vote requires --options" in err


def test_cli_options_without_vote_rejected():
    code, _, err = run_cli(["--models", "m1", "--options", "A,B", "ask"])
    assert code == 1 and "--options only applies with --vote" in err


def test_cli_vote_skips_judge_provider():
    """The judge provider must never be constructed in vote mode — a
    default judge needing an API key can't break a tpu-only vote."""
    built = []

    def factory(model):
        built.append(model)
        return _vote_factory(model)

    code, out, _ = run_cli(
        ["--models", "m1,m2", "--vote", "--options", "A,B", "--json", "q"],
        factory=factory,
    )
    assert code == 0
    assert set(built) == {"m1", "m2"}  # no gpt-5.2 default judge


def test_cli_multi_round_refines():
    """--rounds 2: panel critiques the draft; the judge's second pass sees
    the draft and the critiques."""
    judge_prompts = []

    def factory(model):
        if model == "j":
            def judge_fn(ctx, req):
                judge_prompts.append(req.prompt)
                n = len(judge_prompts)
                return Response(req.model, f"draft-v{n}", "fake", 1.0)
            return ProviderFunc(judge_fn)
        return ProviderFunc(
            lambda ctx, req: Response(
                req.model,
                "critique!" if "Draft answer" in req.prompt else "answer",
                "fake", 1.0,
            )
        )

    code, out, err = run_cli(
        ["--models", "m1,m2", "--judge", "j", "--rounds", "2", "--json", "q"],
        factory=factory,
    )
    assert code == 0, err
    data = json.loads(out)
    assert data["consensus"] == "draft-v2"
    assert len(judge_prompts) == 2
    assert "draft-v1" in judge_prompts[1]       # refine sees the draft
    assert "critique!" in judge_prompts[1]      # ...and the critiques
    # Round 1's panel answers (not critiques) are what the Result records.
    assert all(r["content"] == "answer" for r in data["responses"])


def test_cli_vote_rounds_mutually_exclusive():
    code, _, err = run_cli(
        ["--models", "m1", "--vote", "--options", "A,B", "--rounds", "2", "q"]
    )
    assert code == 1 and "mutually exclusive" in err


def test_cli_rounds_must_be_positive():
    code, _, err = run_cli(["--models", "m1", "--rounds", "0", "q"])
    assert code == 1 and "--rounds must be >= 1" in err


def test_cli_round_failure_keeps_prior_consensus():
    """A failed refinement round must not discard the consensus already
    in hand — it degrades to a warning (best-effort design)."""
    calls = {"panel": 0}

    def factory(model):
        if model == "j":
            return ProviderFunc(
                lambda ctx, req: Response(req.model, "draft-v1", "fake", 1.0)
            )

        def panel_fn(ctx, req):
            calls["panel"] += 1
            if "Draft answer" in req.prompt:
                raise RuntimeError("panel exploded in round 2")
            return Response(req.model, "answer", "fake", 1.0)

        return ProviderFunc(panel_fn)

    code, out, err = run_cli(
        ["--models", "m1", "--judge", "j", "--rounds", "2", "--json", "q"],
        factory=factory,
    )
    assert code == 0, err
    data = json.loads(out)
    # Single model: round 1 is the passthrough answer; round 2 fails and
    # the run keeps it rather than aborting.
    assert data["consensus"] == "answer"
    assert any("round 2 critique failed" in w for w in data.get("warnings", []))


def test_cli_vote_with_tpu_judge_needs_no_tpu_stack():
    """In vote mode a tpu: judge name must not trigger cluster init or
    provider construction."""
    code, out, _ = run_cli(
        ["--models", "m1,m2", "--vote", "--options", "A,B",
         "--judge", "tpu:llama-3-70b", "--json", "q"],
        factory=_vote_factory,
    )
    assert code == 0
    assert json.loads(out)["judge"] == "vote"
