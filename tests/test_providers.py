"""Provider contract tests (reference seam: provider.go:39-55)."""

from llm_consensus_tpu.providers import ProviderFunc, Request, Response
from llm_consensus_tpu.utils import Context


def make_response(model="m", content="hello", provider="test", latency_ms=5.0):
    return Response(model=model, content=content, provider=provider, latency_ms=latency_ms)


def test_provider_func_query():
    p = ProviderFunc(lambda ctx, req: make_response(model=req.model))
    resp = p.query(Context.background(), Request(model="x", prompt="hi"))
    assert resp.model == "x"
    assert resp.content == "hello"


def test_provider_func_stream_fires_callback_once_with_full_content():
    # Parity: ProviderFunc.QueryStream calls Query then invokes the callback
    # exactly once with the complete content (provider.go:48-55).
    p = ProviderFunc(lambda ctx, req: make_response(content="full text"))
    chunks = []
    resp = p.query_stream(Context.background(), Request(model="x", prompt="p"), chunks.append)
    assert chunks == ["full text"]
    assert resp.content == "full text"


def test_provider_func_stream_none_callback():
    p = ProviderFunc(lambda ctx, req: make_response())
    resp = p.query_stream(Context.background(), Request(model="x", prompt="p"), None)
    assert resp.content == "hello"


def test_provider_func_stream_error_skips_callback():
    def fail(ctx, req):
        raise RuntimeError("boom")

    p = ProviderFunc(fail)
    chunks = []
    try:
        p.query_stream(Context.background(), Request(model="x", prompt="p"), chunks.append)
        raise AssertionError("expected error")
    except RuntimeError:
        pass
    assert chunks == []


def test_response_json_shape():
    # Parity: JSON keys model/content/provider/latency_ms (provider.go:30-35).
    d = make_response(latency_ms=123.4).to_dict()
    assert d == {
        "model": "m",
        "content": "hello",
        "provider": "test",
        "latency_ms": 123.4,
    }
