"""FLOPs accounting (utils/flops.py): analytic counts vs real param trees.

The reference's only throughput signal is a chars/4 estimate
(/root/reference/internal/ui/ui.go:142); these tests pin the real
accounting that replaces it.
"""

import jax
import jax.numpy as jnp
import pytest

from llm_consensus_tpu.models import get_config, init_params
from llm_consensus_tpu.utils.flops import (
    decode_mfu,
    device_peak_flops,
    flops_per_token,
    param_count,
)


@pytest.mark.parametrize(
    "preset", ["tiny-llama", "tiny-gemma", "tiny-qwen2", "tiny-mistral", "tiny-mixtral"]
)
def test_param_count_matches_init_params(preset):
    cfg = get_config(preset)
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    actual = sum(x.size for x in jax.tree.leaves(params))
    assert param_count(cfg) == actual


def test_active_param_count_moe():
    cfg = get_config("tiny-mixtral")
    assert param_count(cfg, active_only=True) < param_count(cfg)
    dense = get_config("tiny-llama")
    assert param_count(dense, active_only=True) == param_count(dense)


def test_flops_per_token_grows_with_context():
    cfg = get_config("tiny-llama")
    assert flops_per_token(cfg, 1024) > flops_per_token(cfg, 0)
    # At zero context the count is the classic 2N rule over non-embedding
    # weights; embedding lookup is not a matmul.
    n_weights = param_count(cfg, active_only=True) - cfg.vocab_size * cfg.d_model
    assert flops_per_token(cfg, 0) == 2.0 * n_weights


def test_flops_per_token_tied_embeddings_count_unembed():
    """Gemma ties embed/unembed: the shared table is a real output matmul,
    so its FLOPs must not be subtracted with the lookup."""
    cfg = get_config("tiny-gemma")
    assert cfg.tie_embeddings
    assert flops_per_token(cfg, 0) == 2.0 * param_count(cfg, active_only=True)


def test_n_params_delegates_to_param_count():
    cfg = get_config("tiny-qwen2")  # qkv_bias: the term the old dup missed
    assert cfg.n_params() == param_count(cfg)
    moe = get_config("tiny-mixtral")
    assert moe.n_params(active_only=True) == param_count(moe, active_only=True)


def test_device_peak_lookup():
    assert device_peak_flops("TPU v5 lite") == pytest.approx(197e12)
    assert device_peak_flops("TPU v5p chip") == pytest.approx(459e12)
    assert device_peak_flops("TPU v4") == pytest.approx(275e12)
    assert device_peak_flops("cpu") is None


def test_decode_mfu():
    cfg = get_config("llama-3-8b")
    mfu = decode_mfu(cfg, tokens_per_sec=100.0, device_kind="TPU v5 lite")
    assert mfu is not None and 0 < mfu < 0.05  # 8B @ 100 tok/s on v5e ~0.8%
    assert decode_mfu(cfg, 100.0, "cpu") is None
    # TP over 4 chips divides utilization by the slice size.
    mfu4 = decode_mfu(cfg, 100.0, "TPU v5 lite", n_devices=4)
    assert mfu4 == pytest.approx(mfu / 4)


def test_decode_mbu_accounting():
    from llm_consensus_tpu.models import get_config
    from llm_consensus_tpu.utils.flops import (
        decode_bytes_per_token,
        decode_mbu,
        device_peak_hbm_bw,
        param_count,
    )

    cfg = get_config("consensus-1b")
    # bf16 weights, no context: exactly 2 bytes per active param.
    assert decode_bytes_per_token(cfg, 0, weight_bytes=2, kv_bytes=2) == (
        2 * param_count(cfg, active_only=True)
    )
    # int8 halves the weight term; KV term scales with context and width.
    int8 = decode_bytes_per_token(cfg, 1024, weight_bytes=1, kv_bytes=1)
    bf16 = decode_bytes_per_token(cfg, 1024, weight_bytes=2, kv_bytes=2)
    assert abs(bf16 - 2 * int8) < 1e-6
    assert device_peak_hbm_bw("TPU v5 lite") == 819e9
    assert device_peak_hbm_bw("cpu") is None
    # 500 tok/s of int8 consensus-1b on v5e ≈ 54% of the 819 GB/s roofline.
    mbu = decode_mbu(cfg, 500.0, "TPU v5 lite", weight_bytes=1, kv_bytes=1)
    assert 0.4 < mbu < 0.7


def test_int8_peak_is_double_bf16():
    """MXU int8×int8 runs at 2× the dense bf16 rate; the helper is the
    single owner of the W8A8 MFU normalization convention."""
    from llm_consensus_tpu.utils.flops import (
        device_peak_flops, device_peak_int8_ops)

    assert device_peak_int8_ops("TPU v5 lite") == 2 * device_peak_flops(
        "TPU v5 lite"
    )
    # v4 publishes equal int8 TOPS and bf16 TFLOPS; v2/v3 have no int8
    # MXU rate at all — the helper must not invent a 2x peak there.
    assert device_peak_int8_ops("TPU v4") == device_peak_flops("TPU v4")
    assert device_peak_int8_ops("TPU v3") is None
    assert device_peak_int8_ops("some cpu") is None
