"""Prefill/decode overlap (engine sessions + batcher interleave + judge shim).

One mechanism at two layers: prefill never stalls an active decode
frontier. (1) Interleaved admission in the continuous batcher
(LLMC_PREFILL_BUDGET): a new wave's prefill chunks dispatch BETWEEN
decode chunks — token streams must stay byte-identical to the classic
stall-the-pool admission AND to the single-stream engine. (2) Incremental
judge prefill (Engine.PrefillSession + consensus/overlap.py): the judge
prompt appends to a growing KV as panel answers arrive — parity with the
one-shot prefill, arrival-order determinism, the single-response
shortcut, and a classic fallback whenever the incremental path can't
honor the contract. Flag-off ⇒ both layers are byte-for-byte the classic
path (the PR's determinism guard).
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu import obs
from llm_consensus_tpu.consensus import (
    Judge,
    NoResponsesError,
    make_overlap_judge,
    render_judge_prompt,
)
from llm_consensus_tpu.engine import ContinuousBatcher, Engine, SamplingParams
from llm_consensus_tpu.models import get_config, init_params
from llm_consensus_tpu.providers.base import Response
from llm_consensus_tpu.utils import Context


@pytest.fixture(scope="module")
def engine():
    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    return Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                  stream_interval=8, prefill_chunk=16)


# -- interleaved admission (batcher) ----------------------------------------


LONG_PROMPT = "resident stream that keeps decoding while admissions land"
LATE_PROMPTS = [
    "late admission alpha beta gamma delta epsilon zeta eta theta",
    "a different late stream with its own rather longer prompt text",
]


def _run_pool(engine, budget):
    """One resident stream decodes; two late streams admit mid-flight."""
    s_long = SamplingParams(max_new_tokens=96, ignore_eos=True)
    s_late = SamplingParams(max_new_tokens=12, ignore_eos=True)
    b = ContinuousBatcher(engine, max_batch=4, prefill_budget=budget)
    try:
        streamed = threading.Event()
        f_long = b.submit(
            LONG_PROMPT, s_long, on_text=lambda _t: streamed.set()
        )
        assert streamed.wait(timeout=300), "resident stream never decoded"
        futs = [b.submit(p, s_late) for p in LATE_PROMPTS]
        results = [f_long.result(timeout=300)]
        results += [f.result(timeout=300) for f in futs]
    finally:
        b.close()
    return results


def test_interleaved_admission_byte_identical(engine):
    """Interleaved admission under concurrent decode: every stream's
    tokens are byte-identical to the classic (budget-0) pool AND to the
    single-stream engine — and the interleave path actually ran."""
    rec = obs.Recorder()
    obs.install(rec)
    try:
        interleaved = _run_pool(engine, budget=32)
    finally:
        obs.install(None)
    classic = _run_pool(engine, budget=0)

    s_long = SamplingParams(max_new_tokens=96, ignore_eos=True)
    s_late = SamplingParams(max_new_tokens=12, ignore_eos=True)
    refs = [engine.generate(LONG_PROMPT, s_long)]
    refs += [engine.generate(p, s_late) for p in LATE_PROMPTS]

    for got, ref in zip(interleaved, refs):
        assert got.token_ids == ref.token_ids
        assert got.finish_reason == ref.finish_reason
    for got, ref in zip(classic, refs):
        assert got.token_ids == ref.token_ids
    # The wave really was paced between decode chunks, not admitted
    # classically (the classic span set has no prefill_interleave).
    assert "prefill_interleave" in rec.span_names()


def test_admission_session_paced_equals_one_shot(engine):
    """AdmissionPrefill.step pacing changes WHEN chunks dispatch, never
    what they compute: logits bitwise-equal to the classic drive."""
    rows = [
        list(engine.tokenizer.encode("first admission row with padding")),
        list(engine.tokenizer.encode("second, rather longer, admission row text here")),
    ]
    ll_ref, _cache_ref = engine._prefill_rows([list(r) for r in rows])
    sess = engine.admission_session([list(r) for r in rows])
    steps = 0
    while not sess.step(8):  # tiny budget: many paced calls
        steps += 1
        assert steps < 100
    ll, _cache, width = sess.finish()
    assert width == engine._rows_bucket(max(len(r) for r in rows))
    np.testing.assert_array_equal(
        np.asarray(ll, np.float32), np.asarray(ll_ref, np.float32)
    )


# -- incremental prefill session (engine) -----------------------------------


def test_prefill_session_logits_parity(engine):
    """Append-built KV produces the same last-token logits as the
    one-shot chunked prefill (growing kv_width buckets may reassociate
    float sums — tolerance, not bitwise)."""
    ids = list(engine.tokenizer.encode("parity probe " * 8))[:48]  # 3 chunks
    ll_ref, _ = engine._prefill_ids(list(ids))
    sess = engine.prefill_session()
    sess.append(ids[:10])
    sess.append(ids[10:33])
    sess.append(ids[33:])
    assert sess.prefilled == 48 and sess.tokens == 48
    np.testing.assert_allclose(
        np.asarray(sess._last_logits, np.float32),
        np.asarray(ll_ref, np.float32),
        rtol=2e-4, atol=2e-4,
    )


def test_prefill_session_generate_matches_one_shot(engine):
    """Uneven appends + residue chunk + decode == the classic
    generate_ids path, token for token."""
    ids = list(engine.tokenizer.encode(
        "session decode parity prompt, with some length to it"
    ))
    s = SamplingParams(max_new_tokens=16, ignore_eos=True)
    ref = engine.generate_ids(list(ids), s)
    sess = engine.prefill_session()
    for i in range(0, len(ids), 13):
        sess.append(ids[i:i + 13])
    got = sess.generate(s)
    assert got.token_ids == ref.token_ids
    assert got.text == ref.text
    assert got.prompt_tokens == len(ids)
    with pytest.raises(RuntimeError):
        sess.generate(s)  # single-use: the cache was donated away


def test_prefill_session_append_text_single_bos(engine):
    """Pieces concatenate into ONE prompt: only the first piece keeps
    its BOS — the session's token stream must equal the one-shot encode
    of the concatenation (a BOS per block would condition the judge on
    tokens render_judge_prompt's render never contains)."""
    sess = engine.prefill_session()
    sess.append_text("first piece ")
    sess.append_text("second piece ")
    sess.append_text("third")
    one_shot = engine.tokenizer.encode("first piece second piece third")
    assert sess._ids == list(one_shot)


def test_prefill_session_overflow_flags(engine):
    sess = engine.prefill_session()
    sess.append([1] * (engine.max_seq + 5))
    assert sess.overflowed
    with pytest.raises(ValueError):
        sess.generate(SamplingParams(max_new_tokens=4, ignore_eos=True))


def test_prefill_session_non_multiple_capacity_overflows():
    """max_seq that is not a chunk multiple: a prompt whose final padded
    chunk would end past capacity must flag overflow (clamped
    dynamic_update_slice would otherwise silently shift the write onto
    earlier positions and corrupt the cache) — while chunk-covered
    lengths still work."""
    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    eng = Engine(cfg, params=params, dtype=jnp.float32, max_seq=72,
                 stream_interval=8, prefill_chunk=16)  # 72 % 16 != 0
    sess = eng.prefill_session()
    sess.append([1] * 70)  # legal classic prompt; ceil(70/16)*16 = 80 > 72
    assert sess.overflowed
    ok = eng.prefill_session()
    ok.append([1] * 60)  # ceil(60/16)*16 = 64 <= 72
    assert not ok.overflowed
    out = ok.generate(SamplingParams(max_new_tokens=4, ignore_eos=True))
    assert len(out.token_ids) == 4


# -- judge overlap shim ------------------------------------------------------


class _EngineProvider:
    """Minimal provider over one (float32, deterministic) engine: the
    overlap shim's engine hook plus the classic query path its fallback
    delegates to — both sides of every equality assert run the SAME
    engine, so greedy comparisons don't ride bf16 near-ties."""

    name = "tpu"

    def __init__(self, engine):
        self._engine = engine
        self._ignore_eos = False
        self.stats = {"tokens": 0, "runs": 0}
        self._lock = threading.Lock()

    def _engine_for(self, model):
        return self._engine

    def query(self, ctx, req):
        return self.query_stream(ctx, req, None)

    def query_stream(self, ctx, req, callback):
        s = SamplingParams(
            max_new_tokens=req.max_tokens if req.max_tokens else 64,
            temperature=0.0,
        )
        result = self._engine.generate(req.prompt, s, ctx, on_text=callback)
        return Response(
            model=req.model, content=result.text, provider=self.name,
            truncated=result.truncated_prompt,
        )


@pytest.fixture(scope="module")
def provider():
    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(1), dtype=jnp.float32)
    eng = Engine(cfg, params=params, dtype=jnp.float32, max_seq=2048,
                 stream_interval=8, prefill_chunk=64)
    return _EngineProvider(eng)


PROMPT = "judge overlap probe prompt"
RESP_A = Response(model="model-a", content="alpha answer text", provider="t")
RESP_B = Response(model="model-b", content="beta answer, different", provider="t")


def test_flag_off_is_classic(monkeypatch, provider):
    monkeypatch.delenv("LLMC_JUDGE_OVERLAP", raising=False)
    assert make_overlap_judge(provider, "tpu:tiny-llama", PROMPT) is None
    # Explicit flag wins over the unset env.
    assert make_overlap_judge(
        provider, "tpu:tiny-llama", PROMPT, enabled=True
    ) is not None
    # Providers without an on-device engine (HTTP, broadcast wrappers)
    # never get a shim, flag or no flag.
    from llm_consensus_tpu.providers.base import ProviderFunc

    http = ProviderFunc(lambda ctx, req: Response(
        model=req.model, content="x", provider="fake"))
    assert make_overlap_judge(http, "m", PROMPT, enabled=True) is None


def test_judge_overlap_out_of_order_matches_classic(monkeypatch, provider):
    """Panel answers arriving out of panel-list order: the ARRIVAL order
    is recorded, becomes the judge-prompt order, and matches what the
    classic path produces for that same completion order (the runner's
    responses list IS completion-ordered)."""
    monkeypatch.setenv("LLMC_JUDGE_OVERLAP", "1")
    ov = make_overlap_judge(provider, "tpu:tiny-llama", PROMPT, max_tokens=8)
    assert ov is not None
    ov.on_response(RESP_B)  # B completes before A
    ov.on_response(RESP_A)
    assert [r.model for r in ov.arrival_order] == ["model-b", "model-a"]
    chunks: list = []
    out = ov.synthesize_stream(
        Context.background(), PROMPT, [RESP_B, RESP_A], chunks.append
    )
    assert out and out == "".join(chunks)
    classic = Judge(provider, "tpu:tiny-llama", max_tokens=8).synthesize(
        Context.background(), PROMPT, [RESP_B, RESP_A]
    )
    assert out == classic


def test_judge_overlap_order_mismatch_falls_back(monkeypatch, provider):
    """Streamed order diverging from the responses list (the rare
    outside-the-lock hook race) must not ship a prompt ordered unlike
    the persisted responses: it degrades to the classic path, rendered
    with the GIVEN order."""
    monkeypatch.setenv("LLMC_JUDGE_OVERLAP", "1")
    ov = make_overlap_judge(provider, "tpu:tiny-llama", PROMPT, max_tokens=8)
    ov.on_response(RESP_B)
    ov.on_response(RESP_A)
    out = ov.synthesize_stream(
        Context.background(), PROMPT, [RESP_A, RESP_B], None
    )
    classic = Judge(provider, "tpu:tiny-llama", max_tokens=8).synthesize(
        Context.background(), PROMPT, [RESP_A, RESP_B]
    )
    assert out == classic


def test_judge_overlap_single_response_shortcut(monkeypatch, provider):
    monkeypatch.setenv("LLMC_JUDGE_OVERLAP", "1")
    ov = make_overlap_judge(provider, "tpu:tiny-llama", PROMPT, max_tokens=8)
    ov.on_response(RESP_A)
    chunks: list = []
    out = ov.synthesize_stream(
        Context.background(), PROMPT, [RESP_A], chunks.append
    )
    assert out == RESP_A.content
    assert chunks == [RESP_A.content]  # callback invoked exactly once
    with pytest.raises(NoResponsesError):
        ov.synthesize_stream(Context.background(), PROMPT, [], None)


def test_judge_overlap_unfed_falls_back_classic(monkeypatch, provider):
    """Responses the hook never saw ⇒ the shim degrades to the classic
    path, byte-for-byte (the determinism guard's judge half)."""
    monkeypatch.setenv("LLMC_JUDGE_OVERLAP", "1")
    ov = make_overlap_judge(provider, "tpu:tiny-llama", PROMPT, max_tokens=8)
    out = ov.synthesize_stream(
        Context.background(), PROMPT, [RESP_A, RESP_B], None
    )
    classic = Judge(provider, "tpu:tiny-llama", max_tokens=8).synthesize(
        Context.background(), PROMPT, [RESP_A, RESP_B]
    )
    assert out == classic


def test_judge_overlap_refine_prompt_falls_back(monkeypatch, provider):
    """A synthesis prompt that differs from the one the header was built
    with (refinement rounds) must not ride the stale session."""
    monkeypatch.setenv("LLMC_JUDGE_OVERLAP", "1")
    ov = make_overlap_judge(provider, "tpu:tiny-llama", PROMPT, max_tokens=8)
    ov.on_response(RESP_A)
    ov.on_response(RESP_B)
    other = "a different (refine-round) prompt"
    out = ov.synthesize_stream(
        Context.background(), other, [RESP_A, RESP_B], None
    )
    classic = Judge(provider, "tpu:tiny-llama", max_tokens=8).synthesize(
        Context.background(), other, [RESP_A, RESP_B]
    )
    assert out == classic


def test_runner_on_model_response_feeds_arrival_order(monkeypatch, provider):
    """End-to-end: the runner's on_model_response hook feeds the shim in
    completion order, and synthesis consumes the streamed session."""
    from llm_consensus_tpu.providers.base import ProviderFunc
    from llm_consensus_tpu.providers.registry import Registry
    from llm_consensus_tpu.runner import Callbacks, Runner

    monkeypatch.setenv("LLMC_JUDGE_OVERLAP", "1")
    reg = Registry()
    reg.register("fast", ProviderFunc(lambda ctx, req: Response(
        model=req.model, content="fast answer", provider="fake")))

    import time as _time

    def slow_fn(ctx, req):
        _time.sleep(0.3)
        return Response(model=req.model, content="slow answer", provider="fake")

    reg.register("slow", ProviderFunc(slow_fn))
    ov = make_overlap_judge(provider, "tpu:tiny-llama", PROMPT, max_tokens=8)
    runner = Runner(reg, timeout=30.0)
    result = runner.run(
        Context.background(), ["slow", "fast"], PROMPT,
        callbacks=Callbacks(on_model_response=ov.on_response),
    )
    assert [r.model for r in ov.arrival_order] == ["fast", "slow"]
    out = ov.synthesize_stream(
        Context.background(), PROMPT, result.responses, None
    )
    assert out
    classic = Judge(provider, "tpu:tiny-llama", max_tokens=8).synthesize(
        Context.background(), PROMPT, list(ov.arrival_order)
    )
    assert out == classic


def test_render_judge_prompt_block_contract():
    """The shared block renderer keeps the load-bearing separator format
    (reference judge.go:21-25)."""
    p = render_judge_prompt("q", [RESP_A])
    assert "\n--- Model: model-a | Provider: t ---\nalpha answer text\n" in p
