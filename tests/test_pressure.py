"""Priority-aware preemptive scheduling (pressure/ + batcher surgery).

The load-bearing contracts of ISSUE 9:

  * a preempted-then-resumed greedy stream is BYTE-IDENTICAL to an
    uninterrupted run — across the KV-pool × spec matrix and across a
    mid-generation compaction;
  * admission dequeue is priority-ordered with an aging starvation
    bound for the lowest class, and queue-full arbitration bumps a
    lower-class waiter instead of shedding a higher-class arrival;
  * the governor ladder escalates/de-escalates with hysteresis, and its
    brownout rung downgrades the judge tier with a ``degraded:
    brownout`` tag;
  * shed Retry-After scales by class, and KV-pool exhaustion surfaces
    per response (``kv.truncated``) and per publish (``hbm_squeeze``).
"""

from __future__ import annotations

import threading
import time

import jax
import jax.numpy as jnp
import pytest

from llm_consensus_tpu.engine import ContinuousBatcher, Engine, SamplingParams
from llm_consensus_tpu.models import get_config, init_params
from llm_consensus_tpu.pressure import (
    PRIORITY_HIGH,
    PRIORITY_LOW,
    PRIORITY_NORMAL,
    PressureGovernor,
    parse_priority,
    resolve_priority,
)
from llm_consensus_tpu.serve.admission import AdmissionController, QueueFull


# -- priority classes --------------------------------------------------------


def test_parse_priority_names_and_ints():
    assert parse_priority("high") == PRIORITY_HIGH
    assert parse_priority("Normal") == PRIORITY_NORMAL
    assert parse_priority(2) == PRIORITY_LOW
    for bad in ("urgent", 3, -1, True, 1.5):
        with pytest.raises(ValueError):
            parse_priority(bad)


def test_resolve_priority_explicit_beats_deadline(monkeypatch):
    monkeypatch.setenv("LLMC_PRESSURE_DEADLINE_HIGH_S", "15")
    monkeypatch.setenv("LLMC_PRESSURE_DEADLINE_LOW_S", "600")
    assert resolve_priority("low", timeout_s=1.0) == PRIORITY_LOW
    assert resolve_priority(None, timeout_s=5.0) == PRIORITY_HIGH
    assert resolve_priority(None, timeout_s=120.0) == PRIORITY_NORMAL
    assert resolve_priority(None, timeout_s=3600.0) == PRIORITY_LOW
    assert resolve_priority(None, None) == PRIORITY_NORMAL


# -- governor ladder ---------------------------------------------------------


def _gov(**kw):
    kw.setdefault("high_water", 0.75)
    kw.setdefault("low_water", 0.35)
    kw.setdefault("up_patience", 2)
    kw.setdefault("down_patience", 3)
    return PressureGovernor(**kw)


def test_ladder_escalates_one_rung_per_patience_window():
    g = _gov(up_patience=2)
    assert g.observe(0.9) == "ok"        # 1 of 2 high samples
    assert g.observe(0.9) == "evict"     # patience met: one rung
    assert g.observe(0.9) == "evict"     # streak reset: 1 of 2 again
    assert g.observe(0.9) == "preempt"
    g2 = _gov(up_patience=1)
    for want in ("evict", "preempt", "brownout", "shed"):
        assert g2.observe(1.0) == want
    # ceiling: stays at shed
    assert g2.observe(1.0) == "shed"


def test_ladder_hysteresis_mid_band_resets_streaks():
    g = _gov(up_patience=2)
    g.observe(0.9)
    g.observe(0.5)  # mid-band: resets the up-streak
    g.observe(0.9)
    assert g.state == "ok"  # never two CONSECUTIVE high samples
    g.observe(0.9)
    assert g.state == "evict"


def test_ladder_deescalates_only_after_down_patience():
    g = _gov(up_patience=1, down_patience=3)
    g.observe(1.0)
    g.observe(1.0)
    assert g.state == "preempt"
    g.observe(0.1)
    g.observe(0.1)
    assert g.state == "preempt"  # 2 of 3 quiet samples
    g.observe(0.1)
    assert g.state == "evict"
    snap = g.snapshot()
    assert snap["escalations"] == 2 and snap["de_escalations"] == 1


def test_brownout_rung_propagates_to_providers():
    calls = []

    class P:
        def set_brownout(self, on):
            calls.append(on)

    g = _gov(up_patience=1, down_patience=1, provider_iter=lambda: [P()])
    for _ in range(3):
        g.observe(1.0)
    assert g.state == "brownout" and g.brownout
    assert calls == [True]
    g.observe(0.0)
    assert g.state == "preempt" and not g.brownout
    assert calls == [True, False]


def test_should_shed_only_at_shed_rung_and_only_shed_classes():
    g = _gov(up_patience=1, shed_class=PRIORITY_LOW)
    assert not g.should_shed(PRIORITY_LOW)  # state ok
    for _ in range(4):
        g.observe(1.0)
    assert g.state == "shed"
    assert g.should_shed(PRIORITY_LOW)
    assert not g.should_shed(PRIORITY_NORMAL)
    assert not g.should_shed(PRIORITY_HIGH)
    assert g.snapshot()["shed"] == 1


def test_brownout_judge_fallback_map_and_clamp():
    g = _gov(judge_fallback={"tpu:big": "tpu:small"}, brownout_max_new=64)
    assert g.brownout_judge("tpu:big") == "tpu:small"
    assert g.brownout_judge("tpu:other") == "tpu:other"
    assert g.brownout_judge("tpu:big", available=["tpu:big"]) == "tpu:big"
    assert g.clamp_max_tokens(None) == 64
    assert g.clamp_max_tokens(512) == 64
    assert g.clamp_max_tokens(16) == 16  # never raise a tighter cap


def test_governor_kv_signal_reads_deltas():
    class P:
        def __init__(self):
            self.exhausted = 0

        def kv_stats(self):
            return {"tiny": {
                "exhausted": self.exhausted, "evicted_blocks": 0,
                "occupancy": 0.2,
            }}

    p = P()
    g = _gov(provider_iter=lambda: [p])
    assert g.pressure_signals()["kv"] <= 0.2
    p.exhausted = 3  # new exhaustions since last sample
    assert g.pressure_signals()["kv"] == 1.0
    # no NEW exhaustions: the signal relaxes back to occupancy-based
    assert g.pressure_signals()["kv"] <= 0.2


# -- admission: priority dequeue, aging, bump, retry-after -------------------


def _occupy(ctl):
    return ctl.admit()


def test_priority_ordered_dequeue_with_fifo_within_class():
    ctl = AdmissionController(1, max_queue=8, age_s=1000)
    t0 = _occupy(ctl)
    order: list[str] = []

    def waiter(pri, tag):
        t = ctl.admit(priority=pri)
        order.append(tag)
        t.release()

    threads = []
    for pri, tag in [
        (PRIORITY_LOW, "low0"), (PRIORITY_NORMAL, "norm0"),
        (PRIORITY_LOW, "low1"), (PRIORITY_HIGH, "high0"),
        (PRIORITY_NORMAL, "norm1"),
    ]:
        th = threading.Thread(target=waiter, args=(pri, tag))
        th.start()
        threads.append(th)
        time.sleep(0.05)  # deterministic enqueue order
    t0.release()
    for th in threads:
        th.join(timeout=30)
    assert order == ["high0", "norm0", "norm1", "low0", "low1"], order


def test_aging_bounds_lowest_class_starvation():
    """A LOW waiter promotes one class per age_s: after 2×age_s it ties
    HIGH and its earlier arrival order wins the next slot."""
    ctl = AdmissionController(1, max_queue=8, age_s=0.05)
    t0 = _occupy(ctl)
    order: list[str] = []

    def waiter(pri, tag):
        t = ctl.admit(priority=pri)
        order.append(tag)
        t.release()

    a = threading.Thread(target=waiter, args=(PRIORITY_LOW, "low"))
    a.start()
    time.sleep(0.3)  # ≥ 2×age_s: effective class reaches HIGH
    b = threading.Thread(target=waiter, args=(PRIORITY_HIGH, "high"))
    b.start()
    time.sleep(0.05)
    t0.release()
    a.join(timeout=30)
    b.join(timeout=30)
    assert order[0] == "low", order


def test_queue_full_bumps_lower_class_instead_of_shedding_higher():
    ctl = AdmissionController(1, max_queue=1, age_s=1000)
    t0 = _occupy(ctl)
    outcome: dict = {}

    def low():
        try:
            t = ctl.admit(priority=PRIORITY_LOW)
            outcome["low"] = "admitted"
            t.release()
        except QueueFull as err:
            outcome["low"] = ("bumped", err.retry_after_s)

    th_low = threading.Thread(target=low)
    th_low.start()
    time.sleep(0.1)  # LOW fills the 1-deep queue

    def high():
        t = ctl.admit(priority=PRIORITY_HIGH)
        outcome["high"] = "admitted"
        t.release()

    th_high = threading.Thread(target=high)
    th_high.start()
    time.sleep(0.1)
    t0.release()
    th_low.join(timeout=30)
    th_high.join(timeout=30)
    assert outcome["high"] == "admitted"
    assert outcome["low"][0] == "bumped"
    snap = ctl.snapshot()
    assert snap["bumped"] == 1 and snap["rejected"] == 1


def test_queue_full_sheds_arrival_when_no_lower_class_queued():
    ctl = AdmissionController(1, max_queue=1, age_s=1000)
    t0 = _occupy(ctl)
    th = threading.Thread(
        target=lambda: ctl.admit(priority=PRIORITY_HIGH).release()
    )
    th.start()
    time.sleep(0.1)
    with pytest.raises(QueueFull):
        ctl.admit(priority=PRIORITY_HIGH)  # same class: no bump
    t0.release()
    th.join(timeout=30)


def test_retry_after_scales_by_shed_class():
    ctl = AdmissionController(1, retry_after_s=2.0, retry_spread=0.5)
    neutral = [ctl.retry_after() for _ in range(64)]
    assert all(2.0 <= d < 4.0 for d in neutral)
    high = [ctl.retry_after(PRIORITY_HIGH) for _ in range(64)]
    norm = [ctl.retry_after(PRIORITY_NORMAL) for _ in range(64)]
    low = [ctl.retry_after(PRIORITY_LOW) for _ in range(64)]
    assert all(1.0 <= d < 2.0 for d in high)    # 0.5× base
    assert all(2.0 <= d < 4.0 for d in norm)    # 1× base
    assert all(3.0 <= d < 6.0 for d in low)     # 1.5× base
    assert max(high) < min(low)  # the wave re-admits high first


# -- batcher: preempt-and-resume byte-identity -------------------------------


@pytest.fixture(scope="module")
def tiny():
    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    return cfg, params


def _mk_engine(cfg, params, monkeypatch, pool: bool, max_seq: int = 256):
    monkeypatch.setenv("LLMC_KV_POOL", "1" if pool else "0")
    monkeypatch.setenv("LLMC_KV_POOL_BLOCK", "16")
    return Engine(cfg, params=params, dtype=jnp.float32, max_seq=max_seq,
                  stream_interval=8, prefill_chunk=16)


def _spec_cfg():
    from llm_consensus_tpu.engine.speculative import spec_config_from_env

    return spec_config_from_env(kind="lookup", k=2, ngram=2)


def _run_contended(batcher, low_prompts, hi_prompt, s_low, s_hi,
                   want_preempt: bool = True):
    """Fill the 2-slot pool with LOWs, then submit a HIGH latecomer.

    Preemption needs the HIGH to arrive while both LOWs are still
    resident; under a loaded CI box the LOWs can occasionally finish
    first, so the contended run retries (bounded) until a preemption was
    actually observed — byte identity is asserted by the caller on every
    attempt's results either way."""
    for _attempt in range(4):
        before = batcher.snapshot()["preemptions"]
        futs = [
            batcher.submit(p, s_low, priority=PRIORITY_LOW)
            for p in low_prompts
        ]
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if sum(1 for st in batcher._slots if st is not None) == 2:
                break
            time.sleep(0.005)
        f_hi = batcher.submit(hi_prompt, s_hi, priority=PRIORITY_HIGH)
        r_hi = f_hi.result(timeout=300)
        r_low = [f.result(timeout=300) for f in futs]
        if not want_preempt or batcher.snapshot()["preemptions"] > before:
            return r_low, r_hi
    return r_low, r_hi


@pytest.mark.parametrize("pool", [False, True], ids=["kvpool-off", "kvpool-on"])
@pytest.mark.parametrize("spec", [False, True], ids=["spec-off", "spec-on"])
def test_preempt_resume_byte_identity_matrix(tiny, monkeypatch, pool, spec):
    """The acceptance contract: a HIGH latecomer preempts a LOW resident
    in a full pool, and EVERY stream (victim included) still emits
    exactly the uncontended greedy bytes — KV pool on/off × spec decode
    on/off."""
    cfg, params = tiny
    eng = _mk_engine(cfg, params, monkeypatch, pool)
    s_low = SamplingParams(max_new_tokens=48, ignore_eos=True)
    s_hi = SamplingParams(max_new_tokens=10, ignore_eos=True)
    low_prompts = [f"low class resident stream {i} body" for i in range(2)]
    hi_prompt = "high class latecomer"
    base_low = [eng.generate(p, s_low) for p in low_prompts]
    base_hi = eng.generate(hi_prompt, s_hi)

    b = ContinuousBatcher(
        eng, max_batch=2, spec=_spec_cfg() if spec else None
    )
    try:
        r_low, r_hi = _run_contended(b, low_prompts, hi_prompt, s_low, s_hi)
        assert b.snapshot()["preemptions"] >= 1, b.snapshot()
        assert r_hi.token_ids == base_hi.token_ids
        for i, r in enumerate(r_low):
            assert r.token_ids == base_low[i].token_ids, (
                f"victim stream {i} diverged (pool={pool}, spec={spec})"
            )
    finally:
        b.close()


def test_preempt_resume_across_compaction(tiny, monkeypatch):
    """Preemption composes with the compaction waterline: a tiny
    max_seq forces window slides mid-generation while a preempted
    stream resumes — bytes still exact."""
    cfg, params = tiny
    eng = _mk_engine(cfg, params, monkeypatch, pool=False, max_seq=96)
    s_low = SamplingParams(max_new_tokens=60, ignore_eos=True)
    s_hi = SamplingParams(max_new_tokens=12, ignore_eos=True)
    low_prompts = ["compact lane one", "compact lane two longer prompt"]
    hi_prompt = "compact high latecomer"
    base_low = [eng.generate(p, s_low) for p in low_prompts]
    base_hi = eng.generate(hi_prompt, s_hi)
    b = ContinuousBatcher(eng, max_batch=2)
    try:
        r_low, r_hi = _run_contended(b, low_prompts, hi_prompt, s_low, s_hi)
        assert b.snapshot()["preemptions"] >= 1
        assert r_hi.token_ids == base_hi.token_ids
        for i, r in enumerate(r_low):
            assert r.token_ids == base_low[i].token_ids, f"victim {i}"
    finally:
        b.close()


def test_no_preemption_within_one_class(tiny, monkeypatch):
    """Equal classes never preempt each other: a NORMAL latecomer waits
    for a slot like the classic FIFO pool."""
    cfg, params = tiny
    eng = _mk_engine(cfg, params, monkeypatch, pool=False)
    s = SamplingParams(max_new_tokens=16, ignore_eos=True)
    b = ContinuousBatcher(eng, max_batch=2)
    try:
        futs = [
            b.submit(f"same class stream {i}", s) for i in range(3)
        ]
        for f in futs:
            f.result(timeout=300)
        assert b.snapshot()["preemptions"] == 0
    finally:
        b.close()


def test_priority_orders_batcher_queue(tiny, monkeypatch):
    """With one slot occupied, a queued HIGH overtakes queued LOWs
    (stable within a class)."""
    cfg, params = tiny
    eng = _mk_engine(cfg, params, monkeypatch, pool=False)
    # Preemption off isolates the DEQUEUE-ordering contract.
    monkeypatch.setenv("LLMC_PRESSURE_PREEMPT", "0")
    s = SamplingParams(max_new_tokens=24, ignore_eos=True)
    s_q = SamplingParams(max_new_tokens=4, ignore_eos=True)
    b = ContinuousBatcher(eng, max_batch=1)
    try:
        first = b.submit("resident stream", s, priority=PRIORITY_HIGH)
        time.sleep(0.3)  # resident decoding; queue the rest
        done: list[str] = []

        def track(tag, fut):
            fut.result(timeout=300)
            done.append(tag)

        f_low = b.submit("queued low", s_q, priority=PRIORITY_LOW)
        f_hi = b.submit("queued high", s_q, priority=PRIORITY_HIGH)
        ts = [
            threading.Thread(target=track, args=(tag, f))
            for tag, f in (("low", f_low), ("high", f_hi))
        ]
        for t in ts:
            t.start()
        first.result(timeout=300)
        for t in ts:
            t.join(timeout=300)
        assert done[0] == "high", done
    finally:
        b.close()


def test_preempt_seals_and_reopens_journal_entries(tiny, monkeypatch):
    """A preempted stream's journal entry closes as "preempted" and a
    fresh entry seeded with the emitted prefix carries the resume — so
    crash recovery across a preemption still replays the full stream."""
    from llm_consensus_tpu import recovery

    cfg, params = tiny
    eng = _mk_engine(cfg, params, monkeypatch, pool=False)
    journal = recovery.StreamJournal()
    recovery.install(journal)
    try:
        b = ContinuousBatcher(eng, max_batch=2)
        try:
            s_low = SamplingParams(max_new_tokens=48, ignore_eos=True)
            s_hi = SamplingParams(max_new_tokens=8, ignore_eos=True)
            lows = [f"journal lane {i}" for i in range(2)]
            r_low, _ = _run_contended(
                b, lows, "journal high", s_low, s_hi
            )
            preemptions = b.snapshot()["preemptions"]
            assert preemptions >= 1
            assert journal.depth() == 0  # everything resolved
            # every stream's entry closed, plus one resume entry per
            # preemption (the contended helper may retry the whole run,
            # so count in opened/closed parity, not absolutes)
            assert journal.closed == journal.opened
            assert journal.opened >= 3 + preemptions
        finally:
            b.close()
    finally:
        recovery.reset()


# -- kv exhaustion surfacing -------------------------------------------------


def test_kv_truncated_surfaces_per_response(tiny, monkeypatch):
    from llm_consensus_tpu import faults

    cfg, params = tiny
    faults.install(faults.FaultPlan("pool_exhausted@step=1", seed=3))
    try:
        eng = _mk_engine(cfg, params, monkeypatch, pool=True)
        s = SamplingParams(max_new_tokens=6, ignore_eos=True)
        r = eng.generate("a publish the injected fault truncates " * 2, s)
        assert r.kv_truncated is True
        r2 = eng.generate("a second prompt whose publish proceeds " * 2, s)
        assert r2.kv_truncated is False
    finally:
        faults.reset()


def test_hbm_squeeze_fault_truncates_via_pressure_site(tiny, monkeypatch):
    """``hbm_squeeze@frac=0`` (site pressure, phase=publish) shrinks the
    effective arena to nothing for one publish: same truncation path as
    real exhaustion, exhausted counter moves, correctness never does."""
    from llm_consensus_tpu import faults

    cfg, params = tiny
    s = SamplingParams(max_new_tokens=6, ignore_eos=True)
    prompt = "a squeezed publish loses its tail blocks " * 2
    monkeypatch.setenv("LLMC_KV_POOL", "0")
    base = Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                  stream_interval=8, prefill_chunk=16).generate(prompt, s)
    faults.install(faults.FaultPlan(
        "hbm_squeeze@phase=publish@frac=0@step=1", seed=5
    ))
    try:
        eng = _mk_engine(cfg, params, monkeypatch, pool=True)
        r = eng.generate(prompt, s)
        assert r.token_ids == base.token_ids  # reuse lost, never bytes
        assert r.kv_truncated is True
        stats = eng._kv_pool.stats()
        assert stats["exhausted"] == 1 and stats["published_blocks"] == 0
        # the un-squeezed repeat publishes normally
        r2 = eng.generate(prompt, s)
        assert r2.token_ids == base.token_ids
        assert eng._kv_pool.stats()["published_blocks"] > 0
    finally:
        faults.reset()


def test_priority_storm_floods_real_admissions():
    """The ``pressure`` fault site's ``priority_storm`` pushes synthetic
    LOW admits through the REAL controller — queue pressure the ladder
    (and the high class's bump path) must absorb."""
    from llm_consensus_tpu import faults

    ctl = AdmissionController(2, max_queue=8, age_s=1000)
    faults.install(faults.FaultPlan(
        "priority_storm@phase=governor@n=4@s=0.3", seed=9
    ))
    try:
        g = PressureGovernor(
            admission_snapshot=ctl.snapshot, up_patience=1,
        )
        g._storm_admit = lambda: ctl.admit(priority=PRIORITY_LOW)
        g.sample()  # fires the storm
        wait = time.monotonic() + 10
        while time.monotonic() < wait:
            snap = ctl.snapshot()
            if snap["active"] + snap["waiting"] >= 4:
                break
            time.sleep(0.01)
        snap = ctl.snapshot()
        assert snap["active"] + snap["waiting"] >= 4, snap
        # a HIGH arrival still admits straight through the storm
        t = ctl.admit(priority=PRIORITY_HIGH)
        t.release()
        # storm admits drain and are counted
        wait = time.monotonic() + 10
        while time.monotonic() < wait:
            if g.snapshot()["storm_admits"] + ctl.snapshot()["rejected"] >= 4:
                break
            time.sleep(0.05)
        assert g.snapshot()["storm_admits"] >= 1, g.snapshot()
    finally:
        faults.reset()


# -- gateway: brownout tagging, shed, /statsz -------------------------------


class _FakeProvider:
    """Minimal counting provider (serve tests' fake, trimmed)."""

    def __init__(self):
        self.calls: list[tuple[str, str]] = []
        self._lock = threading.Lock()

    def query(self, ctx, req):
        from llm_consensus_tpu.providers.base import Response

        with self._lock:
            self.calls.append((req.model, req.prompt, req.max_tokens))
        return Response(
            model=req.model, content=f"{req.model} answer", provider="fake"
        )

    def query_stream(self, ctx, req, callback):
        resp = self.query(ctx, req)
        if callback is not None:
            callback(resp.content)
        return resp


def _http_post(port, body):
    import http.client
    import json

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request(
            "POST", "/v1/consensus", json.dumps(body),
            {"Content-Type": "application/json"},
        )
        r = conn.getresponse()
        return r.status, dict(r.getheaders()), r.read()
    finally:
        conn.close()


def _mk_gateway(tmp_path, governor):
    import os

    from llm_consensus_tpu import serve
    from llm_consensus_tpu.providers.registry import Registry

    provider = _FakeProvider()
    registry = Registry()
    for m in ("alpha", "beta", "big-judge", "small-judge"):
        registry.register(m, provider)
    gw = serve.build_gateway(
        registry, ["alpha", "beta"], "big-judge", timeout=30.0,
        max_concurrency=4, cache_size=0,
        data_dir=os.path.join(str(tmp_path), "data"),
        governor=governor,
    )
    gw.start()
    return gw, provider


def test_gateway_brownout_downgrades_judge_and_tags(tmp_path):
    import json

    gov = _gov(
        up_patience=1,
        judge_fallback={"big-judge": "small-judge"},
        brownout_max_new=32,
        poll_s=3600.0,  # the test drives observe(); no sampling thread
    )
    gw, provider = _mk_gateway(tmp_path, gov)
    try:
        port = gw.address[1]
        status, _h, body = _http_post(port, {"prompt": "full quality"})
        doc = json.loads(body)
        assert status == 200 and "degraded" not in doc
        assert doc["judge"] == "big-judge"
        for _ in range(3):
            gov.observe(1.0)
        assert gov.brownout
        status, _h, body = _http_post(port, {"prompt": "brown quality"})
        doc = json.loads(body)
        assert status == 200
        assert doc["degraded"] == "brownout"
        assert doc["judge"] == "small-judge"
        # the judge QUERY really went to the fallback tier, and the
        # brownout clamp rode every query of the degraded run
        assert any(m == "small-judge" for m, _p, _mt in provider.calls)
        assert all(
            mt == 32
            for _m, p, mt in provider.calls if "brown quality" in p
        )
        # /statsz surfaces the governor
        import http.client

        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        try:
            conn.request("GET", "/statsz")
            stats = json.loads(conn.getresponse().read())
        finally:
            conn.close()
        assert stats["pressure"]["state"] == "brownout"
        assert stats["pressure"]["brownouts"] >= 1
    finally:
        gw.close(drain=False, timeout=5.0)


def test_gateway_shed_rejects_low_class_with_scaled_retry_after(tmp_path):
    import json

    gov = _gov(up_patience=1, poll_s=3600.0)
    gw, _provider = _mk_gateway(tmp_path, gov)
    try:
        port = gw.address[1]
        for _ in range(4):
            gov.observe(1.0)
        assert gov.state == "shed"
        status, headers, body = _http_post(
            port, {"prompt": "flood traffic", "priority": "low"}
        )
        assert status == 429, (status, body)
        assert "Retry-After" in headers
        low_ra = json.loads(body)["retry_after_s"]
        status, _h, body = _http_post(
            port, {"prompt": "interactive traffic", "priority": "high"}
        )
        assert status == 200, (status, body)
        # LOW's scaled Retry-After sits above the neutral base window
        assert low_ra >= gw.admission.retry_after_s
    finally:
        gw.close(drain=False, timeout=5.0)


def test_gateway_rejects_bad_priority(tmp_path):
    gov = _gov(poll_s=3600.0)
    gw, _provider = _mk_gateway(tmp_path, gov)
    try:
        port = gw.address[1]
        status, _h, _body = _http_post(
            port, {"prompt": "x", "priority": "urgent"}
        )
        assert status == 400
    finally:
        gw.close(drain=False, timeout=5.0)


def test_evict_cold_respects_target_occupancy(tiny, monkeypatch):
    cfg, params = tiny
    eng = _mk_engine(cfg, params, monkeypatch, pool=True)
    s = SamplingParams(max_new_tokens=4, ignore_eos=True)
    for i in range(3):
        eng.generate(f"distinct prefix number {i} " * 3, s)
    pool = eng._kv_pool
    before = pool.stats()
    assert before["blocks_used"] > 0
    freed = pool.evict_cold(0.0)
    assert freed > 0
    after = pool.stats()
    assert after["blocks_used"] < before["blocks_used"]
    assert pool.evict_cold(1.0) == 0  # already under a full target
