"""Chip-time attribution tests: ledger, sentinels, /metricsz lint.

Covers obs/attrib and its wiring:

  * ledger units — device-time families, the goodput token ledger,
    host-gap accounting, thread-local family tags;
  * the retrace sentinel — a post-warmup XLA compile is attributed to
    the tagged family and fires the warning instant + blackbox dump;
  * the HBM watermark — modeled components, device stats where present,
    and the pre-truncation pressure event;
  * prom rendering of labeled counter/gauge families (round-trip through
    the router's parse/merge path);
  * end-to-end pooled attribution over real tiny engines — decode
    device time recorded, ``useful`` tokens reconcile exactly with the
    tokens emitted;
  * the metric-name lint: every family a gateway's /metricsz exports is
    ``llmc_[a-z0-9_]+``, declared exactly once, and documented in
    docs/observability.md (satellite of ISSUE 12).
"""

from __future__ import annotations

import http.client
import json
import os
import re
import threading
import time

import pytest

from llm_consensus_tpu import faults, obs, serve
from llm_consensus_tpu.obs import attrib as attrib_mod
from llm_consensus_tpu.obs import blackbox as bb_mod
from llm_consensus_tpu.obs import export as obs_export
from llm_consensus_tpu.obs import live as live_mod
from llm_consensus_tpu.obs import prom
from llm_consensus_tpu.obs.attrib import ChipTimeLedger, current_family, tag
from llm_consensus_tpu.obs.blackbox import FlightRecorder
from llm_consensus_tpu.providers.base import Provider, Request, Response
from llm_consensus_tpu.providers.registry import Registry
from llm_consensus_tpu.utils.context import Context

PANEL = ["alpha", "beta"]
JUDGE = "gamma"


@pytest.fixture(autouse=True)
def _clean_planes(monkeypatch):
    monkeypatch.delenv("LLMC_FAULTS", raising=False)
    faults.reset()
    obs.reset()
    live_mod.reset()
    bb_mod.reset()
    attrib_mod.reset()
    yield
    faults.reset()
    obs.reset()
    live_mod.reset()
    bb_mod.reset()
    attrib_mod.reset()


# ---------------------------------------------------------------------------
# ledger units


def test_ledger_device_time_goodput_gaps():
    led = ChipTimeLedger(warmup_s=3600.0)
    led.observe_device("decode", 0.5)
    led.observe_device("decode", 0.25)
    led.observe_device("prefill", 1.0)
    led.token_event("useful", 10)
    led.token_event("overshoot", 3)
    led.token_event("useful", 5)
    led.token_event("spec_rejected", 0)  # no-op
    led.gap(0.1, "admit")
    led.gap(0.2, "admit")
    led.gap(-1.0, "compact")  # negative: dropped
    snap = led.snapshot()
    assert snap["device_s"]["decode"] == pytest.approx(0.75)
    assert snap["device_s"]["prefill"] == pytest.approx(1.0)
    assert snap["busy_s"] == pytest.approx(1.75)
    assert snap["dispatches"] == {"decode": 2, "prefill": 1}
    assert snap["tokens"] == {"overshoot": 3, "useful": 15}
    assert snap["goodput"]["useful"] == 15
    assert snap["goodput"]["wasted"] == 3
    assert snap["goodput"]["fraction"] == pytest.approx(15 / 18, abs=1e-3)
    assert snap["gap_s"] == {"admit": pytest.approx(0.3)}
    assert snap["gaps"] == 2
    assert snap["retraces"] == 0 and not snap["warm"]


def test_family_tag_nests_and_restores():
    assert current_family() is None
    with tag("decode"):
        assert current_family() == "decode"
        with tag("kv_gather"):
            assert current_family() == "kv_gather"
        assert current_family() == "decode"
    assert current_family() is None


def test_ledger_feeds_live_histograms():
    lm = live_mod.LiveMetrics(window_s=60.0)
    live_mod.install(lm)
    led = ChipTimeLedger()
    led.observe_device("decode", 0.01)
    led.gap(0.005, "admit")
    fams = lm.families()
    assert ("device_time") in fams and ("host_gap") in fams
    (labels, hist) = fams["device_time"][0]
    assert labels == {"family": "decode"} and hist.count == 1


# ---------------------------------------------------------------------------
# retrace sentinel


def test_retrace_sentinel_attributes_and_dumps(tmp_path):
    import jax
    import jax.numpy as jnp

    led = ChipTimeLedger(warmup_s=0.0)  # warm immediately
    led.mark_warm()
    attrib_mod.install(led)
    fr = FlightRecorder(
        capacity=64, out_dir=str(tmp_path), min_interval_s=0.0
    )
    bb_mod.install(fr)
    fr.instant("probe", tid="test")  # a dump needs a non-empty ring

    @jax.jit
    def f(x):
        return x * 2 + 1

    with tag("decode"):
        f(jnp.zeros((7, 3)))  # fresh shape: guaranteed compile
    snap = led.snapshot()
    assert snap["compiles"].get("decode", 0) >= 1, snap["compiles"]
    assert snap["compile_s"].get("decode", 0) > 0
    assert snap["retraces"] >= 1
    assert fr.dumps >= 1 and fr.last_reason == "retrace", fr.stats()
    doc = obs_export.load_trace(fr.last_path)
    instants = {
        e["name"] for e in doc["traceEvents"]
        if isinstance(e, dict) and e.get("ph") == "i"
    }
    assert "retrace" in instants


def test_warmup_compiles_counted_but_no_sentinel(tmp_path):
    import jax
    import jax.numpy as jnp

    led = ChipTimeLedger(warmup_s=3600.0)  # still warming up
    attrib_mod.install(led)
    fr = FlightRecorder(
        capacity=64, out_dir=str(tmp_path), min_interval_s=0.0
    )
    bb_mod.install(fr)

    @jax.jit
    def g(x):
        return x - 3

    with tag("prefill"):
        g(jnp.zeros((11,)))
    snap = led.snapshot()
    assert snap["compiles"].get("prefill", 0) >= 1
    assert snap["retraces"] == 0
    assert fr.dumps == 0


# ---------------------------------------------------------------------------
# HBM watermark


def test_hbm_watermark_components_and_pressure_event(tmp_path):
    rec = obs.Recorder()
    obs.install(rec)
    fr = FlightRecorder(
        capacity=64, out_dir=str(tmp_path), min_interval_s=0.0
    )
    bb_mod.install(fr)
    led = ChipTimeLedger()
    led.update_component("weights:tiny", 1000)
    led.update_component("kv_arena:tiny", 500)
    led.update_component("weights:tiny", 800)  # refresh, not add
    snap = led.snapshot()
    assert snap["hbm"]["modeled_bytes"] == 1300
    assert snap["hbm"]["peak_modeled_bytes"] == 1500
    assert snap["hbm"]["components"] == {
        "kv_arena:tiny": 500, "weights:tiny": 800,
    }
    fr.instant("probe", tid="test")
    led.hbm_pressure("kv_pool:tiny", wanted=8, granted=3)
    assert led.snapshot()["hbm"]["events"] == 1
    assert fr.dumps >= 1 and fr.last_reason == "hbm_high_water"
    assert any(
        e.name == "hbm_high_water" and e.args.get("source") == "kv_pool:tiny"
        for e in rec.events()
    )


def test_kv_pool_exhaustion_fires_hbm_sentinel(tmp_path, monkeypatch):
    """The pool's truncation path raises the high-water event BEFORE
    degrading reuse — driven through a real publish with an injected
    pool_exhausted fault."""
    import jax
    import jax.numpy as jnp

    from llm_consensus_tpu.engine import Engine
    from llm_consensus_tpu.models import init_params
    from llm_consensus_tpu.models.config import get_config

    monkeypatch.setenv("LLMC_KV_POOL", "1")
    monkeypatch.setenv("LLMC_KV_POOL_BLOCK", "16")
    led = ChipTimeLedger()
    attrib_mod.install(led)
    fr = FlightRecorder(
        capacity=64, out_dir=str(tmp_path), min_interval_s=0.0
    )
    bb_mod.install(fr)
    faults.install(faults.FaultPlan("pool_exhausted@times=-1", seed=1))
    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    eng = Engine(cfg, params=params, dtype=jnp.float32, max_seq=128,
                 stream_interval=8, prefill_chunk=16)
    from llm_consensus_tpu.engine.engine import SamplingParams

    eng.generate("exhaustion probe prompt body text",
                 SamplingParams(max_new_tokens=24, ignore_eos=True))
    assert led.snapshot()["hbm"]["events"] >= 1
    assert fr.last_reason == "hbm_high_water"
    # The arena registered as a modeled component at pool build.
    assert any(
        k.startswith("kv_arena:") for k in led.snapshot()["hbm"]["components"]
    )


# ---------------------------------------------------------------------------
# prom families


def test_prom_families_render_parse_merge():
    led = ChipTimeLedger()
    led.observe_device("decode", 1.5)
    led.token_event("useful", 7)
    led.token_event("spec_rejected", 2)
    led.gap(0.25, "admit")
    families = led.prom_families()
    families["build_info"] = {
        "type": "gauge",
        "samples": [({"version": "0.1.0", "jax": "0.4.x",
                      "features": "live,attrib"}, 1)],
    }
    text = prom.render(None, families=families)
    assert "# TYPE llmc_device_time_seconds_total counter" in text
    assert "# TYPE llmc_build_info gauge" in text
    parsed = prom.parse_text(text)
    g = parsed["gauges"]
    assert g[(
        "device_time_seconds_total", (("family", "decode"),)
    )] == 1.5
    assert g[("tokens_total", (("disposition", "useful"),))] == 7
    assert g[("host_gap_seconds_total", (("phase", "admit"),))] == 0.25
    # No goodput_fraction gauge: the router sum-merge would corrupt it
    # (the fraction lives on /statsz; counters are the mergeable form).
    assert not any(k[0] == "goodput_fraction" for k in g)
    bi = [k for k in g if k[0] == "build_info"]
    assert len(bi) == 1 and dict(bi[0][1])["features"] == "live,attrib"
    # The router merge path sums counters across replicas.
    merged = prom.merge([parsed, parsed])
    assert merged["gauges"][(
        "tokens_total", (("disposition", "useful"),)
    )] == 14


# ---------------------------------------------------------------------------
# pooled end-to-end attribution over real tiny engines


@pytest.fixture(scope="module")
def tiny():
    import jax
    import jax.numpy as jnp

    from llm_consensus_tpu.models import init_params
    from llm_consensus_tpu.models.config import get_config

    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    return cfg, params


def test_batcher_attribution_and_goodput_reconcile(tiny, monkeypatch):
    import jax.numpy as jnp

    from llm_consensus_tpu.engine import ContinuousBatcher, Engine
    from llm_consensus_tpu.engine.engine import SamplingParams

    monkeypatch.setenv("LLMC_KV_POOL", "0")
    led = ChipTimeLedger()
    attrib_mod.install(led)
    cfg, params = tiny
    eng = Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                 stream_interval=8, prefill_chunk=16)
    b = ContinuousBatcher(eng, max_batch=4)
    try:
        s = SamplingParams(max_new_tokens=24, ignore_eos=True)
        futs = [
            b.submit(f"attrib stream {i} body", s) for i in range(4)
        ]
        results = [f.result(timeout=300) for f in futs]
    finally:
        b.close()
    snap = led.snapshot()
    # Decode intervals were attributed, and admission prefill booked
    # (drained-pipeline wall or impure interval — either lands as
    # "prefill").
    assert snap["device_s"].get("decode", 0) > 0, snap["device_s"]
    assert snap["device_s"].get("prefill", 0) > 0, snap["device_s"]
    assert snap["dispatches"]["decode"] >= 1
    # Goodput reconciliation: every emitted token booked useful EXACTLY
    # once, nothing else produced tokens in this run.
    emitted = sum(len(r.token_ids) for r in results)
    assert emitted == 4 * 24
    assert snap["tokens"]["useful"] == emitted, snap["tokens"]
    # The pool cache registered as a modeled HBM component.
    assert any(
        k.startswith("pool_cache:") for k in snap["hbm"]["components"]
    )


def test_single_stream_engine_attribution(tiny, monkeypatch):
    import jax.numpy as jnp

    from llm_consensus_tpu.engine import Engine
    from llm_consensus_tpu.engine.engine import SamplingParams

    monkeypatch.setenv("LLMC_KV_POOL", "0")
    led = ChipTimeLedger()
    attrib_mod.install(led)
    cfg, params = tiny
    eng = Engine(cfg, params=params, dtype=jnp.float32, max_seq=128,
                 stream_interval=8, prefill_chunk=16)
    r = eng.generate("single stream attrib probe",
                     SamplingParams(max_new_tokens=16, ignore_eos=True))
    snap = led.snapshot()
    assert snap["device_s"].get("prefill", 0) > 0
    assert snap["device_s"].get("decode", 0) > 0
    assert any(
        k.startswith("weights:") for k in snap["hbm"]["components"]
    )
    assert len(r.token_ids) == 16


# ---------------------------------------------------------------------------
# /metricsz lint (satellite: metric-name hygiene + docs table coverage)


class FakeProvider(Provider):
    def query(self, ctx: Context, req: Request) -> Response:
        ctx.raise_if_done()
        return Response(
            model=req.model,
            content=f"{req.model} answers {req.prompt[:16]}",
            provider="fake",
        )

    def query_stream(self, ctx, req, callback):
        resp = self.query(ctx, req)
        if callback is not None:
            callback(resp.content)
        return resp


def _post(port: int, body: dict):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    try:
        conn.request(
            "POST", "/v1/consensus", json.dumps(body),
            {"Content-Type": "application/json"},
        )
        r = conn.getresponse()
        data = r.read()
    finally:
        conn.close()
    return r.status, json.loads(data)


def test_metricsz_name_lint_and_docs_table(tmp_path):
    led = ChipTimeLedger()
    led.observe_device("decode", 0.1)
    led.token_event("useful", 4)
    led.gap(0.01, "admit")
    attrib_mod.install(led)
    provider = FakeProvider()
    registry = Registry()
    for m in PANEL + [JUDGE]:
        registry.register(m, provider)
    gw = serve.build_gateway(
        registry, list(PANEL), JUDGE, timeout=30.0, max_concurrency=4,
        data_dir=os.path.join(str(tmp_path), "data"),
        live=live_mod.LiveMetrics(window_s=60.0),
    )
    gw.start()
    try:
        _, port = gw.address
        for pr in ("high", "low"):
            status, _ = _post(port, {"prompt": f"lint {pr}", "priority": pr})
            assert status == 200
        text = gw.metricsz()
    finally:
        gw.close(drain=False, timeout=5.0)

    name_re = re.compile(r"^llmc_[a-z0-9_]+$")
    declared: list = []
    sampled: set = set()
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            fam, _, ftype = rest.partition(" ")
            declared.append((fam, ftype.strip()))
        elif line and not line.startswith("#"):
            name = line.split("{", 1)[0].split(" ", 1)[0]
            sampled.add(name)
    # 1. Every declared family is a legal llmc_ name, declared ONCE.
    fams = [f for f, _ in declared]
    assert fams, "no families exported"
    for fam, ftype in declared:
        assert name_re.match(fam), fam
        assert ftype in ("histogram", "counter", "gauge"), (fam, ftype)
    assert len(fams) == len(set(fams)), (
        f"duplicate family declarations: "
        f"{sorted(f for f in fams if fams.count(f) > 1)}"
    )
    # 2. Every sample line belongs to a declared family.
    suffixes = ("_bucket", "_sum", "_count")
    for name in sampled:
        base = name
        for sfx in suffixes:
            if name.endswith(sfx) and name[: -len(sfx)] in set(fams):
                base = name[: -len(sfx)]
                break
        assert base in set(fams), f"undeclared sample family {name}"
    # 3. Every exported family appears in the docs reference table.
    docs = open(
        os.path.join(os.path.dirname(__file__), "..", "docs",
                     "observability.md"),
        encoding="utf-8",
    ).read()
    for fam in set(fams):
        assert f"`{fam}`" in docs, (
            f"{fam} exported but missing from docs/observability.md"
        )
    # 4. Every registered /statsz block is documented too.
    for block in gw.stats_registry.names():
        assert f"`{block}`" in docs, (
            f"statsz block {block!r} missing from docs/observability.md"
        )
    # Sanity: the attribution families actually made it out.
    assert ("device_time_seconds_total" in {f[5:] for f in fams})
    assert ("build_info" in {f[5:] for f in fams})


def test_debugz_blackbox_on_demand_dump(tmp_path):
    """POST /debugz/blackbox snapshots the flight recorder on demand —
    200 with the dump path, 429 when rate-limited, 404 when disabled."""
    fr = FlightRecorder(
        capacity=64, out_dir=str(tmp_path / "bb"), min_interval_s=3600.0
    )
    bb_mod.install(fr)
    fr.instant("probe", tid="test")
    provider = FakeProvider()
    registry = Registry()
    for m in PANEL + [JUDGE]:
        registry.register(m, provider)
    gw = serve.build_gateway(
        registry, list(PANEL), JUDGE, timeout=30.0, max_concurrency=4,
        data_dir=os.path.join(str(tmp_path), "data"),
        live=live_mod.LiveMetrics(window_s=60.0),
    )
    gw.start()
    try:
        _, port = gw.address

        def post_debug():
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
            try:
                conn.request("POST", "/debugz/blackbox", b"")
                r = conn.getresponse()
                return r.status, json.loads(r.read())
            finally:
                conn.close()

        status, doc = post_debug()
        assert status == 200, doc
        assert os.path.exists(doc["path"])
        assert doc["dumps"] == 1
        # Inside the rate-limit interval: suppressed, not a second file.
        status, doc = post_debug()
        assert status == 429, doc
        assert doc["suppressed"] >= 1
    finally:
        gw.close(drain=False, timeout=5.0)
    # Disabled recorder: 404.
    bb_mod.install(None)
    gw2 = serve.build_gateway(
        registry, list(PANEL), JUDGE, timeout=30.0, max_concurrency=4,
        data_dir=os.path.join(str(tmp_path), "data2"),
        live=live_mod.LiveMetrics(window_s=60.0),
    )
    status, doc = gw2.debug_blackbox()
    assert status == 404 and "error" in doc


# ---------------------------------------------------------------------------
# one-shot CLI persists the live summary (satellite: CLI parity)


def test_live_summary_shape():
    lm = live_mod.LiveMetrics(window_s=60.0)
    for v in (0.01, 0.02, 0.4):
        lm.observe("ttft", v, outcome="ok", **{"class": "normal"})
    doc = obs_export.live_summary(lm)
    assert "ttft" in doc
    (row,) = doc["ttft"]
    assert row["count"] == 3
    assert row["labels"] == {"class": "normal", "outcome": "ok"}
    assert 0 < row["p50_s"] <= row["p99_s"]
    assert obs_export.live_summary(live_mod.LiveMetrics()) is None


def test_cli_one_shot_persists_live_summary(tmp_path):
    """Without --events, a run whose live plane observed anything still
    persists metrics.json carrying the per-family quantile summary —
    serve-mode scrape parity for one-shot runs."""
    import io

    from llm_consensus_tpu.cli.main import Config, run
    from llm_consensus_tpu.providers import ProviderFunc

    lm = live_mod.LiveMetrics(window_s=60.0)
    live_mod.install(lm)
    led = ChipTimeLedger()
    attrib_mod.install(led)

    def factory(model):
        def answer(ctx, req):
            # Stand-in for the tpu provider's per-token observation.
            lm.observe("token_latency", 0.003, outcome="ok",
                       **{"class": "normal"})
            led.observe_device("decode", 0.01)
            return Response(req.model, f"echo({req.prompt[:8]})", "fake", 1.0)

        return ProviderFunc(answer)

    cfg = Config(models=["a"], judge="a", prompt="p", quiet=True,
                 data_dir=str(tmp_path))
    run(cfg, Context.background(), factory=factory,
        stdout=io.StringIO(), stderr=io.StringIO())
    (run_dir,) = [p for p in tmp_path.iterdir() if p.is_dir()]
    files = {p.name for p in run_dir.iterdir()}
    assert "metrics.json" in files, files
    assert "trace.json" not in files  # no --events: no event timeline
    doc = json.loads((run_dir / "metrics.json").read_text())
    assert "token_latency" in doc["live"]
    assert doc["live"]["token_latency"][0]["count"] >= 1
    assert doc["attrib"]["device_s"]["decode"] > 0
