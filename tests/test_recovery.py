"""Crash recovery tests: stream journal, engine supervision, --resume.

The robustness contract of PR 5 (recovery/): an engine death mid-decode
costs a pause, not the in-flight streams — greedy streams replay
byte-identically onto the rebuilt pool — and a process death mid-run
leaves a ``data/<run-id>/`` dir that ``--resume`` finishes without
rerunning the panel answers its journal already completed.

Engine-level tests run real (tiny) engines on the CPU backend with
deterministic fault plans, the same shape as tests/test_faults.py.
"""

from __future__ import annotations

import io
import json
import os
import threading
import time

import pytest

from llm_consensus_tpu import faults, obs, recovery
from llm_consensus_tpu.engine import SamplingParams
from llm_consensus_tpu.providers import ProviderFunc, Request, Response
from llm_consensus_tpu.utils.context import Context

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean_process_state():
    """Every test starts and ends with no plan/journal/recorder installed
    — these are process-global and the suite shares one interpreter."""
    faults.reset()
    recovery.reset()
    yield
    faults.reset()
    recovery.reset()
    obs.install(None)


# ---------------------------------------------------------------------------
# journal unit tests


def test_journal_entry_lifecycle():
    j = recovery.StreamJournal()
    e = j.record([1, 2, 3], SamplingParams(max_new_tokens=8))
    assert j.depth() == 1 and e.open
    e.append(7)
    e.append(8)
    assert e.tokens() == [7, 8]
    e.close("eos")
    assert j.depth() == 0 and not e.open
    assert e.finish == "eos"
    e.close("length")  # idempotent: first close wins
    assert e.finish == "eos"
    assert j.stats() == {"depth": 0, "opened": 1, "closed": 1}


def test_journal_seal_drops_late_appends():
    j = recovery.StreamJournal()
    e = j.record([1], SamplingParams())
    e.append(5)
    snapshot = e.seal()
    assert snapshot == [5]
    e.append(6)  # a wedged worker waking up late
    assert e.tokens() == [5], "sealed entry accepted a late append"


def test_journal_disk_mirror(tmp_path):
    from llm_consensus_tpu import integrity

    j = recovery.StreamJournal(path=str(tmp_path / "wal"))
    e = j.record([1, 2], SamplingParams(max_new_tokens=4))
    e.append(9)
    e.close("length")
    files = os.listdir(tmp_path / "wal")
    assert len(files) == 1
    # Every record is CRC32C-framed: "<crc-8-hex> <payload>".
    lines = (tmp_path / "wal" / files[0]).read_text().splitlines()
    payloads = [integrity.parse_wal_line(ln) for ln in lines]
    assert None not in payloads, lines
    header = json.loads(payloads[0])
    assert header["prompt_ids"] == [1, 2]
    assert payloads[1] == "9"
    assert payloads[-1] == "#finish=length"
    # The reader round-trips the same records.
    doc = recovery.read_wal(str(tmp_path / "wal" / files[0]))
    assert doc["header"]["prompt_ids"] == [1, 2]
    assert doc["tokens"] == [9]
    assert doc["finish"] == "length"
    assert not doc["truncated"]


# ---------------------------------------------------------------------------
# atomic save_file (satellite)


def test_save_file_is_atomic_and_leaves_no_temp(tmp_path):
    from llm_consensus_tpu.output.persist import save_file

    run_dir = str(tmp_path / "run")
    path = save_file(run_dir, "trace.json", '{"a": 1}')
    assert path == os.path.join(run_dir, "trace.json")
    assert json.load(open(path)) == {"a": 1}
    # Overwrite is atomic-replace, bytes round-trip, no temp debris.
    assert save_file(run_dir, "trace.json", b'{"a": 2}') == path
    assert json.load(open(path)) == {"a": 2}
    assert sorted(os.listdir(run_dir)) == ["trace.json"]


def test_save_file_failure_is_nonfatal(tmp_path):
    from llm_consensus_tpu.output.persist import save_file

    target = tmp_path / "not-a-dir"
    target.write_text("file in the way")
    warnings: list[str] = []
    assert save_file(str(target), "x.json", "{}", warn=warnings.append) is None
    assert warnings and "Failed to save x" in warnings[0]


# ---------------------------------------------------------------------------
# engine supervision: crash replay + wedge detection (real tiny engines)


def _provider(**kw):
    from llm_consensus_tpu.providers.tpu import TPUProvider

    kw.setdefault("ignore_eos", True)
    kw.setdefault("stream_interval", 4)
    kw.setdefault("batch_streams", 2)
    return TPUProvider(**kw)


# THREE prompts onto a 2-slot pool: the third stream is still QUEUED
# when the crash lands, so recovery must also reclassify the cancelled
# queued future as pool death (not a benign close) and replay it.
PROMPTS = [
    "crash replay probe one",
    "crash replay probe two — longer body",
    "crash replay probe three, queued behind the pool",
]


def _query_all(prov, prompts, max_tokens=16, collect=None):
    results: list = [None] * len(prompts)

    def fire(i):
        cb = None
        if collect is not None:
            collect[i] = []
            cb = collect[i].append
        results[i] = prov.query_stream(
            Context.background(),
            Request(model="tpu:tiny-llama", prompt=prompts[i],
                    max_tokens=max_tokens),
            cb,
        )

    threads = [threading.Thread(target=fire, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(r is not None for r in results)
    return results


def test_crash_replay_byte_identity():
    # Baseline: the fault-free greedy outputs (single-stream engine —
    # the batcher's greedy contract is token-exact against it, so the
    # baseline is order-independent even with 3 streams on 2 slots).
    prov = _provider(batch_streams=1)
    base = _query_all(prov, PROMPTS)
    prov.release()
    assert all(r.tokens == 16 for r in base)

    # Crash run: same prompts, journal on, engine crash at the 2nd
    # decode-chunk dispatch — mid-generation, tokens already emitted.
    faults.install(faults.FaultPlan("crash@chunk=2", seed=7))
    recovery.install(recovery.StreamJournal())
    prov2 = _provider()
    try:
        streamed: dict = {}
        got = _query_all(prov2, PROMPTS, collect=streamed)
        for i, r in enumerate(got):
            assert r.content == base[i].content, f"stream {i} diverged"
            assert r.tokens == 16
            # Stream continuity: the chunks the consumer saw concatenate
            # to exactly the final content — nothing dropped, nothing
            # duplicated across the restart seam.
            assert "".join(streamed[i]) == r.content
        sup = prov2._recovery.stats()
        assert sup["restarts"] == 1, sup  # one rebuild served every waiter
        assert sup["replayed_streams"] >= 1, sup
        assert sup["journal"]["depth"] == 0, "journal entries leaked"
    finally:
        prov2.release()


def test_wedge_detection_fires_on_stalled_heartbeat(monkeypatch):
    prov = _provider()
    base = prov.query(Context.background(), Request(
        model="tpu:tiny-llama", prompt="wedge probe", max_tokens=12,
    ))
    prov.release()

    faults.install(faults.FaultPlan("wedge@chunk=2@s=30", seed=7))
    recovery.install(recovery.StreamJournal())
    monkeypatch.setenv("LLMC_ENGINE_HEARTBEAT_S", "2.0")
    prov2 = _provider()
    try:
        t0 = time.monotonic()
        r = prov2.query(Context.background(), Request(
            model="tpu:tiny-llama", prompt="wedge probe", max_tokens=12,
        ))
        wall = time.monotonic() - t0
        assert r.content == base.content
        assert r.tokens == 12
        # The watchdog abandoned the wedged pool and the stream replayed
        # long before the 30 s injected stall would have released it.
        assert wall < 25.0, f"wedge was waited out, not detected ({wall:.1f}s)"
        sup = prov2._recovery.stats()
        assert sup["restarts"] >= 1, sup
        assert sup["replayed_streams"] >= 1, sup
    finally:
        prov2.release()


def test_recovery_stats_shape_without_supervision():
    prov = _provider()
    try:
        prov.query(Context.background(), Request(
            model="tpu:tiny-llama", prompt="stats probe", max_tokens=4,
        ))
        stats = prov.recovery_stats()
        assert stats["state"] == "ok"
        assert stats["restarts"] == 0 and stats["replayed_streams"] == 0
        assert "tiny-llama" in stats["heartbeats"]
        assert stats["heartbeats"]["tiny-llama"]["age_s"] >= 0.0
    finally:
        prov.release()


# ---------------------------------------------------------------------------
# coalesced-follower survival across a restart (gateway over real engines)


def test_coalesced_follower_survives_restart(tmp_path):
    import http.client

    from llm_consensus_tpu import serve
    from llm_consensus_tpu.providers.registry import Registry

    faults.install(faults.FaultPlan("crash@model=tiny-llama", seed=7))
    recovery.install(recovery.StreamJournal())
    prov = _provider(batch_streams=2)
    panel = ["tpu:tiny-llama"]
    judge = "tpu:tiny-gemma"
    reg = Registry()
    for m in panel + [judge]:
        reg.register(m, prov)
    gw = serve.build_gateway(
        reg, panel, judge, max_tokens=8, timeout=300.0,
        max_concurrency=2, max_queue=2,
        data_dir=os.path.join(str(tmp_path), "data"), port=0,
    )
    gw.start()
    try:
        _, port = gw.address

        def post_sse(out, idx):
            conn = http.client.HTTPConnection(
                "127.0.0.1", port, timeout=300
            )
            try:
                conn.request(
                    "POST", "/v1/consensus",
                    json.dumps({"prompt": "follower survival", "stream": True}),
                    {"Content-Type": "application/json"},
                )
                r = conn.getresponse()
                out[idx] = (r.status, r.read())
            finally:
                conn.close()

        results: dict = {}
        threads = [
            threading.Thread(target=post_sse, args=(results, i))
            for i in range(2)
        ]
        threads[0].start()
        # Give the leader a head start so the second request coalesces
        # as a follower instead of racing for leadership.
        time.sleep(0.3)
        threads[1].start()
        for t in threads:
            t.join()

        docs = []
        for i in range(2):
            status, body = results[i]
            assert status == 200, (i, body)
            frames = [
                f for f in body.decode("utf-8").split("\n\n") if f.strip()
            ]
            done = None
            for frame in frames:
                if "event: done" in frame:
                    for line in frame.splitlines():
                        if line.startswith("data: "):
                            done = json.loads(line[len("data: "):])
            assert done is not None, (i, body[-400:])
            docs.append(done)
        # One execution, two completed consumers, identical consensus —
        # the follower rode the leader's flight straight through the
        # engine restart.
        assert gw.scheduler.runs_executed == 1
        assert sum(1 for d in docs if d["coalesced"]) == 1, docs
        assert docs[0]["consensus"] == docs[1]["consensus"]
        assert docs[0]["run_id"] != docs[1]["run_id"]
        assert prov._recovery.stats()["restarts"] >= 1
    finally:
        gw.close(drain=False, timeout=10.0)
        prov.release()


# ---------------------------------------------------------------------------
# --resume (CLI, fake providers)


def _run_cli(argv, factory):
    from llm_consensus_tpu.cli.main import main

    stdout, stderr = io.StringIO(), io.StringIO()
    code = main(
        argv, factory=factory, stdin=io.StringIO(), stdout=stdout,
        stderr=stderr, install_signal_handlers=False,
    )
    return code, stdout.getvalue(), stderr.getvalue()


def test_resume_reuses_completed_panel_answers(tmp_path):
    data = str(tmp_path / "data")
    calls: list[str] = []

    def judge_down(model):
        def fn(ctx, req):
            calls.append(req.model)
            if req.model == "j":
                raise RuntimeError("judge crashed")
            return Response(req.model, f"echo({req.model})", "fake", 1.0)
        return ProviderFunc(fn)

    code, _, err = _run_cli(
        ["--models", "m1,m2", "--judge", "j", "--data-dir", data,
         "--system", "be brief", "--max-tokens", "32", "the question"],
        judge_down,
    )
    assert code == 1 and "consensus synthesis" in err
    run_id = os.listdir(data)[0]
    run_dir = os.path.join(data, run_id)
    assert not os.path.exists(os.path.join(run_dir, "result.json"))
    manifest = json.load(open(os.path.join(run_dir, "run.json")))
    assert manifest["models"] == ["m1", "m2"]
    assert manifest["system"] == "be brief"
    assert len(os.listdir(os.path.join(run_dir, "panel"))) == 2

    # Resume: only the judge reruns; the panel answers come from the
    # journal, the manifest supplies prompt + settings.
    calls2: list[str] = []
    seen_settings: dict = {}

    def healthy(model):
        def fn(ctx, req):
            calls2.append(req.model)
            seen_settings.update(
                system=req.system, max_tokens=req.max_tokens,
            )
            return Response(req.model, f"fresh({req.model})", "fake", 1.0)
        return ProviderFunc(fn)

    code, out, err = _run_cli(
        ["--resume", run_id, "--data-dir", data], healthy
    )
    assert code == 0, err
    assert calls2 == ["j"], calls2
    doc = json.load(open(os.path.join(run_dir, "result.json")))
    assert [r["content"] for r in doc["responses"]] == [
        "echo(m1)", "echo(m2)"
    ]
    assert doc["consensus"] == "fresh(j)"
    assert doc["prompt"] == "the question"
    assert os.path.exists(os.path.join(run_dir, "consensus.md"))


def test_resume_reruns_only_failed_models(tmp_path):
    data = str(tmp_path / "data")

    def m3_and_judge_down(model):
        def fn(ctx, req):
            if req.model in ("m3", "j"):
                raise RuntimeError(f"{req.model} down")
            return Response(req.model, f"echo({req.model})", "fake", 1.0)
        return ProviderFunc(fn)

    # m3 and the judge fail: m1/m2 land in the panel journal, the run
    # dies at synthesis (two survivors ⇒ no single-answer passthrough).
    code, _, _ = _run_cli(
        ["--models", "m1,m2,m3", "--judge", "j", "--data-dir", data, "q"],
        m3_and_judge_down,
    )
    assert code == 1
    run_id = os.listdir(data)[0]

    calls2: list[str] = []

    def healthy(model):
        def fn(ctx, req):
            calls2.append(req.model)
            return Response(req.model, f"fresh({req.model})", "fake", 1.0)
        return ProviderFunc(fn)

    code, _, err = _run_cli(["--resume", run_id, "--data-dir", data], healthy)
    assert code == 0, err
    # m1/m2 were journaled; m3 (failed — never journaled) reran, judge
    # reran.
    assert sorted(calls2) == ["j", "m3"], calls2
    doc = json.load(open(os.path.join(data, run_id, "result.json")))
    assert sorted(r["content"] for r in doc["responses"]) == [
        "echo(m1)", "echo(m2)", "fresh(m3)"
    ]


def test_resume_rejects_completed_or_unknown_runs(tmp_path):
    data = str(tmp_path / "data")

    def healthy(model):
        return ProviderFunc(lambda ctx, req: Response(
            req.model, "ok", "fake", 1.0
        ))

    code, _, _ = _run_cli(
        ["--models", "m1", "--judge", "j", "--data-dir", data, "q"], healthy
    )
    assert code == 0
    run_id = os.listdir(data)[0]
    code, _, err = _run_cli(["--resume", run_id, "--data-dir", data], healthy)
    assert code == 1 and "already completed" in err
    code, _, err = _run_cli(["--resume", "nope", "--data-dir", data], healthy)
    assert code == 1 and "no usable run.json" in err


def test_resume_flag_conflicts():
    from llm_consensus_tpu.cli.main import CLIError, parse_args

    with pytest.raises(CLIError, match="prompt from the saved run"):
        parse_args(["--resume", "r1", "extra prompt"], io.StringIO(),
                   io.StringIO())
    with pytest.raises(CLIError, match="incompatible"):
        parse_args(["--resume", "r1", "--no-save"], io.StringIO(),
                   io.StringIO())
    with pytest.raises(CLIError, match="incompatible"):
        parse_args(["--resume", "r1", "--continue", "r0"], io.StringIO(),
                   io.StringIO())
    # Identity-changing flags are manifest-owned: rejected, not silently
    # discarded.
    with pytest.raises(CLIError, match="saved run's manifest"):
        parse_args(["--resume", "r1", "--models", "a,b"], io.StringIO(),
                   io.StringIO())
    with pytest.raises(CLIError, match="saved run's manifest"):
        parse_args(["--resume", "r1", "--judge", "x"], io.StringIO(),
                   io.StringIO())
