"""Result JSON schema tests (output.go:8-15)."""

import json

from llm_consensus_tpu.output import Result
from llm_consensus_tpu.providers import Response


def test_result_json_shape_full():
    r = Result(
        prompt="p",
        responses=[Response("m1", "c1", "prov", 12.5)],
        consensus="the answer",
        judge="judge-model",
        warnings=["m2: failed"],
        failed_models=["m2"],
    )
    d = json.loads(r.to_json())
    assert list(d.keys()) == [
        "prompt",
        "responses",
        "consensus",
        "judge",
        "warnings",
        "failed_models",
    ]
    assert d["responses"][0] == {
        "model": "m1",
        "content": "c1",
        "provider": "prov",
        "latency_ms": 12.5,
    }


def test_result_omits_empty_warnings_and_failures():
    # omitempty parity (output.go:13-14)
    d = Result(prompt="p", responses=[], consensus="c", judge="j").to_dict()
    assert "warnings" not in d
    assert "failed_models" not in d
