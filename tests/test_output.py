"""Result JSON schema tests (output.go:8-15)."""

import json

from llm_consensus_tpu.output import Result
from llm_consensus_tpu.providers import Response


def test_result_json_shape_full():
    r = Result(
        prompt="p",
        responses=[Response("m1", "c1", "prov", 12.5)],
        consensus="the answer",
        judge="judge-model",
        warnings=["m2: failed"],
        failed_models=["m2"],
    )
    d = json.loads(r.to_json())
    assert list(d.keys()) == [
        "prompt",
        "responses",
        "consensus",
        "judge",
        "warnings",
        "failed_models",
    ]
    assert d["responses"][0] == {
        "model": "m1",
        "content": "c1",
        "provider": "prov",
        "latency_ms": 12.5,
    }


def test_result_omits_empty_warnings_and_failures():
    # omitempty parity (output.go:13-14)
    d = Result(prompt="p", responses=[], consensus="c", judge="j").to_dict()
    assert "warnings" not in d
    assert "failed_models" not in d


# ---------------------------------------------------------------------------
# run ids: collision-free under concurrent server runs (output/persist)


def test_run_ids_unique_within_one_second():
    from llm_consensus_tpu.output.persist import generate_run_id

    # Same wall-clock second for every call — the exact serving regime
    # where timestamp-derived ids used to be able to collide.
    ids = [generate_run_id(now=1_000_000.0) for _ in range(512)]
    assert len(set(ids)) == len(ids)
    # Reference format preserved: <ts>-<6 hex chars>.
    ts = ids[0].rsplit("-", 1)[0]
    assert all(i.rsplit("-", 1)[0] == ts for i in ids)
    assert all(len(i.rsplit("-", 1)[1]) == 6 for i in ids)


def test_run_ids_unique_across_threads():
    import threading

    from llm_consensus_tpu.output.persist import generate_run_id

    ids: list[str] = []
    lock = threading.Lock()

    def draw():
        mine = [generate_run_id(now=2_000_000.0) for _ in range(64)]
        with lock:
            ids.extend(mine)

    threads = [threading.Thread(target=draw) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(set(ids)) == 8 * 64


def test_reserve_run_dir_retries_on_exists(tmp_path, monkeypatch):
    import os

    from llm_consensus_tpu.output import persist

    # A colliding id (another process / an earlier crash already claimed
    # the dir) is redrawn, never reused.
    seq = iter(["20260101-000000-aaaaaa", "20260101-000000-aaaaaa",
                "20260101-000000-bbbbbb"])
    monkeypatch.setattr(persist, "generate_run_id", lambda now=None: next(seq))
    os.makedirs(tmp_path / "20260101-000000-aaaaaa")
    run_id, path = persist.reserve_run_dir(str(tmp_path))
    assert run_id == "20260101-000000-bbbbbb"
    assert os.path.isdir(path)


def test_reserve_run_dir_gives_up_honestly(tmp_path, monkeypatch):
    import os

    import pytest

    from llm_consensus_tpu.output import persist

    monkeypatch.setattr(
        persist, "generate_run_id", lambda now=None: "20260101-000000-cccccc"
    )
    os.makedirs(tmp_path / "20260101-000000-cccccc")
    with pytest.raises(OSError):
        persist.reserve_run_dir(str(tmp_path), attempts=3)
