"""Cross-request paged KV pool (kv/): radix structure, block lifecycle,
and the greedy byte-identity contract pool-on vs pool-off.

Radix tests are pure host (no JAX); pool tests drive real tiny engines
on CPU so the gather/scatter programs and the engine wiring are the
thing under test, not a mock of it.
"""

import itertools

import jax.numpy as jnp
import pytest

from llm_consensus_tpu.engine import Engine, SamplingParams
from llm_consensus_tpu.kv.radix import RadixIndex
from llm_consensus_tpu.models import get_config

# -- radix: insert / match / split ------------------------------------------


def _insert(idx: RadixIndex, ids, slot_gen):
    """Plan + attach like the pool does (slots from a counter)."""
    node, _base, writes = idx.plan_insert(list(ids))
    slots = [next(slot_gen) for _ in writes]
    return idx.attach(node, writes, slots)


def test_radix_insert_match_roundtrip():
    idx = RadixIndex(4)
    slots = itertools.count()
    attached = _insert(idx, list(range(10)), slots)
    assert [len(b.tokens) for b in attached] == [4, 4, 2]
    n, chain = idx.match(list(range(10)))
    assert n == 10 and [b.slot for b in chain] == [b.slot for b in attached]
    # Longer query matches only the stored prefix; shorter query matches
    # partially into the first block.
    assert idx.match(list(range(12)))[0] == 10
    assert idx.match(list(range(3)))[0] == 3
    assert idx.match([99, 98])[0] == 0


def test_radix_split_on_block_divergence():
    """Two chains sharing one full block branch at the node — the shared
    block is stored once and neither insert rewrites the other."""
    idx = RadixIndex(4)
    slots = itertools.count()
    a = [0, 1, 2, 3, 4, 5, 6, 7]
    b = [0, 1, 2, 3, 9, 9, 9, 9]
    got_a = _insert(idx, a, slots)
    got_b = _insert(idx, b, slots)
    assert len(got_a) == 2
    assert len(got_b) == 1  # only the divergent block writes
    assert idx.match(a)[0] == 8 and idx.match(b)[0] == 8
    assert len(idx.root.children) == 1  # one shared head block
    assert {x.slot for x in got_a}.isdisjoint({x.slot for x in got_b})


def test_radix_mid_block_partial_match():
    """Divergence inside a block still reuses the matching head tokens
    (the pool masks the gathered tail past the match point)."""
    idx = RadixIndex(4)
    _insert(idx, [0, 1, 2, 3, 4, 5, 6, 7], itertools.count())
    n, chain = idx.match([0, 1, 2, 3, 4, 5, 99, 99])
    assert n == 6
    assert len(chain) == 2  # head block + partially-matched tail block


def test_radix_partial_tail_copy_on_write():
    """Extending past a partial tail writes FRESH blocks for the whole
    divergent span; the old tail keeps its bytes for whoever matches it."""
    idx = RadixIndex(4)
    slots = itertools.count()
    short = _insert(idx, [0, 1, 2, 3, 4, 5], slots)      # full + partial tail
    longer = _insert(idx, [0, 1, 2, 3, 4, 5, 6, 7], slots)
    assert [len(b.tokens) for b in short] == [4, 2]
    assert [len(b.tokens) for b in longer] == [4]        # fresh (4,5,6,7)
    assert longer[0].slot not in {b.slot for b in short}  # COW, no rewrite
    assert idx.match([0, 1, 2, 3, 4, 5, 6, 7])[0] == 8
    assert idx.match([0, 1, 2, 3, 4, 5])[0] == 6


def test_radix_covered_and_noop_insert():
    idx = RadixIndex(4)
    _insert(idx, list(range(10)), itertools.count())
    assert idx.covered(list(range(10))) == 10
    assert idx.covered(list(range(8))) == 8
    assert idx.covered(list(range(12))) == 10
    assert idx.covered([5, 6]) == 0
    # A repeat (and a shorter partial tail) plans zero writes.
    assert idx.plan_insert(list(range(10)))[2] == []
    assert idx.plan_insert(list(range(9)))[2] == []


def test_radix_concurrent_attach_dedups():
    """Two plans taken before either attaches (the publish race): the
    second attach dedups full blocks onto the first's nodes and only the
    tail actually attaches — its unused slots go back to the caller."""
    idx = RadixIndex(4)
    ids = list(range(10))
    node1, _, writes1 = idx.plan_insert(ids)
    node2, _, writes2 = idx.plan_insert(ids)
    assert writes1 == writes2
    got1 = idx.attach(node1, writes1, [0, 1, 2])
    got2 = idx.attach(node2, writes2, [3, 4, 5])
    assert len(got1) == 3
    assert [len(b.tokens) for b in got2] == [2]  # only the partial tail
    assert got2[0].slot == 5  # slots 3, 4 unconsumed (pool refunds them)


def test_radix_evict_lru_leaves_skip_leased_and_interior():
    idx = RadixIndex(4)
    slots = itertools.count()
    a = _insert(idx, list(range(12)), slots)            # 3-block chain
    b = _insert(idx, [0, 1, 2, 3, 7, 7, 7, 7], slots)   # branches off a[0]
    b[-1].refs += 1  # lease the divergent tail mid-gather
    freed = idx.evict(100)
    # Only a's tail-then-middle free up: a[0] is interior (b hangs off
    # it) and b's tail is leased.
    assert freed == [a[2].slot, a[1].slot]
    b[-1].refs -= 1
    freed2 = idx.evict(100)
    assert set(freed2) == {b[-1].slot, a[0].slot}
    assert idx.entries == 0


def test_radix_evict_order_is_lru():
    idx = RadixIndex(4)
    slots = itertools.count()
    old = _insert(idx, [1, 1, 1, 1], slots)
    new = _insert(idx, [2, 2, 2, 2], slots)
    idx.match([1, 1, 1, 1])  # touch: old chain becomes most-recent
    assert idx.evict(1) == [new[0].slot]
    assert idx.evict(1) == [old[0].slot]


# -- pool: real engines, CPU ------------------------------------------------


@pytest.fixture(scope="module")
def tiny_params():
    cfg = get_config("tiny-llama")
    eng = Engine(cfg, dtype=jnp.float32, max_seq=256, seed=0,
                 prefill_chunk=16)
    return cfg, eng.params


def _engine(cfg, params, monkeypatch, pool: bool, **kw):
    monkeypatch.setenv("LLMC_KV_POOL", "1" if pool else "0")
    monkeypatch.setenv("LLMC_KV_POOL_BLOCK", "16")
    return Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                  prefill_chunk=16, **kw)


def test_pool_greedy_byte_identity_on_vs_off(tiny_params, monkeypatch):
    """The acceptance contract: one request sequence with shared-prefix,
    repeat, and divergent traffic emits IDENTICAL greedy tokens with the
    pool on vs off — and the pooled side really rode the radix."""
    cfg, params = tiny_params
    shared = "system: answer as a careful consensus panel member. " * 2
    prompts = [
        shared + "first user question",
        shared + "second, rather different user question",
        shared + "first user question",       # exact repeat
        "unrelated prompt with no common prefix at all " * 2,
        shared + "third question arrives after the divergent one",
    ]
    s = SamplingParams(max_new_tokens=10, ignore_eos=True)
    off = _engine(cfg, params, monkeypatch, pool=False)
    want = [off.generate(p, s).token_ids for p in prompts]
    on = _engine(cfg, params, monkeypatch, pool=True)
    assert on._kv_pool is not None
    got = [on.generate(p, s).token_ids for p in prompts]
    assert got == want
    stats = on._kv_pool.stats()
    assert stats["hit_tokens"] > 0 and stats["hits"] >= 3
    # Every lease released: nothing pinned once the calls return.
    assert all(
        b.refs == 0 for _n, b in _walk(on._kv_pool._radix)
    )


def test_pool_cross_round_judge_reuse(tiny_params, monkeypatch):
    """Round 2 of a consensus run (judge header + round-1 transcript +
    critique) rides round 1's published blocks — and stays byte-exact."""
    cfg, params = tiny_params
    header = "judge: weigh the panel answers and synthesize. "
    round1 = header + "answer A says yes; answer B says no. "
    round2 = round1 + "critique: A ignored the edge case; revise. "
    s = SamplingParams(max_new_tokens=8, ignore_eos=True)
    on = _engine(cfg, params, monkeypatch, pool=True)
    on.generate(round1, s)
    before = on._kv_pool.stats()["hit_tokens"]
    r2 = on.generate(round2, s)
    gained = on._kv_pool.stats()["hit_tokens"] - before
    assert gained >= len(round1) - on._kv_pool.block_size  # whole-block floor
    off = _engine(cfg, params, monkeypatch, pool=False)
    off.generate(round1, s)
    assert r2.token_ids == off.generate(round2, s).token_ids


def _walk(radix):
    out, stack = [], [radix.root]
    while stack:
        node = stack.pop()
        for child in node.children:
            out.append((node, child.block))
            stack.append(child)
    return out


def test_pool_cow_divergence_keeps_shared_bytes(tiny_params, monkeypatch):
    """A divergent publish forks the chain without rewriting shared
    blocks: re-running the original extended prompt still matches a
    pool-off engine byte for byte."""
    cfg, params = tiny_params
    shared = "x" * 48
    s = SamplingParams(max_new_tokens=8, ignore_eos=True)
    on = _engine(cfg, params, monkeypatch, pool=True)
    on.generate(shared + " branch one tail", s)
    on.generate(shared + " branch TWO goes elsewhere", s)
    probe = shared + " branch one tail, extended further still"
    got = on.generate(probe, s)
    assert on._kv_pool.stats()["hits"] >= 2
    off = _engine(cfg, params, monkeypatch, pool=False)
    assert got.token_ids == off.generate(probe, s).token_ids


def test_pool_eviction_under_pressure(tiny_params, monkeypatch):
    """A 4-block arena under many distinct prompts must evict (LRU) —
    and keep every greedy output identical to the classic path."""
    cfg, params = tiny_params
    monkeypatch.setenv("LLMC_KV_POOL_MB", "0.08")  # 4 blocks of 16 tokens
    s = SamplingParams(max_new_tokens=6, ignore_eos=True)
    prompts = [f"distinct prompt number {i} with its own words " for i in range(4)]
    on = _engine(cfg, params, monkeypatch, pool=True)
    assert on._kv_pool.n_blocks == 4
    off = _engine(cfg, params, monkeypatch, pool=False)
    for p in prompts:
        assert on.generate(p, s).token_ids == off.generate(p, s).token_ids
    stats = on._kv_pool.stats()
    assert stats["evicted_blocks"] > 0
    assert stats["blocks_used"] <= stats["blocks_total"] == 4


def test_pool_exhausted_fault_truncates_publish(tiny_params, monkeypatch):
    """The kv fault site: an injected pool_exhausted drops a publish's
    blocks (reuse lost, never correctness)."""
    from llm_consensus_tpu import faults

    cfg, params = tiny_params
    s = SamplingParams(max_new_tokens=6, ignore_eos=True)
    prompt = "a prompt whose publish the fault plan will reject " * 2
    faults.install(faults.FaultPlan("pool_exhausted@step=1", seed=3))
    try:
        on = _engine(cfg, params, monkeypatch, pool=True)
        first = on.generate(prompt, s)
        stats = on._kv_pool.stats()
        assert stats["exhausted"] == 1 and stats["published_blocks"] == 0
        # Next publish (step 2) proceeds; the repeat is exact either way.
        assert on.generate(prompt, s).token_ids == first.token_ids
        assert on._kv_pool.stats()["published_blocks"] > 0
    finally:
        faults.reset()


def test_pool_off_by_default_and_gated_like_prefix_reuse(
        tiny_params, monkeypatch):
    cfg, params = tiny_params
    monkeypatch.delenv("LLMC_KV_POOL", raising=False)
    assert Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                  prefill_chunk=16)._kv_pool is None
    # chunking off / prefix cache off disable the pool exactly like the
    # classic reuse they replace.
    monkeypatch.setenv("LLMC_KV_POOL", "1")
    assert Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                  prefill_chunk=0)._kv_pool is None
    monkeypatch.setenv("LLMC_PREFIX_CACHE", "0")
    assert Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                  prefill_chunk=16)._kv_pool is None


def test_pool_int8_kv_cache_byte_identity(tiny_params, monkeypatch):
    """Blocks carry the int8 code AND seq-minor scale stacks — quantized
    caches share through the pool byte-exactly too."""
    cfg, params = tiny_params
    shared = "quantized cache shared prefix for every stream " * 2
    s = SamplingParams(max_new_tokens=8, ignore_eos=True)
    on = _engine(cfg, params, monkeypatch, pool=True, kv_quant="int8")
    off = _engine(cfg, params, monkeypatch, pool=False, kv_quant="int8")
    for tail in ("alpha", "beta continues differently"):
        p = shared + tail
        assert on.generate(p, s).token_ids == off.generate(p, s).token_ids
    assert on._kv_pool.stats()["hit_tokens"] > 0
