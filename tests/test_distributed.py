"""Multi-host layer (parallel/distributed.py) on the virtual CPU mesh.

Real multi-host needs multiple processes; what unit tests can pin is the
granule/axis math of hybrid_mesh (the part that decides which collectives
ride DCN vs ICI), the no-op contract of initialize(), and that the
standard sharding/train stack consumes a hybrid mesh unchanged."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.parallel.distributed import hybrid_mesh, initialize, is_initialized


def test_initialize_is_noop_without_config(monkeypatch):
    monkeypatch.delenv("LLMC_COORDINATOR", raising=False)
    monkeypatch.delenv("LLMC_NUM_PROCESSES", raising=False)
    assert initialize() is False
    assert not is_initialized()


def test_hybrid_mesh_axis_order_and_granules():
    """DCN axes are outermost; each ICI granule is a contiguous device run,
    so intra-granule collectives stay on neighboring links."""
    mesh = hybrid_mesh({"dp": 2}, {"tp": 4}, jax.devices())
    assert mesh.axis_names == ("dp", "tp")
    assert dict(mesh.shape) == {"dp": 2, "tp": 4}
    ids = np.vectorize(lambda d: d.id)(mesh.devices)
    # Each dp row (one granule) holds 4 consecutive device ids.
    for row in ids:
        assert list(row) == list(range(row[0], row[0] + 4))


def test_hybrid_mesh_multi_axis():
    mesh = hybrid_mesh({"pp": 2}, {"dp": 2, "tp": 2}, jax.devices())
    assert mesh.axis_names == ("pp", "dp", "tp")
    assert mesh.devices.shape == (2, 2, 2)


def test_hybrid_mesh_size_mismatch_raises():
    with pytest.raises(ValueError, match="needs 16 devices"):
        hybrid_mesh({"dp": 4}, {"tp": 4}, jax.devices())


def test_train_step_runs_on_hybrid_mesh():
    """The dp(DCN)×tp(ICI) layout drives the unchanged train stack: grads
    all-reduce over the outer axis, TP collectives stay inner."""
    import optax

    from llm_consensus_tpu.models import get_config
    from llm_consensus_tpu.train import init_train_state, make_train_step

    cfg = get_config("tiny-llama")
    mesh = hybrid_mesh({"dp": 2}, {"tp": 4}, jax.devices())
    opt = optax.adamw(1e-3)
    state = init_train_state(cfg, jax.random.PRNGKey(0), opt, mesh=mesh)
    step = make_train_step(cfg, opt, mesh=mesh)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size, jnp.int32)
    batch = {
        "tokens": tokens,
        "targets": jnp.roll(tokens, -1, axis=1),
        "mask": jnp.ones((4, 16), jnp.float32),
    }
    _, metrics = step(state, batch)
    assert jnp.isfinite(float(metrics["loss"]))


def test_sharded_engine_on_hybrid_mesh_matches_unsharded():
    """A TP-within-host hybrid placement is still numerics-neutral for
    inference."""
    from llm_consensus_tpu.engine import Engine, SamplingParams
    from llm_consensus_tpu.models import get_config, init_params

    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(2), dtype=jnp.float32)
    base = Engine(cfg, params, dtype=jnp.float32, max_seq=128)
    mesh = hybrid_mesh({"dp": 1}, {"tp": 2}, jax.devices()[:2])
    sharded = Engine(cfg, params, dtype=jnp.float32, max_seq=128, mesh=mesh)
    s = SamplingParams(max_new_tokens=10, ignore_eos=True)
    prompt = "hybrid mesh inference"
    assert sharded.generate(prompt, s).token_ids == base.generate(prompt, s).token_ids


def test_pod_env_detection(monkeypatch):
    """Single-host TPU_WORKER_HOSTNAMES (one hostname) must not read as a
    pod; multiple hostnames or a coordinator marker must."""
    from llm_consensus_tpu.parallel.distributed import _pod_env

    for v in ("LLMC_DISTRIBUTED", "MEGASCALE_COORDINATOR_ADDRESS",
              "CLOUD_TPU_CLUSTER_COORDINATOR_ADDRESS", "TPU_WORKER_HOSTNAMES"):
        monkeypatch.delenv(v, raising=False)
    assert _pod_env() is False
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "localhost")
    assert _pod_env() is False
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host-0,host-1")
    assert _pod_env() is True
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES")
    monkeypatch.setenv("MEGASCALE_COORDINATOR_ADDRESS", "10.0.0.1:1234")
    assert _pod_env() is True
