"""Kernel-microscope tests: roofline ledger, deep profiler, sentinels.

Covers obs/roofline, obs/profiler, and the ISSUE 17 satellites:

  * RooflineLedger units — one static-cost capture per (family, bucket)
    key with every later dispatch a counter bump, the on-device loop
    ``steps`` multiplier, verdicts against an overridden ridge, and the
    attributed-wall coverage join;
  * the modeled-vs-cost-analysis cross-check — agreeing models pass,
    a modeled figure outside ``LLMC_ROOFLINE_TOL`` reports ``ok: false``;
  * ``hbm_device_stats`` on CPU — returns None cleanly (the gauge is
    simply absent off-accelerator, never an exception);
  * DeepProfiler — armed/busy/rate-limited state machine, the atomic
    artifact-dir rename, stop_now, and the gateway's
    ``POST /debugz/profile`` 404/429/200 contract;
  * prom escaped-label values — render → parse → merge → render_parsed
    round-trips backslashes, quotes, newlines, ``}`` and tolerates
    trailing timestamps (the fleet-merge path's hardening);
  * the router's ``llmc_replica_up`` / scrape-staleness gauges;
  * tools/bench_compare.py — direction awareness, the noise band,
    config-key exemption, and the self-test's injected regression.
"""

from __future__ import annotations

import importlib.util
import json
import os
import threading
import time

import pytest

from llm_consensus_tpu import obs, serve
from llm_consensus_tpu.obs import attrib as attrib_mod
from llm_consensus_tpu.obs import live as live_mod
from llm_consensus_tpu.obs import profiler as prof_mod
from llm_consensus_tpu.obs import prom
from llm_consensus_tpu.obs import roofline as roofline_mod
from llm_consensus_tpu.obs.profiler import DeepProfiler
from llm_consensus_tpu.obs.roofline import RooflineLedger
from llm_consensus_tpu.providers.base import Provider, Request, Response
from llm_consensus_tpu.providers.registry import Registry
from llm_consensus_tpu.utils.context import Context

PANEL = ["alpha", "beta"]
JUDGE = "gamma"

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_planes():
    for mod in (obs, live_mod, attrib_mod, roofline_mod, prof_mod):
        mod.reset()
    yield
    for mod in (obs, live_mod, attrib_mod, roofline_mod, prof_mod):
        mod.reset()


def _jitted_matmul():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(x):
        return x @ x

    x = jnp.ones((8, 8), dtype=jnp.float32)
    return f, x


# -- RooflineLedger units ----------------------------------------------------


def test_dispatch_captures_once_then_counts():
    led = RooflineLedger(ridge=32.0)
    f, x = _jitted_matmul()
    for _ in range(3):
        led.dispatch("decode", ("b8",), f, (x,), {}, tokens=4)
    snap = led.snapshot(device_s={"decode": 0.5})
    fam = snap["families"]["decode"]
    assert fam["programs"] == 1
    assert fam["dispatches"] == 3
    assert fam["tokens"] == 12
    # An 8x8 matmul counts 2*8^3 = 1024 FLOPs per dispatch.
    assert fam["flops"] == pytest.approx(3 * 1024)
    assert fam["bytes"] > 0
    assert fam["achieved_flops_per_s"] == pytest.approx(fam["flops"] / 0.5)
    assert fam["achieved_bytes_per_s"] == pytest.approx(fam["bytes"] / 0.5)


def test_steps_multiplier_scales_loop_body_counts():
    f, x = _jitted_matmul()
    led1 = RooflineLedger(ridge=32.0)
    led1.dispatch("decode", ("k",), f, (x,), {}, steps=1)
    led5 = RooflineLedger(ridge=32.0)
    led5.dispatch("decode", ("k",), f, (x,), {}, steps=5)
    f1 = led1.snapshot(device_s={})["families"]["decode"]
    f5 = led5.snapshot(device_s={})["families"]["decode"]
    assert f5["flops"] == pytest.approx(5 * f1["flops"])
    assert f5["bytes"] == pytest.approx(5 * f1["bytes"])


def test_verdicts_follow_the_ridge_override():
    f, x = _jitted_matmul()
    lo = RooflineLedger(ridge=1e-6)  # everything is compute-bound
    lo.dispatch("decode", ("k",), f, (x,), {})
    hi = RooflineLedger(ridge=1e9)  # everything is memory-bound
    hi.dispatch("decode", ("k",), f, (x,), {})
    s_lo = lo.snapshot(device_s={})
    s_hi = hi.snapshot(device_s={})
    assert s_lo["ridge_source"] == "override"
    assert s_lo["families"]["decode"]["verdict"] == "compute_bound"
    assert s_hi["families"]["decode"]["verdict"] == "memory_bound"


def test_coverage_joins_only_instrumented_families():
    led = RooflineLedger(ridge=32.0)
    f, x = _jitted_matmul()
    led.dispatch("decode", ("k",), f, (x,), {})
    snap = led.snapshot(device_s={"decode": 1.0, "allgather": 1.0})
    cov = snap["coverage"]
    assert cov["covered_wall_s"] == pytest.approx(1.0)
    assert cov["attrib_wall_s"] == pytest.approx(2.0)
    assert cov["fraction"] == pytest.approx(0.5)


def test_transfer_bytes_join_a_family_the_compiler_never_saw():
    led = RooflineLedger(ridge=32.0)
    led.note_transfer("kv_handoff", 4096.0)
    fam = led.snapshot(device_s={})["families"]["kv_handoff"]
    assert fam["bytes"] == pytest.approx(4096.0)
    assert fam["source"] == "transfer"
    # Transfer-only families book no dispatches, so they don't claim
    # coverage credit.
    assert fam["dispatches"] == 0


def test_concurrent_first_dispatches_capture_once():
    led = RooflineLedger(ridge=32.0)
    f, x = _jitted_matmul()
    barrier = threading.Barrier(4)

    def fire():
        barrier.wait()
        led.dispatch("decode", ("k",), f, (x,), {}, tokens=1)

    threads = [threading.Thread(target=fire) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    fam = led.snapshot(device_s={})["families"]["decode"]
    assert fam["programs"] == 1
    assert fam["dispatches"] == 4
    assert fam["tokens"] == 4
    assert fam["flops"] == pytest.approx(4 * 1024)


# -- the modeled-vs-XLA cross-check ------------------------------------------


def test_crosscheck_agreeing_model_is_ok():
    led = RooflineLedger(ridge=32.0)
    f, x = _jitted_matmul()
    led.dispatch("decode", ("k",), f, (x,), {}, tokens=4)
    led.note_modeled("decode", 1024 / 4)  # exactly the XLA count
    chk = led.snapshot(device_s={})["crosscheck"]["decode"]
    assert chk["ratio"] == pytest.approx(1.0)
    assert chk["ok"] is True


def test_crosscheck_flags_model_outside_tolerance():
    led = RooflineLedger(ridge=32.0, tol=4.0)
    f, x = _jitted_matmul()
    led.dispatch("decode", ("k",), f, (x,), {}, tokens=4)
    led.note_modeled("decode", (1024 / 4) * 100.0)  # 100x the XLA count
    chk = led.snapshot(device_s={})["crosscheck"]["decode"]
    assert chk["ok"] is False
    assert chk["ratio"] == pytest.approx(0.01)
    # Widening the modeled range back over the measured value heals it:
    # multiple engines legitimately register different analytic costs.
    led.note_modeled("decode", 1024 / 4)
    chk2 = led.snapshot(device_s={})["crosscheck"]["decode"]
    assert chk2["ok"] is True


# -- instrument() wrapper ----------------------------------------------------


def test_instrument_books_under_the_ambient_attrib_tag():
    led = RooflineLedger(ridge=32.0)
    roofline_mod.install(led)
    f, x = _jitted_matmul()
    wrapped = roofline_mod.instrument(f, family="decode")
    wrapped(x)
    with attrib_mod.tag("draft"):
        wrapped(x)
    fams = led.snapshot(device_s={})["families"]
    assert fams["decode"]["dispatches"] == 1
    assert fams["draft"]["dispatches"] == 1


def test_instrument_disabled_is_transparent():
    roofline_mod.install(None)
    f, x = _jitted_matmul()
    wrapped = roofline_mod.instrument(f, family="decode")
    out = wrapped(x)
    assert out.shape == (8, 8)
    assert hasattr(wrapped, "lower")  # jit surface delegates


# -- hbm_device_stats on CPU -------------------------------------------------


def test_hbm_device_stats_returns_none_on_cpu():
    led = attrib_mod.ChipTimeLedger()
    assert led.hbm_device_stats() is None
    # And the snapshot path that embeds it stays clean too.
    snap = led.snapshot()
    assert snap["hbm"].get("device") is None


# -- DeepProfiler ------------------------------------------------------------


def test_profiler_single_flight_rate_limit_and_atomic_dir(tmp_path):
    prof = DeepProfiler(out_dir=str(tmp_path), max_s=5.0,
                        min_interval_s=60.0)
    final, status = prof.arm(0.3, tag="t one!")
    assert status == "armed"
    assert os.path.basename(final).startswith("profile-t-one-")
    path2, status2 = prof.arm(0.1)
    assert (path2, status2) == (None, "busy")
    assert prof.wait(30.0)
    assert os.path.isdir(final) and os.listdir(final)
    assert not os.path.exists(final + ".partial")
    # Window 1 is booked; the next start inside the interval is 429.
    path3, status3 = prof.arm(0.1)
    assert (path3, status3) == (None, "rate_limited")
    st = prof.stats()
    assert st["windows"] == 1
    assert st["suppressed"] == 2
    assert st["last_path"] == final
    assert st["last_error"] is None


def test_profiler_stop_now_closes_early(tmp_path):
    prof = DeepProfiler(out_dir=str(tmp_path), max_s=30.0,
                        min_interval_s=0.0)
    final, status = prof.arm(30.0, tag="early")
    assert status == "armed"
    t0 = time.monotonic()
    assert prof.stop_now() == final
    assert time.monotonic() - t0 < 10.0  # nowhere near the 30 s cap
    assert os.path.isdir(final) and os.listdir(final)
    assert not prof.active()
    assert prof.stop_now() is None  # idempotent when idle


class FakeProvider(Provider):
    def query(self, ctx: Context, req: Request) -> Response:
        ctx.raise_if_done()
        return Response(model=req.model, content="ok", provider="fake")

    def query_stream(self, ctx, req, callback):
        resp = self.query(ctx, req)
        if callback is not None:
            callback(resp.content)
        return resp


def _gateway(tmp_path):
    registry = Registry()
    provider = FakeProvider()
    for m in PANEL + [JUDGE]:
        registry.register(m, provider)
    return serve.build_gateway(
        registry, list(PANEL), JUDGE, timeout=30.0, max_concurrency=4,
        data_dir=os.path.join(str(tmp_path), "data"),
    )


def test_debug_profile_contract_on_the_gateway(tmp_path):
    prof_mod.install(None)
    gw = _gateway(tmp_path)
    status, doc = gw.debug_profile()
    assert status == 404, doc

    prof_mod.install(DeepProfiler(
        out_dir=os.path.join(str(tmp_path), "prof"), max_s=5.0,
        min_interval_s=0.0,
    ))
    gw2 = _gateway(tmp_path)
    status, doc = gw2.debug_profile(duration_s=0.2, tag="contract")
    assert status == 200, doc
    assert doc["status"] == "armed" and doc["path"]
    status2, doc2 = gw2.debug_profile(duration_s=0.2)
    assert status2 == 429, doc2
    assert doc2["status"] == "busy"
    prof = prof_mod.profiler()
    assert prof.wait(30.0)
    assert os.path.isdir(doc["path"]) and os.listdir(doc["path"])


# -- prom: escaped label values round-trip the fleet-merge path --------------

NASTY = [
    'plain',
    'sp ace',
    'quo"te',
    'back\\slash',
    'new\nline',
    'brace}inside',
    'comma,eq=inside',
    'trail\\',
    'mix\\"all\n}"',
]


@pytest.mark.parametrize("value", NASTY)
def test_family_labels_round_trip_render_parse_merge(value):
    fams = {
        "roofline_flops_total": {
            "type": "counter",
            "samples": [({"family": value}, 7.0)],
        },
    }
    text = prom.render(families=fams)
    parsed = prom.parse_text(text)
    [(key, got)] = list(parsed["gauges"].items())
    name, labels = key
    assert name == "roofline_flops_total"
    assert dict(labels)["family"] == value
    assert got == 7.0
    merged = prom.merge([parsed, parsed])
    assert merged["gauges"][key] == 14.0
    # The router re-renders the merge; that text must parse back to the
    # same doc (the fleet scrape is itself scraped).
    reparsed = prom.parse_text(prom.render_parsed(merged))
    assert dict(list(reparsed["gauges"])[0][1])["family"] == value
    assert reparsed["gauges"][key] == 14.0


def test_parse_text_tolerates_trailing_timestamps():
    text = (
        "# TYPE llmc_load_score gauge\n"
        'llmc_load_score{url="http://x:1"} 0.5 1700000000000\n'
    )
    parsed = prom.parse_text(text)
    [(key, v)] = list(parsed["gauges"].items())
    assert v == 0.5
    assert dict(key[1])["url"] == "http://x:1"


def test_parse_labels_keeps_unknown_escapes_verbatim():
    text = (
        "# TYPE llmc_x gauge\n"
        'llmc_x{k="a\\qb"} 1\n'
    )
    parsed = prom.parse_text(text)
    [(key, _)] = list(parsed["gauges"].items())
    assert dict(key[1])["k"] == "a\\qb"


def test_parse_labels_rejects_unquoted_values():
    with pytest.raises(ValueError):
        prom._parse_labels("k=unquoted")
    with pytest.raises(ValueError):
        prom._parse_labels('k="unterminated')


# -- router: replica_up + scrape staleness -----------------------------------


def test_router_exports_replica_up_and_staleness(tmp_path):
    gw = _gateway(tmp_path)
    gw.start()
    router = None
    try:
        host, port = gw.address
        url = f"http://{host}:{port}"
        router = serve.build_router([url], poll_s=60.0)
        router.start()
        text = router.metricsz()
        parsed = prom.parse_text(text)
        up = {
            dict(labels)["url"]: v
            for (name, labels), v in parsed["gauges"].items()
            if name == "replica_up"
        }
        stale = {
            dict(labels)["url"]: v
            for (name, labels), v in parsed["gauges"].items()
            if name == "replica_scrape_staleness_seconds"
        }
        assert up == {url: 1.0}
        assert stale[url] >= 0.0
        gw.close(drain=False, timeout=5.0)
        gw = None
        parsed2 = prom.parse_text(router.metricsz())
        up2 = {
            dict(labels)["url"]: v
            for (name, labels), v in parsed2["gauges"].items()
            if name == "replica_up"
        }
        stale2 = {
            dict(labels)["url"]: v
            for (name, labels), v in parsed2["gauges"].items()
            if name == "replica_scrape_staleness_seconds"
        }
        assert up2 == {url: 0.0}
        assert stale2[url] >= 0.0  # it DID answer once; staleness ages
    finally:
        if router is not None:
            router.close()
        if gw is not None:
            gw.close(drain=False, timeout=5.0)


def test_router_fans_profile_out_to_a_replica(tmp_path):
    import http.client

    prof_mod.install(DeepProfiler(
        out_dir=os.path.join(str(tmp_path), "prof"), max_s=5.0,
        min_interval_s=0.0,
    ))
    gw = _gateway(tmp_path)
    gw.start()
    router = None

    def post(port, body):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        try:
            conn.request("POST", "/debugz/profile", json.dumps(body),
                         {"Content-Type": "application/json"})
            r = conn.getresponse()
            return r.status, json.loads(r.read())
        finally:
            conn.close()

    try:
        host, port = gw.address
        url = f"http://{host}:{port}"
        router = serve.build_router([url], poll_s=60.0)
        router.start()
        _, rport = router.address
        status, doc = post(rport, {"replica": "http://nowhere:1"})
        assert status == 404, doc
        assert doc["replicas"] == [url]
        status, doc = post(rport, {"duration_s": 0.2, "replica": url})
        assert status == 200, doc
        assert doc["replica"] == url and doc["path"]
        prof = prof_mod.profiler()
        assert prof.wait(30.0)
        assert os.path.isdir(doc["path"]) and os.listdir(doc["path"])
    finally:
        if router is not None:
            router.close()
        gw.close(drain=False, timeout=5.0)


# -- tools/bench_compare.py --------------------------------------------------


def _bench_compare():
    spec = importlib.util.spec_from_file_location(
        "bench_compare", os.path.join(REPO, "tools", "bench_compare.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write_round(d, n, parsed):
    path = os.path.join(d, f"BENCH_r{n:02d}.json")
    with open(path, "w") as f:
        json.dump({"n": n, "cmd": "bench", "rc": 0, "tail": "",
                   "parsed": parsed}, f)


def test_bench_compare_direction_awareness(tmp_path):
    bc = _bench_compare()
    prev = {"decode_tokens_per_s": 100.0, "ttft_ms": 50.0, "n_chips": 2.0}
    # Throughput UP and latency DOWN are improvements, never flagged.
    regs, _ = bc.compare(prev, {"decode_tokens_per_s": 150.0,
                                "ttft_ms": 30.0, "n_chips": 2.0}, 0.10)
    assert regs == []
    # Throughput down past the band IS a regression.
    regs, _ = bc.compare(prev, {"decode_tokens_per_s": 80.0,
                                "ttft_ms": 50.0, "n_chips": 2.0}, 0.10)
    assert [r["metric"] for r in regs] == ["decode_tokens_per_s"]
    # Latency UP past the band IS a regression.
    regs, _ = bc.compare(prev, {"decode_tokens_per_s": 100.0,
                                "ttft_ms": 70.0, "n_chips": 2.0}, 0.10)
    assert [r["metric"] for r in regs] == ["ttft_ms"]
    # Inside the band: noise, not a regression.
    regs, _ = bc.compare(prev, {"decode_tokens_per_s": 95.0,
                                "ttft_ms": 52.0, "n_chips": 2.0}, 0.10)
    assert regs == []
    # A config-key change is informational even when it halves.
    regs, rows = bc.compare(prev, {"decode_tokens_per_s": 100.0,
                                   "ttft_ms": 50.0, "n_chips": 1.0}, 0.10)
    assert regs == []
    assert {r["metric"]: r["status"] for r in rows}["n_chips"] == "info"


def test_bench_compare_gates_only_shared_keys(tmp_path):
    bc = _bench_compare()
    regs, rows = bc.compare({"old_phase": 10.0}, {"new_phase": 1.0}, 0.10)
    assert regs == [] and rows == []


def test_bench_compare_main_flags_regression(tmp_path):
    bc = _bench_compare()
    _write_round(str(tmp_path), 1, None)  # unparsed rounds are skipped
    _write_round(str(tmp_path), 2, {"decode_tokens_per_s": 100.0})
    _write_round(str(tmp_path), 3, {"decode_tokens_per_s": 50.0})
    assert bc.main(["--dir", str(tmp_path)]) == 1
    _write_round(str(tmp_path), 4, {"decode_tokens_per_s": 49.0})
    assert bc.main(["--dir", str(tmp_path)]) == 0  # r3 -> r4 is in-band


def test_bench_compare_neutral_without_two_parsed_rounds(tmp_path):
    bc = _bench_compare()
    _write_round(str(tmp_path), 1, None)
    _write_round(str(tmp_path), 2, {"x": 1.0})
    assert bc.main(["--dir", str(tmp_path)]) == 2


def test_bench_compare_self_test_catches_injection(tmp_path):
    bc = _bench_compare()
    # A genuinely improving pair: the injected regression must still be
    # flagged (it degrades relative to PREV, not to the improved cur).
    _write_round(str(tmp_path), 1, {"decode_tokens_per_s": 100.0,
                                    "ttft_ms": 50.0})
    _write_round(str(tmp_path), 2, {"decode_tokens_per_s": 130.0,
                                    "ttft_ms": 40.0})
    assert bc.main(["--dir", str(tmp_path), "--self-test"]) == 0


def test_bench_compare_self_test_on_the_real_trajectory():
    bc = _bench_compare()
    rounds = bc.load_rounds(REPO)
    if bc.latest_pair(rounds) is None:
        pytest.skip("repo has fewer than two parsed BENCH rounds")
    assert bc.main(["--dir", REPO, "--self-test"]) == 0
