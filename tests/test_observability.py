"""Observability extensions: throughput stats, MFU surface, --trace flag.

SURVEY.md §5 (metrics row): parity is the progress UI + summary + warnings;
the TPU build additionally owes real tokens/sec + MFU per model and
jax.profiler traces per phase. No reference analog (its only signal is the
chars/4 estimate, ui.go:142, and `--trace` was proposed-only,
docs/proposed-features.md:262-268).
"""

import io
import os

import pytest

from llm_consensus_tpu.providers.base import Response
from llm_consensus_tpu.ui import print_throughput


def test_response_stats_serialize_only_when_set():
    bare = Response(model="m", content="c", provider="p", latency_ms=1.0)
    assert set(bare.to_dict()) == {"model", "content", "provider", "latency_ms"}
    full = Response(
        model="m", content="c", provider="p", latency_ms=1.0,
        tokens=64, tokens_per_sec=123.456, mfu=0.4321,
    )
    d = full.to_dict()
    assert d["tokens"] == 64
    assert d["tokens_per_sec"] == 123.46
    assert d["mfu"] == 0.4321


def test_print_throughput_skips_statless_responses():
    buf = io.StringIO()
    print_throughput(buf, [Response(model="m", content="c", provider="p")])
    assert buf.getvalue() == ""
    buf = io.StringIO()
    print_throughput(buf, [
        Response(model="a", content="c", provider="p"),
        Response(model="b", content="c", provider="p",
                 tokens=32, tokens_per_sec=50.0, mfu=0.25),
    ])
    out = buf.getvalue()
    assert "b: 32 tokens, 50.0 tok/s, 25.0% MFU" in out
    assert "a:" not in out


def test_engine_reports_steady_state_decode_rate():
    from llm_consensus_tpu.engine import Engine, SamplingParams
    from llm_consensus_tpu.models import get_config

    engine = Engine(get_config("tiny-llama"), stream_interval=4)
    result = engine.generate(
        "measure me", SamplingParams(max_new_tokens=20, ignore_eos=True)
    )
    # 20 tokens at interval 4 crosses several fetch boundaries.
    assert result.decode_tokens > 0
    assert result.decode_s > 0


def test_cancel_during_final_drain_keeps_complete_result():
    """A deadline/cancel landing while the last tokens drain must not mark
    an already-complete generation as failed."""
    from llm_consensus_tpu.engine import Engine, SamplingParams
    from llm_consensus_tpu.models import get_config
    from llm_consensus_tpu.utils.context import Context

    engine = Engine(get_config("tiny-llama"), stream_interval=4)
    ctx = Context.background().with_cancel()
    seen = 0

    def on_token(_tok):
        nonlocal seen
        seen += 1
        if seen == 8:
            ctx.cancel()

    result = engine.generate_ids(
        [1, 2, 3], SamplingParams(max_new_tokens=8, ignore_eos=True),
        ctx, on_token,
    )
    assert len(result.token_ids) == 8
    assert result.finish_reason == "length"
    ctx.close()


def test_tpu_provider_attaches_stats():
    from llm_consensus_tpu.providers.tpu import TPUProvider
    from llm_consensus_tpu.providers.base import Request
    from llm_consensus_tpu.utils.context import Context

    provider = TPUProvider(ignore_eos=True, stream_interval=4)
    resp = provider.query(
        Context.background(),
        Request(model="tpu:tiny-llama", prompt="hi", max_tokens=20),
    )
    assert resp.tokens == 20
    assert resp.tokens_per_sec and resp.tokens_per_sec > 0
    # CPU backend has no known peak — MFU stays None rather than lying.
    assert resp.mfu is None


def test_cli_trace_flag_writes_profile(tmp_path):
    from llm_consensus_tpu.cli.main import Config, run
    from llm_consensus_tpu.providers.base import ProviderFunc
    from llm_consensus_tpu.utils.context import Context

    def fake(ctx, req):
        import jax.numpy as jnp

        (jnp.ones((8, 8)) @ jnp.ones((8, 8))).block_until_ready()
        return Response(model=req.model, content="ans", provider="fake")

    trace_dir = str(tmp_path / "trace")
    cfg = Config(
        models=["a"], judge="a", prompt="p", no_save=True, quiet=True,
        trace=trace_dir,
    )
    run(
        cfg, Context.background(),
        factory=lambda model: ProviderFunc(fake),
        stdout=io.StringIO(), stderr=io.StringIO(),
    )
    found = [
        os.path.join(root, f)
        for root, _, files in os.walk(trace_dir)
        for f in files
    ]
    assert found, "trace directory is empty"


# -- run telemetry (obs/): CLI persistence + aggregate footer ----------------


@pytest.fixture
def _clean_telemetry():
    from llm_consensus_tpu import faults, obs

    obs.reset()
    faults.reset()
    # The live/attrib planes are process-wide and default-on: a prior
    # test's tpu-engine observations would otherwise make the CLI's
    # no-events branch persist a live summary here (fresh-process runs
    # see an empty plane, which is what these tests model).
    obs.live.install(obs.live.LiveMetrics(window_s=60.0))
    obs.attrib.install(None)
    yield
    obs.reset()
    faults.reset()
    obs.live.reset()
    obs.attrib.reset()


def _fake_factory(model):
    from llm_consensus_tpu.providers.base import ProviderFunc

    return ProviderFunc(lambda ctx, req: Response(
        model=req.model, content="ans", provider="fake"
    ))


def test_print_aggregate_statless_prints_nothing():
    from llm_consensus_tpu.ui import print_aggregate

    for agg in (None, {}, {"tokens": 0.0, "tokens_per_sec": 0.0}):
        buf = io.StringIO()
        print_aggregate(buf, agg)
        assert buf.getvalue() == ""


def test_print_aggregate_pool_line():
    from llm_consensus_tpu.ui import print_aggregate

    buf = io.StringIO()
    print_aggregate(buf, {
        "tokens": 200.0, "tokens_per_sec": 50.0, "mfu": 0.25,
    })
    out = buf.getvalue()
    assert "Pool: 200 tokens, 50.0 tok/s, 25.0% MFU" in out


def test_cli_events_flag_persists_trace_and_metrics(tmp_path, _clean_telemetry):
    """--events records the run and persists trace.json + metrics.json
    into the auto-saved run dir next to result.json."""
    import json

    from llm_consensus_tpu.cli.main import Config, run
    from llm_consensus_tpu.obs.export import load_trace, trace_span_names
    from llm_consensus_tpu.utils.context import Context

    cfg = Config(
        models=["a", "b"], judge="a", prompt="p", quiet=True,
        data_dir=str(tmp_path), events=True,
    )
    run(
        cfg, Context.background(), factory=_fake_factory,
        stdout=io.StringIO(), stderr=io.StringIO(),
    )
    (run_dir,) = [p for p in tmp_path.iterdir() if p.is_dir()]
    files = {p.name for p in run_dir.iterdir()}
    assert {"result.json", "trace.json", "metrics.json"} <= files
    doc = load_trace(str(run_dir / "trace.json"))
    # The fake providers never touch a device, but the runner's worker
    # spans must be on the timeline.
    assert "worker" in trace_span_names(doc)
    metrics = json.loads((run_dir / "metrics.json").read_text())
    assert metrics["events"]["recorded"] >= 2
    assert [m["model"] for m in metrics["models"]] == ["a", "b"]


def test_cli_events_without_run_dir_warns(_clean_telemetry):
    """--events with --json (or --output/--no-save) has no run dir to
    persist into: the run says so instead of discarding telemetry
    silently."""
    import json

    from llm_consensus_tpu.cli.main import Config, run
    from llm_consensus_tpu.utils.context import Context

    stdout = io.StringIO()
    cfg = Config(
        models=["a"], judge="a", prompt="p", quiet=True, json=True,
        events=True,
    )
    run(
        cfg, Context.background(), factory=_fake_factory,
        stdout=stdout, stderr=io.StringIO(),
    )
    data = json.loads(stdout.getvalue())
    assert any("not persisted" in w for w in data.get("warnings", []))


def test_cli_events_install_is_flag_scoped(tmp_path, _clean_telemetry):
    """A --events run must not leak its recorder into a later run in the
    same process that didn't ask for telemetry."""
    from llm_consensus_tpu.cli.main import Config, run
    from llm_consensus_tpu.utils.context import Context

    d1, d2 = tmp_path / "one", tmp_path / "two"
    for data_dir, events in ((d1, True), (d2, False)):
        cfg = Config(
            models=["a"], judge="a", prompt="p", quiet=True,
            data_dir=str(data_dir), events=events,
        )
        run(
            cfg, Context.background(), factory=_fake_factory,
            stdout=io.StringIO(), stderr=io.StringIO(),
        )
    (rd1,) = [p for p in d1.iterdir() if p.is_dir()]
    (rd2,) = [p for p in d2.iterdir() if p.is_dir()]
    assert (rd1 / "trace.json").exists()
    assert not (rd2 / "trace.json").exists()


def test_cli_no_events_writes_no_telemetry(tmp_path, _clean_telemetry):
    from llm_consensus_tpu.cli.main import Config, run
    from llm_consensus_tpu.utils.context import Context

    cfg = Config(
        models=["a"], judge="a", prompt="p", quiet=True,
        data_dir=str(tmp_path),
    )
    run(
        cfg, Context.background(), factory=_fake_factory,
        stdout=io.StringIO(), stderr=io.StringIO(),
    )
    (run_dir,) = [p for p in tmp_path.iterdir() if p.is_dir()]
    files = {p.name for p in run_dir.iterdir()}
    assert "trace.json" not in files and "metrics.json" not in files


@pytest.mark.faults
def test_cli_persists_fault_trace_on_chaos_runs(tmp_path, _clean_telemetry):
    """A run driven by a fault plan archives the exact injected sequence
    as faults.txt next to its results — no events flag required."""
    from llm_consensus_tpu import faults
    from llm_consensus_tpu.cli.main import Config, run
    from llm_consensus_tpu.utils.context import Context

    faults.install(faults.FaultPlan("sse_reset@chunk=999", seed=3))
    cfg = Config(
        models=["a"], judge="a", prompt="p", quiet=True,
        data_dir=str(tmp_path),
    )
    run(
        cfg, Context.background(), factory=_fake_factory,
        stdout=io.StringIO(), stderr=io.StringIO(),
    )
    (run_dir,) = [p for p in tmp_path.iterdir() if p.is_dir()]
    assert (run_dir / "faults.txt").read_bytes() == (
        faults.plan().trace_bytes()
    )
