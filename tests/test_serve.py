"""Serving gateway tests over fake (non-TPU) providers.

Covers the serve/ subsystem end-to-end through real HTTP — concurrent
load, duplicate-prompt coalescing (N requests ⇒ 1 provider call per
panel model), cache TTL expiry, queue-full backpressure status codes,
graceful-drain ordering, SSE streaming, and the serve-side telemetry
(queue_wait/admit spans + cache_hit/coalesced instants in the persisted
Chrome trace of a *served* run).
"""

from __future__ import annotations

import http.client
import json
import os
import threading
import time

import pytest

from llm_consensus_tpu import obs
from llm_consensus_tpu import serve
from llm_consensus_tpu.providers.base import Provider, Request, Response
from llm_consensus_tpu.providers.registry import Registry
from llm_consensus_tpu.runner import Callbacks, Runner
from llm_consensus_tpu.utils.context import Context

PANEL = ["alpha", "beta"]
JUDGE = "gamma"


class FakeProvider(Provider):
    """Counting provider; optionally blocks panel queries on an event."""

    def __init__(self, gate: "threading.Event | None" = None):
        self._lock = threading.Lock()
        self.calls: list[tuple[str, str]] = []  # (model, prompt)
        self._gate = gate

    def query(self, ctx: Context, req: Request) -> Response:
        with self._lock:
            self.calls.append((req.model, req.prompt))
        if self._gate is not None and req.model in PANEL:
            assert self._gate.wait(30.0), "test gate never released"
        ctx.raise_if_done()
        return Response(
            model=req.model,
            content=f"{req.model} says: {req.prompt[:24]}",
            provider="fake",
        )

    def query_stream(self, ctx, req, callback):
        resp = self.query(ctx, req)
        if callback is not None:
            callback(resp.content)
        return resp

    def panel_calls(self) -> list[tuple[str, str]]:
        with self._lock:
            return [c for c in self.calls if c[0] in PANEL]


def make_gateway(tmp_path, provider, **kw):
    registry = Registry()
    for m in PANEL + [JUDGE]:
        registry.register(m, provider)
    kw.setdefault("timeout", 30.0)
    kw.setdefault("max_concurrency", 4)
    gw = serve.build_gateway(
        registry, list(PANEL), JUDGE,
        data_dir=os.path.join(str(tmp_path), "data"), **kw,
    )
    gw.start()
    return gw


def post(port: int, body: dict, path: str = "/v1/consensus"):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    try:
        conn.request(
            "POST", path, json.dumps(body),
            {"Content-Type": "application/json"},
        )
        r = conn.getresponse()
        headers = dict(r.getheaders())
        data = r.read()
    finally:
        conn.close()
    return r.status, headers, data


def get(port: int, path: str):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        data = r.read()
    finally:
        conn.close()
    return r.status, json.loads(data)


def wait_for(predicate, timeout=10.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {what}")


# ---------------------------------------------------------------------------
# basic shapes


def test_json_consensus_roundtrip(tmp_path):
    provider = FakeProvider()
    gw = make_gateway(tmp_path, provider)
    try:
        _, port = gw.address
        status, _, data = post(port, {"prompt": "what is up?"})
        assert status == 200, data
        doc = json.loads(data)
        assert doc["consensus"]
        assert doc["judge"] == JUDGE
        assert [r["model"] for r in doc["responses"]] == PANEL
        assert doc["cached"] is False and doc["coalesced"] is False
        # The run persisted into its own data/<run-id>/.
        run_dir = os.path.join(str(tmp_path), "data", doc["run_id"])
        with open(os.path.join(run_dir, "result.json")) as f:
            saved = json.load(f)
        assert saved["consensus"] == doc["consensus"]
        # 2 panel + 1 judge queries.
        assert len(provider.calls) == 3
    finally:
        gw.close(timeout=5.0)


def test_healthz_and_statsz(tmp_path):
    gw = make_gateway(tmp_path, FakeProvider())
    try:
        _, port = gw.address
        status, doc = get(port, "/healthz")
        assert status == 200 and doc == {
            "status": "ok", "draining": False,
            "lifecycle": "serving", "placeable": True,
        }
        status, doc = get(port, "/statsz")
        assert status == 200
        assert doc["admission"]["max_concurrency"] == 4
        assert doc["cache"]["capacity"] == 256
        assert doc["runs_executed"] == 0
    finally:
        gw.close(timeout=5.0)


def test_bad_requests(tmp_path):
    gw = make_gateway(tmp_path, FakeProvider())
    try:
        _, port = gw.address
        status, _, data = post(port, {"prompt": ""})
        assert status == 400 and b"prompt" in data
        status, _, data = post(port, {"prompt": "x", "models": ["nope"]})
        assert status == 400 and b"unknown model" in data
        status, _, data = post(port, {"prompt": "x", "timeout": -1})
        assert status == 400
        status, _, data = post(port, {"prompt": "x"}, path="/v2/nope")
        assert status == 404
    finally:
        gw.close(timeout=5.0)


# ---------------------------------------------------------------------------
# concurrency, coalescing, cache


def test_concurrent_load_distinct_prompts(tmp_path):
    provider = FakeProvider()
    gw = make_gateway(tmp_path, provider, max_concurrency=3, max_queue=16)
    try:
        _, port = gw.address
        n = 6
        results = [None] * n

        def fire(i):
            results[i] = post(port, {"prompt": f"question #{i}"})

        threads = [threading.Thread(target=fire, args=(i,)) for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        docs = []
        for status, _, data in results:
            assert status == 200, data
            docs.append(json.loads(data))
        run_ids = {d["run_id"] for d in docs}
        assert len(run_ids) == n  # collision-free under concurrency
        assert gw.scheduler.runs_executed == n
        assert len(provider.panel_calls()) == n * len(PANEL)
    finally:
        gw.close(timeout=5.0)


def test_duplicate_burst_coalesces_to_one_run(tmp_path):
    gate = threading.Event()
    provider = FakeProvider(gate=gate)
    gw = make_gateway(tmp_path, provider)
    try:
        _, port = gw.address
        m = 4
        results = [None] * m

        def fire(i):
            results[i] = post(port, {"prompt": "identical question"})

        threads = [threading.Thread(target=fire, args=(i,)) for i in range(m)]
        for t in threads:
            t.start()
        # The leader is blocked inside the panel (gate held); wait until
        # every other request has joined its flight as a follower, then
        # release — deterministic: all m-1 coalesce.
        wait_for(
            lambda: gw._flights.followers() == m - 1,
            what="followers to join the flight",
        )
        gate.set()
        for t in threads:
            t.join()

        docs = [json.loads(data) for status, _, data in results]
        assert all(status == 200 for status, _, _ in results)
        # Exactly ONE panel+judge execution...
        assert gw.scheduler.runs_executed == 1
        assert len(provider.panel_calls()) == len(PANEL)
        # ...M streamed responses with the same consensus...
        assert len({d["consensus"] for d in docs}) == 1
        # ...and M distinct, non-colliding run ids, each persisted.
        run_ids = {d["run_id"] for d in docs}
        assert len(run_ids) == m
        for rid in run_ids:
            assert os.path.exists(
                os.path.join(str(tmp_path), "data", rid, "result.json")
            )
        assert sum(1 for d in docs if d["coalesced"]) == m - 1
    finally:
        gw.close(timeout=5.0)


def test_cache_hit_and_ttl_expiry(tmp_path):
    clock = [0.0]
    provider = FakeProvider()
    gw = make_gateway(
        tmp_path, provider, cache_ttl_s=10.0, clock=lambda: clock[0]
    )
    try:
        _, port = gw.address
        body = {"prompt": "cache me"}
        status, _, data = post(port, body)
        assert status == 200 and json.loads(data)["cached"] is False
        first_id = json.loads(data)["run_id"]

        status, _, data = post(port, body)
        doc = json.loads(data)
        assert status == 200 and doc["cached"] is True
        assert doc["run_id"] != first_id  # a hit still gets its own run id
        assert gw.scheduler.runs_executed == 1

        # Different sampling/system = different key = a real run.
        status, _, data = post(port, dict(body, max_tokens=7))
        assert json.loads(data)["cached"] is False
        assert gw.scheduler.runs_executed == 2

        clock[0] = 11.0  # past the TTL: the entry is dead
        status, _, data = post(port, body)
        assert json.loads(data)["cached"] is False
        assert gw.scheduler.runs_executed == 3
        assert gw.cache.stats()["expirations"] == 1
    finally:
        gw.close(timeout=5.0)


# ---------------------------------------------------------------------------
# backpressure + drain


def test_queue_full_backpressure(tmp_path):
    gate = threading.Event()
    provider = FakeProvider(gate=gate)
    gw = make_gateway(tmp_path, provider, max_concurrency=1, max_queue=0)
    try:
        _, port = gw.address
        leader = [None]

        def fire():
            leader[0] = post(port, {"prompt": "slow one"})

        t = threading.Thread(target=fire)
        t.start()
        wait_for(
            lambda: gw.admission.snapshot()["active"] == 1,
            what="leader to occupy the slot",
        )
        # A DIFFERENT prompt (no coalescing) with the slot held and zero
        # queue depth: shed immediately with Retry-After.
        status, headers, data = post(port, {"prompt": "overflow"})
        assert status == 429, data
        assert "Retry-After" in headers
        assert float(headers["Retry-After"]) >= 1
        gate.set()
        t.join()
        assert leader[0][0] == 200
        assert gw.admission.snapshot()["rejected"] == 1
    finally:
        gw.close(timeout=5.0)


def test_graceful_drain_ordering(tmp_path):
    gate = threading.Event()
    provider = FakeProvider(gate=gate)
    gw = make_gateway(tmp_path, provider, max_concurrency=2)
    _, port = gw.address
    inflight = [None]

    def fire():
        inflight[0] = post(port, {"prompt": "riding out the drain"})

    t = threading.Thread(target=fire)
    t.start()
    wait_for(
        lambda: gw.admission.snapshot()["active"] == 1,
        what="request to go in-flight",
    )
    gw.admission.begin_drain()
    # New work is rejected the moment the drain begins...
    status, headers, data = post(port, {"prompt": "too late"})
    assert status == 503, data
    assert "Retry-After" in headers
    # ...health flips so balancers pull the replica...
    status, doc = get(port, "/healthz")
    assert status == 503 and doc["draining"] is True
    # ...while the in-flight run is untouched. Release it and complete
    # the drain: close() returns only after the run finished + flushed.
    threading.Timer(0.1, gate.set).start()
    assert gw.close(drain=True, timeout=10.0) is True
    t.join()
    status, _, data = inflight[0]
    assert status == 200
    doc = json.loads(data)
    assert os.path.exists(
        os.path.join(str(tmp_path), "data", doc["run_id"], "result.json")
    )
    # The server is actually gone.
    with pytest.raises(OSError):
        post(port, {"prompt": "anyone home?"})


def test_follower_of_shed_leader_gets_retryable_status(tmp_path):
    gate = threading.Event()
    provider = FakeProvider(gate=gate)
    gw = make_gateway(tmp_path, provider, max_concurrency=1, max_queue=1)
    try:
        _, port = gw.address
        blocker = [None]
        t0 = threading.Thread(
            target=lambda: blocker.__setitem__(
                0, post(port, {"prompt": "slot holder"})
            )
        )
        t0.start()
        wait_for(
            lambda: gw.admission.snapshot()["active"] == 1,
            what="slot holder to go in-flight",
        )
        # Two identical requests: one leads (queued for the slot), one
        # follows its flight.
        dupes = [None, None]
        threads = [
            threading.Thread(
                target=lambda i=i: dupes.__setitem__(
                    i, post(port, {"prompt": "duplicate pair"})
                )
            )
            for i in range(2)
        ]
        for t in threads:
            t.start()
        wait_for(
            lambda: gw.admission.snapshot()["waiting"] == 1
            and gw._flights.followers() == 1,
            what="leader queued + follower joined",
        )
        # Drain begins: the queued leader is shed with 503 — and so is
        # its follower, with the SAME retryable shape (not a 500).
        gw.admission.begin_drain()
        for t in threads:
            t.join()
        for status, headers, data in dupes:
            assert status == 503, (status, data)
            assert "Retry-After" in headers
        gate.set()
        t0.join()
        assert blocker[0][0] == 200
    finally:
        gw.close(timeout=10.0)


# ---------------------------------------------------------------------------
# SSE streaming


def parse_sse(data: bytes) -> list[tuple[str, dict]]:
    events = []
    for frame in data.decode("utf-8").split("\n\n"):
        if not frame.strip():
            continue
        name, doc = None, None
        for line in frame.splitlines():
            if line.startswith("event: "):
                name = line[len("event: "):]
            elif line.startswith("data: "):
                doc = json.loads(line[len("data: "):])
        events.append((name, doc))
    return events


def test_sse_stream_mirrors_run(tmp_path):
    provider = FakeProvider()
    gw = make_gateway(tmp_path, provider)
    try:
        _, port = gw.address
        status, headers, data = post(
            port, {"prompt": "stream it", "stream": True}
        )
        assert status == 200
        assert headers["Content-Type"] == "text/event-stream"
        events = parse_sse(data)
        chunks = [d for n, d in events if n == "chunk"]
        assert {c["model"] for c in chunks if c["kind"] == "model_chunk"} \
            == set(PANEL)
        assert [c["model"] for c in chunks if c["kind"] == "judge_chunk"] \
            == [JUDGE]
        done = [d for n, d in events if n == "done"]
        assert len(done) == 1 and done[0]["consensus"]
        assert done[0]["run_id"]
    finally:
        gw.close(timeout=5.0)


def test_sse_cached_replay(tmp_path):
    provider = FakeProvider()
    gw = make_gateway(tmp_path, provider)
    try:
        _, port = gw.address
        post(port, {"prompt": "replay me"})
        status, _, data = post(port, {"prompt": "replay me", "stream": True})
        assert status == 200
        events = parse_sse(data)
        done = [d for n, d in events if n == "done"]
        assert done[0]["cached"] is True
        # The replay carries the full response set as chunks.
        chunks = [d for n, d in events if n == "chunk"]
        assert {c["model"] for c in chunks if c["kind"] == "model_chunk"} \
            == set(PANEL)
        assert gw.scheduler.runs_executed == 1
    finally:
        gw.close(timeout=5.0)


# ---------------------------------------------------------------------------
# serve-side telemetry: spans + instants land in the persisted trace


def test_served_run_records_serve_spans(tmp_path):
    recorder = obs.Recorder()
    obs.install(recorder)
    try:
        provider = FakeProvider()
        gw = make_gateway(tmp_path, provider)
        try:
            _, port = gw.address
            # Run 1 executes; its repeat is a cache hit (instant recorded);
            # run 2 executes and persists a trace that carries everything
            # so far — executed runs are the only ones that snapshot the
            # (process-scoped) recorder into their run dir.
            post(port, {"prompt": "observe me"})
            status, _, data = post(port, {"prompt": "observe me"})
            hit_doc = json.loads(data)
            assert hit_doc["cached"] is True
            status, _, data = post(port, {"prompt": "something else"})
            run2 = json.loads(data)["run_id"]

            # A cache hit persists its result but no telemetry snapshot.
            hit_dir = os.path.join(str(tmp_path), "data", hit_doc["run_id"])
            assert os.path.exists(os.path.join(hit_dir, "result.json"))
            assert not os.path.exists(os.path.join(hit_dir, "trace.json"))

            from llm_consensus_tpu.obs import export as obs_export

            trace_path = os.path.join(
                str(tmp_path), "data", run2, "trace.json"
            )
            doc = obs_export.load_trace(trace_path)
            spans = obs_export.trace_span_names(doc)
            assert {"queue_wait", "admit"} <= spans, spans
            instants = {
                e["name"] for e in doc["traceEvents"] if e.get("ph") == "i"
            }
            assert "cache_hit" in instants, instants
            with open(os.path.join(
                str(tmp_path), "data", run2, "metrics.json"
            )) as f:
                metrics = json.load(f)
            assert metrics["counters"]["serve.cache_hit"] == 1
            assert metrics["counters"]["serve.admitted"] == 2
            assert metrics["counters"]["serve.runs"] == 2
        finally:
            gw.close(timeout=5.0)
    finally:
        obs.reset()


def test_coalesced_instant_recorded(tmp_path):
    recorder = obs.Recorder()
    obs.install(recorder)
    try:
        gate = threading.Event()
        provider = FakeProvider(gate=gate)
        gw = make_gateway(tmp_path, provider)
        try:
            _, port = gw.address
            results = [None, None]

            def fire(i):
                results[i] = post(port, {"prompt": "twins"})

            threads = [
                threading.Thread(target=fire, args=(i,)) for i in range(2)
            ]
            for t in threads:
                t.start()
            wait_for(
                lambda: gw._flights.followers() == 1,
                what="the follower to join",
            )
            gate.set()
            for t in threads:
                t.join()
            assert all(r[0] == 200 for r in results)
            assert recorder.counters()["serve.coalesced"] == 1
            names = {e.name for e in recorder.events() if e.ph == "i"}
            assert "coalesced" in names
        finally:
            gw.close(timeout=5.0)
    finally:
        obs.reset()


# ---------------------------------------------------------------------------
# fault injection at the serve site


@pytest.mark.faults
def test_injected_queue_full_rejects(tmp_path):
    from llm_consensus_tpu import faults

    faults.install(faults.FaultPlan("queue_full", seed=3))
    try:
        provider = FakeProvider()
        gw = make_gateway(tmp_path, provider)  # admission binds the plan
        try:
            _, port = gw.address
            status, headers, data = post(port, {"prompt": "shed me"})
            assert status == 429, data
            assert "Retry-After" in headers
            # The plan fires once (times=1): the retry is served.
            status, _, data = post(port, {"prompt": "shed me"})
            assert status == 200, data
        finally:
            gw.close(timeout=5.0)
    finally:
        faults.reset()


@pytest.mark.faults
def test_injected_slow_admit_delays_grant(tmp_path):
    from llm_consensus_tpu import faults
    from llm_consensus_tpu.serve.admission import AdmissionController

    faults.install(faults.FaultPlan("slow_admit@s=0.2", seed=3))
    try:
        admission = AdmissionController(max_concurrency=1)
        t0 = time.monotonic()
        ticket = admission.admit()
        elapsed = time.monotonic() - t0
        ticket.release()
        assert elapsed >= 0.2
    finally:
        faults.reset()


@pytest.mark.faults
def test_injected_disconnect_stops_stream_not_run(tmp_path):
    from llm_consensus_tpu import faults

    # First stream-phase fire becomes a client disconnect: the SSE body
    # ends early (no done event) but the run completes and is cached.
    # (@phase=stream: the serve site's counter is shared with admit
    # fires, so the matcher keys on the phase attribute, not the count.)
    faults.install(faults.FaultPlan("disconnect@phase=stream", seed=3))
    try:
        provider = FakeProvider()
        gw = make_gateway(tmp_path, provider)
        try:
            _, port = gw.address
            status, _, data = post(
                port, {"prompt": "vanishing client", "stream": True}
            )
            assert status == 200
            events = parse_sse(data)
            assert not [d for n, d in events if n == "done"]
            assert gw.scheduler.runs_executed == 1
            # The finished run is served from cache to the next client.
            status, _, data = post(port, {"prompt": "vanishing client"})
            assert status == 200 and json.loads(data)["cached"] is True
        finally:
            gw.close(timeout=5.0)
    finally:
        faults.reset()


# ---------------------------------------------------------------------------
# shared-runner callback isolation (the serve/scheduler contract)


def test_runner_per_run_callbacks_do_not_cross_talk():
    from llm_consensus_tpu.providers.base import ProviderFunc

    registry = Registry()
    registry.register("m", ProviderFunc(lambda ctx, req: Response(
        model=req.model, content=req.prompt, provider="fake",
    )))
    runner = Runner(registry, timeout=10.0)
    seen: dict[str, list[str]] = {"a": [], "b": []}
    barrier = threading.Barrier(2, timeout=10.0)
    out: dict[str, object] = {}

    def go(tag: str) -> None:
        barrier.wait()
        cbs = Callbacks(
            on_model_stream=lambda m, c, _tag=tag: seen[_tag].append(c)
        )
        out[tag] = runner.run(
            Context.background(), ["m"], f"prompt-{tag}", callbacks=cbs
        )

    threads = [threading.Thread(target=go, args=(t,)) for t in ("a", "b")]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert seen["a"] == ["prompt-a"]
    assert seen["b"] == ["prompt-b"]


# ---------------------------------------------------------------------------
# recovery surfacing (PR 5): Retry-After jitter + engine liveness


def test_retry_after_jitter_bounds_and_spread():
    from llm_consensus_tpu.serve.admission import AdmissionController

    ctl = AdmissionController(1, retry_after_s=2.0)
    draws = [ctl.retry_after() for _ in range(64)]
    assert all(2.0 <= d < 4.0 for d in draws), draws
    # Uniform jitter must actually spread a shed wave — identical values
    # would re-synchronize every client's retry.
    assert len({round(d, 6) for d in draws}) > 8


def test_shed_responses_carry_jittered_retry_after(tmp_path):
    gate = threading.Event()
    provider = FakeProvider(gate=gate)
    gw = make_gateway(tmp_path, provider, max_concurrency=1, max_queue=0)
    try:
        _, port = gw.address
        leader = [None]

        def fire():
            leader[0] = post(port, {"prompt": "jitter leader"})

        t = threading.Thread(target=fire)
        t.start()
        wait_for(
            lambda: gw.admission.snapshot()["active"] == 1,
            what="leader to occupy the slot",
        )
        bodies = [
            json.loads(post(port, {"prompt": f"overflow {i}"})[2])
            for i in range(8)
        ]
        assert all(1.0 <= b["retry_after_s"] < 2.0 for b in bodies), bodies
        assert len({b["retry_after_s"] for b in bodies}) > 1, (
            "every shed client got the identical retry instant"
        )
        gate.set()
        t.join()
        assert leader[0][0] == 200
    finally:
        gw.close(timeout=5.0)


class RecoveryStubProvider(FakeProvider):
    """FakeProvider that reports engine liveness like TPUProvider."""

    def recovery_stats(self):
        return {
            "state": "recovering",
            "restarts": 2,
            "replayed_streams": 3,
            "journal_depth": 1,
            "heartbeats": {"tiny-llama": {"age_s": 0.5, "busy": True}},
            "decode_heartbeat_age_s": 0.5,
        }


def test_healthz_and_statsz_report_recovery(tmp_path):
    gw = make_gateway(tmp_path, RecoveryStubProvider())
    try:
        _, port = gw.address
        status, doc = get(port, "/healthz")
        # Recovering is still 200: the gateway keeps serving (streams
        # replay onto the rebuilt pool); only drain pulls the replica.
        assert status == 200
        assert doc["status"] == "recovering"
        assert doc["engines"]["state"] == "recovering"
        assert doc["engines"]["decode_heartbeat_age_s"] == 0.5
        assert doc["engines"]["heartbeats"]["tiny-llama"]["busy"] is True
        status, doc = get(port, "/statsz")
        assert status == 200
        assert doc["recovery"] == {
            "state": "recovering", "restarts": 2,
            "replayed_streams": 3, "journal_depth": 1,
        }
    finally:
        gw.close(timeout=5.0)


def test_healthz_shape_unchanged_without_recovery_providers(tmp_path):
    gw = make_gateway(tmp_path, FakeProvider())
    try:
        _, port = gw.address
        status, doc = get(port, "/healthz")
        assert status == 200 and doc == {
            "status": "ok", "draining": False,
            "lifecycle": "serving", "placeable": True,
        }
        status, doc = get(port, "/statsz")
        assert "recovery" not in doc
    finally:
        gw.close(timeout=5.0)


# ---------------------------------------------------------------------------
# load_score (the router tier's placement signal)


def test_statsz_load_score_idle_and_under_load(tmp_path):
    gate = threading.Event()
    gw = make_gateway(tmp_path, FakeProvider(gate=gate), max_concurrency=2)
    try:
        _, port = gw.address
        _, doc = get(port, "/statsz")
        assert doc["load_score"] == 0.0  # idle replica
        inflight = [None]

        def fire():
            inflight[0] = post(port, {"prompt": "load probe"})

        t = threading.Thread(target=fire)
        t.start()
        wait_for(
            lambda: gw.admission.snapshot()["active"] == 1, what="admission"
        )
        _, doc = get(port, "/statsz")
        assert 0.0 < doc["load_score"] <= 1.0  # one of two slots held
        gate.set()
        t.join()
        assert inflight[0][0] == 200
    finally:
        gate.set()
        gw.close(timeout=5.0)


def test_recovering_engines_raise_load_score(tmp_path):
    gw = make_gateway(tmp_path, RecoveryStubProvider())
    try:
        _, port = gw.address
        _, doc = get(port, "/statsz")
        # Idle slots, but the recovering engine component reads loaded.
        assert doc["load_score"] > 0.0
    finally:
        gw.close(timeout=5.0)


# ---------------------------------------------------------------------------
# queued-client disconnect (dropped at dequeue, followers honored)


def abandoned_post(port: int, body: dict) -> None:
    """Send a full request, then hang up before reading the response."""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request(
        "POST", "/v1/consensus", json.dumps(body),
        {"Content-Type": "application/json"},
    )
    conn.close()


def test_queued_client_disconnect_dropped_at_dequeue(tmp_path):
    gate = threading.Event()
    provider = FakeProvider(gate=gate)
    gw = make_gateway(tmp_path, provider, max_concurrency=1, max_queue=4)
    try:
        _, port = gw.address
        leader = [None]

        def lead():
            leader[0] = post(port, {"prompt": "slot holder"})

        t = threading.Thread(target=lead)
        t.start()
        wait_for(
            lambda: gw.admission.snapshot()["active"] == 1, what="leader slot"
        )
        # A second, DIFFERENT request queues... and its client hangs up.
        # The probe sees the dead socket while the request waits, so the
        # drop lands without ever granting it a slot.
        abandoned_post(port, {"prompt": "abandoned while queued"})
        wait_for(
            lambda: gw.admission.snapshot()["dropped_disconnected"] == 1,
            what="disconnect drop",
        )
        gate.set()
        t.join(timeout=30)
        assert leader[0][0] == 200
        assert ("alpha", "abandoned while queued") not in provider.calls
        assert gw.scheduler.runs_executed == 1
        # Slot accounting survived the drop: the next request serves.
        status, _, _data = post(port, {"prompt": "after the drop"})
        assert status == 200
    finally:
        gate.set()
        gw.close(timeout=5.0)


def test_queued_leader_with_followers_still_runs(tmp_path):
    gate = threading.Event()
    provider = FakeProvider(gate=gate)
    gw = make_gateway(
        tmp_path, provider, max_concurrency=1, max_queue=4, cache_size=0
    )
    try:
        _, port = gw.address
        blocker = [None]

        def block():
            blocker[0] = post(port, {"prompt": "blocker"})

        tb = threading.Thread(target=block)
        tb.start()
        wait_for(
            lambda: gw.admission.snapshot()["active"] == 1, what="blocker slot"
        )
        # The coalesced leader queues behind the blocker with its socket
        # still open (a closed-at-once socket could be dropped before
        # the follower arrives); only after the follower has joined its
        # flight does the leader's client hang up.
        leader_conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        leader_conn.request(
            "POST", "/v1/consensus",
            json.dumps({"prompt": "shared question"}),
            {"Content-Type": "application/json"},
        )
        wait_for(
            lambda: gw.admission.snapshot()["waiting"] == 1, what="leader queued"
        )
        follower = [None]

        def follow():
            follower[0] = post(port, {"prompt": "shared question"})

        tf = threading.Thread(target=follow)
        tf.start()
        wait_for(lambda: gw._flights.followers() == 1, what="follower joined")
        leader_conn.close()  # the leader's client is gone; follower rides
        gate.set()
        tb.join(timeout=30)
        tf.join(timeout=30)
        assert blocker[0][0] == 200
        # The dead-client leader still ran — its follower needed the
        # result — and the follower got it, coalesced.
        status, _, data = follower[0]
        assert status == 200, data
        doc = json.loads(data)
        assert doc["coalesced"] is True and doc["consensus"]
        assert gw.admission.snapshot()["dropped_disconnected"] == 0
        # One execution for the shared question.
        shared = [c for c in provider.panel_calls()
                  if c[1] == "shared question"]
        assert len(shared) == len(PANEL)
    finally:
        gate.set()
        gw.close(timeout=5.0)
