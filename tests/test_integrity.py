"""End-to-end integrity plane (integrity/ + every byte-crossing seam).

The contract under test, per ISSUE 20's detect → contain → repair:

  * **WAL** — every ``StreamJournal`` record is CRC32C-framed; a
    ``torn_wal_tail`` or ``bit_flip`` on the mirror file truncates to
    the last good record on read (repair feeds the normal replay
    contract), never parses wrong.
  * **KV** — pool blocks carry publish-time digests; a sampled gather
    verification that fails drops the radix chain and recomputes the
    prefill — reuse lost, never correctness — and a clean run with the
    plane on is byte-identical to plane-off.
  * **Handoff** — a cross-mesh wave whose staged bytes don't reproduce
    the prefill-side digests resolves failed: nothing publishes, the
    caller falls back to the classic path.
  * **Checkpoint** — a params tree that doesn't reproduce the digest
    stamped in ``version.json`` is refused before install (provider
    ``accepted=False``; the gateway maps it to 409) and never becomes
    the resident version.
  * **Logits** — the fused finite-logit sentinel fails exactly the
    poisoned row (``nan_logits``) with a typed
    :class:`IntegrityError`; slot neighbors emit byte-identically.
  * **Quarantine** — repeated strikes walk one replica SERVING →
    QUARANTINED (router stops placing, /healthz 503s); consecutive
    clean probe windows walk it back (hysteresis, reversible).
  * **Corpus** — a distillation pair whose bytes don't reproduce
    their ``integrity_digest`` is booked in ``corrupt_ids`` and
    excluded, never trained on.
"""

from __future__ import annotations

import glob
import http.client
import json
import os
import threading

import jax
import jax.numpy as jnp
import pytest

from llm_consensus_tpu import faults, integrity, obs, serve
from llm_consensus_tpu.engine import ContinuousBatcher, Engine, SamplingParams
from llm_consensus_tpu.engine.handoff import KVHandoff
from llm_consensus_tpu.faults import FaultPlan
from llm_consensus_tpu.flywheel.corpus import build_corpus, pair_digest
from llm_consensus_tpu.models import get_config, init_params
from llm_consensus_tpu.parallel.mesh import make_mesh
from llm_consensus_tpu.providers.base import Provider, Request, Response
from llm_consensus_tpu.providers.registry import Registry
from llm_consensus_tpu.recovery.journal import StreamJournal, read_wal
from llm_consensus_tpu.serve.elastic import (
    QUARANTINED,
    SERVING,
    MigrationRecord,
    placeable,
)
from llm_consensus_tpu.utils.context import Context

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean_planes(monkeypatch):
    for knob in (
        "LLMC_INTEGRITY", "LLMC_INTEGRITY_SAMPLE",
        "LLMC_INTEGRITY_QUARANTINE_AFTER", "LLMC_INTEGRITY_PROBE_N",
        "LLMC_FAULTS", "LLMC_KV_POOL", "LLMC_JOURNAL",
    ):
        monkeypatch.delenv(knob, raising=False)
    faults.reset()
    integrity.reset()
    obs.reset()
    yield
    faults.reset()
    integrity.reset()
    obs.reset()


def _arm(monkeypatch, sample="1.0", quarantine_after="0", probe_n="3"):
    """Turn the plane on with test knobs and return it."""
    monkeypatch.setenv("LLMC_INTEGRITY", "1")
    monkeypatch.setenv("LLMC_INTEGRITY_SAMPLE", sample)
    monkeypatch.setenv("LLMC_INTEGRITY_QUARANTINE_AFTER", quarantine_after)
    monkeypatch.setenv("LLMC_INTEGRITY_PROBE_N", probe_n)
    integrity.reset()
    plane = integrity.plane()
    assert plane is not None
    return plane


@pytest.fixture(scope="module")
def tiny():
    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    return cfg, params


# ---------------------------------------------------------------------------
# WAL framing: torn tail truncates to last good record, replay-identical


def test_wal_frame_roundtrip_and_refusal():
    line = integrity.frame_wal_line("#finish=eos")
    assert integrity.parse_wal_line(line) == "#finish=eos"
    # One flipped payload character: the CRC no longer reproduces.
    bad = line[:-1] + chr(ord(line[-1]) ^ 1)
    assert integrity.parse_wal_line(bad) is None
    assert integrity.parse_wal_line("nonsense") is None
    assert integrity.parse_wal_line("") is None


def _journal_one(tmp_path, tokens, finish="eos"):
    j = StreamJournal(path=str(tmp_path))
    s = SamplingParams(max_new_tokens=8)
    e = j.record([5, 6, 7], s)
    for t in tokens:
        e.append(t)
    e.close(finish)
    (path,) = glob.glob(os.path.join(str(tmp_path), "*.wal"))
    return path


def test_wal_torn_tail_truncates_to_last_good(tmp_path, monkeypatch):
    """torn_wal_tail mid-finish-record: read_wal keeps the full emitted
    prefix (header + every token), truncates the file to it, and a
    second read sees a clean — byte-identical — replay input."""
    plane = _arm(monkeypatch)
    clean = _journal_one(tmp_path / "clean", [10, 11, 12])
    want = read_wal(clean)
    assert want["finish"] == "eos" and not want["truncated"]

    faults.install(FaultPlan("torn_wal_tail", seed=1))
    torn = _journal_one(tmp_path / "torn", [10, 11, 12])
    doc = read_wal(torn)
    assert doc["truncated"]
    assert doc["finish"] is None  # the finish record was the torn tail
    assert doc["header"]["prompt_ids"] == want["header"]["prompt_ids"]
    assert doc["tokens"] == want["tokens"] == [10, 11, 12]
    assert plane.stats()["failures"].get("wal", 0) >= 1
    # Repair really truncated the file: the re-read is clean and
    # byte-identical to the surviving prefix (the replay contract's
    # input — prompt ids + sampling + emitted tokens).
    again = read_wal(torn)
    assert not again["truncated"]
    assert again["tokens"] == doc["tokens"]
    assert again["header"] == doc["header"]


def test_wal_bit_flip_record_refused_not_misparsed(tmp_path, monkeypatch):
    """A single flipped bit in a framed record is refused by the CRC —
    the reader truncates there instead of parsing a wrong value."""
    plane = _arm(monkeypatch)
    faults.install(FaultPlan("bit_flip@surface=wal", seed=1))
    path = _journal_one(tmp_path, [42, 43])
    doc = read_wal(path)
    assert doc["truncated"] and doc["finish"] is None
    assert doc["tokens"] == [42, 43]  # everything before the flip survives
    assert plane.stats()["failures"].get("wal", 0) >= 1
    assert plane.stats()["checks"].get("wal", 0) >= 3


# ---------------------------------------------------------------------------
# KV pool: sampled gather verification, byte-identity, drop + recompute


def _pool_engine(cfg, params, monkeypatch, pool: bool, **kw):
    monkeypatch.setenv("LLMC_KV_POOL", "1" if pool else "0")
    monkeypatch.setenv("LLMC_KV_POOL_BLOCK", "16")
    return Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                  prefill_chunk=16, **kw)


@pytest.mark.parametrize("kv_quant", [None, "int8"], ids=["bf16kv", "int8kv"])
def test_kv_sampled_gather_verify_byte_identity(tiny, monkeypatch, kv_quant):
    """Plane on + verify-every-gather: pooled greedy output stays
    byte-identical to pool-off, and the verifications really ran."""
    cfg, params = tiny
    shared = "integrity plane shared system prefix " * 2
    prompts = [shared + "first question", shared + "first question",
               shared + "second, different question"]
    s = SamplingParams(max_new_tokens=10, ignore_eos=True)
    off = _pool_engine(cfg, params, monkeypatch, pool=False,
                       kv_quant=kv_quant)
    want = [off.generate(p, s).token_ids for p in prompts]

    plane = _arm(monkeypatch, sample="1.0")
    on = _pool_engine(cfg, params, monkeypatch, pool=True, kv_quant=kv_quant)
    assert on._kv_pool is not None
    got = [on.generate(p, s).token_ids for p in prompts]
    assert got == want
    stats = on._kv_pool.stats()
    assert stats["verified_blocks"] > 0
    assert stats["corrupt_blocks"] == 0
    assert plane.stats()["checks"].get("kv", 0) > 0
    assert not plane.stats()["failures"]


def test_kv_gather_corruption_drops_chain_and_recomputes(tiny, monkeypatch):
    """An injected bit_flip on a verified gather books the corruption,
    drops the radix chain, and re-prefills — tokens stay byte-identical
    (reuse lost, never correctness) and the NEXT request reuses the
    republished clean bytes."""
    cfg, params = tiny
    prompt = "kv corruption containment probe prompt " * 2
    s = SamplingParams(max_new_tokens=10, ignore_eos=True)
    off = _pool_engine(cfg, params, monkeypatch, pool=False)
    want = off.generate(prompt, s).token_ids

    plane = _arm(monkeypatch, sample="1.0")
    faults.install(FaultPlan("bit_flip@surface=kv", seed=2))
    on = _pool_engine(cfg, params, monkeypatch, pool=True)
    assert on.generate(prompt, s).token_ids == want  # publishes
    assert on.generate(prompt, s).token_ids == want  # corrupt gather
    stats = on._kv_pool.stats()
    assert stats["corrupt_blocks"] == 1, stats
    assert plane.stats()["failures"].get("kv", 0) == 1
    # The fault fired once; the drop forced a republish — the third
    # request gathers the clean bytes and verifies them.
    before = on._kv_pool.stats()["verified_blocks"]
    assert on.generate(prompt, s).token_ids == want
    stats = on._kv_pool.stats()
    assert stats["verified_blocks"] > before
    assert stats["corrupt_blocks"] == 1  # no new corruption
    faults.reset()


# ---------------------------------------------------------------------------
# handoff: a corrupted cross-mesh wave resolves failed, classic fallback


def test_handoff_digest_mismatch_fails_wave_then_clean_retry(tiny,
                                                             monkeypatch):
    """bit_flip on the staged handoff bytes: the wave's digests don't
    reproduce, run() resolves (False, False) — nothing publishes, the
    caller takes the classic path — and the spent fault leaves the next
    submit to complete and publish normally."""
    cfg, params = tiny
    devs = jax.devices()
    plane = _arm(monkeypatch, sample="1.0")
    faults.install(FaultPlan("bit_flip@surface=handoff", seed=3))
    monkeypatch.setenv("LLMC_KV_POOL_BLOCK", "16")
    monkeypatch.setenv("LLMC_KV_POOL", "0")
    pe = Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                prefill_chunk=16, mesh=make_mesh({"dp": 1, "tp": 1},
                                                 devs[2:3]))
    monkeypatch.setenv("LLMC_KV_POOL", "1")
    de = Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                prefill_chunk=16, mesh=make_mesh({"dp": 1, "tp": 2},
                                                 devs[:2]))
    assert de._kv_pool is not None
    ids = [(7 * i + 3) % 120 + 1 for i in range(40)]
    h = KVHandoff(pe, de, name="test")
    try:
        ok, truncated = h.run(list(ids), priority=0)
        assert (ok, truncated) == (False, False)
        assert h.snapshot()["fallbacks"] == 1
        assert plane.stats()["failures"].get("handoff", 0) >= 1
        # Containment: the poisoned wave published NOTHING.
        n, _cache = de._kv_pool.lookup(list(ids) + [121], min_tokens=1,
                                       shard_fn=de._shard_fn)
        assert n == 0
        # Repair: the fault is spent; a clean retry transfers and the
        # bytes verify.
        ok, truncated = h.run(list(ids), priority=0)
        assert ok and not truncated, h.snapshot()
        assert plane.stats()["failures"].get("handoff", 0) == 1
    finally:
        h.close()


# ---------------------------------------------------------------------------
# migration records: digest over the resume state


def test_migration_record_digest_stamp_verify_tamper():
    rec = MigrationRecord(
        key="run:0",
        resume={"tiny": {"prompt_ids": [1, 2, 3], "tokens": [9, 9]}},
        priority=1,
    )
    assert rec.verify_digest()  # no digest yet: pre-plane records pass
    rec.stamp_digest()
    assert rec.verify_digest()
    # JSON round trip (the wire) preserves the digest relation.
    back = MigrationRecord.from_doc(json.loads(json.dumps(rec.to_doc())))
    assert back.verify_digest()
    back.resume["tiny"]["tokens"] = [9, 8]
    assert not back.verify_digest()


class _FakeProvider(Provider):
    """Deterministic non-streaming fake for gateway-level tests."""

    def query(self, ctx: Context, req: Request) -> Response:
        return Response(model=req.model, content=f"{req.model} ok",
                        provider="fake")

    def query_stream(self, ctx, req, callback):
        r = self.query(ctx, req)
        if callback is not None:
            callback(r.content)
        return r


PANEL = ["alpha", "beta"]
JUDGE = "gamma"


def _gateway(tmp_path, provider=None, start=False, **kw):
    registry = Registry()
    for m in PANEL + [JUDGE]:
        registry.register(m, provider or _FakeProvider())
    kw.setdefault("timeout", 30.0)
    kw.setdefault("max_concurrency", 4)
    kw.setdefault("cache_size", 0)
    gw = serve.build_gateway(
        registry, list(PANEL), JUDGE,
        data_dir=os.path.join(str(tmp_path), "data"), **kw,
    )
    if start:
        gw.start()
    return gw


def test_gateway_refuses_digest_mismatched_migration(tmp_path, monkeypatch):
    """accept_migration re-verifies the record digest before parking:
    a tampered resume payload is refused (never parked, never resumed)
    and books a migration-surface failure + strike."""
    plane = _arm(monkeypatch)
    gw = _gateway(tmp_path)
    try:
        rec = MigrationRecord(key="run:7", resume={"m": {"text": "ab"}})
        rec.stamp_digest()
        doc = rec.to_doc()
        status, out = gw.accept_migration(json.dumps(doc).encode())
        assert status == 200 and out["accepted"]
        doc = rec.to_doc()
        doc["resume"] = {"m": {"text": "TAMPERED"}}
        status, out = gw.accept_migration(json.dumps(doc).encode())
        assert status == 200 and not out["accepted"]
        assert "digest" in out["error"]
        assert plane.stats()["failures"].get("migration", 0) == 1
        assert plane.stats()["checks"].get("migration", 0) >= 2
    finally:
        gw.close(drain=False)


# ---------------------------------------------------------------------------
# checkpoint digests: refused before install, 409 on the admin surface


class _RottenSwapProvider(_FakeProvider):
    """swap_weights stub that reports the integrity plane's refusal —
    the shape providers/tpu.py returns on a params-digest mismatch."""

    def swap_weights(self, model, path, version=None, *, wait=False,
                     meta=None):
        return {"accepted": False, "rejected": "params_digest_mismatch",
                "weight_version": 1}


def test_gateway_swap_maps_digest_refusal_to_409(tmp_path, monkeypatch):
    """A digest-refused swap returns 409, never flips the resident
    version, and counts a ckpt strike — repeated rotten checkpoints
    walk the replica to quarantined."""
    _arm(monkeypatch, quarantine_after="2")
    gw = _gateway(tmp_path, provider=_RottenSwapProvider())
    try:
        doc = {"model": "alpha", "checkpoint": "/nonexistent/params",
               "version": 2}
        status, out = gw.swap_checkpoint(doc)
        assert status == 409
        assert out["rejected"] == "params_digest_mismatch"
        assert gw.lifecycle == SERVING  # one strike: under threshold
        status, _out = gw.swap_checkpoint(doc)
        assert status == 409
        assert gw.lifecycle == QUARANTINED  # second strike crossed it
    finally:
        gw.close(drain=False)


def test_provider_refuses_params_digest_mismatch(tiny, monkeypatch):
    """The real provider half: an injected bit_flip@surface=ckpt makes
    the re-derived tree digest miss the stamped one — the swap is
    refused before the engine installs anything, and the resident
    version never moves; the clean retry installs."""
    from llm_consensus_tpu.providers.tpu import TPUProvider

    cfg, params = tiny
    plane = _arm(monkeypatch)
    prov = TPUProvider(ignore_eos=True)
    prov.prepare(["tpu:tiny-llama"], None, devices=jax.devices()[:1])
    try:
        eng = prov._engine_for("tiny-llama")
        resident = eng.weight_version
        meta = {"params_digest": integrity.digest_tree(params)}
        faults.install(FaultPlan("bit_flip@surface=ckpt", seed=4))
        out = prov.swap_weights("tiny-llama", params,
                                resident + 1, wait=True, meta=meta)
        assert out["accepted"] is False
        assert out["rejected"] == "params_digest_mismatch"
        assert eng.weight_version == resident
        assert plane.stats()["failures"].get("ckpt", 0) == 1
        # Fault spent: the same checkpoint now verifies and installs.
        out = prov.swap_weights("tiny-llama", params,
                                resident + 1, wait=True, meta=meta)
        assert out["accepted"] is True
        assert eng.weight_version == resident + 1
        assert plane.stats()["checks"].get("ckpt", 0) == 2
    finally:
        faults.reset()
        prov.release()


# ---------------------------------------------------------------------------
# finite-logit sentinel: nan_logits fails one row, neighbors untouched


def test_nan_row_fails_typed_neighbors_byte_identical(tiny, monkeypatch):
    """nan_logits@row=0 poisons exactly one decode row: that stream
    fails with a typed IntegrityError (finish reason ``integrity``);
    its slot neighbor finishes byte-identical to an undisturbed
    single-stream run."""
    cfg, params = tiny
    s = SamplingParams(max_new_tokens=16, ignore_eos=True)
    prompts = ["the poisoned stream", "the innocent neighbor stream"]
    ref = Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                 stream_interval=8)
    want = ref.generate(prompts[1], s).token_ids

    plane = _arm(monkeypatch)
    faults.install(FaultPlan("nan_logits@row=0", seed=5))
    eng = Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                 stream_interval=8)
    b = ContinuousBatcher(eng, max_batch=2)
    try:
        f0 = b.submit(prompts[0], s)
        f1 = b.submit(prompts[1], s)
        with pytest.raises(integrity.IntegrityError) as excinfo:
            f0.result(timeout=300)
        assert excinfo.value.surface == "logits"
        assert f1.result(timeout=300).token_ids == want
        assert plane.stats()["failures"].get("logits", 0) == 1
        assert plane.stats()["checks"].get("logits", 0) >= 1
    finally:
        b.close()
        faults.reset()


# ---------------------------------------------------------------------------
# quarantine lifecycle: enter exactly once, probe hysteresis, exit


def test_quarantine_tracker_hysteresis():
    t = integrity.QuarantineTracker(threshold=3, probe_n=2)
    assert not t.strike() and not t.strike()
    assert t.strike()            # exactly at the threshold crossing
    assert not t.strike()        # past it: never re-fires
    assert not t.clean_probe()   # 1 of 2
    assert not t.strike()        # dirty window resets the clean run
    assert not t.clean_probe()
    assert t.clean_probe()       # 2 consecutive: earned its way back
    snap = t.snapshot()
    assert snap["strikes"] == 0 and snap["quarantines"] == 1
    # The full cycle re-arms: strikes count fresh toward re-quarantine.
    assert not t.strike() and not t.strike()
    assert t.strike()
    assert t.snapshot()["quarantines"] == 2


def test_gateway_strikes_quarantine_probe_lifts(tmp_path, monkeypatch):
    """Strike-driven walk on a real gateway: threshold strikes flip
    SERVING → QUARANTINED (unplaceable, counted); clean probe windows
    lift it; a dirty window (new integrity failure) resets the run."""
    plane = _arm(monkeypatch, quarantine_after="3", probe_n="2")
    gw = _gateway(tmp_path)
    try:
        gw.record_integrity_strike("kv")
        gw.record_integrity_strike("kv")
        assert gw.lifecycle == SERVING
        gw.record_integrity_strike("kv")
        assert gw.lifecycle == QUARANTINED
        assert not placeable(gw.lifecycle)
        # A window that saw a fresh failure is dirty: no progress.
        plane.failure("kv", "still rotten")
        assert gw.probe_quarantine() is False
        assert gw.lifecycle == QUARANTINED
        # Two consecutive clean windows lift it.
        assert gw.probe_quarantine() is False
        assert gw.probe_quarantine() is True
        assert gw.lifecycle == SERVING
        stats = gw.stats()
        assert stats["integrity"]["quarantine"]["quarantines"] == 1
        assert stats["elastic"]["quarantines"] == 1
        assert stats["elastic"]["unquarantines"] == 1
    finally:
        gw.close(drain=False)


def test_quarantine_admin_endpoint_and_healthz(tmp_path, monkeypatch):
    """The admin surface: POST /v1/quarantine walks the replica out of
    rotation, /healthz reports 503 + the probe snapshot, and the beat's
    probes walk it back to 200/ok."""
    _arm(monkeypatch, quarantine_after="3", probe_n="2")
    gw = _gateway(tmp_path, start=True)
    host, port = gw.address
    try:
        conn = http.client.HTTPConnection(host, port, timeout=10)
        conn.request("POST", "/v1/quarantine", body=b"{}",
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        out = json.loads(resp.read())
        assert resp.status == 200 and out["lifecycle"] == QUARANTINED
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        doc = json.loads(resp.read())
        assert resp.status == 503
        assert doc["status"] == "quarantined" and not doc["placeable"]
        assert doc["quarantine"]["probe_n"] == 2
        # probe_n clean windows lift it; /healthz recovers.
        assert gw.probe_quarantine() is False
        assert gw.probe_quarantine() is True
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        doc = json.loads(resp.read())
        assert resp.status == 200 and doc["status"] == "ok"
        conn.close()
    finally:
        gw.close(drain=False)


# ---------------------------------------------------------------------------
# corpus: digest-mismatched pairs booked and excluded from distillation


def _write_run(data_dir, run_id, prompt, verdict, tamper=False):
    d = os.path.join(data_dir, run_id)
    os.makedirs(d)
    with open(os.path.join(d, "run.json"), "w", encoding="utf-8") as f:
        json.dump({"prompt": prompt}, f)
    result = {
        "prompt": prompt,
        "consensus": verdict,
        "responses": [
            {"model": "alpha", "content": f"A: {prompt}", "provider": "f"},
            {"model": "beta", "content": f"B: {prompt}", "provider": "f"},
        ],
    }
    result["integrity_digest"] = pair_digest(result)
    if tamper:
        result["consensus"] = verdict + " [rotted]"
    with open(os.path.join(d, "result.json"), "w", encoding="utf-8") as f:
        json.dump(result, f)


def test_corpus_excludes_digest_mismatched_pairs(tmp_path, monkeypatch):
    plane = _arm(monkeypatch)
    data = str(tmp_path / "data")
    os.makedirs(data)
    _write_run(data, "run-good", "what is up", "the sky")
    _write_run(data, "run-bad", "what is down", "the floor", tamper=True)
    corpus = build_corpus(data_dir=data, holdout=0.0)
    assert corpus.runs_scanned == 2
    assert corpus.runs_corrupt == 1
    assert corpus.corrupt_ids == ["run-bad"]
    assert [ex.run_id for ex in corpus.train] == ["run-good"]
    assert plane.stats()["failures"].get("corpus", 0) == 1
    assert plane.stats()["checks"].get("corpus", 0) == 2
    doc = corpus.summary()
    assert doc["runs_corrupt"] == 1 and doc["corrupt_ids"] == ["run-bad"]


# ---------------------------------------------------------------------------
# counters surface (obs satellite): stats + prom family shapes


def test_integrity_counters_and_prom_families(monkeypatch):
    plane = _arm(monkeypatch, sample="0.05")
    plane.check("kv", 3)
    plane.failure("wal", "torn")
    stats = plane.stats()
    assert stats["checks"]["kv"] == 3
    assert stats["failures"]["wal"] == 1
    assert stats["checks_total"] == 3 and stats["failures_total"] == 1
    assert stats["sample"] == 0.05
    fams = plane.counters.prom_families()
    checks = fams["integrity_checks_total"]
    fails = fams["integrity_failures_total"]
    assert ({"surface": "kv"}, 3) in [(s[0], s[1]) for s in checks["samples"]]
    assert ({"surface": "wal"}, 1) in [(s[0], s[1]) for s in fails["samples"]]
