"""Train step: loss math, convergence, and sharded execution.

Runs on the 8-device virtual CPU mesh (tests/conftest.py); the driver's
dryrun_multichip covers the same path at other device counts.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from llm_consensus_tpu.models import get_config
from llm_consensus_tpu.parallel.mesh import make_mesh
from llm_consensus_tpu.train import (
    TrainState,
    cross_entropy_loss,
    init_train_state,
    make_train_step,
)
from llm_consensus_tpu.train.step import default_optimizer


@pytest.fixture(autouse=True, scope="module")
def _no_persistent_cache():
    """The persistent XLA:CPU cache is unreliable for THIS module's
    sharded train-step executables on this jaxlib: four distinct
    full-suite crashes (SIGSEGV in compilation_cache
    get_executable_and_time on a stale entry; SIGSEGV/abort in
    put_executable_and_time serializing fresh ones), every one under
    tests/test_train.py, none elsewhere. Flipping
    jax_compilation_cache_dir to None did NOT stop the writes (the
    cache holds its own initialized state), so stub the two (de)-
    serialization entry points outright for the module. Programs
    compile fresh — ~2.5 min standalone, amortized by jit's in-process
    cache."""
    import jax._src.compilation_cache as cc

    old_get, old_put = cc.get_executable_and_time, cc.put_executable_and_time
    cc.get_executable_and_time = lambda *a, **k: (None, None)
    cc.put_executable_and_time = lambda *a, **k: None
    yield
    cc.get_executable_and_time, cc.put_executable_and_time = old_get, old_put


def _batch(key, cfg, batch=2, seq=16):
    tokens = jax.random.randint(key, (batch, seq), 0, cfg.vocab_size, jnp.int32)
    targets = jnp.roll(tokens, -1, axis=1)
    mask = jnp.ones((batch, seq), jnp.float32).at[:, -1].set(0.0)
    return {"tokens": tokens, "targets": targets, "mask": mask}


class TestCrossEntropy:
    def test_uniform_logits_give_log_vocab(self):
        v = 64
        logits = jnp.zeros((1, 8, v))
        targets = jnp.zeros((1, 8), jnp.int32)
        loss = cross_entropy_loss(logits, targets)
        np.testing.assert_allclose(float(loss), np.log(v), rtol=1e-5)

    def test_perfect_prediction_near_zero(self):
        targets = jnp.arange(8, dtype=jnp.int32)[None, :]
        logits = jax.nn.one_hot(targets, 32) * 100.0
        assert float(cross_entropy_loss(logits, targets)) < 1e-3

    def test_mask_excludes_positions(self):
        v = 16
        logits = jnp.zeros((1, 4, v))
        targets = jnp.zeros((1, 4), jnp.int32)
        # Position 0 predicted perfectly, rest uniform; only count position 0.
        logits = logits.at[0, 0, 0].set(100.0)
        mask = jnp.asarray([[1.0, 0.0, 0.0, 0.0]])
        assert float(cross_entropy_loss(logits, targets, mask)) < 1e-3


class TestTrainStep:
    def test_loss_decreases_single_device(self):
        cfg = get_config("tiny-llama")
        opt = default_optimizer(lr=1e-2)
        state = init_train_state(cfg, jax.random.PRNGKey(0), opt)
        step = make_train_step(cfg, opt, remat=False)
        batch = _batch(jax.random.PRNGKey(1), cfg)
        state, first = step(state, batch)
        for _ in range(10):
            state, metrics = step(state, batch)
        assert float(metrics["loss"]) < float(first["loss"])
        assert int(state.step) == 11

    def test_remat_matches_no_remat(self):
        cfg = get_config("tiny-llama")
        opt = optax.sgd(1e-2)
        batch = _batch(jax.random.PRNGKey(1), cfg)
        states = []
        for remat in (False, True):
            state = init_train_state(cfg, jax.random.PRNGKey(0), opt)
            step = make_train_step(cfg, opt, remat=remat)
            state, metrics = step(state, batch)
            states.append((state, float(metrics["loss"])))
        assert np.isclose(states[0][1], states[1][1], rtol=1e-5)
        a = jax.tree.leaves(states[0][0].params)[0]
        b = jax.tree.leaves(states[1][0].params)[0]
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32), rtol=2e-2, atol=1e-4)

    @pytest.mark.parametrize("axes", [
        {"dp": 2, "tp": 4},
        {"dp": 2, "tp": 2, "sp": 2},
        {"dp": 8},
    ])
    def test_sharded_matches_single_device(self, axes):
        cfg = get_config("tiny-llama")
        opt = optax.sgd(1e-2)
        batch = _batch(jax.random.PRNGKey(1), cfg, batch=4, seq=16)

        ref = init_train_state(cfg, jax.random.PRNGKey(0), opt)
        ref, ref_m = make_train_step(cfg, opt, remat=False)(ref, batch)

        mesh = make_mesh(axes)
        state = init_train_state(cfg, jax.random.PRNGKey(0), opt, mesh=mesh)
        state, m = make_train_step(cfg, opt, mesh=mesh, remat=False)(state, batch)
        # rtol 5e-4, not 1e-4: with bf16 params the sharded step's
        # reduction order (psum/ring) legitimately shifts the loss by a
        # few bf16 ulps relative to single-device; 1e-4 sat one ulp away
        # from the observed diff and flipped when the init draw moved by
        # last-ulp rounding (fused init kernel). A real sharding bug
        # (wrong spec, missing collective) shows up orders of magnitude
        # larger.
        np.testing.assert_allclose(float(m["loss"]), float(ref_m["loss"]),
                                   rtol=5e-4)

    def test_moe_with_expert_axis(self):
        cfg = get_config("tiny-mixtral")
        opt = default_optimizer(lr=1e-2)
        mesh = make_mesh({"dp": 2, "ep": 4})
        state = init_train_state(cfg, jax.random.PRNGKey(0), opt, mesh=mesh)
        step = make_train_step(cfg, opt, mesh=mesh)
        batch = _batch(jax.random.PRNGKey(1), cfg)
        state, first = step(state, batch)
        for _ in range(5):
            state, metrics = step(state, batch)
        assert float(metrics["loss"]) < float(first["loss"])
        assert np.isfinite(float(metrics["grad_norm"]))
