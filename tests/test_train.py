"""Train step: loss math, convergence, and sharded execution.

Runs on the 8-device virtual CPU mesh (tests/conftest.py); the driver's
dryrun_multichip covers the same path at other device counts.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from llm_consensus_tpu.models import get_config
from llm_consensus_tpu.parallel.mesh import make_mesh
from llm_consensus_tpu.train import (
    TrainState,
    cross_entropy_loss,
    distill_loss,
    init_train_state,
    make_train_step,
)
from llm_consensus_tpu.train.step import default_optimizer


@pytest.fixture(autouse=True, scope="module")
def _no_persistent_cache():
    """The persistent XLA:CPU cache is unreliable for THIS module's
    sharded train-step executables on this jaxlib: four distinct
    full-suite crashes (SIGSEGV in compilation_cache
    get_executable_and_time on a stale entry; SIGSEGV/abort in
    put_executable_and_time serializing fresh ones), every one under
    tests/test_train.py, none elsewhere. Flipping
    jax_compilation_cache_dir to None did NOT stop the writes (the
    cache holds its own initialized state), so stub the two (de)-
    serialization entry points outright for the module. Programs
    compile fresh — ~2.5 min standalone, amortized by jit's in-process
    cache."""
    import jax._src.compilation_cache as cc

    old_get, old_put = cc.get_executable_and_time, cc.put_executable_and_time
    cc.get_executable_and_time = lambda *a, **k: (None, None)
    cc.put_executable_and_time = lambda *a, **k: None
    yield
    cc.get_executable_and_time, cc.put_executable_and_time = old_get, old_put


def _batch(key, cfg, batch=2, seq=16):
    tokens = jax.random.randint(key, (batch, seq), 0, cfg.vocab_size, jnp.int32)
    targets = jnp.roll(tokens, -1, axis=1)
    mask = jnp.ones((batch, seq), jnp.float32).at[:, -1].set(0.0)
    return {"tokens": tokens, "targets": targets, "mask": mask}


class TestCrossEntropy:
    def test_uniform_logits_give_log_vocab(self):
        v = 64
        logits = jnp.zeros((1, 8, v))
        targets = jnp.zeros((1, 8), jnp.int32)
        loss = cross_entropy_loss(logits, targets)
        np.testing.assert_allclose(float(loss), np.log(v), rtol=1e-5)

    def test_perfect_prediction_near_zero(self):
        targets = jnp.arange(8, dtype=jnp.int32)[None, :]
        logits = jax.nn.one_hot(targets, 32) * 100.0
        assert float(cross_entropy_loss(logits, targets)) < 1e-3

    def test_mask_excludes_positions(self):
        v = 16
        logits = jnp.zeros((1, 4, v))
        targets = jnp.zeros((1, 4), jnp.int32)
        # Position 0 predicted perfectly, rest uniform; only count position 0.
        logits = logits.at[0, 0, 0].set(100.0)
        mask = jnp.asarray([[1.0, 0.0, 0.0, 0.0]])
        assert float(cross_entropy_loss(logits, targets, mask)) < 1e-3


class TestTrainStep:
    def test_loss_decreases_single_device(self):
        cfg = get_config("tiny-llama")
        opt = default_optimizer(lr=1e-2)
        state = init_train_state(cfg, jax.random.PRNGKey(0), opt)
        step = make_train_step(cfg, opt, remat=False)
        batch = _batch(jax.random.PRNGKey(1), cfg)
        state, first = step(state, batch)
        for _ in range(10):
            state, metrics = step(state, batch)
        assert float(metrics["loss"]) < float(first["loss"])
        assert int(state.step) == 11

    def test_remat_matches_no_remat(self):
        cfg = get_config("tiny-llama")
        opt = optax.sgd(1e-2)
        batch = _batch(jax.random.PRNGKey(1), cfg)
        states = []
        for remat in (False, True):
            state = init_train_state(cfg, jax.random.PRNGKey(0), opt)
            step = make_train_step(cfg, opt, remat=remat)
            state, metrics = step(state, batch)
            states.append((state, float(metrics["loss"])))
        assert np.isclose(states[0][1], states[1][1], rtol=1e-5)
        a = jax.tree.leaves(states[0][0].params)[0]
        b = jax.tree.leaves(states[1][0].params)[0]
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32), rtol=2e-2, atol=1e-4)

    @pytest.mark.parametrize("axes", [
        {"dp": 2, "tp": 4},
        {"dp": 2, "tp": 2, "sp": 2},
        {"dp": 8},
    ])
    def test_sharded_matches_single_device(self, axes):
        cfg = get_config("tiny-llama")
        opt = optax.sgd(1e-2)
        batch = _batch(jax.random.PRNGKey(1), cfg, batch=4, seq=16)

        ref = init_train_state(cfg, jax.random.PRNGKey(0), opt)
        ref, ref_m = make_train_step(cfg, opt, remat=False)(ref, batch)

        mesh = make_mesh(axes)
        state = init_train_state(cfg, jax.random.PRNGKey(0), opt, mesh=mesh)
        state, m = make_train_step(cfg, opt, mesh=mesh, remat=False)(state, batch)
        # rtol 5e-4, not 1e-4: with bf16 params the sharded step's
        # reduction order (psum/ring) legitimately shifts the loss by a
        # few bf16 ulps relative to single-device; 1e-4 sat one ulp away
        # from the observed diff and flipped when the init draw moved by
        # last-ulp rounding (fused init kernel). A real sharding bug
        # (wrong spec, missing collective) shows up orders of magnitude
        # larger.
        np.testing.assert_allclose(float(m["loss"]), float(ref_m["loss"]),
                                   rtol=5e-4)

    def test_moe_with_expert_axis(self):
        cfg = get_config("tiny-mixtral")
        opt = default_optimizer(lr=1e-2)
        mesh = make_mesh({"dp": 2, "ep": 4})
        state = init_train_state(cfg, jax.random.PRNGKey(0), opt, mesh=mesh)
        step = make_train_step(cfg, opt, mesh=mesh)
        batch = _batch(jax.random.PRNGKey(1), cfg)
        state, first = step(state, batch)
        for _ in range(5):
            state, metrics = step(state, batch)
        assert float(metrics["loss"]) < float(first["loss"])
        assert np.isfinite(float(metrics["grad_norm"]))


class TestDistillLoss:
    """The flywheel objective (train/loss.py distill_loss): KL/CE mix,
    masking, temperature — pure loss math, no model forward."""

    def _logits(self, key, b=2, t=8, v=32):
        ks, kt = jax.random.split(key)
        return (
            jax.random.normal(ks, (b, t, v)),
            jax.random.normal(kt, (b, t, v)),
        )

    def test_alpha_mixes_kl_and_ce(self):
        s, tch = self._logits(jax.random.PRNGKey(0))
        targets = jnp.zeros((2, 8), jnp.int32)
        loss, aux = distill_loss(s, tch, targets, alpha=0.3)
        np.testing.assert_allclose(
            float(loss), 0.3 * float(aux["kl"]) + 0.7 * float(aux["ce"]),
            rtol=1e-5,
        )
        pure_kl, _ = distill_loss(s, tch, targets, alpha=1.0)
        np.testing.assert_allclose(float(pure_kl), float(aux["kl"]),
                                   rtol=1e-5)
        pure_ce, _ = distill_loss(s, tch, targets, alpha=0.0)
        np.testing.assert_allclose(float(pure_ce), float(aux["ce"]),
                                   rtol=1e-5)

    def test_matching_teacher_zero_kl(self):
        s, _ = self._logits(jax.random.PRNGKey(1))
        targets = jnp.zeros((2, 8), jnp.int32)
        for temp in (1.0, 2.0, 4.0):
            _loss, aux = distill_loss(s, s, targets, temperature=temp)
            assert abs(float(aux["kl"])) < 1e-5, (temp, aux)

    def test_mask_gates_both_halves(self):
        s, tch = self._logits(jax.random.PRNGKey(2), b=1, t=4)
        targets = jnp.zeros((1, 4), jnp.int32)
        # Only position 0 counts; make the OTHER positions wildly wrong
        # for both halves — a mask leak shows up as a huge loss.
        s = s.at[0, 1:, :].set(0.0)
        s = s.at[0, 1:, 1].set(100.0)
        tch = tch.at[0, 1:, :].set(0.0)
        tch = tch.at[0, 1:, 2].set(100.0)
        mask = jnp.asarray([[1.0, 0.0, 0.0, 0.0]])
        masked, aux_m = distill_loss(s, tch, targets, mask)
        only_first, aux_f = distill_loss(
            s[:, :1, :], tch[:, :1, :], targets[:, :1]
        )
        np.testing.assert_allclose(float(masked), float(only_first),
                                   rtol=1e-5)
        np.testing.assert_allclose(float(aux_m["kl"]), float(aux_f["kl"]),
                                   rtol=1e-5)

    def test_temperature_softens_kl(self):
        # A sharp teacher/student mismatch: at high temperature both
        # distributions flatten toward uniform, so the per-position KL
        # shrinks — but the T^2 correction keeps the term comparable
        # (it must not vanish, or alpha would silently mean "CE only").
        s, tch = self._logits(jax.random.PRNGKey(3))
        s, tch = s * 10.0, tch * 10.0
        targets = jnp.zeros((2, 8), jnp.int32)
        _l1, aux1 = distill_loss(s, tch, targets, temperature=1.0)
        _l4, aux4 = distill_loss(s, tch, targets, temperature=4.0)
        assert float(aux1["kl"]) > 0 and float(aux4["kl"]) > 0
        # Raw (un-corrected) KL at T=4 would be ~T^2 smaller; with the
        # correction the two stay within one order of magnitude.
        ratio = float(aux1["kl"]) / float(aux4["kl"])
        assert 0.1 < ratio < 10.0, ratio

    def test_teacher_logits_carry_no_gradient(self):
        s, tch = self._logits(jax.random.PRNGKey(4))
        targets = jnp.zeros((2, 8), jnp.int32)

        def teacher_side(t):
            loss, _ = distill_loss(s, t, targets, alpha=1.0)
            return loss

        g = jax.grad(teacher_side)(tch)
        np.testing.assert_allclose(np.asarray(g), 0.0)


class TestDistillStep:
    """flywheel/distill.py: the pjit data-parallel step + the ZeRO-1
    style optimizer-state placement."""

    def _setup(self, mesh=None, alpha=0.5):
        import optax

        from llm_consensus_tpu.flywheel.distill import (
            init_distill_state, make_distill_step,
        )

        cfg = get_config("tiny-llama")
        opt = optax.sgd(1e-2)  # stateless: parity unclouded by moments
        state = init_distill_state(
            cfg, jax.random.PRNGKey(0), opt, mesh=mesh, dtype=jnp.float32
        )
        teacher = init_train_state(cfg, jax.random.PRNGKey(7), opt).params
        step = make_distill_step(
            cfg, cfg, opt, mesh=mesh, remat=False, alpha=alpha
        )
        return cfg, state, teacher, step

    @pytest.mark.slow  # two full pjit compiles (dp=1 and dp=2/tp=4)
    def test_dp1_vs_dp2_gradient_parity(self):
        cfg, ref_state, teacher, ref_step = self._setup()
        batch = _batch(jax.random.PRNGKey(1), cfg, batch=4, seq=16)
        ref_state, ref_m = ref_step(ref_state, teacher, batch)

        mesh = make_mesh({"dp": 2, "tp": 4})
        _cfg, state, teacher2, step = self._setup(mesh=mesh)
        state, m = step(state, teacher2, batch)
        # tp=4 reorders the fp32 contraction sums; parity is semantic,
        # not bit-exact, across mesh shapes.
        np.testing.assert_allclose(float(m["loss"]), float(ref_m["loss"]),
                                   rtol=2e-3)
        np.testing.assert_allclose(
            float(m["grad_norm"]), float(ref_m["grad_norm"]), rtol=5e-3
        )
        a = np.asarray(jax.tree.leaves(ref_state.params)[0], np.float32)
        b = np.asarray(jax.tree.leaves(state.params)[0], np.float32)
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=5e-4)

    @pytest.mark.slow  # sharded compile + 9 optimizer steps
    def test_loss_and_kl_decrease(self):
        # alpha=1.0: pure KL distillation, so the KL term IS the trained
        # objective — with a mixed loss the CE half can trade off against
        # it step to step and a monotone-KL assert would be flaky.
        cfg, state, teacher, step = self._setup(
            mesh=make_mesh({"dp": 2, "tp": 4}), alpha=1.0
        )
        batch = _batch(jax.random.PRNGKey(2), cfg, batch=4, seq=16)
        state, first = step(state, teacher, batch)
        for _ in range(8):
            state, metrics = step(state, teacher, batch)
        assert float(metrics["loss"]) < float(first["loss"])
        assert float(metrics["kl"]) < float(first["kl"])

    @pytest.mark.parametrize("axes", [
        {"dp": 2, "tp": 4},
        {"dp": 2, "tp": 2, "sp": 2},
    ])
    def test_opt_state_dp_sharded(self, axes):
        import optax

        from llm_consensus_tpu.flywheel.distill import opt_state_shardings
        from llm_consensus_tpu.models import init_params

        cfg = get_config("tiny-llama")
        mesh = make_mesh(axes)
        opt = optax.adamw(1e-3)
        params = jax.eval_shape(
            lambda: init_params(cfg, jax.random.PRNGKey(0))
        )
        shardings = opt_state_shardings(opt, params, cfg, mesh)
        flat = jax.tree_util.tree_flatten_with_path(shardings)[0]
        moment_specs = [
            sh.spec for path, sh in flat
            if any(getattr(e, "name", None) in ("mu", "nu") for e in path)
        ]
        assert moment_specs, "no mu/nu leaves found in the optimizer state"
        # The whole point: moments partition over dp, not mirror per
        # replica — at least the big 2D+ tensors' specs must carry "dp".
        dp_sharded = [
            spec for spec in moment_specs
            if any("dp" in (ax if isinstance(ax, tuple) else (ax,))
                   for ax in spec if ax is not None)
        ]
        assert dp_sharded, f"no moment buffer sharded over dp: {moment_specs[:8]}"
        # Non-moment leaves (step counts) stay replicated.
        from jax.sharding import PartitionSpec as P

        other = [
            sh.spec for path, sh in flat
            if not any(
                getattr(e, "name", None) in ("mu", "nu") for e in path
            )
        ]
        assert all(spec == P() for spec in other), other
