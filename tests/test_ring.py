"""Ring attention == full attention, without any full-sequence residency."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.ops.attention import attention, make_attention_mask
from llm_consensus_tpu.parallel.mesh import make_mesh
from llm_consensus_tpu.parallel.ring import ring_attention


def _qkv(key, b=2, s=32, hq=4, hkv=2, dh=16, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, hq, dh), dtype)
    k = jax.random.normal(kk, (b, s, hkv, dh), dtype)
    v = jax.random.normal(kv, (b, s, hkv, dh), dtype)
    return q, k, v


def _reference(q, k, v, sliding_window=None):
    b, s = q.shape[:2]
    pos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None, :], (b, s))
    mask = make_attention_mask(pos, pos, None, sliding_window)
    return attention(q, k, v, mask)


class TestRingAttention:
    @pytest.mark.parametrize("sp", [2, 4, 8])
    def test_matches_full_attention(self, sp):
        mesh = make_mesh({"sp": sp}, jax.devices()[:sp])
        q, k, v = _qkv(jax.random.PRNGKey(0))
        out = ring_attention(q, k, v, mesh)
        ref = _reference(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_sliding_window(self):
        mesh = make_mesh({"sp": 4}, jax.devices()[:4])
        q, k, v = _qkv(jax.random.PRNGKey(1), s=64)
        out = ring_attention(q, k, v, mesh, sliding_window=16)
        ref = _reference(q, k, v, sliding_window=16)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_gqa_groups(self):
        mesh = make_mesh({"sp": 4}, jax.devices()[:4])
        q, k, v = _qkv(jax.random.PRNGKey(2), hq=8, hkv=2)
        out = ring_attention(q, k, v, mesh)
        ref = _reference(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_bf16_inputs(self):
        mesh = make_mesh({"sp": 4}, jax.devices()[:4])
        q, k, v = _qkv(jax.random.PRNGKey(3), dtype=jnp.bfloat16)
        out = ring_attention(q, k, v, mesh)
        ref = _reference(q, k, v)
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            rtol=5e-2, atol=5e-2,
        )

    def test_rejects_indivisible_sequence(self):
        mesh = make_mesh({"sp": 8}, jax.devices()[:8])
        q, k, v = _qkv(jax.random.PRNGKey(4), s=36)
        with pytest.raises(ValueError, match="not divisible"):
            ring_attention(q, k, v, mesh)

    def test_jit_under_mesh(self):
        mesh = make_mesh({"sp": 4}, jax.devices()[:4])
        q, k, v = _qkv(jax.random.PRNGKey(5))
        jitted = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))
        np.testing.assert_allclose(
            np.asarray(jitted(q, k, v)), np.asarray(_reference(q, k, v)),
            rtol=1e-5, atol=1e-5,
        )

    def test_logit_softcap_matches_full_attention(self):
        # Gemma-family softcap must survive the ring path (it changes
        # scores pre-softmax, so omitting it silently diverges).
        mesh = make_mesh({"sp": 4}, jax.devices()[:4])
        q, k, v = _qkv(jax.random.PRNGKey(6))
        out = ring_attention(q, k, v, mesh, logit_softcap=30.0)
        b, s = q.shape[:2]
        pos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None, :], (b, s))
        mask = make_attention_mask(pos, pos, None, None)
        ref = attention(q, k, v, mask, logit_softcap=30.0)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
