"""Weight-only int8 quantization (ops/quant.py).

TPU-build extension — no reference analog (SURVEY.md §2: the reference's
compute is remote HTTP). Decode streams weights from HBM every step, so
int8 storage halves the bandwidth bound; these tests pin the numerics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.engine import Engine, SamplingParams
from llm_consensus_tpu.models import get_config, init_params
from llm_consensus_tpu.ops.quant import _quantize_leaf, qeinsum, quantize_params
from llm_consensus_tpu.parallel.mesh import make_mesh


def test_quantize_leaf_error_bound():
    """Per-element dequant error ≤ half a quantization step (scale/2)."""
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 32), jnp.float32)
    q = _quantize_leaf(w.copy())
    deq = q["q8"].astype(jnp.float32) * q["s"].astype(jnp.float32)
    err = jnp.abs(deq - w)
    assert jnp.all(err <= q["s"].astype(jnp.float32) / 2 + 1e-7)


def test_qeinsum_exact_on_representable_weights():
    """Weights that are exact int8 multiples of the per-channel scale must
    survive quantize → qeinsum bit-for-bit (fp32)."""
    key = jax.random.PRNGKey(1)
    q_int = jax.random.randint(key, (16, 8), -127, 128).astype(jnp.float32)
    q_int = q_int.at[0, :].set(127.0)  # pin every channel's max to 127
    w = q_int * 0.01
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 16), jnp.float32)
    qw = _quantize_leaf(w.copy())
    np.testing.assert_array_equal(qw["q8"], q_int.astype(jnp.int8))
    # rtol covers the (sum·s) vs (sum of ·s) reassociation and 0.01 not
    # being binary-exact; the int8 codes themselves matched exactly above.
    np.testing.assert_allclose(
        qeinsum("nd,df->nf", x, qw), jnp.einsum("nd,df->nf", x, w),
        rtol=1e-4, atol=1e-5,
    )


def test_quantize_params_covers_matmuls_only():
    cfg = get_config("tiny-mixtral")
    params = quantize_params(init_params(cfg, jax.random.PRNGKey(0)))
    layers = params["layers"]
    for name in ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"):
        assert "q8" in layers[name] and layers[name]["q8"].dtype == jnp.int8
    # Router, norms, embeddings stay high-precision.
    assert not isinstance(layers["w_router"], dict)
    assert not isinstance(layers["attn_norm"], dict)
    assert not isinstance(params["embed"], dict)


def test_quant_engine_generates():
    cfg = get_config("tiny-llama")
    e = Engine(cfg, dtype=jnp.float32, max_seq=128, quant="int8")
    r = e.generate("hello world", SamplingParams(max_new_tokens=8, ignore_eos=True))
    assert len(r.token_ids) == 8


def test_quant_moe_engine_generates():
    cfg = get_config("tiny-mixtral")
    e = Engine(cfg, dtype=jnp.float32, max_seq=128, quant="int8")
    r = e.generate("hello world", SamplingParams(max_new_tokens=8, ignore_eos=True))
    assert len(r.token_ids) == 8


def test_quant_logits_close_to_full_precision():
    """8-bit weight error on a 2-layer tiny model must not blow up: logits
    stay within a small absolute band of the fp32 model's."""
    from llm_consensus_tpu.models import forward

    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(3), dtype=jnp.float32)
    qparams = quantize_params(jax.tree.map(lambda x: x.copy(), params))
    tokens = jnp.arange(16, dtype=jnp.int32)[None, :] % cfg.vocab_size
    ref, _ = forward(params, cfg, tokens, None)
    quant, _ = forward(qparams, cfg, tokens, None)
    scale = jnp.maximum(jnp.max(jnp.abs(ref)), 1.0)
    assert jnp.max(jnp.abs(quant - ref)) / scale < 0.05


def test_quant_engine_does_not_consume_caller_params():
    """Caller-supplied params must survive building a quantized engine —
    donation is restricted to engine-created trees."""
    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(9), dtype=jnp.float32)
    Engine(cfg, params, dtype=jnp.float32, max_seq=64, quant="int8")
    baseline = Engine(cfg, params, dtype=jnp.float32, max_seq=64)
    r = baseline.generate("still alive", SamplingParams(max_new_tokens=4, ignore_eos=True))
    assert len(r.token_ids) == 4


def test_quant_explicit_off_ignores_env(monkeypatch):
    """quant='bf16' is an explicit off-switch even with LLMC_QUANT=int8 in
    the environment (bench.py relies on this for honest records)."""
    monkeypatch.setenv("LLMC_QUANT", "int8")
    cfg = get_config("tiny-llama")
    e = Engine(cfg, dtype=jnp.float32, max_seq=64, quant="bf16")
    assert e.quant is None
    assert not isinstance(e.params["layers"]["wq"], dict)


def test_quant_invalid_mode_fails_fast():
    with pytest.raises(ValueError, match="unknown quant mode"):
        Engine(get_config("tiny-llama"), dtype=jnp.float32, quant="int2")


def test_quant_sharded_matches_unsharded():
    """int8 + TP sharding compose: same quantized weights on a tp=2 mesh
    must produce identical greedy tokens (placement is not numerics)."""
    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(5), dtype=jnp.float32)
    base = Engine(cfg, jax.tree.map(lambda x: x.copy(), params),
                  dtype=jnp.float32, max_seq=128, quant="int8")
    mesh = make_mesh({"dp": 1, "tp": 2}, jax.devices()[:2])
    sharded = Engine(cfg, jax.tree.map(lambda x: x.copy(), params),
                     dtype=jnp.float32, max_seq=128, mesh=mesh, quant="int8")
    s = SamplingParams(max_new_tokens=12, ignore_eos=True)
    prompt = "compare tensor and pipeline parallelism"
    assert sharded.generate(prompt, s).token_ids == base.generate(prompt, s).token_ids


# -- int8 KV cache -----------------------------------------------------------


def test_kv_roundtrip_error_bound():
    """Quantize-on-write into the stacked cache (kv_write_rows), read back
    through kv_layer/kv_read: per-element error ≤ half a row's scale step."""
    from llm_consensus_tpu.ops.quant import kv_layer, kv_read, kv_write_rows
    from llm_consensus_tpu.models import get_config, init_kv_cache

    cfg = get_config("tiny-llama")
    cache = init_kv_cache(cfg, batch=1, max_seq=32, dtype=jnp.float32, quant="int8")
    k = jax.random.normal(
        jax.random.PRNGKey(0), (1, 8, cfg.n_kv_heads, cfg.head_dim), jnp.float32
    )
    layer = jnp.asarray(1, jnp.int32)
    full = kv_write_rows(cache["k"], k, layer, 4)  # write layer 1, pos 4
    out = kv_read(kv_layer(full, layer), jnp.float32)[:, 4:12]
    scale = jnp.max(jnp.abs(k), axis=-1, keepdims=True) / 127.0
    assert jnp.all(jnp.abs(out - k) <= scale / 2 + 1e-7)
    # Other layers stay untouched (zeros).
    assert jnp.all(kv_read(kv_layer(full, jnp.asarray(0, jnp.int32)), jnp.float32) == 0)


def test_kv_quant_engine_logits_close():
    """int8 KV must track the bf16-cache model closely on a short greedy
    run — same first token, logits within a small band."""
    from llm_consensus_tpu.models import forward, get_config, init_kv_cache, init_params

    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(3), dtype=jnp.float32)
    tokens = jnp.arange(24, dtype=jnp.int32)[None, :] % cfg.vocab_size
    ref, _ = forward(
        params, cfg, tokens,
        init_kv_cache(cfg, batch=1, max_seq=64, dtype=jnp.float32), start_pos=0,
    )
    quant, _ = forward(
        params, cfg, tokens,
        init_kv_cache(cfg, batch=1, max_seq=64, dtype=jnp.float32, quant="int8"),
        start_pos=0,
    )
    scale = jnp.maximum(jnp.max(jnp.abs(ref)), 1.0)
    assert jnp.max(jnp.abs(quant - ref)) / scale < 0.05
    assert jnp.argmax(quant[0, -1]) == jnp.argmax(ref[0, -1])


def test_kv_quant_engine_generates_and_composes_with_weight_quant():
    cfg = get_config("tiny-llama")
    e = Engine(cfg, dtype=jnp.float32, max_seq=128, quant="int8", kv_quant="int8")
    r = e.generate("hello kv cache", SamplingParams(max_new_tokens=8, ignore_eos=True))
    assert len(r.token_ids) == 8


def test_kv_quant_chunked_prefill_runs():
    cfg = get_config("tiny-llama")
    e = Engine(cfg, dtype=jnp.float32, max_seq=128, kv_quant="int8",
               prefill_chunk=16)
    r = e.generate("x" * 60, SamplingParams(max_new_tokens=6, ignore_eos=True))
    assert len(r.token_ids) == 6


def test_kv_quant_sharded_matches_unsharded():
    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(5), dtype=jnp.float32)
    base = Engine(cfg, params, dtype=jnp.float32, max_seq=128, kv_quant="int8")
    mesh = make_mesh({"dp": 1, "tp": 2}, jax.devices()[:2])
    sharded = Engine(cfg, params, dtype=jnp.float32, max_seq=128, mesh=mesh,
                     kv_quant="int8")
    s = SamplingParams(max_new_tokens=10, ignore_eos=True)
    prompt = "sharded kv cache"
    assert sharded.generate(prompt, s).token_ids == base.generate(prompt, s).token_ids


def test_quantize_params_idempotent():
    """Passing an already-quantized tree (e.g. one engine's params into
    another engine) must be a no-op, not a crash."""
    cfg = get_config("tiny-llama")
    q1 = quantize_params(init_params(cfg, jax.random.PRNGKey(0)))
    q2 = quantize_params(q1)
    assert q2["layers"]["wq"] is q1["layers"]["wq"]


def test_engine_accepts_prequantized_params():
    cfg = get_config("tiny-llama")
    e1 = Engine(cfg, dtype=jnp.float32, max_seq=64, quant="int8")
    e2 = Engine(cfg, params=e1.params, dtype=jnp.float32, max_seq=64,
                quant="int8")
    r = e2.generate("hi", SamplingParams(max_new_tokens=4, ignore_eos=True))
    assert len(r.token_ids) == 4


# -- int4 (packed nibbles, group-wise scales) --------------------------------


def _int4_bound(q, C):
    """Per-element dequant error bound: half a step of the group's scale."""
    s = q["s"].astype(jnp.float32)
    G = s.shape[-3]
    shp = s.shape[:-3] + (G, C // G, s.shape[-1])
    return jnp.broadcast_to(s, shp).reshape(s.shape[:-3] + (C, s.shape[-1])) / 2


def test_int4_roundtrip_error_bound():
    from llm_consensus_tpu.ops.quant import _quantize4, _unpack4

    w = jax.random.normal(jax.random.PRNGKey(0), (256, 64), jnp.float32)
    q = _quantize4(w)
    assert q["q4"].shape == (2, 64, 64) and q["q4"].dtype == jnp.uint8
    deq = _unpack4(q, jnp.float32)
    assert jnp.all(jnp.abs(deq - w) <= _int4_bound(q, 256) + 1e-7)


def test_int4_odd_size_falls_back_to_per_channel():
    from llm_consensus_tpu.ops.quant import _quantize4, _unpack4

    w = jax.random.normal(jax.random.PRNGKey(1), (100, 8), jnp.float32)
    q = _quantize4(w)
    assert q["q4"].shape == (1, 50, 8)  # one group = per-channel scales
    deq = _unpack4(q, jnp.float32)
    assert jnp.all(jnp.abs(deq - w) <= _int4_bound(q, 100) + 1e-7)


def test_int4_nibble_lowering_matches_unpack():
    """The decode lowering (dot on raw nibbles + output-side offset/scale
    repair) must agree with the reference dequantize-then-dot form for
    every einsum spec the model uses."""
    from llm_consensus_tpu.ops.quant import (
        _int4_nibble_einsum, _quantize4, _unpack4)

    with jax.default_matmul_precision("highest"):
        w = jax.random.normal(jax.random.PRNGKey(2), (256, 64), jnp.float32)
        q = _quantize4(w)
        deq = _unpack4(q, jnp.float32)
        x = jax.random.normal(jax.random.PRNGKey(3), (4, 256), jnp.float32)
        np.testing.assert_allclose(
            _int4_nibble_einsum("nd,df->nf", x, q),
            jnp.einsum("nd,df->nf", x, deq), rtol=2e-3, atol=2e-3)
        x2 = jax.random.normal(jax.random.PRNGKey(4), (2, 3, 256), jnp.float32)
        np.testing.assert_allclose(
            _int4_nibble_einsum("...d,df->...f", x2, q),
            jnp.einsum("...d,df->...f", x2, deq), rtol=2e-3, atol=2e-3)
        wm = jax.random.normal(jax.random.PRNGKey(5), (4, 256, 32), jnp.float32)
        qm = _quantize4(wm)
        dm = _unpack4(qm, jnp.float32)
        xm = jax.random.normal(jax.random.PRNGKey(6), (4, 2, 256), jnp.float32)
        np.testing.assert_allclose(
            _int4_nibble_einsum("ecd,edf->ecf", xm, qm),
            jnp.einsum("ecd,edf->ecf", xm, dm), rtol=2e-3, atol=2e-3)


def test_int4_nibble_honors_preferred_element_type():
    from llm_consensus_tpu.ops.quant import _int4_nibble_einsum, _quantize4

    w = jax.random.normal(jax.random.PRNGKey(7), (256, 16), jnp.float32)
    q = _quantize4(w)
    x = jax.random.normal(jax.random.PRNGKey(8), (1, 256), jnp.bfloat16)
    y = _int4_nibble_einsum(
        "nd,dv->nv", x, q, preferred_element_type=jnp.float32)
    assert y.dtype == jnp.float32


def test_int4_qeinsum_wide_rows_use_unpack_path():
    """Above the row bound qeinsum takes the prefill form; both must agree."""
    from llm_consensus_tpu.ops.quant import _quantize4, _unpack4, qeinsum

    with jax.default_matmul_precision("highest"):
        w = jax.random.normal(jax.random.PRNGKey(9), (256, 64), jnp.float32)
        q = _quantize4(w)
        deq = _unpack4(q, jnp.float32)
        xl = jax.random.normal(jax.random.PRNGKey(10), (32, 256), jnp.float32)
        np.testing.assert_allclose(
            qeinsum("nd,df->nf", xl, q),
            jnp.einsum("nd,df->nf", xl, deq), rtol=2e-3, atol=2e-3)


def test_int4_engine_generates():
    cfg = get_config("tiny-llama")
    e = Engine(cfg, dtype=jnp.float32, max_seq=128, quant="int4")
    r = e.generate("hello world", SamplingParams(max_new_tokens=8, ignore_eos=True))
    assert len(r.token_ids) == 8


def test_int4_moe_engine_generates():
    cfg = get_config("tiny-mixtral")
    e = Engine(cfg, dtype=jnp.float32, max_seq=128, quant="int4")
    r = e.generate("hello world", SamplingParams(max_new_tokens=8, ignore_eos=True))
    assert len(r.token_ids) == 8


def test_int4_logits_close_to_full_precision():
    """4-bit quantized logits stay bounded relative to fp32's. The band is
    wide: tiny-llama's 128-dim contractions make group-128 scales
    effectively per-channel, the worst case for int4 (real-model dims get
    ≥16 groups per contraction)."""
    from llm_consensus_tpu.models import forward

    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(3), dtype=jnp.float32)
    qparams = quantize_params(
        jax.tree.map(lambda x: x.copy(), params), mode="int4")
    tokens = jnp.arange(16, dtype=jnp.int32)[None, :] % cfg.vocab_size
    ref, _ = forward(params, cfg, tokens, None)
    quant, _ = forward(qparams, cfg, tokens, None)
    scale = jnp.maximum(jnp.max(jnp.abs(ref)), 1.0)
    assert jnp.max(jnp.abs(quant - ref)) / scale < 0.6


def test_int4_prefix_decode_consistency():
    """Greedy decode with int4 weights is deterministic across generates
    (prefill path and decode path share the same quantized weights)."""
    cfg = get_config("tiny-llama")
    e = Engine(cfg, dtype=jnp.float32, max_seq=128, quant="int4")
    s = SamplingParams(max_new_tokens=12, ignore_eos=True)
    a = e.generate("determinism check", s).token_ids
    b = e.generate("determinism check", s).token_ids
    assert a == b


def test_w8a8_qeinsum_close_to_int8_reference(monkeypatch):
    """LLMC_W8A8=1 routes int8-weight einsums through int8×int8 dots with
    per-row activation scales; the result must track the bf16-activation
    quantized path within the activation-rounding band, for the dense,
    batched, and MoE-expert spec shapes."""
    from llm_consensus_tpu.ops.quant import _quantize, qeinsum

    key = jax.random.PRNGKey(0)
    cases = [
        ("btd,dk->btk", (2, 3, 64), (64, 32)),
        ("...d,df->...f", (5, 64), (64, 48)),
        ("ecd,edf->ecf", (4, 6, 64), (4, 64, 32)),
    ]
    for spec, xs, ws in cases:
        kx, kw, key = jax.random.split(key, 3)
        x = jax.random.normal(kx, xs, jnp.float32)
        w = _quantize(jax.random.normal(kw, ws, jnp.float32))
        ref = qeinsum(spec, x, w)
        monkeypatch.setenv("LLMC_W8A8", "1")
        got = qeinsum(spec, x, w)
        monkeypatch.setenv("LLMC_W8A8", "0")
        scale = float(jnp.maximum(jnp.max(jnp.abs(ref)), 1.0))
        err = float(jnp.max(jnp.abs(got - ref))) / scale
        assert err < 0.05, (spec, err)


def test_w8a8_engine_generates_deterministically(monkeypatch):
    """The full engine under LLMC_W8A8=1: generation runs, is finite, and
    greedy decode is deterministic (the flag is engine-global, so every
    path shares the same quantized-activation numerics). The flag is
    resolved at engine build into a STATIC program arg — an engine built
    with it off in the same process must not be served by (or serve) the
    w8a8 executables out of the jit cache."""
    monkeypatch.setenv("LLMC_W8A8", "1")
    cfg = get_config("tiny-llama")
    e = Engine(cfg, dtype=jnp.float32, max_seq=128, quant="int8")
    assert e.w8a8 is True
    s = SamplingParams(max_new_tokens=10, ignore_eos=True)
    a = e.generate("w8a8 determinism check", s).token_ids
    b = e.generate("w8a8 determinism check", s).token_ids
    assert len(a) == 10
    assert a == b
    monkeypatch.setenv("LLMC_W8A8", "0")
    plain = Engine(cfg, dtype=jnp.float32, max_seq=128, quant="int8")
    assert plain.w8a8 is False
    c = plain.generate("w8a8 determinism check", s).token_ids
    assert len(c) == 10


def test_w8a8_requires_int8_weights(monkeypatch):
    """bf16 and int4 engines must not claim the w8a8 lane (it only
    exists for int8 weights; the bench gates its phase the same way)."""
    monkeypatch.setenv("LLMC_W8A8", "1")
    cfg = get_config("tiny-llama")
    assert Engine(cfg, dtype=jnp.float32, max_seq=64).w8a8 is False
    assert Engine(cfg, dtype=jnp.float32, max_seq=64, quant="int4").w8a8 is False


def test_streamed_init_quantization_matches_posthoc():
    """init_params_quantized (leaf-streamed, the 8B-fits-one-chip path)
    must produce EXACTLY the tree quantize_params(init_params(...))
    does — same key sequence, same per-leaf quantizer."""
    import numpy as np

    from llm_consensus_tpu.models import get_config, init_params
    from llm_consensus_tpu.ops.quant import (
        init_params_quantized, quantize_params)

    cfg = get_config("tiny-llama")
    a = init_params_quantized(cfg, jax.random.PRNGKey(3))
    b = quantize_params(
        init_params(cfg, jax.random.PRNGKey(3)), donate=True
    )
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y)
        ), a, b,
    )


def test_streamed_init_int4_matches_posthoc():
    import numpy as np

    from llm_consensus_tpu.models import get_config, init_params
    from llm_consensus_tpu.ops.quant import (
        init_params_quantized, quantize_params)

    cfg = get_config("tiny-llama")
    a = init_params_quantized(cfg, jax.random.PRNGKey(5), mode="int4")
    b = quantize_params(
        init_params(cfg, jax.random.PRNGKey(5)), donate=True, mode="int4"
    )
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y)
        ), a, b,
    )


def test_streamed_init_on_one_device_mesh_matches_unmeshed():
    """The provider's planner pins even 1-chip engines to a mesh; the
    streamed init-quantization path must engage there too (round-4 8B
    ladder OOM: init→shard→quantize materialized the full bf16 tree)
    and produce the same greedy tokens as the unmeshed engine."""
    from llm_consensus_tpu.engine import Engine, SamplingParams
    from llm_consensus_tpu.models import get_config
    from llm_consensus_tpu.ops.quant import is_quantized
    from llm_consensus_tpu.parallel.mesh import make_mesh

    cfg = get_config("tiny-llama")
    mesh = make_mesh({"dp": 1, "tp": 1}, jax.devices()[:1])
    a = Engine(cfg, quant="int8", max_seq=128, stream_interval=8, mesh=mesh)
    b = Engine(cfg, quant="int8", max_seq=128, stream_interval=8)
    assert is_quantized(a.params["layers"]["w_gate"])
    s = SamplingParams(max_new_tokens=10, ignore_eos=True)
    pa = a.generate("one device mesh streamed init prompt", s)
    pb = b.generate("one device mesh streamed init prompt", s)
    assert pa.token_ids == pb.token_ids
