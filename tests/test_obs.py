"""Run telemetry (llm_consensus_tpu/obs/): recorder semantics, Chrome
trace export, multihost merge, and the zero-overhead-when-disabled
contract.

The recorder follows the faults-package binding pattern (resolve once,
bind at construction), so these tests install/reset the process recorder
explicitly and verify that consumers built while telemetry is OFF never
touch a recorder installed later — the whole cost of a disabled run is
the bound None-check.
"""

from __future__ import annotations

import json
import threading

import pytest

from llm_consensus_tpu import faults, obs
from llm_consensus_tpu.obs import export as obs_export
from llm_consensus_tpu.obs.multihost import merge_timelines


@pytest.fixture(autouse=True)
def _clean_obs_state():
    """Process-wide recorder/fault state must never leak across tests."""
    obs.reset()
    faults.reset()
    yield
    obs.reset()
    faults.reset()
    from llm_consensus_tpu.parallel import multicontroller as mc

    mc.reset_degraded()


# -- recorder ----------------------------------------------------------------


def test_recorder_concurrent_writers_lose_nothing():
    """N threads × M events each: every event and counter increment lands,
    and each thread's own events keep their program order (appends happen
    under one lock; the per-thread subsequence is the thread's call
    order)."""
    rec = obs.Recorder()
    n_threads, n_events = 8, 200

    def writer(tid: int) -> None:
        for i in range(n_events):
            t0 = rec.now()
            rec.complete(f"span-{tid}", t0, tid=f"w{tid}", i=i)
            rec.count("total")
            rec.count(f"per-{tid}")

    threads = [
        threading.Thread(target=writer, args=(t,)) for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    events = rec.events()
    assert len(events) == n_threads * n_events
    counters = rec.counters()
    assert counters["total"] == n_threads * n_events
    for t in range(n_threads):
        mine = [e for e in events if e.tid == f"w{t}"]
        assert [e.args["i"] for e in mine] == list(range(n_events))
        assert counters[f"per-{t}"] == n_events
    assert rec.dropped == 0


def test_recorder_bounds_memory_and_counts_drops():
    rec = obs.Recorder(max_events=10)
    for i in range(25):
        rec.instant("e", tid="t", i=i)
    # 10 recorded + the ONE-TIME events_dropped warning instant (one
    # event past the cap, so a truncated timeline says so on its face).
    events = rec.events()
    assert len(events) == 11
    warnings_ = [e for e in events if e.name == "events_dropped"]
    assert len(warnings_) == 1 and warnings_[0].tid == "obs"
    assert rec.dropped == 15
    # Drop accounting surfaces as a counter too (metrics.json/metricsz).
    assert rec.counters()["obs.dropped_events"] == 15
    rec.clear()
    rec.instant("e", tid="t")
    assert rec.dropped == 0 and len(rec.events()) == 1


def test_span_context_manager_records_on_exception():
    rec = obs.Recorder()
    with pytest.raises(ValueError):
        with rec.span("doomed", tid="t"):
            raise ValueError("boom")
    assert rec.span_names() == {"doomed"}


# -- Chrome trace export -----------------------------------------------------


def test_chrome_trace_export_golden():
    """The exported document is valid trace-event JSON: metadata names the
    process and every subsystem row, spans carry ``dur``, instants carry a
    scope, and the timeline is rebased to zero."""
    rec = obs.Recorder()
    t0 = rec.now()
    rec.complete("prefill", t0, tid="engine", tokens=7)
    rec.complete("decode", rec.now(), tid="batcher", steps=4)
    rec.instant("fault:decode_fault", tid="faults", site="decode")

    doc = obs_export.local_trace(rec, pid=3)
    # Round-trips as JSON (what Perfetto loads).
    doc = json.loads(json.dumps(doc))
    events = doc["traceEvents"]
    assert isinstance(events, list)

    meta = [e for e in events if e["ph"] == "M"]
    assert {"process_name"} == {
        e["name"] for e in meta if e["tid"] == 0
    }
    thread_names = {
        e["args"]["name"] for e in meta if e["name"] == "thread_name"
    }
    assert thread_names == {"engine", "batcher", "faults"}

    spans = [e for e in events if e["ph"] == "X"]
    assert {e["name"] for e in spans} == {"prefill", "decode"}
    assert all(e["pid"] == 3 and e["dur"] >= 0 for e in spans)
    assert obs_export.trace_span_names(doc) == {"prefill", "decode"}

    instants = [e for e in events if e["ph"] == "i"]
    assert instants[0]["s"] == "t"
    assert instants[0]["args"]["site"] == "decode"

    # Rebased: the earliest event sits at ts == 0.
    assert min(e["ts"] for e in spans + instants) == 0.0


def test_metrics_summary_aggregates_counters():
    rec = obs.Recorder()
    rec.count("decode_tokens", 100)
    rec.count("decode_s", 4.0)
    rec.count("mfu_weighted_tokens", 100 * 0.5)
    rec.count("mfu_tokens", 100)
    m = obs_export.metrics_summary(
        rec, batcher_stats={"tiny": {"decode_tokens": 100}},
        fault_trace=["decode#1[]->-"], failed_models=["m"],
    )
    # No decode spans recorded → falls back to the summed decode walls.
    assert m["aggregate"]["tokens_per_sec"] == pytest.approx(25.0)
    assert m["aggregate"]["mfu"] == pytest.approx(0.5)
    assert m["batchers"]["tiny"]["decode_tokens"] == 100
    assert m["faults"] == ["decode#1[]->-"]
    assert m["failed_models"] == ["m"]
    json.dumps(m)


def test_aggregate_throughput_uses_union_window_not_summed_walls():
    """Concurrent streams overlap their decode windows: the pool rate
    divides by the union window spanned by the decode/fetch spans, not
    the sum of per-stream walls (which would understate the pool by the
    concurrency factor)."""
    from llm_consensus_tpu.obs.recorder import Event

    rec = obs.Recorder()
    # Four streams, each "100 tokens in 2s", all in the SAME 2s window.
    base = rec.now()
    for _ in range(4):
        rec.count("decode_tokens", 100)
        rec.count("decode_s", 2.0)
    rec._events.append(Event(
        name="decode", ph="X", ts_ns=base, tid="batcher",
        dur_ns=1_000_000_000,
    ))
    rec._events.append(Event(
        name="fetch", ph="X", ts_ns=base + 1_000_000_000, tid="batcher",
        dur_ns=1_000_000_000,
    ))
    agg = obs_export.aggregate_throughput(rec)
    # 400 tokens over the 2s union window = 200 tok/s; the summed-wall
    # form would report 400/8 = 50.
    assert agg["tokens_per_sec"] == pytest.approx(200.0)
    assert agg["window_s"] == pytest.approx(2.0)


def test_aggregate_mfu_ignores_mfu_less_tokens():
    """A model whose chip reports no MFU contributes tokens to the pool
    rate but must not dilute the MFU mean."""
    rec = obs.Recorder()
    rec.count("decode_tokens", 100)      # model A: mfu 0.5
    rec.count("mfu_weighted_tokens", 50)
    rec.count("mfu_tokens", 100)
    rec.count("decode_tokens", 100)      # model B: no known peak
    rec.count("decode_s", 4.0)
    agg = obs_export.aggregate_throughput(rec)
    assert agg["mfu"] == pytest.approx(0.5)


def test_recorder_clear_empties_in_place():
    rec = obs.Recorder(max_events=1)
    rec.instant("a", tid="t")
    rec.instant("b", tid="t")  # dropped (cap 1)
    rec.count("c", 2.0)
    assert rec.dropped == 1
    rec.clear()
    assert rec.events() == [] and rec.counters() == {} and rec.dropped == 0
    rec.instant("d", tid="t")
    assert len(rec.events()) == 1


# -- multihost merge ---------------------------------------------------------


@pytest.mark.faults
def test_multihost_merge_with_degraded_peer():
    """A controller that never reaches the timeline exchange costs its
    timeline, not the merge: the survivors' events still produce a
    loadable trace and the missing peer is reported."""
    faults.install(faults.FaultPlan("controller_drop@host=1", seed=5))
    from llm_consensus_tpu.parallel import multicontroller as mc

    mc.reset_degraded()
    rec = obs.Recorder()
    obs.install(rec)
    rec.complete("prefill", rec.now(), tid="engine")

    doc, missing = merge_timelines(rec, timeout=2.0)
    assert missing == [1]
    assert mc.degraded_peers() == frozenset({1})
    # Survivor-only merge: every real event belongs to process 0 and the
    # local spans survive.
    pids = {e["pid"] for e in doc["traceEvents"]}
    assert pids == {0}
    assert "prefill" in obs_export.trace_span_names(doc)
    # The exchange itself still recorded its allgather span (it lands
    # after the snapshot the merge shipped, so on the recorder, not in
    # this doc).
    assert "allgather" in rec.span_names()
    json.dumps(doc)


def test_multihost_merge_single_process_is_local_identity():
    rec = obs.Recorder()
    obs.install(rec)
    rec.complete("decode", rec.now(), tid="engine")
    doc, missing = merge_timelines(rec, timeout=2.0)
    assert missing == []
    assert obs_export.trace_span_names(doc) == {"decode"}
    # The exchange recorded its own span after snapshotting the events.
    assert "allgather" in rec.span_names()


# -- zero overhead when disabled ---------------------------------------------


def test_engine_hot_loops_consult_only_bound_none(monkeypatch):
    """An engine built with telemetry off binds None ONCE; a recorder
    installed afterwards must see nothing from its decode/fetch loops —
    the disabled hot path touches no recorder state."""
    monkeypatch.delenv("LLMC_EVENTS", raising=False)
    obs.reset()
    from llm_consensus_tpu.engine import Engine, SamplingParams
    from llm_consensus_tpu.models import get_config

    engine = Engine(get_config("tiny-llama"), stream_interval=4)
    assert engine._obs is None
    late = obs.Recorder()
    obs.install(late)
    out = engine.generate(
        "quiet run", SamplingParams(max_new_tokens=12, ignore_eos=True)
    )
    assert len(out.token_ids) == 12
    assert late.events() == []
    assert late.counters() == {}


def test_batcher_binds_recorder_at_construction(monkeypatch):
    monkeypatch.delenv("LLMC_EVENTS", raising=False)
    obs.reset()
    from llm_consensus_tpu.engine import ContinuousBatcher, Engine, SamplingParams
    from llm_consensus_tpu.models import get_config

    engine = Engine(get_config("tiny-llama"), stream_interval=4)
    batcher = ContinuousBatcher(engine, max_batch=2)
    try:
        assert batcher._obs is None
        late = obs.Recorder()
        obs.install(late)
        fut = batcher.submit(
            "quiet pool", SamplingParams(max_new_tokens=8, ignore_eos=True)
        )
        assert len(fut.result(timeout=120).token_ids) == 8
        assert late.events() == []
    finally:
        batcher.close()


def test_enabled_engine_records_required_spans():
    rec = obs.Recorder()
    obs.install(rec)
    from llm_consensus_tpu.engine import Engine, SamplingParams
    from llm_consensus_tpu.models import get_config

    engine = Engine(get_config("tiny-llama"), stream_interval=4)
    engine.generate(
        "loud run", SamplingParams(max_new_tokens=12, ignore_eos=True)
    )
    assert {"prefill", "decode", "fetch"} <= rec.span_names()


def test_enabled_batcher_records_admit_and_decode_spans():
    rec = obs.Recorder()
    obs.install(rec)
    from llm_consensus_tpu.engine import ContinuousBatcher, Engine, SamplingParams
    from llm_consensus_tpu.models import get_config

    engine = Engine(get_config("tiny-llama"), stream_interval=4)
    batcher = ContinuousBatcher(engine, max_batch=2)
    try:
        fut = batcher.submit(
            "loud pool", SamplingParams(max_new_tokens=8, ignore_eos=True)
        )
        assert len(fut.result(timeout=120).token_ids) == 8
    finally:
        batcher.close()
    batcher_spans = {
        e.name for e in rec.events() if e.ph == "X" and e.tid == "batcher"
    }
    assert {"admit", "decode", "fetch"} <= batcher_spans
    snap = batcher.snapshot()
    assert isinstance(snap, dict) and "decode_tokens" in snap


@pytest.mark.faults
def test_fault_fire_lands_instant_on_timeline():
    rec = obs.Recorder()
    obs.install(rec)
    plan = faults.FaultPlan("decode_fault@step=2", seed=1)
    assert plan.fire("decode") is None
    assert plan.fire("decode") is not None
    instants = [e for e in rec.events() if e.ph == "i"]
    assert [e.name for e in instants] == ["fault:decode_fault"]
    assert instants[0].args["site"] == "decode"
    assert instants[0].args["n"] == 2
