"""Tests for the model-registry-sync tool.

Coverage model: the reference ships the sync binary untested; SURVEY.md §4
calls out provider-level tests against a fake HTTP server as missing
coverage the new build owes. These tests run the real fetchers against a
local ``http.server`` — no network.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from llm_consensus_tpu.tools.registry_sync import (
    ModelRecord,
    SourceError,
    fetch_local_models,
    fetch_openai_models,
    fetch_openrouter_models,
    main,
    render,
    sync,
)

OPENAI_PAYLOAD = {
    "object": "list",
    "data": [
        {"id": "gpt-b", "object": "model", "owned_by": "openai"},
        {"id": "gpt-a", "object": "model", "owned_by": "openai"},
    ],
}

OPENROUTER_PAYLOAD = {
    "data": [
        {
            "id": "meta/llama-3-8b",
            "name": "Llama 3 8B",
            "context_length": 8192,
            "pricing": {"prompt": "0.0000001", "completion": 0.0000002},
        },
        {"id": "no/extras"},
        {"id": None, "name": "null id — must be dropped, not become 'None'"},
        {"id": "junk/nonfinite", "name": None, "context_length": float("inf")},
    ]
}


class _Handler(BaseHTTPRequestHandler):
    behavior = "ok"  # ok | error | malformed

    def do_GET(self):  # noqa: N802 (stdlib API)
        if self.behavior == "error":
            self.send_response(500)
            self.end_headers()
            self.wfile.write(b"boom")
            return
        if self.behavior == "malformed":
            body = b"not json {"
        elif "openrouter" in self.path or self.headers.get("X-Flavor") == "openrouter":
            body = json.dumps(OPENROUTER_PAYLOAD).encode()
        else:
            body = json.dumps(OPENAI_PAYLOAD).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):  # silence test output
        pass


@pytest.fixture
def server():
    class H(_Handler):
        pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        yield H, f"http://127.0.0.1:{srv.server_address[1]}"
    finally:
        srv.shutdown()
        srv.server_close()


def test_openai_fetch_and_normalize(server):
    _, base = server
    recs = fetch_openai_models(base_url=base, api_key="k")
    assert [r.id for r in recs] == ["gpt-b", "gpt-a"]
    assert all(r.source == "openai" for r in recs)
    assert recs[0].raw["owned_by"] == "openai"


def test_openai_requires_key(server, monkeypatch):
    _, base = server
    monkeypatch.delenv("OPENAI_API_KEY", raising=False)
    with pytest.raises(SourceError, match="OPENAI_API_KEY"):
        fetch_openai_models(base_url=base)


def test_openrouter_fetch_normalizes_context_and_pricing(server):
    _, base = server
    recs = fetch_openrouter_models(base_url=base + "/openrouter", api_key="")
    assert recs[0].context_length == 8192
    # pricing values normalized to strings regardless of feed type
    assert recs[0].pricing == {"prompt": "0.0000001", "completion": "2e-07"}
    assert recs[1].context_length is None and recs[1].pricing is None


def test_openrouter_junk_entries_are_sanitized(server):
    """Null ids are dropped (never the literal "None"); non-finite
    context_length (json accepts Infinity/NaN) degrades to None instead of
    raising past sync()'s per-source isolation."""
    _, base = server
    recs = fetch_openrouter_models(base_url=base + "/openrouter", api_key="")
    assert [r.id for r in recs] == ["meta/llama-3-8b", "no/extras", "junk/nonfinite"]
    junk = recs[2]
    assert junk.context_length is None
    assert junk.name == ""


def test_http_error_is_source_error(server):
    H, base = server
    H.behavior = "error"
    with pytest.raises(SourceError, match="status 500"):
        fetch_openai_models(base_url=base, api_key="k")


def test_malformed_json_is_source_error(server):
    H, base = server
    H.behavior = "malformed"
    with pytest.raises(SourceError, match="invalid JSON"):
        fetch_openai_models(base_url=base, api_key="k")


def test_local_source_covers_every_preset():
    from llm_consensus_tpu.models import MODEL_PRESETS

    recs = fetch_local_models()
    assert {r.name for r in recs} == set(MODEL_PRESETS)
    assert all(r.id.startswith("tpu:") and r.source == "local" for r in recs)
    assert all(r.context_length and r.raw["n_params"] > 0 for r in recs)


def test_sync_sorts_and_tolerates_partial_failure():
    def ok():
        return [ModelRecord("zz", "b"), ModelRecord("zz", "a")]

    def bad():
        raise SourceError("down")

    records, warnings = sync({"bad": bad, "zz": ok, "local": fetch_local_models})
    assert warnings == ["bad: down"]
    keys = [(r.source, r.id) for r in records]
    assert keys == sorted(keys)  # stable (source, id) ordering
    assert ("zz", "a") in keys and ("zz", "b") in keys


def test_render_raw_toggle():
    rec = ModelRecord("s", "m", raw={"secret": 1})
    assert "secret" not in render([rec], include_raw=False)
    assert "secret" in render([rec], include_raw=True)


def test_main_writes_file_and_partial_failure_exit_codes(server, tmp_path, capsys):
    _, base = server
    out = tmp_path / "models.json"
    # Remote source down (unused port), local healthy → exit 0 + warning.
    rc = main(
        [
            "--out", str(out),
            "--no-openrouter",
            "--openai-base-url", "http://127.0.0.1:9",
            "--timeout", "0.2",
        ]
    )
    assert rc == 0
    assert "warning: openai" in capsys.readouterr().err
    data = json.loads(out.read_text())
    assert all(r["source"] == "local" for r in data)

    # Every enabled source down, zero records → exit 1.
    rc = main(
        [
            "--no-local",
            "--no-openrouter",
            "--openai-base-url", "http://127.0.0.1:9",
            "--timeout", "0.2",
        ]
    )
    assert rc == 1


def test_shipped_catalog_snapshot_is_in_sync():
    """providers/models/models.json (parity: the reference's shipped
    internal/provider/models/models.json snapshot) must match what the
    local source generates today — regenerate with
    `python -m llm_consensus_tpu.tools.registry_sync --no-openai
    --no-openrouter --raw --out llm_consensus_tpu/providers/models/models.json`
    whenever a model preset changes."""
    import os

    import llm_consensus_tpu
    from llm_consensus_tpu.tools.registry_sync import fetch_local_models, render

    path = os.path.join(
        os.path.dirname(llm_consensus_tpu.__file__), "providers", "models",
        "models.json",
    )
    with open(path, encoding="utf-8") as f:
        shipped = json.load(f)
    records = sorted(fetch_local_models(), key=lambda r: (r.source, r.id))
    expected = json.loads(render(records, include_raw=True))
    assert shipped == expected
