"""Runner fan-out tests.

Ports the reference's table-driven scenarios (runner_test.go:12-105) — all
succeed, partial failure, all fail, unregistered model — plus the real-time
timeout test (runner_test.go:107-129), plus streaming/callback coverage the
reference lacks.
"""

import threading
import time

import pytest

from llm_consensus_tpu.providers import ProviderFunc, Registry, Request, Response
from llm_consensus_tpu.runner import AllModelsFailed, Callbacks, Runner
from llm_consensus_tpu.utils import Context


def ok_provider(provider_name="test"):
    return ProviderFunc(
        lambda ctx, req: Response(req.model, f"answer from {req.model}", provider_name)
    )


def err_provider(msg="provider exploded"):
    def fn(ctx, req):
        raise RuntimeError(msg)

    return ProviderFunc(fn)


def make_registry(**providers):
    r = Registry()
    for model, p in providers.items():
        r.register(model, p)
    return r


def run(registry, models, timeout=5.0, callbacks=None):
    r = Runner(registry, timeout)
    if callbacks:
        r.with_callbacks(callbacks)
    return r.run(Context.background(), models, "the prompt")


def test_all_models_succeed():
    reg = make_registry(m1=ok_provider(), m2=ok_provider(), m3=ok_provider())
    result = run(reg, ["m1", "m2", "m3"])
    assert len(result.responses) == 3
    assert result.warnings == []
    assert result.failed_models == []
    assert {r.model for r in result.responses} == {"m1", "m2", "m3"}


def test_partial_failure_is_best_effort():
    reg = make_registry(good=ok_provider(), bad=err_provider())
    result = run(reg, ["good", "bad"])
    assert len(result.responses) == 1
    assert result.responses[0].model == "good"
    assert len(result.warnings) == 1
    assert "bad" in result.warnings[0]
    assert result.failed_models == ["bad"]


def test_all_models_fail_raises():
    reg = make_registry(b1=err_provider(), b2=err_provider())
    with pytest.raises(AllModelsFailed):
        run(reg, ["b1", "b2"])


def test_unregistered_model_is_warning_not_fatal():
    # Registry miss is a per-model failure, not a run abort (runner.go:73-83).
    reg = make_registry(known=ok_provider())
    result = run(reg, ["known", "ghost"])
    assert len(result.responses) == 1
    assert result.failed_models == ["ghost"]
    assert "ghost" in result.warnings[0]


def test_only_unregistered_model_raises():
    reg = make_registry(known=ok_provider())
    with pytest.raises(AllModelsFailed):
        run(reg, ["ghost"])


def test_per_model_timeout():
    # A provider that sleeps past the runner timeout but honors cancellation
    # (runner_test.go:107-129: 100ms timeout vs 10s provider).
    def slow(ctx, req):
        ctx.sleep(10.0)
        ctx.raise_if_done()
        return Response(req.model, "too late", "slow")

    reg = make_registry(slow=ProviderFunc(slow), fast=ok_provider())
    start = time.monotonic()
    result = run(reg, ["slow", "fast"], timeout=0.1)
    elapsed = time.monotonic() - start
    assert elapsed < 5.0, "runner must not wait out the full provider sleep"
    assert [r.model for r in result.responses] == ["fast"]
    assert result.failed_models == ["slow"]


def test_parent_cancel_propagates():
    ctx = Context.background().with_cancel()
    release = threading.Event()

    def slow(c, req):
        release.set()
        c.sleep(10.0)
        c.raise_if_done()
        return Response(req.model, "late", "slow")

    reg = make_registry(slow=ProviderFunc(slow))
    r = Runner(reg, timeout=30.0)
    t = threading.Thread(target=lambda: release.wait(5) and ctx.cancel())
    t.start()
    start = time.monotonic()
    with pytest.raises(AllModelsFailed):
        r.run(ctx, ["slow"], "p")
    assert time.monotonic() - start < 5.0
    t.join()


def test_callbacks_fire_in_order():
    events = []
    lock = threading.Lock()

    def record(kind):
        def cb(model, *rest):
            with lock:
                events.append((kind, model))

        return cb

    reg = make_registry(good=ok_provider(), bad=err_provider())
    cbs = Callbacks(
        on_model_start=record("start"),
        on_model_stream=record("stream"),
        on_model_complete=record("complete"),
        on_model_error=record("error"),
    )
    run(reg, ["good", "bad"], callbacks=cbs)
    good = [k for k, m in events if m == "good"]
    bad = [k for k, m in events if m == "bad"]
    # ProviderFunc streams the full content once, so good sees start→stream→complete.
    assert good == ["start", "stream", "complete"]
    assert bad == ["start", "error"]


def test_raising_callback_recorded_as_failure():
    # A buggy caller callback must not silently lose the model from the
    # accounting (workers never raise).
    def boom(model):
        if model == "good":
            raise RuntimeError("buggy UI hook")

    reg = make_registry(good=ok_provider(), other=ok_provider())
    result = run(reg, ["good", "other"], callbacks=Callbacks(on_model_start=boom))
    assert result.failed_models == ["good"]
    assert "buggy UI hook" in result.warnings[0]
    assert [r.model for r in result.responses] == ["other"]


def test_empty_model_list_raises():
    # Zero responses is a run failure even with zero models (runner.go:122-124).
    with pytest.raises(AllModelsFailed):
        run(make_registry(), [])


def test_child_contexts_released_after_run():
    # The per-model contexts must not accumulate on the run context
    # (the analog of the reference's deferred cancel).
    ctx = Context.background()
    reg = make_registry(m=ok_provider())
    for _ in range(5):
        Runner(reg, 5.0).run(ctx, ["m"], "p")
    assert len(ctx._children) == 0


def test_child_created_during_parent_cancel_sees_cancel():
    # Race regression: a context derived concurrently with the parent's
    # cancel must still observe the cancellation.
    for _ in range(50):
        parent = Context.background().with_cancel()
        children = []

        def derive():
            children.append(parent.with_timeout(100))

        t1 = threading.Thread(target=derive)
        t2 = threading.Thread(target=parent.cancel)
        t1.start(); t2.start()
        t1.join(); t2.join()
        assert children[0].done(), "derived context missed parent cancel"


def test_truncated_response_surfaces_warning():
    from llm_consensus_tpu.providers import ProviderFunc, Registry, Response

    def fn(ctx, req):
        return Response(model=req.model, content="ok", provider="fake",
                        truncated=True)

    registry = Registry()
    registry.register("m1", ProviderFunc(fn))
    result = Runner(registry, timeout=5.0).run(Context.background(), ["m1"], "p")
    assert any("truncated" in w for w in result.warnings)
    assert result.failed_models == []


def test_concurrent_streaming_stress_no_corruption():
    """Race-detection analog (SURVEY §5: the reference is race-clean by
    mutex discipline, runner.go:54-98): 24 models streaming concurrently
    in small chunks must produce exactly their own content, with
    callbacks never interleaving across a single model's stream order."""
    registry = Registry()
    n_models = 24
    chunks_per_model = 20
    models = [f"m{i}" for i in range(n_models)]
    from llm_consensus_tpu.providers import Provider

    class ChunkStreamer(Provider):
        name = "fake"

        def __init__(self, i):
            self.i = i

        def query(self, ctx, req):
            return self.query_stream(ctx, req, None)

        def query_stream(self, ctx, req, cb):
            content = ""
            for c in range(chunks_per_model):
                piece = f"<{self.i}:{c}>"
                content += piece
                if cb is not None:
                    cb(piece)
                time.sleep(0.0005 * (self.i % 3))
            return Response(model=req.model, content=content, provider="fake")

    for i, name in enumerate(models):
        registry.register(name, ChunkStreamer(i))

    streamed: dict[str, list[str]] = {m: [] for m in models}
    lock = threading.Lock()

    def on_stream(model, chunk):
        with lock:
            streamed[model].append(chunk)

    runner = Runner(registry, timeout=30.0).with_callbacks(
        Callbacks(on_model_stream=on_stream)
    )
    result = runner.run(Context.background(), models, "stress")
    assert len(result.responses) == n_models
    assert not result.warnings and not result.failed_models
    for i, name in enumerate(models):
        expected = [f"<{i}:{c}>" for c in range(chunks_per_model)]
        assert streamed[name] == expected  # in order, nothing foreign
        resp = next(r for r in result.responses if r.model == name)
        assert resp.content == "".join(expected)
