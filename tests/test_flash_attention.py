"""Pallas flash attention vs the XLA reference (ops/attention.py).

Runs in interpret mode on the CPU test mesh (conftest pins JAX_PLATFORMS=cpu),
which executes the exact kernel program without TPU hardware.
"""

import jax
import jax.numpy as jnp
import pytest

from llm_consensus_tpu.ops.attention import attention, make_attention_mask
from llm_consensus_tpu.ops.pallas import flash_attention, flash_supported


def _reference(q, k, v, q_offset, sliding_window=None, logit_softcap=None):
    """XLA attention with the mask transformer.forward builds for a cache."""
    b, t = q.shape[0], q.shape[1]
    s = k.shape[1]
    q_pos = q_offset + jnp.arange(t, dtype=jnp.int32)[None, :]
    q_pos = jnp.broadcast_to(q_pos, (b, t))
    kv_pos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None, :], (b, s))
    kv_valid = jnp.broadcast_to((kv_pos[0] < q_offset + t)[None, :], (b, s))
    mask = make_attention_mask(q_pos, kv_pos, kv_valid, sliding_window)
    return attention(q, k, v, mask, logit_softcap=logit_softcap)


def _qkv(key, b, t, s, hq, hkv, dh, dtype):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, t, hq, dh), dtype)
    k = jax.random.normal(kk, (b, s, hkv, dh), dtype)
    v = jax.random.normal(kv, (b, s, hkv, dh), dtype)
    return q, k, v


CASES = [
    # (b, t, s, hq, hkv, dh, q_offset, window, softcap)
    (1, 64, 64, 4, 4, 32, 0, None, None),       # MHA, square
    (1, 64, 256, 4, 2, 32, 0, None, None),      # GQA, cache larger than T
    (2, 32, 128, 8, 2, 16, 0, None, None),      # batch + 4-way GQA
    (1, 32, 128, 4, 2, 32, 64, None, None),     # chunked prefill (q_offset > 0)
    (1, 64, 128, 4, 4, 32, 0, 24, None),        # sliding window
    (1, 64, 64, 4, 2, 32, 0, None, 5.0),        # logit softcap (gemma)
    (1, 48, 96, 4, 2, 32, 16, 20, 8.0),         # everything at once, ragged S
    (1, 256, 256, 4, 2, 32, 0, None, None),     # multi-kv-block: online carry
    (1, 128, 512, 4, 2, 32, 128, 96, None),     # multi-block + offset + window
]


@pytest.mark.parametrize("case", CASES)
def test_flash_matches_reference_f32(case):
    b, t, s, hq, hkv, dh, off, window, cap = case
    q, k, v = _qkv(jax.random.PRNGKey(0), b, t, s, hq, hkv, dh, jnp.float32)
    with jax.default_matmul_precision("highest"):
        got = flash_attention(
            q, k, v, q_offset=off, sliding_window=window, logit_softcap=cap,
            interpret=True,
        )
        want = _reference(q, k, v, off, window, cap)
    assert got.shape == want.shape
    assert jnp.allclose(got, want, atol=1e-5, rtol=1e-5), (
        float(jnp.abs(got - want).max())
    )


def test_flash_bf16_close_to_f32_reference():
    b, t, s, hq, hkv, dh = 1, 64, 128, 4, 2, 32
    q, k, v = _qkv(jax.random.PRNGKey(1), b, t, s, hq, hkv, dh, jnp.bfloat16)
    got = flash_attention(q, k, v, interpret=True).astype(jnp.float32)
    want = _reference(q, k, v, 0).astype(jnp.float32)
    assert got.dtype == jnp.float32
    assert jnp.allclose(got, want, atol=3e-2, rtol=3e-2), (
        float(jnp.abs(got - want).max())
    )


def test_flash_never_reads_beyond_frontier():
    """Garbage (NaN) in unwritten cache slots must not leak into the output."""
    b, t, s, hq, hkv, dh = 1, 32, 256, 4, 2, 32
    q, k, v = _qkv(jax.random.PRNGKey(2), b, t, s, hq, hkv, dh, jnp.float32)
    poison = jnp.full_like(k[:, t:], jnp.nan)
    k = k.at[:, t:].set(poison)
    v = v.at[:, t:].set(poison)
    got = flash_attention(q, k, v, interpret=True)
    assert not bool(jnp.isnan(got).any())
    want = _reference(
        q.astype(jnp.float32),
        jnp.nan_to_num(k), jnp.nan_to_num(v), 0,
    )
    assert jnp.allclose(got, want, atol=1e-5)


def test_flash_supported_gate():
    assert flash_supported(64, 8, 2)
    assert flash_supported(16, 4, 4)
    assert not flash_supported(1, 8, 2)      # decode: single row, use XLA
    assert not flash_supported(20, 8, 3)     # ragged GQA
    assert not flash_supported(6, 8, 2)      # block too small


def test_flash_under_jit_and_grad_free_path():
    """The kernel composes with jit (engine prefill jits the whole step)."""
    q, k, v = _qkv(jax.random.PRNGKey(3), 1, 32, 64, 4, 2, 16, jnp.float32)

    @jax.jit
    def f(q, k, v):
        return flash_attention(q, k, v, interpret=True)

    assert jnp.allclose(f(q, k, v), _reference(q, k, v, 0), atol=1e-5)


def test_forward_flash_matches_xla_logits():
    """Full-model prefill through the kernel == XLA masked attention."""
    from llm_consensus_tpu.models import forward, get_config, init_params, init_kv_cache

    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 32), 0, cfg.vocab_size)
    cache_a = init_kv_cache(cfg, batch=1, max_seq=128, dtype=jnp.float32)
    cache_b = init_kv_cache(cfg, batch=1, max_seq=128, dtype=jnp.float32)
    want, cache_a = forward(params, cfg, tokens, cache_a, start_pos=0)
    got, cache_b = forward(params, cfg, tokens, cache_b, start_pos=0, attn_impl="flash")
    assert jnp.allclose(got, want, atol=1e-4, rtol=1e-4)
    for side in ("k", "v"):
        assert jnp.allclose(cache_a[side], cache_b[side], atol=1e-5)


def test_engine_flash_prefill_same_tokens(monkeypatch):
    """Engine with flash prefill decodes the identical greedy sequence."""
    from llm_consensus_tpu.engine import Engine, SamplingParams
    from llm_consensus_tpu.models import get_config

    cfg = get_config("tiny-llama")
    base = Engine(cfg, dtype=jnp.float32, max_seq=128, attn_impl="xla")
    flash = Engine(
        cfg, params=base.params, dtype=jnp.float32, max_seq=128, attn_impl="flash"
    )
    sampling = SamplingParams(max_new_tokens=12, ignore_eos=True)
    prompt = "the quick brown fox jumps over the lazy dog"
    assert (
        base.generate(prompt, sampling).token_ids
        == flash.generate(prompt, sampling).token_ids
    )
