"""Sequence-parallel (ring-attention) prefill through the Engine.

The long-context path SURVEY §5/§7 calls for: the judge prompt
concatenates every panel answer, and past a slice's HBM the sequence
dim itself must shard. These tests drive the full engine path — sp
prefill assembling the decode cache, then standard decode — on the
virtual CPU mesh and pin equivalence against the unsharded engine."""

import jax
import jax.numpy as jnp

from llm_consensus_tpu.engine import Engine, SamplingParams
from llm_consensus_tpu.models import get_config, init_params
from llm_consensus_tpu.parallel.mesh import make_mesh

PROMPT = "Explain the difference between data and tensor parallelism. " * 3


def _greedy(engine, n=12):
    r = engine.generate(PROMPT, SamplingParams(max_new_tokens=n, ignore_eos=True))
    assert len(r.token_ids) == n
    return r.token_ids


def test_sp_prefill_matches_unsharded():
    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(11), dtype=jnp.float32)
    base = Engine(cfg, params, dtype=jnp.float32, max_seq=256)
    mesh = make_mesh({"sp": 2}, jax.devices()[:2])
    sp = Engine(cfg, params, dtype=jnp.float32, max_seq=256, mesh=mesh)
    assert _greedy(sp) == _greedy(base)


def test_sp_tp_prefill_matches_unsharded():
    """sp×tp compose: ring over sp with heads sharded over tp."""
    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(11), dtype=jnp.float32)
    base = Engine(cfg, params, dtype=jnp.float32, max_seq=256)
    mesh = make_mesh({"sp": 2, "tp": 2}, jax.devices()[:4])
    sp = Engine(cfg, params, dtype=jnp.float32, max_seq=256, mesh=mesh)
    assert _greedy(sp) == _greedy(base)


def test_sp_prefill_with_int8_kv_cache():
    cfg = get_config("tiny-llama")
    mesh = make_mesh({"sp": 2}, jax.devices()[:2])
    e = Engine(cfg, dtype=jnp.float32, max_seq=256, mesh=mesh, kv_quant="int8")
    assert len(_greedy(e, 8)) == 8


def test_sp_prefill_sliding_window_model():
    """Sliding-window attention (mistral family) rides the ring's
    windowed mask path."""
    cfg = get_config("tiny-mistral")
    params = init_params(cfg, jax.random.PRNGKey(13), dtype=jnp.float32)
    base = Engine(cfg, params, dtype=jnp.float32, max_seq=256)
    mesh = make_mesh({"sp": 2}, jax.devices()[:2])
    sp = Engine(cfg, params, dtype=jnp.float32, max_seq=256, mesh=mesh)
    assert _greedy(sp) == _greedy(base)


def test_ring_forward_rejects_bad_call():
    import pytest

    from llm_consensus_tpu.models import forward

    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    tokens = jnp.ones((1, 16), jnp.int32)
    with pytest.raises(ValueError, match="ring"):
        forward(params, cfg, tokens, None, start_pos=0, attn_impl="ring")


def test_sp_falls_back_when_bucket_not_divisible():
    """max_seq=250 with sp=2: a long prompt's bucket clamps to 250, which
    doesn't shard over sp — the engine must fall back to the replicated
    path rather than crash, and still match the unsharded engine."""
    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(17), dtype=jnp.float32)
    base = Engine(cfg, params, dtype=jnp.float32, max_seq=250, prefill_chunk=0)
    mesh = make_mesh({"sp": 4}, jax.devices()[:4])
    sp = Engine(cfg, params, dtype=jnp.float32, max_seq=250, mesh=mesh,
                prefill_chunk=0)
    prompt = "y" * 200  # bucket = min(256, 250) = 250, 250 % 4 != 0
    s = SamplingParams(max_new_tokens=6, ignore_eos=True)
    assert sp.generate(prompt, s).token_ids == base.generate(prompt, s).token_ids


def test_sp_with_non_dividing_tp_replicates_heads():
    """tiny-llama has Hkv=2; tp=4 can't shard heads, so the ring runs with
    heads replicated over tp instead of crashing."""
    cfg = get_config("tiny-llama")
    assert cfg.n_kv_heads % 4 != 0
    params = init_params(cfg, jax.random.PRNGKey(19), dtype=jnp.float32)
    base = Engine(cfg, params, dtype=jnp.float32, max_seq=256)
    mesh = make_mesh({"sp": 2, "tp": 4}, jax.devices()[:8])
    sp = Engine(cfg, params, dtype=jnp.float32, max_seq=256, mesh=mesh)
    assert _greedy(sp, 8) == _greedy(base, 8)
