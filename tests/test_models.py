"""Model-core tests: every family, causality, cache consistency, ops.

Runs on CPU (conftest pins JAX_PLATFORMS=cpu with an 8-device virtual mesh);
tiny configs keep compiles fast.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.models import forward, get_config, init_kv_cache, init_params
from llm_consensus_tpu.ops import rms_norm, sample_token
from llm_consensus_tpu.ops.moe import moe_block
from llm_consensus_tpu.ops.rope import apply_rope, rope_angles, rope_inv_freq

FAMILIES = ["tiny-llama", "tiny-gemma", "tiny-qwen2", "tiny-mistral", "tiny-mixtral"]


def setup_model(name, dtype=jnp.float32):
    cfg = get_config(name)
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=dtype)
    return cfg, params


@pytest.mark.parametrize("name", FAMILIES)
def test_forward_shapes_all_families(name):
    cfg, params = setup_model(name)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    logits, cache = forward(params, cfg, tokens)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    assert cache is None
    assert bool(jnp.all(jnp.isfinite(logits)))


@pytest.mark.parametrize("name", ["tiny-llama", "tiny-mistral"])
def test_causality(name):
    # Logits at position t must not depend on tokens after t.
    cfg, params = setup_model(name)
    key = jax.random.PRNGKey(2)
    tokens = jax.random.randint(key, (1, 12), 0, cfg.vocab_size)
    altered = tokens.at[0, -1].set((tokens[0, -1] + 7) % cfg.vocab_size)
    la, _ = forward(params, cfg, tokens)
    lb, _ = forward(params, cfg, altered)
    np.testing.assert_allclose(la[0, :-1], lb[0, :-1], rtol=1e-5, atol=1e-5)
    assert not np.allclose(la[0, -1], lb[0, -1])


@pytest.mark.parametrize("name", FAMILIES)
def test_cache_decode_matches_full_forward(name):
    # prefill + stepwise decode through the KV cache must reproduce the
    # no-cache forward logits — the core correctness invariant of the engine.
    cfg, params = setup_model(name)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (1, 10), 0, cfg.vocab_size)
    full_logits, _ = forward(params, cfg, tokens)

    cache = init_kv_cache(cfg, batch=1, max_seq=32, dtype=jnp.float32)
    prefill_len = 6
    logits_pre, cache = forward(params, cfg, tokens[:, :prefill_len], cache, start_pos=0)
    np.testing.assert_allclose(
        full_logits[:, :prefill_len], logits_pre, rtol=2e-4, atol=2e-4
    )
    for i in range(prefill_len, 10):
        step_logits, cache = forward(params, cfg, tokens[:, i : i + 1], cache, start_pos=i)
        np.testing.assert_allclose(
            full_logits[:, i : i + 1], step_logits, rtol=2e-4, atol=2e-4
        )


def test_sliding_window_masks_far_tokens():
    cfg = get_config("tiny-mistral")  # window 32 > test len; shrink it
    from dataclasses import replace

    cfg = replace(cfg, sliding_window=4)
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(4), (1, 16), 0, cfg.vocab_size)
    # Changing a token > window steps in the past must not affect current logits.
    altered = tokens.at[0, 2].set((tokens[0, 2] + 3) % cfg.vocab_size)
    la, _ = forward(params, cfg, tokens)
    lb, _ = forward(params, cfg, altered)
    np.testing.assert_allclose(la[0, -1], lb[0, -1], rtol=1e-5, atol=1e-5)


def test_gemma_embed_scaling_applied():
    cfg, params = setup_model("tiny-gemma")
    tokens = jnp.zeros((1, 4), jnp.int32)
    logits, _ = forward(params, cfg, tokens)
    assert bool(jnp.all(jnp.isfinite(logits)))
    # tied embeddings: no separate lm_head in the pytree
    assert "lm_head" not in params


def test_qwen_bias_params_exist():
    cfg, params = setup_model("tiny-qwen2")
    assert "bq" in params["layers"] and "bk" in params["layers"]


# -- ops ---------------------------------------------------------------------


def test_rms_norm_unit_scale():
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 64))
    out = rms_norm(x, jnp.ones((64,)))
    rms = jnp.sqrt(jnp.mean(out**2, axis=-1))
    np.testing.assert_allclose(rms, jnp.ones_like(rms), rtol=1e-3)


def test_rms_norm_gemma_offset():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 64))
    # stored weight 0 with offset 1 == stored weight 1 with offset 0
    a = rms_norm(x, jnp.zeros((64,)), offset=1.0)
    b = rms_norm(x, jnp.ones((64,)), offset=0.0)
    np.testing.assert_allclose(a, b, rtol=1e-6)


def test_rope_preserves_norm_and_relative_angle():
    inv = rope_inv_freq(32, 10000.0)
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 6, 2, 32))
    pos = jnp.arange(6)[None, :]
    cos, sin = rope_angles(pos, inv)
    rotated = apply_rope(x, cos, sin)
    np.testing.assert_allclose(
        jnp.linalg.norm(rotated, axis=-1), jnp.linalg.norm(x, axis=-1), rtol=1e-5
    )
    # position 0 is the identity rotation
    np.testing.assert_allclose(rotated[:, 0], x[:, 0], rtol=1e-6)


def test_rope_llama3_scaling_changes_long_wavelengths():
    base = rope_inv_freq(64, 500000.0)
    scaled = rope_inv_freq(
        64, 500000.0,
        {"factor": 8.0, "low_freq_factor": 1.0, "high_freq_factor": 4.0,
         "original_max_position_embeddings": 8192},
    )
    assert not np.allclose(base, scaled)
    np.testing.assert_allclose(base[0], scaled[0], rtol=1e-6)  # highest freq kept


def test_moe_routes_all_tokens_with_ample_capacity():
    key = jax.random.PRNGKey(0)
    e, d, f, k = 4, 32, 64, 2
    ks = jax.random.split(key, 5)
    x = jax.random.normal(ks[0], (2, 8, d))
    wr = jax.random.normal(ks[1], (d, e)) * 0.1
    wg = jax.random.normal(ks[2], (e, d, f)) * 0.1
    wu = jax.random.normal(ks[3], (e, d, f)) * 0.1
    wd = jax.random.normal(ks[4], (e, f, d)) * 0.1
    out = moe_block(x, wr, wg, wu, wd, top_k=k, capacity_factor=8.0)
    assert out.shape == x.shape
    assert bool(jnp.all(jnp.isfinite(out)))
    # With huge capacity no token is dropped: output must differ from zero
    assert float(jnp.abs(out).mean()) > 0


def test_moe_zero_capacity_drops_everything():
    e, d, f = 4, 16, 32
    x = jnp.ones((1, 4, d))
    wr = jnp.eye(d, e)
    wg = jnp.ones((e, d, f)) * 0.01
    wu = jnp.ones((e, d, f)) * 0.01
    wd = jnp.ones((e, f, d)) * 0.01
    # capacity_factor tiny → capacity clamps to 1 slot; most tokens dropped,
    # but the op must stay finite and well-formed.
    out = moe_block(x, wr, wg, wu, wd, top_k=2, capacity_factor=0.01)
    assert bool(jnp.all(jnp.isfinite(out)))


def test_sample_greedy_is_argmax():
    logits = jnp.array([[0.1, 5.0, -2.0], [3.0, 0.0, 1.0]])
    out = sample_token(logits, jax.random.PRNGKey(0), temperature=0.0)
    np.testing.assert_array_equal(out, jnp.array([1, 0]))


def test_sample_top_k_restricts_support():
    logits = jnp.array([[10.0, 9.0, -50.0, -50.0]])
    for seed in range(20):
        tok = sample_token(logits, jax.random.PRNGKey(seed), temperature=1.0, top_k=2)
        assert int(tok[0]) in (0, 1)


def test_sample_top_p_restricts_support():
    logits = jnp.log(jnp.array([[0.6, 0.3, 0.05, 0.05]]))
    for seed in range(20):
        tok = sample_token(logits, jax.random.PRNGKey(seed), temperature=1.0, top_p=0.5)
        assert int(tok[0]) == 0  # 0.6 ≥ 0.5 → only the top token survives


def test_n_params_plausible():
    cfg = get_config("llama-3-8b")
    assert 7.5e9 < cfg.n_params() < 8.5e9
    cfg70 = get_config("llama-3-70b")
    assert 6.5e10 < cfg70.n_params() < 7.5e10


def test_forward_logits_index_matches_full():
    """logits_index must be a pure FLOP-saving slice: equal to selecting
    from the full logits after the fact."""
    import jax
    import jax.numpy as jnp

    from llm_consensus_tpu.models import forward, get_config, init_kv_cache, init_params

    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    tokens = jnp.arange(12, dtype=jnp.int32)[None, :] % cfg.vocab_size
    cache = init_kv_cache(cfg, batch=1, max_seq=32, dtype=jnp.float32)
    full, _ = forward(params, cfg, tokens,
                      init_kv_cache(cfg, batch=1, max_seq=32, dtype=jnp.float32),
                      start_pos=0)
    idx = jnp.asarray([7])
    sliced, _ = forward(params, cfg, tokens, cache, start_pos=0, logits_index=idx)
    assert sliced.shape == (1, 1, cfg.vocab_size)
    assert jnp.allclose(sliced[:, 0], full[:, 7], atol=1e-6)
