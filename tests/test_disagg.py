"""Disaggregated prefill/decode serving (engine/handoff.py + the
parallel/mesh role split behind LLMC_DISAGG).

Covers the handoff correctness contract end to end on real tiny engines
(CPU, virtual multi-device — conftest pins 8 devices):

  * role carving: ``split_roles`` / ``plan_panel(disagg_fraction=...)``
    produce disjoint pow2 sub-meshes with per-role best_tp;
  * cross-mesh publish bitwise-equals the prefill-side bytes —
    including int8 KV code+scale stacks and NON-DIVIDING tp between
    roles (prefill tp=1 → decode tp=2): the handoff is a
    byte-preserving reshard, so a decode-side gather returns exactly
    what the prefill mesh computed;
  * per-wave fallback on an injected ``prefill_worker_crash`` keeps
    greedy output byte-identical to the classic path, and the worker
    survives for later waves;
  * the bounded handoff queue pops priority-ordered (stable within a
    class — the PR 9 order) and rejects beyond its depth;
  * pressure-governor interaction: a preempted stream's resume prefill
    rides the handoff-published KV (radix gather, not recompute) and
    stays byte-identical;
  * the small-fix satellite: a publish truncated on the HANDOFF path
    surfaces ``kv.truncated`` on the response exactly like the local
    path, and the staging buffer registers as an HBM component.
"""

from __future__ import annotations

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu import faults, obs
from llm_consensus_tpu.engine import Engine, SamplingParams
from llm_consensus_tpu.engine.handoff import KVHandoff
from llm_consensus_tpu.models import get_config, init_params
from llm_consensus_tpu.obs import attrib as attrib_mod
from llm_consensus_tpu.ops.quant import kv_seq_axis
from llm_consensus_tpu.parallel.mesh import (
    best_tp, make_mesh, plan_panel, split_roles)
from llm_consensus_tpu.providers.base import Request
from llm_consensus_tpu.providers.tpu import TPUProvider
from llm_consensus_tpu.utils.context import Context


@pytest.fixture(autouse=True)
def _clean_planes(monkeypatch):
    monkeypatch.delenv("LLMC_FAULTS", raising=False)
    monkeypatch.delenv("LLMC_DISAGG", raising=False)
    faults.reset()
    obs.reset()
    yield
    faults.reset()
    obs.reset()


@pytest.fixture(scope="module")
def tiny():
    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    return cfg, params


# ---------------------------------------------------------------------------
# role carving


def test_split_roles_disjoint_pow2():
    cfg = get_config("tiny-llama")
    devs = jax.devices()
    for n in (2, 3, 4, 8):
        pmesh, dmesh = split_roles(cfg, devs[:n], 0.5)
        assert pmesh is not None, n
        pids = {d.id for d in pmesh.devices.flat}
        dids = {d.id for d in dmesh.devices.flat}
        assert pids and dids and not (pids & dids), (n, pids, dids)
        for mesh in (pmesh, dmesh):
            size = mesh.devices.size
            assert size & (size - 1) == 0, (n, size)  # pow2
            assert size == best_tp(cfg, size)  # tp-valid by construction


def test_split_roles_single_device_no_split():
    cfg = get_config("tiny-llama")
    pmesh, dmesh = split_roles(cfg, jax.devices()[:1], 0.5)
    assert pmesh is None
    assert dmesh.devices.size == 1


def test_plan_panel_disagg_placements():
    cfg = get_config("tiny-llama")
    plan = plan_panel(
        [("tiny-llama", cfg)], None, devices=jax.devices()[:4],
        disagg_fraction=0.5,
    )
    (p,) = plan.placements
    assert p.prefill_mesh is not None
    pids = {d.id for d in p.prefill_mesh.devices.flat}
    dids = {d.id for d in p.mesh.devices.flat}
    assert not (pids & dids)
    # Default (no disagg_fraction) keeps the classic single-mesh form.
    plan2 = plan_panel([("tiny-llama", cfg)], None, devices=jax.devices()[:4])
    assert plan2.placements[0].prefill_mesh is None


# ---------------------------------------------------------------------------
# cross-mesh publish bitwise-equals the prefill-side bytes


def _leaf_eq_to(a, b, n: int) -> bool:
    """Leaves bitwise-equal over seq positions [0, n)."""
    ax = kv_seq_axis(a)
    sl = [slice(None)] * a.ndim
    sl[ax] = slice(0, n)
    return np.array_equal(
        np.asarray(a)[tuple(sl)], np.asarray(b)[tuple(sl)]
    )


@pytest.mark.parametrize("kv_quant", [None, "int8"], ids=["bf16kv", "int8kv"])
def test_cross_mesh_publish_bitwise_equals_prefill(tiny, monkeypatch,
                                                   kv_quant):
    """The transport contract: KV handed off from a tp=1 prefill mesh
    into a tp=2 decode pool (non-dividing tp between roles) gathers
    back bitwise-equal to the bytes the prefill mesh computed — int8
    code+scale stacks included."""
    cfg, params = tiny
    devs = jax.devices()
    monkeypatch.setenv("LLMC_KV_POOL_BLOCK", "16")
    # Prefill engine: pool OFF (the worker needs no pool of its own
    # here), single device, fp32 so both roles share exact dtypes.
    monkeypatch.setenv("LLMC_KV_POOL", "0")
    pmesh = make_mesh({"dp": 1, "tp": 1}, devs[2:3])
    pe = Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                prefill_chunk=16, mesh=pmesh, kv_quant=kv_quant)
    # Decode engine: pool ON, tp=2 over a disjoint slice.
    monkeypatch.setenv("LLMC_KV_POOL", "1")
    dmesh = make_mesh({"dp": 1, "tp": 2}, devs[:2])
    de = Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                prefill_chunk=16, mesh=dmesh, kv_quant=kv_quant)
    assert de._kv_pool is not None

    ids = [(7 * i + 3) % 120 + 1 for i in range(100)]
    # Reference: the exact bytes the worker's wave computes (same
    # params, same admission-prefill programs, same device) — computed
    # BEFORE the handoff so no reuse path can shortcut it.
    _lg, ref_cache = pe._prefill_rows([list(ids)])

    h = KVHandoff(pe, de, name="test")
    try:
        ok, truncated = h.run(list(ids), priority=0)
        assert ok and not truncated, h.snapshot()
        bs = de._kv_pool.block_size
        span = (len(ids) // bs) * bs
        n, gathered = de._kv_pool.lookup(
            list(ids) + [121], min_tokens=1, shard_fn=de._shard_fn
        )
        assert n == span, (n, span)
        ref_leaves = jax.tree.leaves(ref_cache)
        got_leaves = jax.tree.leaves(gathered)
        assert len(ref_leaves) == len(got_leaves)
        for ref, got in zip(ref_leaves, got_leaves):
            assert _leaf_eq_to(got, ref, span), (
                f"handoff bytes diverged (kv_quant={kv_quant}, "
                f"leaf {ref.shape} vs {got.shape})"
            )
    finally:
        h.close()


# ---------------------------------------------------------------------------
# priority-ordered bounded queue


def test_handoff_queue_priority_order_and_depth(tiny, monkeypatch):
    cfg, params = tiny
    monkeypatch.setenv("LLMC_KV_POOL_BLOCK", "16")
    monkeypatch.setenv("LLMC_KV_POOL", "1")
    de = Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                prefill_chunk=16)
    pe = Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                prefill_chunk=16)
    order: list = []
    done = threading.Event()

    def fake_wave(self, batch, wave_n):
        if order == []:
            # The first wave blocks until every later ticket is queued,
            # so the pop order under contention is observable.
            done.wait(10)
        for t in batch:
            order.append(tuple(t.ids[:2]))
            t.resolve(True)

    monkeypatch.setattr(KVHandoff, "_wave", fake_wave)
    h = KVHandoff(pe, de, depth=8, wave_rows=1, name="test")
    try:
        first = h.submit([9, 9] + list(range(30)), priority=1)
        assert first is not None
        time.sleep(0.05)  # worker picks the first wave and blocks
        t_low = h.submit([2, 2] + list(range(30)), priority=2)
        t_norm = h.submit([1, 1] + list(range(31)), priority=1)
        t_hi = h.submit([0, 0] + list(range(32)), priority=0)
        t_norm2 = h.submit([1, 3] + list(range(33)), priority=1)
        done.set()
        for t in (first, t_low, t_norm, t_hi, t_norm2):
            assert t is not None and t.wait(10)
        # After the blocked first wave: HIGH, then the NORMALs in FIFO
        # order, then LOW.
        assert order == [(9, 9), (0, 0), (1, 1), (1, 3), (2, 2)], order
    finally:
        h.close()


def test_handoff_queue_rejects_beyond_depth(tiny, monkeypatch):
    cfg, params = tiny
    monkeypatch.setenv("LLMC_KV_POOL_BLOCK", "16")
    monkeypatch.setenv("LLMC_KV_POOL", "1")
    de = Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                prefill_chunk=16)
    pe = Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                prefill_chunk=16)
    gate = threading.Event()

    def fake_wave(self, batch, wave_n):
        gate.wait(10)
        for t in batch:
            t.resolve(True)

    monkeypatch.setattr(KVHandoff, "_wave", fake_wave)
    h = KVHandoff(pe, de, depth=2, wave_rows=1, name="test")
    try:
        tickets = [
            h.submit([i] + list(range(20 + i)), priority=1) for i in range(5)
        ]
        # One in flight (popped), two queued, the rest rejected —
        # bounded depth backpressures instead of stacking latency.
        rejected = sum(1 for t in tickets if t is None)
        assert rejected >= 1, tickets
        assert h.snapshot()["rejected"] == rejected
        assert h.saturation() > 0.0
        gate.set()
        for t in tickets:
            if t is not None:
                assert t.wait(10)
    finally:
        h.close()


# ---------------------------------------------------------------------------
# provider-level: fallback on crash, byte identity, stats surfaces


def _fire_all(prov, prompts, max_tokens=10):
    results = [None] * len(prompts)

    def one(i):
        results[i] = prov.query_stream(
            Context.background(),
            Request(model="tpu:tiny-llama", prompt=prompts[i],
                    max_tokens=max_tokens),
            lambda _t: None,
        )

    threads = [
        threading.Thread(target=one, args=(i,)) for i in range(len(prompts))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(r is not None for r in results)
    return results


def _disagg_env(monkeypatch):
    monkeypatch.setenv("LLMC_PREFILL_CHUNK", "16")
    monkeypatch.setenv("LLMC_KV_POOL_BLOCK", "16")
    monkeypatch.setenv("LLMC_POOL_PREFIX_MIN", "65536")


def test_fallback_on_crash_byte_identity(tiny, monkeypatch):
    """An injected prefill_worker_crash at wave 1 falls back per-wave to
    the classic path — greedy bytes identical to a classic run — and
    the worker survives to complete the NEXT wave."""
    _disagg_env(monkeypatch)
    prompts = ["shared fleet system prompt " * 4 + f"user {i}"
               for i in range(2)]

    monkeypatch.setenv("LLMC_KV_POOL", "0")
    prov = TPUProvider(ignore_eos=True, stream_interval=4, batch_streams=2)
    # Baseline pinned to the DECODE slice (the role split's decode
    # sub-mesh = devices[:1] at 2 devices): byte-identity is asserted
    # against the classic path on the SAME decode placement — the role
    # split reassigns chips, and a tp-degree change is a placement
    # change (different float reduction order), not a handoff property.
    prov.prepare(["tpu:tiny-llama"], None, devices=jax.devices()[:1])
    base = [r.content for r in _fire_all(prov, prompts)]
    prov.release()

    monkeypatch.setenv("LLMC_KV_POOL", "1")
    faults.install(faults.FaultPlan("prefill_worker_crash@wave=1", seed=3))
    prov2 = TPUProvider(ignore_eos=True, stream_interval=4, batch_streams=2,
                        disagg=True)
    prov2.prepare(["tpu:tiny-llama"], None, devices=jax.devices()[:2])
    got = [r.content for r in _fire_all(prov2, prompts)]
    assert got == base
    snap = prov2.disagg_stats()["tiny-llama"]
    assert snap["fallbacks"] >= 1, snap
    # Second wave completes: the crash cost one wave, not the worker.
    got2 = [r.content for r in _fire_all(prov2, prompts)]
    assert got2 == base
    snap2 = prov2.disagg_stats()["tiny-llama"]
    assert snap2["completed"] + snap2["covered"] > 0, snap2
    prov2.release()


@pytest.mark.faults
def test_handoff_stall_times_out_to_classic_fallback(tiny, monkeypatch):
    """An injected ``handoff_stall`` longer than the submitter's bounded
    wait times the submitter out — ``run`` returns (False, False), the
    caller proceeds down the classic path (reuse lost, never
    correctness) — while the stalled worker SURVIVES: the wave still
    completes behind the timeout and the next submit finds its blocks
    pool-resident. Closes the fault-coverage gap the analysis checker
    (FC01) found: ``handoff_stall`` was declared but never fired."""
    cfg, params = tiny
    monkeypatch.setenv("LLMC_KV_POOL_BLOCK", "16")
    monkeypatch.setenv("LLMC_KV_POOL", "1")
    de = Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                prefill_chunk=16)
    monkeypatch.setenv("LLMC_KV_POOL", "0")
    pe = Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                prefill_chunk=16)
    faults.install(faults.FaultPlan("handoff_stall@s=0.4", seed=11))
    ids = [(3 * i + 5) % 120 + 1 for i in range(64)]
    h = KVHandoff(pe, de, wait_s=0.05, name="test")
    try:
        ok, truncated = h.run(list(ids), priority=1)
        assert (ok, truncated) == (False, False)
        snap = h.snapshot()
        assert snap["timeouts"] >= 1, snap
        # The worker rode out the stall: the wave completes behind the
        # timed-out submitter and repeat traffic skips the queue.
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if h.snapshot()["completed"] >= 1:
                break
            time.sleep(0.02)
        assert h.snapshot()["completed"] >= 1, h.snapshot()
        t = h.submit(list(ids), priority=1)
        assert t is not None and t.wait(10) and t.ok
        assert h.snapshot()["covered"] >= 1, h.snapshot()
    finally:
        h.close()
        faults.reset()


def test_run_overlapped_abandons_on_cancelled_ctx(tiny, monkeypatch):
    """Overlapped bounded wait (LLMC_DISAGG_OVERLAP): the submitter
    POLLS its handoff ticket instead of blocking the full bounded wait,
    so a request cancelled while its wave is queued abandons within one
    poll slice — the classic ``run`` would sit out all of ``wait_s``
    first. The abandoned wave still completes behind it and warms the
    pool."""
    cfg, params = tiny
    monkeypatch.setenv("LLMC_KV_POOL_BLOCK", "16")
    monkeypatch.setenv("LLMC_KV_POOL", "1")
    de = Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                prefill_chunk=16)
    pe = Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                prefill_chunk=16)
    gate = threading.Event()
    resolved = threading.Event()

    def fake_wave(self, batch, wave_n):
        gate.wait(10)
        for t in batch:
            t.resolve(True)
        resolved.set()

    monkeypatch.setattr(KVHandoff, "_wave", fake_wave)
    h = KVHandoff(pe, de, depth=2, wave_rows=1, wait_s=30.0, name="test")
    try:
        ctx = Context.background().with_cancel()
        threading.Timer(0.2, ctx.cancel).start()
        t0 = time.monotonic()
        ok, truncated = h.run_overlapped(
            list(range(24)), priority=1, ctx=ctx, poll_s=0.05
        )
        elapsed = time.monotonic() - t0
        assert (ok, truncated) == (False, False)
        # Nowhere near the 30s bounded wait: the cancel was honored
        # within poll-slice granularity.
        assert elapsed < 5.0, elapsed
        snap = h.snapshot()
        assert snap["overlap_abandons"] == 1, snap
        assert snap["overlap_polls"] >= 1, snap
        # The abandoned wave still completes behind the submitter.
        gate.set()
        assert resolved.wait(10)
    finally:
        gate.set()
        h.close()


def test_run_overlapped_matches_run_on_success(tiny, monkeypatch):
    """With a live worker the overlapped wait returns exactly what the
    classic blocking wait would — (ok, truncated) from the resolved
    ticket — and the knob defaults the overlapped path ON."""
    from llm_consensus_tpu.utils import knobs as knobs_mod

    assert knobs_mod.get_bool("LLMC_DISAGG_OVERLAP") is True
    cfg, params = tiny
    monkeypatch.setenv("LLMC_KV_POOL_BLOCK", "16")
    monkeypatch.setenv("LLMC_KV_POOL", "1")
    de = Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                prefill_chunk=16)
    pe = Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                prefill_chunk=16)

    def fake_wave(self, batch, wave_n):
        time.sleep(0.1)  # long enough that at least one poll slice fires
        for t in batch:
            t.resolve(True)

    monkeypatch.setattr(KVHandoff, "_wave", fake_wave)
    h = KVHandoff(pe, de, depth=2, wave_rows=1, wait_s=10.0, name="test")
    try:
        ok, truncated = h.run_overlapped(
            list(range(24)), priority=1, poll_s=0.02
        )
        assert (ok, truncated) == (True, False)
        assert h.snapshot()["overlap_abandons"] == 0
    finally:
        h.close()


def test_disagg_off_no_handoff_state(tiny, monkeypatch):
    """Default off: no prefill meshes, no handoffs, no disagg stats —
    the classic path is structurally untouched."""
    _disagg_env(monkeypatch)
    monkeypatch.setenv("LLMC_KV_POOL", "1")
    prov = TPUProvider(ignore_eos=True, stream_interval=4, batch_streams=2)
    prov.prepare(["tpu:tiny-llama"], None, devices=jax.devices()[:2])
    _fire_all(prov, ["plain request body " * 4])
    assert prov._prefill_meshes == {}
    assert prov._handoffs == {}
    assert prov.disagg_stats() == {}
    prov.release()


def test_handoff_telemetry_and_pressure_signal(tiny, monkeypatch):
    """disagg_stats carries the handoff counters, utilization_stats
    grows a per-role prefill entry, and pressure_stats folds the
    handoff queue into the governor's queued signal."""
    _disagg_env(monkeypatch)
    monkeypatch.setenv("LLMC_KV_POOL", "1")
    prov = TPUProvider(ignore_eos=True, stream_interval=4, batch_streams=2,
                       disagg=True)
    prov.prepare(["tpu:tiny-llama"], None, devices=jax.devices()[:2])
    prompts = ["telemetry stream body words " * 4 + str(i) for i in range(2)]
    _fire_all(prov, prompts)
    snap = prov.disagg_stats()["tiny-llama"]
    assert snap["completed"] > 0 and snap["handoff_bytes"] > 0, snap
    assert snap["prefill_devices"] >= 1 and snap["decode_devices"] >= 1
    util = prov.utilization_stats()
    assert "tiny-llama:prefill" in util, util
    assert util["tiny-llama:prefill"]["role"] == "prefill"
    ps = prov.pressure_stats()
    assert "tiny-llama" in ps  # shape intact; handoff_queued only when >0
    kv = prov.kv_stats()["tiny-llama"]
    assert kv["handoff_blocks"] > 0, kv
    prov.release()


def test_handoff_truncation_surfaces_kv_truncated(tiny, monkeypatch):
    """The small-fix satellite: pool exhaustion on the HANDOFF path
    surfaces kv.truncated on the response exactly like the local path,
    and the staging buffer registered as an HBM component."""
    _disagg_env(monkeypatch)
    monkeypatch.setenv("LLMC_KV_POOL", "1")
    led = attrib_mod.ChipTimeLedger(warmup_s=3600.0)
    attrib_mod.install(led)
    faults.install(faults.FaultPlan("pool_exhausted@times=-1", seed=7))
    try:
        prov = TPUProvider(ignore_eos=True, stream_interval=4,
                           batch_streams=2, disagg=True)
        prov.prepare(["tpu:tiny-llama"], None, devices=jax.devices()[:2])
        (resp,) = _fire_all(prov, ["exhaustion-bound prompt body " * 4])
        assert resp.kv == {"truncated": True}, resp.kv
        snap = prov.disagg_stats()["tiny-llama"]
        assert snap["truncated"] >= 1, snap
        comps = led.snapshot()["hbm"]["components"]
        assert "handoff_staging:tiny-llama" in comps, comps
        # kv_handoff device time booked against the new family.
        assert led.snapshot()["device_s"].get("kv_handoff", 0) > 0
        prov.release()
    finally:
        attrib_mod.reset()


# ---------------------------------------------------------------------------
# pressure-governor interaction: preempted resume rides the handoff KV


def test_preempt_resume_rides_handoff_kv(tiny, monkeypatch):
    """A HIGH latecomer preempts a LOW resident in a full disaggregated
    pool: every stream still emits the uncontended greedy bytes, and
    the victim's resume prefill rides the handoff-published KV (the
    pool's hit counter moves — gather, not recompute)."""
    from llm_consensus_tpu.pressure.priority import (
        PRIORITY_HIGH, PRIORITY_LOW)

    _disagg_env(monkeypatch)
    monkeypatch.setenv("LLMC_PRESSURE_PREEMPT", "1")
    low_prompts = [f"low class resident stream {i} body words " * 3
                   for i in range(2)]
    low_tokens, hi_tokens = 24, 8
    hi_prompt = "high class latecomer body"

    monkeypatch.setenv("LLMC_KV_POOL", "0")
    prov = TPUProvider(ignore_eos=True, stream_interval=8, batch_streams=2)
    # Same decode placement as the disagg leg's decode sub-mesh (see
    # test_fallback_on_crash_byte_identity's baseline note).
    prov.prepare(["tpu:tiny-llama"], None, devices=jax.devices()[:1])
    ctx = Context.background()
    base_low = [
        prov.query_stream(ctx, Request(model="tpu:tiny-llama", prompt=p,
                                       max_tokens=low_tokens), None).content
        for p in low_prompts
    ]
    base_hi = prov.query_stream(
        ctx, Request(model="tpu:tiny-llama", prompt=hi_prompt,
                     max_tokens=hi_tokens), None,
    ).content
    prov.release()

    monkeypatch.setenv("LLMC_KV_POOL", "1")
    prov2 = TPUProvider(ignore_eos=True, stream_interval=8, batch_streams=2,
                        disagg=True)
    prov2.prepare(["tpu:tiny-llama"], None, devices=jax.devices()[:2])
    # Warm pass, uncontended: byte-identity through the handoff, and the
    # prompts publish into the pool so the contended attempts' handoffs
    # resolve via the covered fast path — cold handoff waves would admit
    # the LOWs one at a time and the slots might never be full together.
    for i, p in enumerate(low_prompts):
        r = prov2.query_stream(
            Context.background(),
            Request(model="tpu:tiny-llama", prompt=p, max_tokens=24,
                    priority=PRIORITY_LOW), None,
        )
        assert r.content == base_low[i], f"warm stream {i} diverged"
    batcher = None
    for _attempt in range(3):
        results: dict = {}

        def one(key, prompt, max_tokens, priority):
            results[key] = prov2.query_stream(
                Context.background(),
                Request(model="tpu:tiny-llama", prompt=prompt,
                        max_tokens=max_tokens, priority=priority),
                None,
            )

        threads = [
            threading.Thread(
                target=one, args=(i, p, low_tokens, PRIORITY_LOW)
            )
            for i, p in enumerate(low_prompts)
        ]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 15
        batcher = None
        while time.monotonic() < deadline:
            entry = prov2._batchers.get("tiny-llama")
            if entry is not None:
                batcher = entry[1]
                if sum(1 for s in batcher._slots if s is not None) == 2:
                    break
            time.sleep(0.005)
        t_hi = threading.Thread(
            target=one, args=("hi", hi_prompt, hi_tokens, PRIORITY_HIGH)
        )
        t_hi.start()
        for t in threads + [t_hi]:
            t.join()
        if batcher.snapshot()["preemptions"] >= 1:
            break
    assert batcher is not None and batcher.snapshot()["preemptions"] >= 1
    assert results["hi"].content == base_hi
    for i in range(2):
        assert results[i].content == base_low[i], f"victim {i} diverged"
    pool = prov2._engines["tiny-llama"]._kv_pool
    stats = pool.stats()
    # The resume's re-prefill found the handoff-published prompt blocks
    # resident: gather traffic, not recompute.
    assert stats["hit_tokens"] > 0, stats
    assert stats["handoff_blocks"] > 0, stats
    prov2.release()
