"""Sharded engine placement: mesh slices through the Provider seam.

Covers SURVEY.md §7 build steps 4-5 — panel models on disjoint mesh
slices and a TP-sharded judge — on the 8-device virtual CPU mesh.
The reference has no analog (its "placement" is a model→HTTP-endpoint
table, /root/reference/cmd/llm-consensus/main.go:49-61); this is the
TPU-native replacement.
"""

import jax
import jax.numpy as jnp
import pytest

from llm_consensus_tpu.consensus import Judge
from llm_consensus_tpu.engine import Engine, SamplingParams
from llm_consensus_tpu.models import get_config, init_params
from llm_consensus_tpu.parallel.mesh import make_mesh
from llm_consensus_tpu.providers.registry import Registry
from llm_consensus_tpu.providers.tpu import TPUProvider
from llm_consensus_tpu.runner import Runner
from llm_consensus_tpu.utils.context import Context

PROMPT = "Summarize the tradeoffs of tensor parallel inference."


def _greedy(engine: Engine, n: int) -> list[int]:
    result = engine.generate(
        PROMPT, SamplingParams(max_new_tokens=n, ignore_eos=True)
    )
    assert len(result.token_ids) == n
    return result.token_ids


def test_sharded_engine_matches_unsharded():
    """TP=2 sharding is a placement, not a numerics change: greedy tokens
    from the same fp32 weights must match the single-device engine."""
    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(7), dtype=jnp.float32)
    base = Engine(cfg, params, dtype=jnp.float32, stream_interval=4)
    mesh = make_mesh({"dp": 1, "tp": 2}, jax.devices()[:2])
    sharded = Engine(cfg, params, dtype=jnp.float32, mesh=mesh, stream_interval=4)
    assert _greedy(sharded, 12) == _greedy(base, 12)


def test_sharded_flash_prefill_matches_xla():
    """The Pallas prefill kernel under shard_map over TP heads must match
    the unsharded XLA attention path bit-for-bit in fp32."""
    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(3), dtype=jnp.float32)
    base = Engine(cfg, params, dtype=jnp.float32, stream_interval=4,
                  attn_impl="xla")
    mesh = make_mesh({"dp": 1, "tp": 2}, jax.devices()[:2])
    flash = Engine(cfg, params, dtype=jnp.float32, mesh=mesh,
                   stream_interval=4, attn_impl="flash")
    assert _greedy(flash, 12) == _greedy(base, 12)


def test_sharded_flash_gating_rejects_non_tp_meshes(monkeypatch):
    """Flash under sharding is tp-only; a mesh with a real dp axis falls
    back to the XLA path rather than mis-sharding the kernel. The kernel
    is stubbed to raise so the test fails if it is invoked at all."""
    import llm_consensus_tpu.ops.pallas as pallas_pkg
    from llm_consensus_tpu.models import forward, init_kv_cache

    def _boom(*a, **k):
        raise AssertionError("Pallas kernel invoked on a non-tp-only mesh")

    monkeypatch.setattr(pallas_pkg, "flash_attention", _boom)

    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(3), dtype=jnp.float32)
    mesh = make_mesh({"dp": 2, "tp": 2}, jax.devices()[:4])
    cache = init_kv_cache(cfg, batch=2, max_seq=64, dtype=jnp.float32)
    tokens = jnp.ones((2, 16), jnp.int32)
    logits, _ = forward(params, cfg, tokens, cache, start_pos=0,
                        attn_impl="flash", mesh=mesh)
    ref, _ = forward(
        params, cfg, tokens,
        init_kv_cache(cfg, batch=2, max_seq=64, dtype=jnp.float32),
        start_pos=0, attn_impl="xla",
    )
    assert jnp.allclose(logits, ref, atol=1e-5)


def test_sharded_moe_engine_runs():
    """Expert-parallel judge path: MoE experts shard over the tp axis."""
    cfg = get_config("tiny-mixtral")
    mesh = make_mesh({"dp": 1, "tp": 2}, jax.devices()[:2])
    engine = Engine(cfg, mesh=mesh, stream_interval=4)
    assert len(_greedy(engine, 8)) == 8


def test_prepare_places_panel_and_judge_on_disjoint_slices():
    provider = TPUProvider()
    panel = ["tpu:tiny-llama", "tpu:tiny-mistral"]
    provider.prepare(panel, "tpu:tiny-mixtral")

    slices = {}
    for m in panel + ["tpu:tiny-mixtral"]:
        mesh = provider.placement(m)
        assert mesh is not None
        slices[m] = {d.id for d in mesh.devices.flat}

    # Judge gets a multi-chip TP slice; every slice pair is disjoint.
    assert len(slices["tpu:tiny-mixtral"]) >= 2
    names = list(slices)
    for i, a in enumerate(names):
        for b in names[i + 1:]:
            assert not (slices[a] & slices[b]), (a, b, slices)


def test_consensus_run_on_sharded_slices():
    """Full on-device consensus with every model on its own mesh slice."""
    provider = TPUProvider(ignore_eos=True, stream_interval=4)
    panel = ["tpu:tiny-llama", "tpu:tiny-mistral"]
    judge_model = "tpu:tiny-gemma"
    provider.prepare(panel, judge_model)

    registry = Registry()
    for m in panel + [judge_model]:
        registry.register(m, provider)
    runner = Runner(registry, timeout=300.0, max_tokens=8)
    result = runner.run(Context.background(), panel, PROMPT)
    assert len(result.responses) == 2
    assert not result.failed_models

    judge = Judge(provider, judge_model, max_tokens=8)
    consensus = judge.synthesize(Context.background(), PROMPT, result.responses)
    assert consensus

    for m in panel + [judge_model]:
        engine = provider._engines[m.split(":", 1)[1]]
        assert engine.mesh is provider.placement(m)


def test_prepare_same_layout_keeps_cached_engine():
    provider = TPUProvider(ignore_eos=True, stream_interval=4)
    provider.prepare(["tpu:tiny-llama"], None)
    engine = provider._engine_for("tpu:tiny-llama")
    provider.prepare(["tpu:tiny-llama"], None)
    assert provider._engine_for("tpu:tiny-llama") is engine


def test_prepare_layout_change_rebuilds_engine():
    provider = TPUProvider(ignore_eos=True, stream_interval=4)
    provider.prepare(["tpu:tiny-llama", "tpu:tiny-mistral"], None)
    engine = provider._engine_for("tpu:tiny-llama")
    # Re-plan with tiny-llama as the judge: it moves to the judge slice.
    provider.prepare(["tpu:tiny-mistral"], "tpu:tiny-llama")
    assert provider._engine_for("tpu:tiny-llama") is not engine


def test_prepare_evicts_presets_absent_from_new_plan():
    """A re-plan without a previously placed model drops its placement and
    engine — stale slices must never overlap fresh ones."""
    provider = TPUProvider(ignore_eos=True, stream_interval=4)
    provider.prepare(["tpu:tiny-llama", "tpu:tiny-mistral"], None)
    provider._engine_for("tpu:tiny-llama")
    provider.prepare(["tpu:tiny-mistral"], None)
    assert provider.placement("tpu:tiny-llama") is None
    assert "tiny-llama" not in provider._engines


def test_prepare_scopes_to_given_devices():
    devices = jax.devices()[:4]
    provider = TPUProvider()
    provider.prepare(["tpu:tiny-llama"], "tpu:tiny-mistral", devices=devices)
    used = set()
    for m in ("tpu:tiny-llama", "tpu:tiny-mistral"):
        used |= {d.id for d in provider.placement(m).devices.flat}
    assert used <= {d.id for d in devices}


def test_cli_prepare_called_once_per_provider():
    """The CLI announces the run composition to each unique provider."""
    from llm_consensus_tpu.cli.main import Config, run
    from llm_consensus_tpu.providers.base import Provider, Response

    calls = []

    class Fake(Provider):
        def prepare(self, models, judge):
            calls.append((tuple(models), judge))

        def query(self, ctx, req):
            return Response(model=req.model, content="ans", provider="fake")

        def query_stream(self, ctx, req, callback):
            resp = self.query(ctx, req)
            if callback:
                callback(resp.content)
            return resp

    fake = Fake()
    import io

    cfg = Config(models=["a", "b"], judge="j", prompt="p", no_save=True, quiet=True)
    run(
        cfg,
        Context.background(),
        factory=lambda model: fake,
        stdout=io.StringIO(),
        stderr=io.StringIO(),
    )
    assert calls == [(("a", "b"), "j")]


def test_sharded_chunked_prefill_matches_unsharded():
    """Chunked prefill on a TP-sharded engine (the long judge-prompt path,
    SURVEY §5): GSPMD partitions the dynamic-start chunk program; greedy
    tokens must match the unsharded one-shot engine."""
    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(7), dtype=jnp.float32)
    base = Engine(cfg, params, dtype=jnp.float32, stream_interval=4,
                  prefill_chunk=0)
    mesh = make_mesh({"dp": 1, "tp": 2}, jax.devices()[:2])
    sharded = Engine(cfg, params, dtype=jnp.float32, mesh=mesh,
                     stream_interval=4, prefill_chunk=16)
    long_prompt = PROMPT * 4  # 216 ids → 14 chunks of 16
    s = SamplingParams(max_new_tokens=12, ignore_eos=True)
    assert (
        sharded.generate(long_prompt, s).token_ids
        == base.generate(long_prompt, s).token_ids
    )


# -- multi-host placement (parallel/mesh.py plan_panel hosts policy) --------


def _fake_hosts(n_hosts, per_host):
    devs = jax.devices()
    assert len(devs) >= n_hosts * per_host
    return [
        list(devs[h * per_host:(h + 1) * per_host]) for h in range(n_hosts)
    ]


def test_multihost_panel_spreads_across_hosts():
    """Panel models land on DIFFERENT hosts; every slice stays inside one
    host's ICI domain (no mesh spans two host groups)."""
    from llm_consensus_tpu.parallel.mesh import plan_panel

    hosts = _fake_hosts(2, 4)
    panel = [("m0", get_config("tiny-llama")), ("m1", get_config("tiny-mistral"))]
    judge = ("j", get_config("tiny-gemma"))
    plan = plan_panel(panel, judge, devices=sum(hosts, []), hosts=hosts)
    host_of = {id(d): h for h, group in enumerate(hosts) for d in group}

    def hosts_used(p):
        return {host_of[id(d)] for d in p.mesh.devices.flat}

    placements = {p.model: p for p in plan.placements}
    assert len(placements) == 3
    for p in plan.placements:
        assert len(hosts_used(p)) == 1, f"{p.model} spans hosts"
    # Both hosts carry models (fan-out over ICI domains), and co-tenant
    # slices within a host are disjoint.
    assert {h for p in plan.placements for h in hosts_used(p)} == {0, 1}
    by_host = {}
    for p in plan.placements:
        by_host.setdefault(next(iter(hosts_used(p))), []).append(p)
    for group in by_host.values():
        seen = set()
        for p in group:
            ids = {d.id for d in p.mesh.devices.flat}
            assert not (ids & seen), f"{p.model} overlaps a co-tenant"
            seen |= ids


def test_multihost_three_hosts_three_panels():
    from llm_consensus_tpu.parallel.mesh import plan_panel

    hosts = _fake_hosts(4, 2)
    panel = [(f"m{i}", get_config("tiny-llama")) for i in range(3)]
    judge = ("j", get_config("tiny-llama"))
    plan = plan_panel(panel, judge, devices=sum(hosts, []), hosts=hosts)
    host_of = {id(d): h for h, group in enumerate(hosts) for d in group}
    used = {
        p.model: {host_of[id(d)] for d in p.mesh.devices.flat}
        for p in plan.placements
    }
    # Three panel models over three non-judge hosts: one each.
    panel_hosts = [next(iter(used[f"m{i}"])) for i in range(3)]
    assert len(set(panel_hosts)) == 3
    assert used["j"].isdisjoint(set(panel_hosts))


def test_multihost_no_judge_uses_all_hosts():
    from llm_consensus_tpu.parallel.mesh import plan_panel

    hosts = _fake_hosts(2, 4)
    panel = [(f"m{i}", get_config("tiny-llama")) for i in range(2)]
    plan = plan_panel(panel, None, devices=sum(hosts, []), hosts=hosts)
    host_of = {id(d): h for h, group in enumerate(hosts) for d in group}
    panel_hosts = {
        next(iter({host_of[id(d)] for d in p.mesh.devices.flat}))
        for p in plan.placements
    }
    assert panel_hosts == {0, 1}


def test_multihost_consensus_run_end_to_end():
    """The full serving path (provider prepare -> runner -> judge) over an
    explicit 2-host grouping of the virtual mesh."""
    from llm_consensus_tpu.parallel import mesh as mesh_mod

    hosts = _fake_hosts(2, 4)
    real_plan_panel = mesh_mod.plan_panel

    def hosted_plan(panel, judge=None, devices=None, **kw):
        kw.setdefault("hosts", hosts)
        return real_plan_panel(panel, judge, devices=devices, **kw)

    provider = TPUProvider(ignore_eos=True, stream_interval=4)
    mesh_mod.plan_panel = hosted_plan
    try:
        panel = ["tpu:tiny-llama", "tpu:tiny-mistral"]
        provider.prepare(panel, "tpu:tiny-gemma")
        registry = Registry()
        for m in panel + ["tpu:tiny-gemma"]:
            registry.register(m, provider)
        from llm_consensus_tpu.utils.context import Context

        result = Runner(registry, timeout=600.0, max_tokens=4).run(
            Context.background(), panel, "multi host dry run"
        )
        assert len(result.responses) == 2
        consensus = Judge(provider, "tpu:tiny-gemma", max_tokens=4).synthesize(
            Context.background(), "multi host dry run", result.responses
        )
        assert consensus
    finally:
        mesh_mod.plan_panel = real_plan_panel


def test_single_explicit_host_group_restricts_devices():
    """hosts=[subset] with ONE group must confine placement to that
    subset, not fall through to the full device list."""
    from llm_consensus_tpu.parallel.mesh import plan_panel

    subset = list(jax.devices())[:4]
    panel = [("m0", get_config("tiny-llama")), ("m1", get_config("tiny-llama"))]
    plan = plan_panel(panel, ("j", get_config("tiny-llama")),
                      hosts=[subset])
    allowed = {d.id for d in subset}
    for p in plan.placements:
        assert {d.id for d in p.mesh.devices.flat} <= allowed, p.model


def test_70b_judge_abstract_sharding():
    """BASELINE config[3] structural check: the 70B judge's parameter
    tree shards over a tp=8 mesh abstractly (shapes/specs only — no
    weights), with >95% of bytes TP-sharded so per-device int8 residency
    fits a v5e chip."""
    import numpy as np
    from jax.sharding import Mesh

    from llm_consensus_tpu.models import get_config
    from llm_consensus_tpu.parallel.sharding import abstract_param_bytes

    cfg = get_config("llama-3-70b")
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(1, 8), ("dp", "tp"))
    total, sharded = abstract_param_bytes(cfg, mesh)
    assert total > 120e9  # it really is the 70B tree (bf16)
    assert sharded / total > 0.95
    per_dev_int8 = (sharded / 8 + (total - sharded)) / 2
    assert per_dev_int8 < 16e9  # int8 weights fit a 16 GB v5e chip


def test_multihost_biggest_model_gets_biggest_host_regardless_of_role():
    """Weight-proportional placement (round-2 VERDICT #5): a 70B PANEL
    model outranks an 8B judge for the biggest host — placement follows
    parameter count, not role."""
    from llm_consensus_tpu.parallel.mesh import plan_panel

    devs = jax.devices()
    hosts = [list(devs[:4]), list(devs[4:6])]  # sizes 4, 2
    panel = [
        ("big-panel", get_config("llama-3-70b")),
        ("small-panel", get_config("llama-3.2-1b")),
    ]
    judge = ("judge", get_config("llama-3-8b"))
    plan = plan_panel(panel, judge, devices=sum(hosts, []), hosts=hosts)
    host_of = {id(d): h for h, group in enumerate(hosts) for d in group}
    used = {
        p.model: {host_of[id(d)] for d in p.mesh.devices.flat}
        for p in plan.placements
    }
    assert used["big-panel"] == {0}, "70B panel model must take the big host"
    assert used["judge"] == {1}, "8B judge yields the big host to the 70B"
    sizes = {p.model: p.mesh.devices.size for p in plan.placements}
    assert sizes["big-panel"] >= sizes["judge"]


def test_multihost_heterogeneous_five_model_panel():
    """BASELINE config[4] shape (Mixtral EP judge + 5 heterogeneous
    panel): every model places inside one host's ICI domain, co-tenants
    split chips weight-proportionally (the heaviest co-tenant never gets
    fewer chips than a lighter one), and nothing silently spans hosts."""
    from llm_consensus_tpu.parallel.mesh import plan_panel

    devs = jax.devices()
    hosts = [list(devs[:4]), list(devs[4:8])]
    panel = [
        ("llama8b", get_config("llama-3-8b")),
        ("mistral", get_config("mistral-7b")),
        ("gemma", get_config("gemma-7b")),
        ("qwen", get_config("qwen2-7b")),
        ("llama3b", get_config("llama-3.2-3b")),
    ]
    judge = ("mixtral", get_config("mixtral-8x7b"))
    plan = plan_panel(panel, judge, devices=sum(hosts, []), hosts=hosts)
    assert len(plan.placements) == 6
    host_of = {id(d): h for h, group in enumerate(hosts) for d in group}
    weights = {p.model: p.cfg.n_params(active_only=True) for p in plan.placements}
    by_host = {}
    for p in plan.placements:
        spans = {host_of[id(d)] for d in p.mesh.devices.flat}
        assert len(spans) == 1, f"{p.model} spans hosts"
        by_host.setdefault(next(iter(spans)), []).append(p)
    for group in by_host.values():
        group = sorted(group, key=lambda p: -weights[p.model])
        for heavy, light in zip(group, group[1:]):
            assert heavy.mesh.devices.size >= light.mesh.devices.size, (
                f"{heavy.model} (heavier) got fewer chips than {light.model}"
            )


def test_plan_panel_warns_on_wrap_sharing():
    """More models than chips: slices time-multiplex, with a warning
    (round-2 VERDICT #5: sharing was silent)."""
    import warnings as _w

    from llm_consensus_tpu.parallel.mesh import plan_panel

    devs = jax.devices()[:2]
    panel = [(f"m{i}", get_config("tiny-llama")) for i in range(4)]
    with _w.catch_warnings(record=True) as caught:
        _w.simplefilter("always")
        plan = plan_panel(panel, None, devices=devs)
    assert len(plan.placements) == 4
    assert any("time-multiplex" in str(c.message) for c in caught), (
        [str(c.message) for c in caught]
    )
