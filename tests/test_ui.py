"""UI tests — progress state machine, rendering, printers (ui.go parity)."""

import io
import time

from llm_consensus_tpu.ui import (
    ModelStatus,
    Progress,
    print_consensus,
    print_header,
    print_model_response,
    print_summary,
)
from llm_consensus_tpu.ui.progress import spinner, truncate


def test_truncate():
    # ui.go:252-259: newlines → spaces, trim, ellipsis past max.
    assert truncate("hello", 10) == "hello"
    assert truncate("a\nb\nc", 10) == "a b c"
    assert truncate("x" * 30, 10) == "x" * 9 + "…"
    assert truncate("  padded  ", 10) == "padded"


def test_spinner_cycles_all_frames():
    frames = {spinner(t / 10.0) for t in range(10)}
    assert len(frames) == 10


def test_state_machine_transitions():
    buf = io.StringIO()
    p = Progress(buf, ["m1", "m2"], quiet=True)
    assert p._models["m1"].status is ModelStatus.PENDING
    p.model_started("m1")
    assert p._models["m1"].status is ModelStatus.RUNNING
    p.model_streaming("m1", "hello world!")  # 12 chars → 3 tokens
    assert p._models["m1"].status is ModelStatus.STREAMING
    assert p._models["m1"].token_est == 3
    p.model_completed("m1")
    assert p._models["m1"].status is ModelStatus.COMPLETE
    p.model_failed("m2", RuntimeError("nope"))
    assert p._models["m2"].status is ModelStatus.FAILED


def test_token_estimate_accumulates_chars_div_4():
    # ui.go:142 — chars/4 across chunks.
    p = Progress(io.StringIO(), ["m"], quiet=True)
    for _ in range(10):
        p.model_streaming("m", "abcdefgh")  # 80 chars total
    assert p._models["m"].token_est == 20


def test_unknown_model_updates_ignored():
    p = Progress(io.StringIO(), ["m"], quiet=True)
    p.model_started("ghost")  # must not raise (ui.go guards map lookups)
    p.model_streaming("ghost", "x")
    p.model_completed("ghost")


def test_render_paints_and_clears():
    buf = io.StringIO()
    p = Progress(buf, ["model-a"], quiet=False)
    p.start()
    p.model_started("model-a")
    p.model_streaming("model-a", "some output text")
    time.sleep(0.25)  # let the 100ms repaint loop run a few frames
    p.stop()
    out = buf.getvalue()
    assert "Querying 1 models" in out
    assert "model-a" in out
    assert "\033[A\033[K" in out  # cursor-up + clear-line repaint (ui.go:238-242)
    assert "streaming ~4 tokens" in out


def test_quiet_progress_writes_nothing():
    buf = io.StringIO()
    p = Progress(buf, ["m"], quiet=True)
    p.start()
    p.model_started("m")
    p.stop()
    assert buf.getvalue() == ""


def test_printers_shapes():
    buf = io.StringIO()
    print_header(buf, "what is the answer to everything?" * 5)
    print_model_response(buf, "m1", "prov", "line1\nline2", 1500.0)
    print_consensus(buf, "the answer")
    print_summary(buf, 3, 2, 1, 12.34)
    out = buf.getvalue()
    assert "LLM Consensus" in out
    assert "m1 (prov) [1.5s]" in out
    assert "│\033[0m line1" in out and "│\033[0m line2" in out
    assert "CONSENSUS" in out and "║\033[0m the answer" in out
    assert "Models queried: 3" in out and "2 succeeded" in out and "1 failed" in out
    assert "Total time: 12.3s" in out
