"""Hostile-payload shapes must degrade to SourceError (warn-and-continue),
never crash the tool — one bad source can't wipe healthy sources' output."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from llm_consensus_tpu.tools.registry_sync import SourceError, fetch_openai_models


@pytest.mark.parametrize(
    "body,match",
    [
        (b"[]", "expected JSON object"),
        (b'["gpt-a"]', "expected JSON object"),
        (b'{"data": "nope"}', "'data' is not a list"),
    ],
)
def test_non_object_payloads_are_source_errors(body, match):
    class H(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802
            self.send_response(200)
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        base = f"http://127.0.0.1:{srv.server_address[1]}"
        with pytest.raises(SourceError, match=match):
            fetch_openai_models(base_url=base, api_key="k")
    finally:
        srv.shutdown()
        srv.server_close()


def test_non_dict_items_in_data_are_skipped():
    class H(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802
            self.send_response(200)
            self.end_headers()
            self.wfile.write(
                json.dumps({"data": ["junk", 7, {"id": "gpt-ok"}]}).encode()
            )

        def log_message(self, *a):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        base = f"http://127.0.0.1:{srv.server_address[1]}"
        recs = fetch_openai_models(base_url=base, api_key="k")
        assert [r.id for r in recs] == ["gpt-ok"]
    finally:
        srv.shutdown()
        srv.server_close()
