"""Live observability tests: histograms, /metricsz, trace ids, blackbox.

Covers the obs/live + obs/prom + obs/blackbox plane and its serving
wiring:

  * histogram correctness — bucket boundaries (Prometheus ``le``
    inclusive-upper semantics), quantile estimates against exact values
    on known distributions (log buckets bound the relative error by the
    growth factor), bucket-wise merge associativity (the router's fleet
    aggregation relies on it), and window rotation under concurrent
    writers (no observation lost, rings bounded);
  * ``GET /metricsz`` on a gateway — Prometheus text format with
    TTFT/queue-wait/e2e histograms labeled by priority class, plus the
    /statsz blocks flattened through the stats registry;
  * the router's ``/metricsz`` equals the bucket-wise merge of its
    replicas' histograms;
  * one trace id linking router → gateway → run spans, surviving an
    injected ``replica_down`` failover, returned in the done envelope;
  * the flight recorder: bounded ring, Perfetto-loadable dumps, rate
    limiting, the governor's escalation trigger, and an injected engine
    crash producing a dump with pre-crash decode spans with events OFF.
"""

from __future__ import annotations

import http.client
import json
import os
import threading
import time

import pytest

from llm_consensus_tpu import faults, obs, serve
from llm_consensus_tpu.obs import blackbox as bb_mod
from llm_consensus_tpu.obs import export as obs_export
from llm_consensus_tpu.obs import live as live_mod
from llm_consensus_tpu.obs import prom
from llm_consensus_tpu.obs.blackbox import FlightRecorder
from llm_consensus_tpu.obs.live import (
    BUCKET_EDGES,
    Histogram,
    LiveMetrics,
    SLOWatcher,
    WindowedHistogram,
    bucket_index,
)
from llm_consensus_tpu.providers.base import Provider, Request, Response
from llm_consensus_tpu.providers.registry import Registry
from llm_consensus_tpu.utils.context import Context

PANEL = ["alpha", "beta"]
JUDGE = "gamma"


@pytest.fixture(autouse=True)
def _clean_planes(monkeypatch):
    from llm_consensus_tpu.obs import attrib as attrib_mod

    monkeypatch.delenv("LLMC_FAULTS", raising=False)
    faults.reset()
    obs.reset()
    live_mod.reset()
    bb_mod.reset()
    attrib_mod.reset()
    yield
    faults.reset()
    obs.reset()
    live_mod.reset()
    bb_mod.reset()
    attrib_mod.reset()


# ---------------------------------------------------------------------------
# histogram correctness


def test_bucket_boundaries_le_inclusive():
    # Exact upper edges land IN their bucket (Prometheus le semantics);
    # epsilon past an edge lands in the next.
    for i, edge in enumerate(BUCKET_EDGES):
        assert bucket_index(edge) == i, edge
        assert bucket_index(edge * 1.0001) == i + 1
    assert bucket_index(0.0) == 0
    assert bucket_index(-1.0) == 0
    # Past the top finite edge: the +Inf overflow bucket.
    assert bucket_index(BUCKET_EDGES[-1] * 2) == len(BUCKET_EDGES)
    h = Histogram()
    h.observe(BUCKET_EDGES[-1] * 10)
    assert h.counts[-1] == 1 and h.count == 1


def test_quantile_estimate_vs_exact_known_distributions():
    # Log buckets with growth 2 ⇒ any estimate is within one growth
    # factor of the exact sample quantile. Check on a uniform and a
    # heavy-tailed deterministic distribution.
    import random

    rng = random.Random(7)
    for samples in (
        [rng.uniform(0.001, 10.0) for _ in range(2000)],
        [0.001 * (1.5 ** (i % 25)) for i in range(2000)],
    ):
        h = Histogram()
        for v in samples:
            h.observe(v)
        s = sorted(samples)
        for q in (0.5, 0.9, 0.99):
            exact = s[min(len(s) - 1, int(q * len(s)))]
            est = h.quantile(q)
            assert est is not None
            assert exact / 2.0 <= est <= exact * 2.0, (q, exact, est)
    assert Histogram().quantile(0.5) is None


def test_merge_associative_and_commutative():
    import random

    rng = random.Random(3)

    def rand_hist():
        h = Histogram()
        for _ in range(200):
            h.observe(rng.uniform(1e-5, 500.0))
        return h

    a, b, c = rand_hist(), rand_hist(), rand_hist()

    def merged(*hs):
        out = Histogram()
        for h in hs:
            out.merge_from(h.copy())
        return out

    left = merged(merged(a, b), c)
    right = merged(a, merged(b, c))
    swapped = merged(c, a, b)
    for other in (right, swapped):
        assert left.counts == other.counts
        assert left.count == other.count
        assert abs(left.sum - other.sum) < 1e-9


def test_window_rotation_under_concurrent_writers():
    lm = LiveMetrics(window_s=60.0, windows=4)
    n_threads, n_obs = 8, 500
    stop = threading.Event()

    def rotator():
        while not stop.is_set():
            lm.rotate()
            time.sleep(0.001)

    def writer(t):
        for i in range(n_obs):
            lm.observe("ttft", 0.01 * (t + 1), outcome="ok",
                       **{"class": "normal"})

    rot = threading.Thread(target=rotator)
    rot.start()
    threads = [
        threading.Thread(target=writer, args=(t,)) for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    rot.join()
    # Rotation never loses an observation from the CUMULATIVE total.
    assert lm.counts("ttft") == n_threads * n_obs
    # Rings stay bounded at their configured depth.
    wh = next(iter(lm._hists.values()))
    assert len(wh.ring) <= 4


def test_windowed_recent_excludes_open_window():
    wh = WindowedHistogram(windows=3)
    wh.observe(1.0)
    assert wh.recent(1).count == 0  # still in the open window
    wh.rotate()
    assert wh.recent(1).count == 1
    wh.observe(2.0)
    wh.rotate()
    assert wh.recent(2).count == 2


# ---------------------------------------------------------------------------
# Prometheus render / parse / merge


def test_prom_roundtrip_and_bucketwise_merge():
    lm = LiveMetrics(window_s=60.0)
    for v, cls in ((0.01, "high"), (0.2, "normal"), (3.0, "normal")):
        lm.observe("ttft", v, outcome="ok", **{"class": cls})
    text = prom.render(
        lm, stats_blocks={"kv": {"p": {"hits": 3}}},
        gauges={"load_score": 0.25},
    )
    parsed = prom.parse_text(text)
    key = ("ttft", (("class", "normal"), ("outcome", "ok")))
    assert parsed["histograms"][key]["count"] == 2
    assert parsed["gauges"][("load_score", ())] == 0.25
    assert parsed["gauges"][
        ("stat", (("block", "kv"), ("key", "p.hits")))
    ] == 3
    # Canonical round-trip: parse(render_parsed(parse(x))) == parse(x).
    again = prom.parse_text(prom.render_parsed(parsed))
    assert again == parsed
    # Merge doubles every bucket/count/sum.
    doubled = prom.merge([parsed, parsed])
    assert doubled["histograms"][key]["count"] == 4
    for le, n in parsed["histograms"][key]["buckets"].items():
        assert doubled["histograms"][key]["buckets"][le] == 2 * n


# ---------------------------------------------------------------------------
# SLO watcher + flight recorder


def test_slo_watcher_burns_after_n_windows():
    burns = []
    w = SLOWatcher(threshold_s=0.1, windows=3, on_burn=burns.append)
    lm = LiveMetrics(window_s=60.0)
    for i in range(3):
        lm.observe("ttft", 5.0, outcome="ok", **{"class": "high"})
        lm.rotate()
        fired = w.check(lm)
        assert fired == (i == 2), i
    assert len(burns) == 1 and burns[0]["threshold_s"] == 0.1
    # A quiet window resets the streak.
    lm2 = LiveMetrics(window_s=60.0)
    w2 = SLOWatcher(threshold_s=0.1, windows=2, on_burn=burns.append)
    lm2.observe("ttft", 5.0, outcome="ok", **{"class": "high"})
    lm2.rotate()
    assert not w2.check(lm2)
    lm2.rotate()  # empty window
    assert not w2.check(lm2)
    assert len(burns) == 1
    # Disabled watcher (threshold 0) never fires.
    assert not SLOWatcher(threshold_s=0.0).check(lm)


def test_flight_recorder_ring_bound_dump_and_rate_limit(tmp_path):
    fr = FlightRecorder(
        capacity=32, out_dir=str(tmp_path), min_interval_s=3600.0
    )
    for i in range(100):
        t0 = fr.now()
        fr.complete("decode", t0, tid="batcher", i=i)
    assert fr.depth() == 32  # bounded ring: oldest evicted
    path = fr.dump("unit_test", extra={"k": 1})
    assert path is not None and os.path.exists(path)
    doc = obs_export.load_trace(path)  # Perfetto-loadable trace document
    assert "decode" in obs_export.trace_span_names(doc)
    assert doc["blackbox"]["reason"] == "unit_test"
    assert doc["blackbox"]["k"] == 1
    # Rate limit: a second dump inside the interval is suppressed.
    assert fr.dump("again") is None
    assert fr.suppressed == 1
    assert fr.dump("forced", force=True) is not None
    # An empty ring never writes.
    fr.clear()
    assert fr.dump("empty", force=True) is None


def test_governor_escalation_past_preempt_dumps_blackbox(tmp_path):
    from llm_consensus_tpu.pressure import PressureGovernor

    bb_mod.install(FlightRecorder(
        capacity=64, out_dir=str(tmp_path), min_interval_s=0.0
    ))
    gov = PressureGovernor(
        high_water=0.8, low_water=0.2, up_patience=1, down_patience=100,
    )
    # Walk ok → evict → preempt → brownout: the brownout escalation is
    # PAST preempt, so it must snapshot the flight recorder.
    for _ in range(3):
        gov.observe(1.0)
    assert gov.state == "brownout"
    fr = bb_mod.ring()
    assert fr.dumps >= 1 and fr.last_reason == "pressure_brownout"
    doc = obs_export.load_trace(fr.last_path)
    names = {
        e.get("name") for e in doc["traceEvents"] if isinstance(e, dict)
    }
    assert "pressure_escalate" in names


# ---------------------------------------------------------------------------
# stats registry


def test_stats_registry_contract():
    from llm_consensus_tpu.serve.stats import StatsRegistry

    reg = StatsRegistry()
    reg.register("good", lambda: {"x": 1})
    reg.register("empty", lambda: {})
    reg.register("none", lambda: None)
    reg.register("boom", lambda: 1 / 0)
    out = reg.collect()
    assert out == {"good": {"x": 1}}
    assert reg.names() == ["good", "empty", "none", "boom"]
    reg.register("good", lambda: {"x": 2})  # replace, not duplicate
    assert reg.collect() == {"good": {"x": 2}}


# ---------------------------------------------------------------------------
# gateway /metricsz + trace ids over real HTTP (fake providers)


class FakeProvider(Provider):
    def query(self, ctx: Context, req: Request) -> Response:
        ctx.raise_if_done()
        return Response(
            model=req.model,
            content=f"{req.model} answers {req.prompt[:16]}",
            provider="fake",
        )

    def query_stream(self, ctx, req, callback):
        resp = self.query(ctx, req)
        if callback is not None:
            for i in range(0, len(resp.content), 8):
                callback(resp.content[i:i + 8])
        return resp


def make_gateway(tmp_path, name="gw", live=None, **kw):
    provider = FakeProvider()
    registry = Registry()
    for m in PANEL + [JUDGE]:
        registry.register(m, provider)
    kw.setdefault("timeout", 30.0)
    kw.setdefault("max_concurrency", 4)
    gw = serve.build_gateway(
        registry, list(PANEL), JUDGE,
        data_dir=os.path.join(str(tmp_path), "data", name),
        live=live if live is not None else LiveMetrics(window_s=60.0),
        **kw,
    )
    gw.start()
    return gw


def post(port: int, body: dict, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    try:
        hdrs = {"Content-Type": "application/json"}
        hdrs.update(headers or {})
        conn.request("POST", "/v1/consensus", json.dumps(body), hdrs)
        r = conn.getresponse()
        data = r.read()
    finally:
        conn.close()
    return r.status, json.loads(data)


def get_text(port: int, path: str):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        ctype = r.getheader("Content-Type", "")
        data = r.read().decode("utf-8")
    finally:
        conn.close()
    return r.status, ctype, data


def test_gateway_metricsz_histograms_labeled_by_class(tmp_path):
    gw = make_gateway(tmp_path)
    try:
        _, port = gw.address
        status, doc = post(port, {"prompt": "interactive q",
                                  "priority": "high"})
        assert status == 200
        assert doc["trace_id"]
        status, doc2 = post(port, {"prompt": "batch q", "priority": "low"})
        assert status == 200

        # The e2e observation lands in the handler's finally AFTER the
        # response bytes are written — poll briefly so a fast scrape
        # doesn't race the second request's bookkeeping.
        deadline = time.monotonic() + 5.0
        while True:
            status, ctype, text = get_text(port, "/metricsz")
            assert status == 200
            assert ctype.startswith("text/plain")
            parsed = prom.parse_text(text)
            hists = parsed["histograms"]
            e2e_total = sum(
                h["count"] for (m, _), h in hists.items() if m == "e2e"
            )
            if e2e_total >= 2 or time.monotonic() > deadline:
                break
            time.sleep(0.02)
        for metric in ("ttft", "e2e", "queue_wait"):
            classes = {
                dict(labels).get("class")
                for (m, labels) in hists if m == metric
            }
            assert {"high", "low"} <= classes, (metric, classes)
            total = sum(
                h["count"] for (m, _), h in hists.items() if m == metric
            )
            assert total >= 2, (metric, total)
        # Judge synthesis rides the run too (judge class = one above).
        assert any(m == "judge_synthesis" for (m, _) in hists)
        # Outcome labels present and well-formed.
        outcomes = {
            dict(labels).get("outcome") for (m, labels) in hists
        }
        assert outcomes <= set(live_mod.OUTCOMES), outcomes
        # The /statsz blocks flattened through the ONE registry.
        stat_blocks = {
            dict(labels)["block"]
            for (name, labels) in parsed["gauges"] if name == "stat"
        }
        assert {"admission", "cache"} <= stat_blocks, stat_blocks
        assert ("load_score", ()) in parsed["gauges"]
        # /statsz itself iterates the same registry.
        status, _, stats_text = get_text(port, "/statsz")
        stats = json.loads(stats_text)
        assert "admission" in stats and "cache" in stats
    finally:
        gw.close(drain=False, timeout=5.0)


def test_trace_header_honored_and_returned(tmp_path):
    gw = make_gateway(tmp_path, name="tr")
    try:
        _, port = gw.address
        status, doc = post(
            port, {"prompt": "traced"},
            headers={"X-LLMC-Trace": "feedbeefcafe0001"},
        )
        assert status == 200
        assert doc["trace_id"] == "feedbeefcafe0001"
        # And a minted one when absent: 16 hex chars.
        status, doc = post(port, {"prompt": "untraced"})
        assert len(doc["trace_id"]) == 16
        int(doc["trace_id"], 16)
    finally:
        gw.close(drain=False, timeout=5.0)


# ---------------------------------------------------------------------------
# router: fleet /metricsz merge + trace across failover


def sse_request(port: int, body: dict, timeout=60):
    body = dict(body)
    body["stream"] = True
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    events = []
    try:
        conn.request(
            "POST", "/v1/consensus", json.dumps(body),
            {"Content-Type": "application/json",
             "Accept": "text/event-stream"},
        )
        resp = conn.getresponse()
        assert resp.status == 200, resp.read()
        event, data_lines = None, []
        for raw in resp:
            line = raw.decode("utf-8").rstrip("\r\n")
            if line.startswith("event: "):
                event = line[len("event: "):]
            elif line.startswith("data: "):
                data_lines.append(line[len("data: "):])
            elif not line and (event or data_lines):
                events.append((event, json.loads("\n".join(data_lines))))
                if event in ("done", "error"):
                    break
                event, data_lines = None, []
    finally:
        conn.close()
    return events


@pytest.mark.faults
def test_router_metricsz_is_bucketwise_merge_of_replicas(tmp_path):
    gws = [
        make_gateway(tmp_path, name=f"r{i}", cache_size=0)
        for i in range(2)
    ]
    router = None
    try:
        router = serve.build_router(
            [f"http://{h}:{p}" for h, p in (g.address for g in gws)],
            poll_s=60.0,
        )
        router.start()
        _, rport = router.address
        for i in range(4):
            status, doc = post(rport, {"prompt": f"merge probe {i}"})
            assert status == 200, doc
            assert doc["trace_id"]

        def request_families(parsed):
            return {
                k: v for k, v in parsed["histograms"].items()
                if k[0] in ("ttft", "e2e", "queue_wait", "token_latency",
                            "judge_synthesis")
            }

        replica_parsed = []
        for g in gws:
            _, _, text = get_text(g.address[1], "/metricsz")
            replica_parsed.append(prom.parse_text(text))
        _, _, rtext = get_text(rport, "/metricsz")
        merged = prom.merge(replica_parsed)
        assert request_families(prom.parse_text(rtext)) == request_families(
            merged
        )
        # Both replicas exist in the fleet picture even if placement
        # sent every probe to one home.
        assert sum(
            h["count"] for h in request_families(merged).values()
        ) >= 4
    finally:
        if router is not None:
            router.close()
        for g in gws:
            g.close(drain=False, timeout=5.0)


@pytest.mark.faults
def test_one_trace_id_links_hops_across_failover(tmp_path):
    rec = obs.Recorder()
    obs.install(rec)
    faults.install(faults.FaultPlan(
        "replica_down@phase=proxy@frame=2", seed=11
    ))
    gws = [
        make_gateway(tmp_path, name=f"f{i}", cache_size=0)
        for i in range(2)
    ]
    router = None
    try:
        router = serve.build_router(
            [f"http://{h}:{p}" for h, p in (g.address for g in gws)],
            poll_s=60.0,
        )
        router.start()
        _, rport = router.address
        events = sse_request(rport, {"prompt": "failover trace probe"})
        assert events[-1][0] == "done", events[-1]
        done = events[-1][1]
        trace = done["trace_id"]
        assert trace and done.get("failovers", 0) >= 1

        def spans_named(name):
            return [
                e for e in rec.events()
                if e.ph == "X" and e.name == name
                and e.args.get("trace") == trace
            ]

        # The client sees the done frame BEFORE the router thread
        # unwinds into the finally that records its route span — poll.
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and not spans_named("route"):
            time.sleep(0.02)
        route_spans = spans_named("route")
        run_spans = spans_named("consensus_run")
        worker_spans = spans_named("worker")
        # One id stitches the router hop, the (re-executed) gateway run,
        # and the runner fan-out — across the replica_down seam.
        assert route_spans and run_spans and worker_spans
        assert route_spans[0].args.get("outcome") == "failover"
    finally:
        if router is not None:
            router.close()
        for g in gws:
            g.close(drain=False, timeout=5.0)


# ---------------------------------------------------------------------------
# trace id survives preempt -> resume (the PR 9×10 gap)


def test_trace_id_survives_preempt_resume():
    """One trace id links BOTH batcher residencies of a preempted
    stream: the sealed journal entry (closed "preempted") and the
    reopened resume entry carry the same id, and the resumed result is
    marked preempted — so the live plane and any post-mortem can stitch
    the full story of a preempted request from one id."""
    import jax
    import jax.numpy as jnp

    from llm_consensus_tpu import recovery
    from llm_consensus_tpu.engine import ContinuousBatcher, Engine
    from llm_consensus_tpu.engine.engine import SamplingParams
    from llm_consensus_tpu.models import init_params
    from llm_consensus_tpu.models.config import get_config
    from llm_consensus_tpu.pressure import PRIORITY_HIGH, PRIORITY_LOW

    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    eng = Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                 stream_interval=8, prefill_chunk=16)
    journal = recovery.StreamJournal()
    recovery.install(journal)
    seen_entries = []
    orig_record = journal.record

    def record(*args, **kwargs):
        entry = orig_record(*args, **kwargs)
        seen_entries.append(entry)
        return entry

    journal.record = record
    try:
        b = ContinuousBatcher(eng, max_batch=2)
        try:
            s_low = SamplingParams(max_new_tokens=48, ignore_eos=True)
            s_hi = SamplingParams(max_new_tokens=8, ignore_eos=True)
            low_traces = ["10w0000000000001", "10w0000000000002"]
            r_low = r_hi = None
            for _attempt in range(4):
                seen_entries.clear()
                futs = [
                    b.submit(f"trace lane {i} body", s_low,
                             priority=PRIORITY_LOW, trace_id=low_traces[i])
                    for i in range(2)
                ]
                deadline = time.monotonic() + 120
                while time.monotonic() < deadline:
                    if sum(1 for st in b._slots if st is not None) == 2:
                        break
                    time.sleep(0.005)
                f_hi = b.submit("trace high latecomer", s_hi,
                                priority=PRIORITY_HIGH,
                                trace_id="feedfeedfeed0001")
                r_hi = f_hi.result(timeout=300)
                r_low = [f.result(timeout=300) for f in futs]
                if any(r.preempted for r in r_low):
                    break
            assert any(r.preempted for r in r_low), "no preemption observed"
            assert r_hi.token_ids  # the high class actually ran
            # The victim's ORIGINAL entry sealed as "preempted" and its
            # RESUME entry — both carry the victim's trace id.
            preempted = [
                e for e in seen_entries if e.finish == "preempted"
            ]
            assert preempted, [e.finish for e in seen_entries]
            for old in preempted:
                assert old.trace in low_traces, old.trace
                resumes = [
                    e for e in seen_entries
                    if e.replay_of == old.sid
                ]
                assert resumes, "preempted entry has no resume entry"
                assert resumes[0].trace == old.trace
            # And the high-priority request kept ITS id.
            hi_entries = [
                e for e in seen_entries if e.trace == "feedfeedfeed0001"
            ]
            assert len(hi_entries) == 1
        finally:
            b.close()
    finally:
        recovery.reset()


# ---------------------------------------------------------------------------
# blackbox: injected engine crash with events OFF (real tiny engines)


@pytest.mark.faults
def test_engine_crash_dumps_blackbox_with_events_off(tmp_path):
    import jax

    from llm_consensus_tpu import recovery
    from llm_consensus_tpu.providers.tpu import TPUProvider

    assert obs.recorder() is None  # events OFF is the point
    bb_mod.install(FlightRecorder(
        capacity=256, out_dir=str(tmp_path), min_interval_s=0.0
    ))
    faults.install(faults.FaultPlan("crash@chunk=2", seed=5))
    recovery.install(recovery.StreamJournal())
    prov = None
    try:
        prov = TPUProvider(
            ignore_eos=True, stream_interval=4, batch_streams=2
        )
        prov.prepare(["tpu:tiny-llama"], None, devices=jax.devices()[:2])
        resp = prov.query_stream(
            Context.background(),
            Request(model="tpu:tiny-llama", prompt="crash probe body",
                    max_tokens=12, trace_id="deadbeef00000001"),
            None,
        )
        assert resp.tokens == 12  # recovered and replayed
        fr = bb_mod.ring()
        assert fr.dumps >= 1 and fr.last_reason == "engine_crash"
        doc = obs_export.load_trace(fr.last_path)
        names = obs_export.trace_span_names(doc)
        # The dump holds decode spans from BEFORE the crash.
        assert "decode" in names, names
        instants = {
            e["name"] for e in doc["traceEvents"]
            if isinstance(e, dict) and e.get("ph") == "i"
        }
        assert "engine_crash" in instants
    finally:
        if prov is not None:
            prov.release()
        recovery.reset()
