"""Shared-prefix pool serving (engine/batcher.py + models prefix merge).

The consensus workload fans ONE user prompt to N streams (the reference's
runner fan-out — /root/reference/internal/runner/runner.go:62-63); the
pool exploits it by establishing the wave's common prompt prefix as a
single KV copy, admitting suffix-only rows, and decoding with the exact
prefix/suffix softmax merge. The load-bearing property is unchanged from
plain continuous batching: every stream's greedy tokens are EXACTLY what
the single-stream engine produces, whatever sharing happened underneath.
"""

import time

import jax
import jax.numpy as jnp
import pytest

from llm_consensus_tpu.engine import ContinuousBatcher, Engine, SamplingParams
from llm_consensus_tpu.models import get_config, init_params

PREFIX = (
    "a shared consensus prompt prefix that every stream of the wave "
    "carries verbatim before its own question suffix begins"
)


@pytest.fixture(scope="module")
def engine():
    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    return Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                  stream_interval=8)


@pytest.fixture()
def batcher(engine, monkeypatch):
    monkeypatch.setenv("LLMC_POOL_PREFIX_MIN", "64")
    b = ContinuousBatcher(engine, max_batch=4)
    yield b
    b.close()


def test_shared_prefix_wave_matches_single_stream(engine, batcher):
    """A burst of same-prefix prompts establishes the pool prefix and
    every stream still produces the single-stream greedy tokens."""
    s = SamplingParams(max_new_tokens=16, ignore_eos=True)
    prompts = [f"{PREFIX} stream number {i}" for i in range(4)]
    futs = [batcher.submit(p, s) for p in prompts]
    results = [f.result(timeout=600) for f in futs]
    assert batcher._prefix_cache is not None  # sharing actually engaged
    assert batcher._prefix_len_host >= 64
    for p, r in zip(prompts, results):
        ref = engine.generate(p, s)
        assert r.token_ids == ref.token_ids, p
        assert r.text == ref.text


def test_followup_wave_joins_established_prefix(engine, batcher):
    """A second burst with the same prefix admits into the live pool
    (suffix-only) and stays exact; the pool keeps the one prefix copy."""
    s = SamplingParams(max_new_tokens=24, ignore_eos=True)
    first = [batcher.submit(f"{PREFIX} early {i}", s) for i in range(2)]
    time.sleep(0.5)  # let the first wave establish + start decoding
    second = [batcher.submit(f"{PREFIX} late {i}", s) for i in range(2)]
    for i, f in enumerate(first):
        assert f.result(timeout=600).token_ids == engine.generate(
            f"{PREFIX} early {i}", s
        ).token_ids
    for i, f in enumerate(second):
        assert f.result(timeout=600).token_ids == engine.generate(
            f"{PREFIX} late {i}", s
        ).token_ids
    assert batcher._prefix_cache is not None


def test_non_matching_stream_next_to_prefix_rows(engine, batcher):
    """A prompt that does NOT share the pool prefix decodes correctly in
    a slot next to prefix-sharing rows (full-prompt window, inactive
    prefix flag)."""
    s = SamplingParams(max_new_tokens=16, ignore_eos=True)
    shared = [f"{PREFIX} q{i}" for i in range(2)]
    futs = [batcher.submit(p, s) for p in shared]
    time.sleep(0.5)
    other = "a completely unrelated prompt with its own content"
    f_other = batcher.submit(other, s)
    for p, f in zip(shared, futs):
        assert f.result(timeout=600).token_ids == engine.generate(p, s).token_ids
    assert f_other.result(timeout=600).token_ids == engine.generate(
        other, s
    ).token_ids


def test_short_common_prefix_disables_sharing(engine, monkeypatch):
    """Below the establishment threshold the pool must not share — and
    still be exact."""
    monkeypatch.setenv("LLMC_POOL_PREFIX_MIN", "64")
    b = ContinuousBatcher(engine, max_batch=4)
    try:
        s = SamplingParams(max_new_tokens=12, ignore_eos=True)
        prompts = [f"short {i} prompt with little shared text" for i in range(3)]
        futs = [b.submit(p, s) for p in prompts]
        results = [f.result(timeout=600) for f in futs]
        assert b._prefix_cache is None
        for p, r in zip(prompts, results):
            assert r.token_ids == engine.generate(p, s).token_ids
    finally:
        b.close()


def test_prefix_pool_compaction_stays_exact(monkeypatch):
    """Suffix windows hitting the compaction waterline mid-decode must
    keep every stream exact (the prefix cache itself never moves)."""
    monkeypatch.setenv("LLMC_POOL_PREFIX_MIN", "64")
    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    eng = Engine(cfg, params=params, dtype=jnp.float32, max_seq=160,
                 stream_interval=8)
    b = ContinuousBatcher(eng, max_batch=2)
    try:
        s = SamplingParams(max_new_tokens=40, ignore_eos=True)
        prompts = [f"{PREFIX} compaction probe {i}" for i in range(2)]
        futs = [b.submit(p, s) for p in prompts]
        results = [f.result(timeout=600) for f in futs]
        assert b._prefix_cache is not None
        for p, r in zip(prompts, results):
            ref = eng.generate(p, s)
            assert r.token_ids == ref.token_ids, p
            assert r.finish_reason == ref.finish_reason
    finally:
        b.close()


def test_prefix_disabled_by_env(engine, monkeypatch):
    monkeypatch.setenv("LLMC_POOL_PREFIX", "0")
    b = ContinuousBatcher(engine, max_batch=4)
    try:
        s = SamplingParams(max_new_tokens=8, ignore_eos=True)
        prompts = [f"{PREFIX} off {i}" for i in range(3)]
        futs = [b.submit(p, s) for p in prompts]
        for p, f in zip(prompts, futs):
            assert f.result(timeout=600).token_ids == engine.generate(
                p, s
            ).token_ids
        assert b._prefix_cache is None
    finally:
        b.close()


def test_suffix_wave_prefill_failure_degrades_to_full_admission(
    engine, monkeypatch
):
    """A deterministically failing suffix-wave prefill must NOT livelock
    the scheduler: sharing disables itself and the wave re-admits as
    full-prompt rows (the review-flagged failure mode)."""
    monkeypatch.setenv("LLMC_POOL_PREFIX_MIN", "64")
    b = ContinuousBatcher(engine, max_batch=4)
    try:
        def boom(*a, **k):
            raise RuntimeError("injected suffix prefill failure")

        monkeypatch.setattr(engine, "_prefill_rows_suffix", boom)
        s = SamplingParams(max_new_tokens=8, ignore_eos=True)
        prompts = [f"{PREFIX} fail {i}" for i in range(3)]
        # Submit INSIDE the warns context: the warning fires on the
        # scheduler thread as soon as the wave admits, which can precede
        # a context entered only after submission.
        with pytest.warns(RuntimeWarning, match="disabling pool prefix"):
            futs = [b.submit(p, s) for p in prompts]
            results = [f.result(timeout=600) for f in futs]
        assert not b._prefix_enabled
        for p, r in zip(prompts, results):
            assert r.token_ids == engine.generate(p, s).token_ids
    finally:
        b.close()


def test_establishment_failure_disables_sharing(engine, monkeypatch):
    """A failing ESTABLISHMENT prefill (the [1, S] prefix pass) must
    disable sharing like the suffix-wave path does — otherwise every
    subsequent idle wave re-runs the same failing prefill before
    degrading (ADVICE r3)."""
    monkeypatch.setenv("LLMC_POOL_PREFIX_MIN", "64")
    b = ContinuousBatcher(engine, max_batch=4)
    try:
        real = engine._prefill_ids
        calls = {"n": 0}

        def boom(ids):
            # The FIRST _prefill_ids call of this wave is the
            # establishment pass (the scheduler establishes before any
            # admission prefill); failing exactly it exercises the
            # disable path while later full-prompt admissions keep
            # working so the wave degrades instead of failing. Every
            # call counts, so a re-establishment attempt (or any other
            # unexpected _prefill_ids traffic) shows as calls > 1.
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("injected establishment failure")
            return real(ids)

        monkeypatch.setattr(engine, "_prefill_ids", boom)
        s = SamplingParams(max_new_tokens=8, ignore_eos=True)
        prompts = [f"{PREFIX} estfail {i}" for i in range(3)]
        with pytest.warns(RuntimeWarning, match="disabling pool prefix"):
            futs = [b.submit(p, s) for p in prompts]
            results = [f.result(timeout=600) for f in futs]
        assert not b._prefix_enabled
        assert calls["n"] == 1  # no repeated re-establishment attempts
        monkeypatch.setattr(engine, "_prefill_ids", real)
        for p, r in zip(prompts, results):
            assert r.token_ids == engine.generate(p, s).token_ids
    finally:
        b.close()


def test_oversized_dense_prefix_falls_back_to_no_sharing(engine, monkeypatch):
    """A prefix whose DENSE compute-dtype copy exceeds the prefix-cache
    byte cap must not establish (ADVICE r3: the [L,1,p_cap,Hkv,dh] copy
    was unbounded) — and the wave still serves, unshared and exact."""
    monkeypatch.setenv("LLMC_POOL_PREFIX_MIN", "64")
    b = ContinuousBatcher(engine, max_batch=3)
    saved_cap = engine._prefix_max_bytes
    try:
        s = SamplingParams(max_new_tokens=8, ignore_eos=True)
        # Establish a prefix normally first: the cap path must CLEAR it
        # (pool is idle; a resident prefix nobody references would hold
        # exactly the HBM the cap bounds).
        futs = [b.submit(f"{PREFIX} pre {i}", s) for i in range(2)]
        [f.result(timeout=600) for f in futs]
        assert b._prefix_cache is not None
        engine._prefix_max_bytes = 1  # force the cap below any real prefix
        other = (
            "a different shared prefix long enough to qualify for pool "
            "establishment but denied by the dense-copy byte cap now"
        )
        prompts = [f"{other} capped {i}" for i in range(3)]
        futs = [b.submit(p, s) for p in prompts]
        results = [f.result(timeout=600) for f in futs]
        assert b._prefix_cache is None  # prior prefix cleared, none installed
        assert b._prefix_enabled  # cap is a fallback, not a failure
        for p, r in zip(prompts, results):
            assert r.token_ids == engine.generate(p, s).token_ids
    finally:
        engine._prefix_max_bytes = saved_cap
        b.close()


def test_decode_phase_stats_accumulate(engine, batcher):
    """Steady (admission-free) decode chunks accumulate live-token and
    wall-time counters; the rate they imply is what the bench reports as
    the decode-phase aggregate."""
    s = SamplingParams(max_new_tokens=40, ignore_eos=True)  # 5 chunks of 8
    futs = [batcher.submit(f"{PREFIX} stats {i}", s) for i in range(2)]
    [f.result(timeout=600) for f in futs]
    assert batcher.stats["decode_tokens"] > 0
    assert batcher.stats["decode_s"] > 0.0


def test_tp_sharded_pool_shares_prefix(monkeypatch):
    """The north-star judge is TP-sharded; its pool must share the panel
    prompt too. tp=2 over two CPU devices: sharing engages (the decode
    kernel's merge state rides shard_map over the head axis; prefix
    attention partitions under GSPMD) and greedy outputs stay exact."""
    monkeypatch.setenv("LLMC_POOL_PREFIX_MIN", "64")
    from llm_consensus_tpu.parallel.mesh import make_mesh

    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    mesh = make_mesh({"dp": 1, "tp": 2}, jax.devices()[:2])
    eng = Engine(cfg, params=params, dtype=jnp.float32, max_seq=256,
                 stream_interval=8, mesh=mesh)
    b = ContinuousBatcher(eng, max_batch=3)
    try:
        s = SamplingParams(max_new_tokens=12, ignore_eos=True)
        prompts = [f"{PREFIX} tp stream {i}" for i in range(3)]
        futs = [b.submit(p, s) for p in prompts]
        results = [f.result(timeout=600) for f in futs]
        assert b._prefix_cache is not None
        for p, r in zip(prompts, results):
            assert r.token_ids == eng.generate(p, s).token_ids, p
    finally:
        b.close()


def test_reestablishment_after_drain(engine, batcher):
    """Pool drains, a new burst with a DIFFERENT shared prefix arrives:
    the pool re-establishes and stays exact."""
    s = SamplingParams(max_new_tokens=10, ignore_eos=True)
    futs = [batcher.submit(f"{PREFIX} gen1 {i}", s) for i in range(2)]
    [f.result(timeout=600) for f in futs]
    first_ids = batcher._prefix_ids
    other_prefix = (
        "an entirely different but equally long shared prompt prefix "
        "used by the second generation of the serving burst"
    )
    futs = [batcher.submit(f"{other_prefix} g2 {i}", s) for i in range(3)]
    results = [f.result(timeout=600) for f in futs]
    assert batcher._prefix_ids is not None
    assert batcher._prefix_ids != first_ids
    for i, r in enumerate(results):
        assert r.token_ids == engine.generate(
            f"{other_prefix} g2 {i}", s
        ).token_ids
