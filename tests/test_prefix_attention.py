"""Shared-prefix (Hydragen/cascade) attention: op- and forward-level parity.

The serving pool's one-prompt fan-out pattern (the reference fans ONE user
prompt to N models — /root/reference/internal/runner/runner.go:62-63)
means co-resident streams share a long prompt prefix. The shared-prefix
decode path attends ONE [P, Hkv, dh] prefix copy (a dense MXU matmul)
plus each row's own suffix window, merged with the exact two-source
online-softmax combine — instead of streaming B replicated copies of the
prefix KV from HBM every step. These tests pin the math against the
plain full-cache attention semantics at every level:

  * ``attention(return_state)`` + ``merge_attention_states``: splitting
    the KV at any point and merging must reproduce the full softmax.
  * ``prefix_attention`` + the Pallas decode kernel's ``return_state``
    (interpret mode): merged == the XLA reference over the concatenated
    cache.
  * ``forward(prefix=...)``: suffix-resident prefill and decode (both
    attention impls) must produce the logits of the full-prompt path —
    RoPE offsets, causal seam, and per-row participation included.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.models import forward, get_config, init_kv_cache, init_params
from llm_consensus_tpu.ops.attention import (
    attention, make_attention_mask, merge_attention_states, prefix_attention)
from llm_consensus_tpu.ops.pallas import decode_attention


def _full_reference(q, k, v, mask, softcap=None):
    return attention(q, k, v, mask, logit_softcap=softcap)


def test_attention_state_split_merge_matches_full():
    """Splitting KV into [0, s) + [s, S) and merging == one softmax."""
    key = jax.random.PRNGKey(0)
    b, t, hq, hkv, dh, s_total, split = 2, 3, 8, 4, 64, 48, 20
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, t, hq, dh))
    k = jax.random.normal(kk, (b, s_total, hkv, dh))
    v = jax.random.normal(kv, (b, s_total, hkv, dh))
    qpos = jnp.broadcast_to(jnp.arange(s_total - t, s_total)[None], (b, t))
    kvpos = jnp.broadcast_to(jnp.arange(s_total)[None], (b, s_total))
    mask = make_attention_mask(qpos, kvpos, None)

    with jax.default_matmul_precision("highest"):
        want = _full_reference(q, k, v, mask)
        o1, m1, l1 = attention(
            q, k[:, :split], v[:, :split], mask[:, :, :split],
            return_state=True,
        )
        o2, m2, l2 = attention(
            q, k[:, split:], v[:, split:], mask[:, :, split:],
            return_state=True,
        )
        got = merge_attention_states(o1, m1, l1, o2, m2, l2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_attention_state_fully_masked_source_drops_out():
    """A source with no valid columns must contribute nothing."""
    key = jax.random.PRNGKey(1)
    b, t, hq, hkv, dh, s = 1, 2, 4, 2, 64, 16
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, t, hq, dh))
    k = jax.random.normal(kk, (b, s, hkv, dh))
    v = jax.random.normal(kv, (b, s, hkv, dh))
    full = jnp.ones((b, t, s), bool)
    none = jnp.zeros((b, t, s), bool)
    with jax.default_matmul_precision("highest"):
        want = _full_reference(q, k, v, full)
        o1, m1, l1 = attention(q, k, v, full, return_state=True)
        o2, m2, l2 = attention(q, k, v, none, return_state=True)
        got = merge_attention_states(o1, m1, l1, o2, m2, l2)
        flipped = merge_attention_states(o2, m2, l2, o1, m1, l1)
    assert bool(jnp.all(l2 == 0.0))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)
    np.testing.assert_allclose(np.asarray(flipped), np.asarray(want), atol=2e-5)


@pytest.mark.parametrize("softcap", [None, 30.0])
def test_prefix_plus_decode_kernel_matches_concat_reference(softcap):
    """prefix_attention + Pallas kernel (interpret) merged == XLA attention
    over the concatenated [prefix + suffix] KV at the decode step."""
    key = jax.random.PRNGKey(2)
    b, hq, hkv, dh = 4, 8, 4, 128
    p_len, p_cap, width, pos = 30, 32, 64, 40
    kq, kp, ks = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, 1, hq, dh))
    pk = jax.random.normal(kp, (2, p_cap, hkv, dh))
    sk = jax.random.normal(ks, (2, b, width, hkv, dh))
    row_start = jnp.asarray([0, 3, 11, 0], jnp.int32)

    with jax.default_matmul_precision("highest"):
        o2, m2, l2 = decode_attention(
            q, sk[0][None], sk[1][None],
            jnp.asarray(pos, jnp.int32), 0, row_start,
            logit_softcap=softcap, return_state=True,
        )
        o1, m1, l1 = prefix_attention(
            q, pk[0, :p_len], pk[1, :p_len],
            jnp.asarray(p_len, jnp.int32), jnp.ones((b,), bool),
            logit_softcap=softcap,
        )
        got = merge_attention_states(
            o1, m1, l1, o2, m2[:, None], l2[:, None]
        )

        # Reference: one attention over [prefix ++ suffix-window] with the
        # pool's mask semantics (prefix always valid, suffix windowed).
        k_cat = jnp.concatenate(
            [jnp.broadcast_to(pk[0, :p_len][None], (b, p_len, hkv, dh)),
             sk[0]], axis=1,
        )
        v_cat = jnp.concatenate(
            [jnp.broadcast_to(pk[1, :p_len][None], (b, p_len, hkv, dh)),
             sk[1]], axis=1,
        )
        slots = jnp.arange(width, dtype=jnp.int32)[None, :]
        suffix_valid = jnp.logical_and(
            slots <= pos, slots >= row_start[:, None]
        )
        valid = jnp.concatenate(
            [jnp.ones((b, p_len), bool), suffix_valid], axis=1
        )
        want = attention(
            q, k_cat, v_cat, valid[:, None, :], logit_softcap=softcap,
        )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=3e-5)


# -- forward-level parity ----------------------------------------------------


def _setup(name="tiny-llama"):
    cfg = get_config(name)
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    return cfg, params


def _prefill_prefix(cfg, params, prefix_tokens, p_cap):
    """Batch-1 prefill of the shared prefix → its KV cache stack."""
    pcache = init_kv_cache(cfg, batch=1, max_seq=p_cap, dtype=jnp.float32)
    _, pcache = forward(
        params, cfg, prefix_tokens[None], pcache, start_pos=0,
    )
    return pcache


@pytest.mark.parametrize("attn_impl", ["xla", "flash"])
def test_forward_prefix_decode_matches_full_prompt(attn_impl):
    """Suffix-resident decode with a shared prefix == full-prompt decode.

    Two rows share a 24-token prefix with different 8-token suffixes;
    the prefix path holds only suffixes in the batch cache. Logits at
    every decode step must match the plain full-cache path row by row.
    """
    cfg, params = _setup()
    key = jax.random.PRNGKey(3)
    p_len, s_len, p_cap, s_cap, steps = 24, 8, 32, 32, 4
    prefix = jax.random.randint(key, (p_len,), 0, cfg.vocab_size)
    suffixes = jax.random.randint(
        jax.random.PRNGKey(4), (2, s_len), 0, cfg.vocab_size
    )
    full_prompts = jnp.concatenate(
        [jnp.broadcast_to(prefix[None], (2, p_len)), suffixes], axis=1
    )

    with jax.default_matmul_precision("highest"):
        # Reference: plain full-prompt prefill + decode, batch of 2.
        ref_cache = init_kv_cache(cfg, batch=2, max_seq=64, dtype=jnp.float32)
        ref_logits, ref_cache = forward(
            params, cfg, full_prompts, ref_cache, start_pos=0,
        )
        # Prefix path: suffix-only batch cache against the shared prefix.
        pcache = _prefill_prefix(cfg, params, prefix, p_cap)
        got_cache = init_kv_cache(cfg, batch=2, max_seq=s_cap, dtype=jnp.float32)
        got_logits, got_cache = forward(
            params, cfg, suffixes, got_cache, start_pos=0,
            prefix=pcache, prefix_len=jnp.asarray(p_len, jnp.int32),
            prefix_rows=jnp.ones((2,), bool),
        )
        np.testing.assert_allclose(
            np.asarray(got_logits), np.asarray(ref_logits[:, p_len:]),
            atol=2e-3, rtol=2e-3,
        )

        tok = jnp.argmax(ref_logits[:, -1], axis=-1).astype(jnp.int32)
        ref_pos, got_pos = p_len + s_len, s_len
        for _ in range(steps):
            ref_step, ref_cache = forward(
                params, cfg, tok[:, None], ref_cache, start_pos=ref_pos,
                attn_impl=attn_impl,
            )
            got_step, got_cache = forward(
                params, cfg, tok[:, None], got_cache, start_pos=got_pos,
                attn_impl=attn_impl,
                prefix=pcache, prefix_len=jnp.asarray(p_len, jnp.int32),
                prefix_rows=jnp.ones((2,), bool),
            )
            np.testing.assert_allclose(
                np.asarray(got_step), np.asarray(ref_step),
                atol=2e-3, rtol=2e-3,
            )
            tok = jnp.argmax(ref_step[:, -1], axis=-1).astype(jnp.int32)
            ref_pos += 1
            got_pos += 1


@pytest.mark.parametrize("attn_impl", ["xla", "flash"])
def test_forward_prefix_mixed_rows(attn_impl):
    """A pool may hold prefix-sharing rows NEXT TO full-prompt rows: row 0
    attends the shared prefix (suffix-only window), row 1 carries its
    whole (unrelated) prompt in its own window with a row_start offset."""
    cfg, params = _setup()
    p_len, s_len, cap = 24, 8, 64
    n_other = p_len + s_len  # row 1's full prompt, same total length
    prefix = jax.random.randint(jax.random.PRNGKey(5), (p_len,), 0, cfg.vocab_size)
    suffix = jax.random.randint(jax.random.PRNGKey(6), (s_len,), 0, cfg.vocab_size)
    other = jax.random.randint(jax.random.PRNGKey(7), (n_other,), 0, cfg.vocab_size)

    with jax.default_matmul_precision("highest"):
        # References: two independent single-row runs.
        full_a = jnp.concatenate([prefix, suffix])[None]
        ca = init_kv_cache(cfg, batch=1, max_seq=cap, dtype=jnp.float32)
        la, ca = forward(params, cfg, full_a, ca, start_pos=0)
        cb = init_kv_cache(cfg, batch=1, max_seq=cap, dtype=jnp.float32)
        lb, cb = forward(params, cfg, other[None], cb, start_pos=0)
        tok_a = jnp.argmax(la[0, -1]).astype(jnp.int32)
        tok_b = jnp.argmax(lb[0, -1]).astype(jnp.int32)

        # Pool: shared frontier at n_other; row 0's suffix occupies
        # [n_other − s_len, n_other), row 1's prompt [0, n_other).
        pcache = _prefill_prefix(cfg, params, prefix, 32)
        pool = init_kv_cache(cfg, batch=2, max_seq=cap, dtype=jnp.float32)
        row_start = jnp.asarray([n_other - s_len, 0], jnp.int32)
        prefix_rows = jnp.asarray([True, False])
        # Admission-style splice: prefill each row separately, then place
        # its KV at the right offset by re-prefilling in place (simplest
        # correct construction for a unit test: write row 0's suffix and
        # row 1's prompt through the model at their pool offsets).
        sfx_logits, pool = forward(
            params, cfg,
            jnp.stack([
                jnp.concatenate([other[: n_other - s_len], suffix]),
                other,
            ]),
            pool, start_pos=0, row_start=row_start,
            prefix=pcache, prefix_len=jnp.asarray(p_len, jnp.int32),
            prefix_rows=prefix_rows,
        )
        # Row 0's slots below row_start hold junk from the construction
        # above; the mask must exclude them. Decode both rows together.
        tok = jnp.stack([tok_a, tok_b])
        pos = n_other
        for _ in range(3):
            ra, ca = forward(
                params, cfg, tok[:1, None] * 0 + tok_a, ca,
                start_pos=p_len + s_len + (pos - n_other), attn_impl=attn_impl,
            )
            rb, cb = forward(
                params, cfg, tok[1:, None] * 0 + tok_b, cb,
                start_pos=pos, attn_impl=attn_impl,
            )
            step, pool = forward(
                params, cfg, tok[:, None], pool, start_pos=pos,
                row_start=row_start, attn_impl=attn_impl,
                prefix=pcache, prefix_len=jnp.asarray(p_len, jnp.int32),
                prefix_rows=prefix_rows,
            )
            np.testing.assert_allclose(
                np.asarray(step[0]), np.asarray(ra[0]), atol=2e-3, rtol=2e-3,
            )
            np.testing.assert_allclose(
                np.asarray(step[1]), np.asarray(rb[0]), atol=2e-3, rtol=2e-3,
            )
            tok_a = jnp.argmax(ra[0, -1]).astype(jnp.int32)
            tok_b = jnp.argmax(rb[0, -1]).astype(jnp.int32)
            tok = jnp.stack([tok_a, tok_b])
            pos += 1


def test_forward_prefix_int8_kv_paths():
    """int8 KV caches (codes + seq-minor scales) through the prefix path:
    suffix decode with an int8 prefix + int8 pool must track the same
    int8 full-prompt reference within quantization tolerance."""
    cfg, params = _setup()
    p_len, s_len = 24, 8
    prefix = jax.random.randint(jax.random.PRNGKey(8), (p_len,), 0, cfg.vocab_size)
    suffixes = jax.random.randint(
        jax.random.PRNGKey(9), (2, s_len), 0, cfg.vocab_size
    )
    full_prompts = jnp.concatenate(
        [jnp.broadcast_to(prefix[None], (2, p_len)), suffixes], axis=1
    )
    with jax.default_matmul_precision("highest"):
        ref_cache = init_kv_cache(
            cfg, batch=2, max_seq=64, dtype=jnp.float32, quant="int8"
        )
        ref_logits, ref_cache = forward(
            params, cfg, full_prompts, ref_cache, start_pos=0,
        )
        pcache = init_kv_cache(
            cfg, batch=1, max_seq=32, dtype=jnp.float32, quant="int8"
        )
        _, pcache = forward(params, cfg, prefix[None], pcache, start_pos=0)
        got_cache = init_kv_cache(
            cfg, batch=2, max_seq=32, dtype=jnp.float32, quant="int8"
        )
        got_logits, got_cache = forward(
            params, cfg, suffixes, got_cache, start_pos=0,
            prefix=pcache, prefix_len=jnp.asarray(p_len, jnp.int32),
            prefix_rows=jnp.ones((2,), bool),
        )
        np.testing.assert_allclose(
            np.asarray(got_logits), np.asarray(ref_logits[:, p_len:]),
            atol=5e-2, rtol=5e-2,
        )
        tok = jnp.argmax(ref_logits[:, -1], axis=-1).astype(jnp.int32)
        ref_step, _ = forward(
            params, cfg, tok[:, None], ref_cache, start_pos=p_len + s_len,
            attn_impl="flash",
        )
        got_step, _ = forward(
            params, cfg, tok[:, None], got_cache, start_pos=s_len,
            attn_impl="flash",
            prefix=pcache, prefix_len=jnp.asarray(p_len, jnp.int32),
            prefix_rows=jnp.ones((2,), bool),
        )
        np.testing.assert_allclose(
            np.asarray(got_step), np.asarray(ref_step), atol=5e-2, rtol=5e-2,
        )


@pytest.mark.parametrize("name", ["tiny-gemma", "tiny-qwen2", "tiny-mixtral"])
def test_forward_prefix_other_families(name):
    """Family-specific details must survive the prefix split: gemma's
    norm offset + embed scale, qwen2's qkv bias, mixtral's routed MoE
    block (attention-side sharing must not disturb expert routing). One
    prefill + one decode step, suffix-resident vs full-prompt."""
    cfg, params = _setup(name)
    p_len, s_len = 24, 8
    prefix = jax.random.randint(jax.random.PRNGKey(10), (p_len,), 0, cfg.vocab_size)
    suffixes = jax.random.randint(
        jax.random.PRNGKey(11), (2, s_len), 0, cfg.vocab_size
    )
    full_prompts = jnp.concatenate(
        [jnp.broadcast_to(prefix[None], (2, p_len)), suffixes], axis=1
    )
    with jax.default_matmul_precision("highest"):
        ref_cache = init_kv_cache(cfg, batch=2, max_seq=64, dtype=jnp.float32)
        ref_logits, ref_cache = forward(
            params, cfg, full_prompts, ref_cache, start_pos=0,
        )
        pcache = _prefill_prefix(cfg, params, prefix, 32)
        got_cache = init_kv_cache(cfg, batch=2, max_seq=32, dtype=jnp.float32)
        got_logits, got_cache = forward(
            params, cfg, suffixes, got_cache, start_pos=0,
            prefix=pcache, prefix_len=jnp.asarray(p_len, jnp.int32),
            prefix_rows=jnp.ones((2,), bool),
        )
        np.testing.assert_allclose(
            np.asarray(got_logits), np.asarray(ref_logits[:, p_len:]),
            atol=2e-3, rtol=2e-3,
        )
        tok = jnp.argmax(ref_logits[:, -1], axis=-1).astype(jnp.int32)
        ref_step, _ = forward(
            params, cfg, tok[:, None], ref_cache, start_pos=p_len + s_len,
            attn_impl="flash",
        )
        got_step, _ = forward(
            params, cfg, tok[:, None], got_cache, start_pos=s_len,
            attn_impl="flash",
            prefix=pcache, prefix_len=jnp.asarray(p_len, jnp.int32),
            prefix_rows=jnp.ones((2,), bool),
        )
        np.testing.assert_allclose(
            np.asarray(got_step), np.asarray(ref_step), atol=2e-3, rtol=2e-3,
        )


def test_forward_prefix_rejects_sliding_window():
    cfg, params = _setup("tiny-mistral")
    pcache = init_kv_cache(cfg, batch=1, max_seq=32, dtype=jnp.float32)
    cache = init_kv_cache(cfg, batch=1, max_seq=32, dtype=jnp.float32)
    tokens = jnp.zeros((1, 4), jnp.int32)
    with pytest.raises(ValueError, match="sliding_window"):
        forward(
            params, cfg, tokens, cache, start_pos=0,
            prefix=pcache, prefix_len=jnp.asarray(8, jnp.int32),
        )
