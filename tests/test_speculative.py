"""Speculative decoding (engine/speculative.py + the batched pool mode).

TPU-build extension — no reference analog (SURVEY.md §2: remote HTTP
compute). The load-bearing property: greedy speculative output is
TOKEN-EXACT against the plain target engine for ANY draft — the draft
changes only speed. Acceptance-rate machinery is validated at both
extremes: a self-draft (target drafts for itself → every draft accepted)
and an unrelated random draft (≈ nothing accepted). The BATCHED form
(ContinuousBatcher spec mode: shared frontier + per-row holes behind
the written-slot bitmap) is validated against the single-stream engine
across batch sizes, mid-round exit/admission, and compaction.
"""

import time

import jax
import jax.numpy as jnp
import pytest

from llm_consensus_tpu.engine import (
    ContinuousBatcher, Engine, OracleDrafter, PromptLookupDrafter,
    SamplingParams, SpecConfig, SpeculativeEngine)
from llm_consensus_tpu.models import get_config, init_params
from llm_consensus_tpu.utils import Context


def _engine(preset, seed, **kw):
    cfg = get_config(preset)
    params = init_params(cfg, jax.random.PRNGKey(seed), dtype=jnp.float32)
    kw.setdefault("max_seq", 512)
    kw.setdefault("stream_interval", 8)
    return Engine(cfg, params=params, dtype=jnp.float32, **kw)


@pytest.fixture(scope="module")
def target():
    return _engine("tiny-llama", 0)


@pytest.fixture(scope="module")
def unrelated_draft():
    return _engine("tiny-llama", 7)  # same family, different weights


def test_exact_vs_plain_with_unrelated_draft(target, unrelated_draft):
    """Near-zero acceptance: output still byte-identical to the target."""
    spec = SpeculativeEngine(target, unrelated_draft, k=3)
    s = SamplingParams(max_new_tokens=48, ignore_eos=True)
    prompt = "speculative decoding exactness probe"
    got = spec.generate(prompt, s)
    ref = target.generate(prompt, s)
    assert got.token_ids == ref.token_ids
    assert got.text == ref.text
    assert got.finish_reason == ref.finish_reason
    # Random unrelated draft: acceptance stays near the 1-token floor.
    assert 1.0 <= spec.mean_accepted < 2.0


def test_self_draft_accepts_everything(target):
    """Target drafting for itself: every draft token matches, so each
    round advances k+1 tokens and output stays exact."""
    spec = SpeculativeEngine(target, target, k=3)
    s = SamplingParams(max_new_tokens=40, ignore_eos=True)
    prompt = "self speculation accepts all drafts"
    got = spec.generate(prompt, s)
    ref = target.generate(prompt, s)
    assert got.token_ids == ref.token_ids
    assert spec.mean_accepted == pytest.approx(4.0)  # k+1


def test_self_draft_shares_engine_safely(target):
    """Using one Engine object as both target and draft must not corrupt
    state across generates (separate caches per call)."""
    spec = SpeculativeEngine(target, target, k=2)
    s = SamplingParams(max_new_tokens=16, ignore_eos=True)
    a = spec.generate("first call", s).token_ids
    b = spec.generate("first call", s).token_ids
    assert a == b


def test_eos_respected(target, unrelated_draft):
    spec = SpeculativeEngine(target, unrelated_draft, k=3)
    s = SamplingParams(max_new_tokens=64)  # honors EOS
    got = spec.generate("eos handling probe", s)
    ref = target.generate("eos handling probe", s)
    assert got.finish_reason == ref.finish_reason
    assert got.token_ids == ref.token_ids


def test_streaming_callbacks(target, unrelated_draft):
    spec = SpeculativeEngine(target, unrelated_draft, k=2)
    s = SamplingParams(max_new_tokens=20, ignore_eos=True)
    chunks: list[str] = []
    got = spec.generate("stream me", s, on_text=chunks.append)
    assert "".join(chunks) == got.text


def test_topk_topp_delegate_to_plain_engine(target, unrelated_draft):
    """Truncated-distribution sampling stays on the plain engine (the
    documented rejection-sampling scope is pure temperature)."""
    spec = SpeculativeEngine(target, unrelated_draft, k=2)
    s = SamplingParams(max_new_tokens=12, temperature=0.8, top_k=20, seed=3,
                       ignore_eos=True)
    got = spec.generate("sampled fallback", s)
    ref = target.generate("sampled fallback", s)
    assert got.token_ids == ref.token_ids  # same engine, same seed path


def test_sampled_rejection_speculation_runs(target, unrelated_draft):
    """Pure-temperature sampling rides the draft via rejection sampling:
    requested token count, valid vocabulary ids, sane stats."""
    spec = SpeculativeEngine(target, unrelated_draft, k=3)
    s = SamplingParams(max_new_tokens=24, temperature=0.8, seed=5,
                       ignore_eos=True)
    got = spec.generate("rejection sampling probe", s)
    assert len(got.token_ids) == 24
    assert all(0 <= t < target.cfg.vocab_size for t in got.token_ids)
    assert got.finish_reason == "length"
    assert spec.stats["rounds"] > 0
    assert spec.mean_accepted >= 1.0


def test_sampled_self_draft_mean_acceptance_above_one(target):
    """Correlated draft (the target drafting for itself: p == q, so the
    acceptance probability is exactly 1): mean accepted run length must
    approach k+1 — the >1 acceptance pin for the sampled path (round-2
    VERDICT #4)."""
    spec = SpeculativeEngine(target, target, k=3)
    s = SamplingParams(max_new_tokens=32, temperature=0.7, seed=11,
                       ignore_eos=True)
    got = spec.generate("self drafted sampled speculation", s)
    assert len(got.token_ids) == 32
    assert spec.mean_accepted > 3.0, spec.mean_accepted  # k+1 = 4 ideal


def test_cancellation(target, unrelated_draft):
    spec = SpeculativeEngine(target, unrelated_draft, k=2)
    ctx = Context.background().with_timeout(0.0)
    got = spec.generate(
        "deadline immediately",
        SamplingParams(max_new_tokens=400, ignore_eos=True), ctx=ctx,
    )
    assert got.finish_reason == "deadline"
    assert len(got.token_ids) < 400


def test_partial_acceptance_regime_stays_exact(target):
    """A quantized copy of the target's own weights drafts for it:
    mostly-agreeing but imperfect proposals land acceptance strictly
    between the floor (1) and the ceiling (k+1), exercising the
    mid-round correction path (out[leading-1] re-ingestion) — and the
    output must STILL be token-exact."""
    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    draft = Engine(cfg, params=params, dtype=jnp.float32, max_seq=512,
                   stream_interval=8, quant="int8")
    spec = SpeculativeEngine(target, draft, k=4)
    s = SamplingParams(max_new_tokens=64, ignore_eos=True)
    prompt = "partial acceptance statistics probe"
    got = spec.generate(prompt, s)
    ref = target.generate(prompt, s)
    assert got.token_ids == ref.token_ids
    assert 1.0 < spec.mean_accepted < 5.0  # neither floor nor ceiling


def test_draft_window_too_small_delegates(target):
    small_draft = _engine("tiny-llama", 3, max_seq=16)
    spec = SpeculativeEngine(target, small_draft, k=4)
    s = SamplingParams(max_new_tokens=12, ignore_eos=True)
    prompt = "a prompt comfortably longer than the draft's tiny window"
    got = spec.generate(prompt, s)
    ref = target.generate(prompt, s)
    assert got.token_ids == ref.token_ids
    assert len(got.token_ids) == 12


def test_multi_device_engines_rejected(target):
    import numpy as np
    from jax.sharding import Mesh

    sharded = _engine("tiny-llama", 1)
    sharded.mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("tp",))
    with pytest.raises(ValueError, match="unsharded"):
        SpeculativeEngine(target, sharded)


def test_same_single_device_mesh_accepted():
    """The panel planner pins one-chip models to single-device meshes —
    speculation must attach there (pure placement, no sharding)."""
    import numpy as np
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1), ("tp",))
    tgt = _engine("tiny-llama", 0, mesh=mesh)
    drf = _engine("tiny-llama", 7, mesh=mesh)
    spec = SpeculativeEngine(tgt, drf, k=2)
    s = SamplingParams(max_new_tokens=10, ignore_eos=True)
    prompt = "single device mesh speculation"
    assert spec.generate(prompt, s).token_ids == tgt.generate(prompt, s).token_ids


def test_requested_tokens_beyond_draft_window_delegate(target):
    """A draft whose window is smaller than prompt + requested max_new
    must not silently cap the output (the round-1 bug returned 31 of a
    requested 120 tokens): the target's limits alone decide length."""
    small_draft = _engine("tiny-llama", 3, max_seq=64)
    spec = SpeculativeEngine(target, small_draft, k=4)
    s = SamplingParams(max_new_tokens=120, ignore_eos=True)
    prompt = "short prompt"  # fits the draft; prompt + 120 does not
    got = spec.generate(prompt, s)
    ref = target.generate(prompt, s)
    assert got.token_ids == ref.token_ids
    assert len(got.token_ids) == 120
    assert got.finish_reason == ref.finish_reason == "length"


def test_provider_draft_flag_exactness():
    """LLMC_DRAFT through the provider seam: greedy output with a draft
    attached is identical to the plain provider path, and the spec
    engine is actually engaged."""
    from llm_consensus_tpu.providers.base import Request
    from llm_consensus_tpu.providers.tpu import TPUProvider

    plain = TPUProvider(ignore_eos=True, stream_interval=4)
    drafted = TPUProvider(ignore_eos=True, stream_interval=4,
                          draft="tiny-llama")
    req = Request(model="tpu:tiny-mistral", prompt="drafted consensus check",
                  max_tokens=16)
    want = plain.query(Context.background(), req)
    got = drafted.query(Context.background(), req)
    assert got.content == want.content
    entry = drafted._specs.get("tiny-mistral")
    assert entry is not None and entry[1] is not None
    assert entry[1].stats["rounds"] > 0


def test_provider_draft_self_pair_disabled():
    """target == draft configures nothing (a model can't draft itself
    through the map; the self-draft case is a test-only construction)."""
    from llm_consensus_tpu.providers.tpu import TPUProvider

    provider = TPUProvider(ignore_eos=True, stream_interval=4,
                           draft="tiny-llama")
    assert provider._draft_preset_for("tiny-llama") is None
    assert provider._draft_preset_for("tiny-mistral") == "tiny-llama"


def test_provider_draft_pair_spec_parsing():
    from llm_consensus_tpu.providers.tpu import _parse_draft_spec

    assert _parse_draft_spec("") == {}
    assert _parse_draft_spec("tiny-llama") == {"*": "tiny-llama"}
    assert _parse_draft_spec("a=b, c=d") == {"a": "b", "c": "d"}
    assert _parse_draft_spec("a=b,fallback") == {"a": "b", "*": "fallback"}


def _ids(eng, prompt, max_new):
    return eng._budget_prompt(eng.tokenizer.encode(prompt), max_new)[0]


def _pool_run(eng, prompts, max_new, spec, stagger_s=0.0):
    b = ContinuousBatcher(eng, max_batch=4, spec=spec)
    try:
        futs = []
        for p, m in zip(prompts, max_new):
            futs.append(b.submit(
                p, SamplingParams(max_new_tokens=m, ignore_eos=True)
            ))
            if stagger_s:
                time.sleep(stagger_s)
        results = [f.result(timeout=600) for f in futs]
        snap = b.spec_snapshot()
    finally:
        b.close()
    return results, snap


class TestBatchedSpec:
    """ContinuousBatcher spec mode: batched verification over the shared
    frontier with per-row acceptance as data (holes + bitmap)."""

    def test_token_exact_across_batch_sizes(self, target):
        prompts = [
            "batched speculative exactness probe",
            "a second stream with a rather longer prompt body to vary",
            "third",
            "the fourth resident stream",
        ]
        max_new = [24, 17, 31, 9]  # staggered mid-round exits
        refs = [
            target.generate(
                p, SamplingParams(max_new_tokens=m, ignore_eos=True)
            )
            for p, m in zip(prompts, max_new)
        ]
        for n in (1, 4):
            results, snap = _pool_run(
                target, prompts[:n], max_new[:n],
                SpecConfig(kind="lookup", k=3, governor=False),
            )
            assert [r.token_ids for r in results] == \
                [r.token_ids for r in refs[:n]]
            assert snap["rounds"] > 0

    def test_mid_stream_admission(self, target):
        """A stream admitted while the pool is mid-spec-rounds (splice at
        the advanced frontier, bitmap row installed over the spliced
        window) must still be token-exact."""
        p1, p2 = "the long-running resident stream", "late admission"
        r1 = target.generate(
            p1, SamplingParams(max_new_tokens=48, ignore_eos=True)
        )
        r2 = target.generate(
            p2, SamplingParams(max_new_tokens=16, ignore_eos=True)
        )
        results, _snap = _pool_run(
            target, [p1, p2], [48, 16],
            SpecConfig(kind="lookup", k=3, governor=False),
            stagger_s=0.5,
        )
        assert results[0].token_ids == r1.token_ids
        assert results[1].token_ids == r2.token_ids

    def test_oracle_full_acceptance(self, target):
        """An oracle replaying the target's own greedy output forces
        a=k+1 every round — the machinery's ceiling — and the output is
        still token-exact."""
        prompts = ["oracle pool stream a", "oracle pool stream b longer"]
        max_new = [20, 26]
        refs = {
            p: target.generate(
                p, SamplingParams(max_new_tokens=m, ignore_eos=True)
            )
            for p, m in zip(prompts, max_new)
        }
        by_ids = {
            tuple(_ids(target, p, m)): refs[p].token_ids
            for p, m in zip(prompts, max_new)
        }
        results, snap = _pool_run(
            target, prompts, max_new,
            SpecConfig(
                kind="oracle", k=3, adaptive=False, governor=False,
                oracle=lambda ids: by_ids.get(tuple(ids), []),
            ),
        )
        for r, p in zip(results, prompts):
            assert r.token_ids == refs[p].token_ids
        assert snap["mean_accepted"] > 3.0, snap  # k+1 = 4 ceiling

    def test_compaction_with_holes(self):
        """The waterline path under spec mode: rejected-slot holes mean
        row_start no longer names the window start — compaction's
        retire/reclaim must read slot_base, roll the bitmap with the
        cache, and stay token-exact through the slide."""
        from llm_consensus_tpu import obs

        cfg = get_config("tiny-llama")
        params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
        eng = Engine(cfg, params=params, dtype=jnp.float32, max_seq=128,
                     stream_interval=8)
        pa = "waterline filler prompt " * 9  # pushes the idle frontier up
        pb = "the stream that outlives compaction"
        ra = eng.generate(
            pa, SamplingParams(max_new_tokens=10, ignore_eos=True)
        )
        rb = eng.generate(
            pb, SamplingParams(max_new_tokens=24, ignore_eos=True)
        )
        obs.install(obs.Recorder())
        try:
            results, _snap = _pool_run(
                eng, [pa, pb], [10, 24],
                SpecConfig(kind="lookup", k=3, adaptive=False,
                           governor=False),
            )
            assert results[0].token_ids == ra.token_ids
            assert results[1].token_ids == rb.token_ids
            # Deterministic given fixed weights: stream B outlives A and
            # drives the frontier to capacity, so the slide really ran.
            assert "compact" in obs.recorder().span_names()
        finally:
            obs.reset()

    def test_sampled_template_keeps_classic_path(self, target):
        """A spec-enabled pool whose template is sampled must decode
        through the classic chunk program (spec rounds are greedy-only),
        not fail or bend the distribution machinery."""
        b = ContinuousBatcher(
            target, max_batch=2,
            spec=SpecConfig(kind="lookup", k=3, governor=False),
        )
        try:
            fut = b.submit("sampled template probe", SamplingParams(
                max_new_tokens=8, temperature=0.8, seed=3,
                ignore_eos=True,
            ))
            r = fut.result(timeout=600)
            snap = b.spec_snapshot()
        finally:
            b.close()
        assert len(r.token_ids) == 8
        assert snap["rounds"] == 0  # no spec round ever dispatched

    def test_spec_with_kv_pool(self, monkeypatch):
        """Spec streams lease/publish through the paged KV pool like any
        other stream (LLMC_KV_POOL=1): admission prefill rides pool hits
        and greedy bytes stay identical pool-on vs pool-off."""
        monkeypatch.setenv("LLMC_KV_POOL", "1")
        monkeypatch.setenv("LLMC_KV_POOL_BLOCK", "16")
        cfg = get_config("tiny-llama")
        params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
        eng = Engine(cfg, params=params, dtype=jnp.float32, max_seq=512,
                     stream_interval=8)
        assert eng._kv_pool is not None
        prompts = ["kv pool spec stream one", "kv pool spec stream two"]
        refs = [
            eng.generate(
                p, SamplingParams(max_new_tokens=14, ignore_eos=True)
            )
            for p in prompts
        ]
        results, snap = _pool_run(
            eng, prompts, [14, 14],
            SpecConfig(kind="lookup", k=3, governor=False),
        )
        assert [r.token_ids for r in results] == \
            [r.token_ids for r in refs]
        assert snap["rounds"] > 0

    def test_acceptance_collapse_fault_exact(self, target):
        """The spec fault site: permanent acceptance_collapse junks
        every round's proposals — acceptance pins to ~1 and greedy
        output must be UNCHANGED (speed fault, never correctness)."""
        from llm_consensus_tpu import faults

        prompt = "collapse fault exactness probe"
        ref = target.generate(
            prompt, SamplingParams(max_new_tokens=20, ignore_eos=True)
        )
        faults.install(
            faults.FaultPlan("acceptance_collapse@times=-1", seed=3)
        )
        try:
            # Fresh engine AFTER the install: fault plans bind at
            # construction (the zero-cost pattern), so the module-scoped
            # target never sees this plan.
            cfg = get_config("tiny-llama")
            params = init_params(cfg, jax.random.PRNGKey(0),
                                 dtype=jnp.float32)
            eng = Engine(cfg, params=params, dtype=jnp.float32,
                         max_seq=512, stream_interval=8)
            results, snap = _pool_run(
                eng, [prompt], [20],
                SpecConfig(kind="lookup", k=3, adaptive=False,
                           governor=False),
            )
        finally:
            faults.reset()
        assert results[0].token_ids == ref.token_ids
        assert snap["collapse_faults"] > 0
        assert snap["mean_accepted"] < 1.5, snap  # proposals were junk


class TestControlPlane:
    """AdaptiveK ladder + SpecGovernor state machine (host-side units)."""

    def test_adaptive_k_converges_down_on_collapse(self):
        from llm_consensus_tpu.engine.speculative import AdaptiveK

        c = AdaptiveK(8)
        assert c.k == 8  # optimistic start
        for _ in range(40):
            c.observe(1.0, c.k)  # only the correction token, every round
        assert c.k == 1

    def test_adaptive_k_regrows_on_wins(self):
        from llm_consensus_tpu.engine.speculative import AdaptiveK

        c = AdaptiveK(8)
        for _ in range(40):
            c.observe(1.0, c.k)
        assert c.k == 1
        for _ in range(60):
            c.observe(c.k + 1, c.k)  # ceiling acceptance at every rung
        assert c.k == 8

    def test_adaptive_k_ladder_is_pow2_bounded(self):
        from llm_consensus_tpu.engine.speculative import k_ladder

        assert k_ladder(1) == [1]
        assert k_ladder(4) == [1, 2, 4]
        assert k_ladder(6) == [1, 2, 4, 6]
        assert k_ladder(8) == [1, 2, 4, 8]

    def test_adaptive_off_pins_k(self):
        from llm_consensus_tpu.engine.speculative import AdaptiveK

        c = AdaptiveK(4, adaptive=False)
        for _ in range(50):
            c.observe(1.0, c.k)
        assert c.k == 4

    def test_governor_locks_faster_mode(self):
        from llm_consensus_tpu.engine.speculative import SpecGovernor

        g = SpecGovernor(probe_tokens=10)
        assert g.mode == "spec"
        assert g.feed(10, 1.0) is True          # spec probe: 10 tok/s
        assert g.mode == "plain"
        assert g.feed(10, 0.5) is False         # plain probe: 20 tok/s
        assert g.state == "plain_locked"
        assert g.disabled_spec is True
        assert g.mode == "plain"

    def test_governor_keeps_winning_spec(self):
        from llm_consensus_tpu.engine.speculative import SpecGovernor

        g = SpecGovernor(probe_tokens=10)
        g.feed(10, 0.5)                          # spec: 20 tok/s
        assert g.feed(10, 1.0) is True           # plain: 10 tok/s
        assert g.state == "spec_locked"
        assert g.disabled_spec is False
        assert g.mode == "spec"

    def test_governor_disabled_runs_spec_forever(self):
        from llm_consensus_tpu.engine.speculative import SpecGovernor

        g = SpecGovernor(enabled=False)
        assert g.state == "spec_locked"
        assert g.feed(1000, 1000.0) is False
        assert g.mode == "spec"


class TestDrafters:
    """Buffer drafter proposal programs (device units)."""

    def test_prompt_lookup_proposes_matched_continuation(self):
        from llm_consensus_tpu.engine.speculative import _lookup_propose

        # Buffer: ... 7 8 9 ... 7 8 | known length 12, gram (7, 8).
        buf = jnp.asarray(
            [[1, 2, 7, 8, 9, 4, 5, 6, 3, 2, 7, 8, 0, 0, 0, 0]], jnp.int32
        )
        blen = jnp.asarray([12], jnp.int32)
        props = _lookup_propose(buf, blen, k=3, g=2)
        # Most recent earlier occurrence of (7, 8) is at 2; continuation
        # is 9, 4, 5.
        assert props.tolist() == [[9, 4, 5]]

    def test_prompt_lookup_no_match_repeats_last(self):
        from llm_consensus_tpu.engine.speculative import _lookup_propose

        buf = jnp.asarray([[1, 2, 3, 4, 5, 6, 0, 0]], jnp.int32)
        blen = jnp.asarray([6], jnp.int32)
        props = _lookup_propose(buf, blen, k=2, g=3)
        assert props.tolist() == [[6, 6]]  # repetition fallback

    def test_oracle_propose_accept_knob(self):
        from llm_consensus_tpu.engine.speculative import _oracle_propose

        obuf = jnp.asarray([[10, 11, 12, 13, 14, 15, 16, 17]], jnp.int32)
        blen = jnp.asarray([3], jnp.int32)
        full = _oracle_propose(obuf, blen, k=3, vocab=100)
        assert full.tolist() == [[13, 14, 15]]
        forced = _oracle_propose(obuf, blen, k=3, vocab=100, accept=2)
        # First accept-1 = 1 proposal true, the rest perturbed (+1).
        assert forced.tolist() == [[13, 15, 16]]

    def test_oracle_forced_acceptance_levels(self, target):
        """accept=a makes every single-stream round accept EXACTLY a
        (the bench's sweep knob) while output stays exact."""
        prompt = "forced acceptance sweep probe"
        s = SamplingParams(max_new_tokens=24, ignore_eos=True)
        ref = target.generate(prompt, s)
        cont = ref.token_ids
        for accept in (1, 2):
            spec = SpeculativeEngine(
                target, OracleDrafter(cont, accept=accept), k=3,
                adaptive=False, governor=False,
            )
            got = spec.generate(prompt, s)
            assert got.token_ids == ref.token_ids
            assert spec.mean_accepted == pytest.approx(accept, abs=0.35)

    def test_oracle_single_stream_ceiling(self, target):
        prompt = "oracle ceiling probe"
        s = SamplingParams(max_new_tokens=24, ignore_eos=True)
        ref = target.generate(prompt, s)
        spec = SpeculativeEngine(
            target, OracleDrafter(ref.token_ids), k=3,
            adaptive=False, governor=False,
        )
        got = spec.generate(prompt, s)
        assert got.token_ids == ref.token_ids
        assert spec.mean_accepted == pytest.approx(4.0, abs=0.5)

    def test_prompt_lookup_single_stream_exact(self, target):
        spec = SpeculativeEngine(
            target, PromptLookupDrafter(), k=3, governor=False,
        )
        s = SamplingParams(max_new_tokens=32, ignore_eos=True)
        prompt = "prompt lookup drafter single stream probe"
        got = spec.generate(prompt, s)
        ref = target.generate(prompt, s)
        assert got.token_ids == ref.token_ids
        assert got.spec is not None and got.spec["rounds"] > 0


def test_sampled_key_schedule_immune_to_fetch_batching(target,
                                                       unrelated_draft):
    """The sampled path's key schedule is a pure function of the round
    counter — NOT of drain cadence — so changing rounds_per_chunk (fetch
    batching) must not change a seeded generation's tokens. A schedule
    keyed on len(out_ids)/pos_ub would collide across fetch batches and
    bend the output distribution. k is pinned (adaptive off): the
    controller observes at DRAIN boundaries, so adaptive k would
    legitimately walk different ladders under different cadences."""
    s = SamplingParams(max_new_tokens=24, temperature=0.8, seed=9,
                      ignore_eos=True)
    prompt = "key schedule collision probe"
    one = SpeculativeEngine(
        target, unrelated_draft, k=3, rounds_per_chunk=1, adaptive=False,
    ).generate(prompt, s)
    batched = SpeculativeEngine(
        target, unrelated_draft, k=3, rounds_per_chunk=8, adaptive=False,
    ).generate(prompt, s)
    assert one.token_ids == batched.token_ids


def test_cli_draft_flag_token_exact(monkeypatch):
    """--draft through the full CLI produces the identical consensus to a
    run without it (greedy exactness at the product surface) — and the
    draft actually engages (placement pinned to one device; a wider
    planner mesh would silently disable speculation and make the
    exactness assertion vacuous)."""
    import io
    import json

    from llm_consensus_tpu.cli.main import main
    from llm_consensus_tpu.providers.tpu import TPUProvider

    orig_prepare = TPUProvider.prepare
    monkeypatch.setattr(
        TPUProvider, "prepare",
        lambda self, models, judge, devices=None: orig_prepare(
            self, models, judge, devices=jax.devices()[:1]
        ),
    )

    def run_cli(extra):
        # Fresh shared provider per invocation: draft state and engines
        # must not carry across the compared runs.
        monkeypatch.setattr(TPUProvider, "_shared", None)
        stdout, stderr = io.StringIO(), io.StringIO()
        code = main(
            ["--models", "tpu:tiny-mistral", "--judge", "tpu:tiny-mistral",
             "--json", "--no-save", "--max-tokens", "16", "exact check"]
            + extra,
            stdin=io.StringIO(""), stdout=stdout, stderr=stderr,
            install_signal_handlers=False,
        )
        assert code == 0, stderr.getvalue()
        return json.loads(stdout.getvalue()), TPUProvider._shared

    plain, _ = run_cli([])
    drafted, provider = run_cli(["--draft", "tiny-llama"])
    assert drafted["responses"][0]["content"] == plain["responses"][0]["content"]
    assert drafted["consensus"] == plain["consensus"]
    entry = provider._specs.get("tiny-mistral")
    assert entry is not None and entry[1] is not None, "draft never engaged"
    assert entry[1].stats["rounds"] > 0
