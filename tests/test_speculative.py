"""Speculative decoding (engine/speculative.py).

TPU-build extension — no reference analog (SURVEY.md §2: remote HTTP
compute). The load-bearing property: greedy speculative output is
TOKEN-EXACT against the plain target engine for ANY draft — the draft
changes only speed. Acceptance-rate machinery is validated at both
extremes: a self-draft (target drafts for itself → every draft accepted)
and an unrelated random draft (≈ nothing accepted).
"""

import jax
import jax.numpy as jnp
import pytest

from llm_consensus_tpu.engine import Engine, SamplingParams, SpeculativeEngine
from llm_consensus_tpu.models import get_config, init_params
from llm_consensus_tpu.utils import Context


def _engine(preset, seed, **kw):
    cfg = get_config(preset)
    params = init_params(cfg, jax.random.PRNGKey(seed), dtype=jnp.float32)
    kw.setdefault("max_seq", 512)
    kw.setdefault("stream_interval", 8)
    return Engine(cfg, params=params, dtype=jnp.float32, **kw)


@pytest.fixture(scope="module")
def target():
    return _engine("tiny-llama", 0)


@pytest.fixture(scope="module")
def unrelated_draft():
    return _engine("tiny-llama", 7)  # same family, different weights


def test_exact_vs_plain_with_unrelated_draft(target, unrelated_draft):
    """Near-zero acceptance: output still byte-identical to the target."""
    spec = SpeculativeEngine(target, unrelated_draft, k=3)
    s = SamplingParams(max_new_tokens=48, ignore_eos=True)
    prompt = "speculative decoding exactness probe"
    got = spec.generate(prompt, s)
    ref = target.generate(prompt, s)
    assert got.token_ids == ref.token_ids
    assert got.text == ref.text
    assert got.finish_reason == ref.finish_reason
    # Random unrelated draft: acceptance stays near the 1-token floor.
    assert 1.0 <= spec.mean_accepted < 2.0


def test_self_draft_accepts_everything(target):
    """Target drafting for itself: every draft token matches, so each
    round advances k+1 tokens and output stays exact."""
    spec = SpeculativeEngine(target, target, k=3)
    s = SamplingParams(max_new_tokens=40, ignore_eos=True)
    prompt = "self speculation accepts all drafts"
    got = spec.generate(prompt, s)
    ref = target.generate(prompt, s)
    assert got.token_ids == ref.token_ids
    assert spec.mean_accepted == pytest.approx(4.0)  # k+1


def test_self_draft_shares_engine_safely(target):
    """Using one Engine object as both target and draft must not corrupt
    state across generates (separate caches per call)."""
    spec = SpeculativeEngine(target, target, k=2)
    s = SamplingParams(max_new_tokens=16, ignore_eos=True)
    a = spec.generate("first call", s).token_ids
    b = spec.generate("first call", s).token_ids
    assert a == b


def test_eos_respected(target, unrelated_draft):
    spec = SpeculativeEngine(target, unrelated_draft, k=3)
    s = SamplingParams(max_new_tokens=64)  # honors EOS
    got = spec.generate("eos handling probe", s)
    ref = target.generate("eos handling probe", s)
    assert got.finish_reason == ref.finish_reason
    assert got.token_ids == ref.token_ids


def test_streaming_callbacks(target, unrelated_draft):
    spec = SpeculativeEngine(target, unrelated_draft, k=2)
    s = SamplingParams(max_new_tokens=20, ignore_eos=True)
    chunks: list[str] = []
    got = spec.generate("stream me", s, on_text=chunks.append)
    assert "".join(chunks) == got.text


def test_sampled_params_delegate_to_plain_engine(target, unrelated_draft):
    spec = SpeculativeEngine(target, unrelated_draft, k=2)
    s = SamplingParams(max_new_tokens=12, temperature=0.8, seed=3,
                       ignore_eos=True)
    got = spec.generate("sampled fallback", s)
    ref = target.generate("sampled fallback", s)
    assert got.token_ids == ref.token_ids  # same engine, same seed path


def test_cancellation(target, unrelated_draft):
    spec = SpeculativeEngine(target, unrelated_draft, k=2)
    ctx = Context.background().with_timeout(0.0)
    got = spec.generate(
        "deadline immediately",
        SamplingParams(max_new_tokens=400, ignore_eos=True), ctx=ctx,
    )
    assert got.finish_reason == "deadline"
    assert len(got.token_ids) < 400


def test_partial_acceptance_regime_stays_exact(target):
    """A quantized copy of the target's own weights drafts for it:
    mostly-agreeing but imperfect proposals land acceptance strictly
    between the floor (1) and the ceiling (k+1), exercising the
    mid-round correction path (out[leading-1] re-ingestion) — and the
    output must STILL be token-exact."""
    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    draft = Engine(cfg, params=params, dtype=jnp.float32, max_seq=512,
                   stream_interval=8, quant="int8")
    spec = SpeculativeEngine(target, draft, k=4)
    s = SamplingParams(max_new_tokens=64, ignore_eos=True)
    prompt = "partial acceptance statistics probe"
    got = spec.generate(prompt, s)
    ref = target.generate(prompt, s)
    assert got.token_ids == ref.token_ids
    assert 1.0 < spec.mean_accepted < 5.0  # neither floor nor ceiling


def test_draft_window_too_small_delegates(target):
    small_draft = _engine("tiny-llama", 3, max_seq=16)
    spec = SpeculativeEngine(target, small_draft, k=4)
    s = SamplingParams(max_new_tokens=12, ignore_eos=True)
    prompt = "a prompt comfortably longer than the draft's tiny window"
    got = spec.generate(prompt, s)
    ref = target.generate(prompt, s)
    assert got.token_ids == ref.token_ids
    assert len(got.token_ids) == 12


def test_sharded_engines_rejected(target):
    class FakeMesh:
        pass

    sharded = _engine("tiny-llama", 1)
    sharded.mesh = FakeMesh()
    with pytest.raises(ValueError, match="unsharded"):
        SpeculativeEngine(target, sharded)
