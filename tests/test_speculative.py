"""Speculative decoding (engine/speculative.py).

TPU-build extension — no reference analog (SURVEY.md §2: remote HTTP
compute). The load-bearing property: greedy speculative output is
TOKEN-EXACT against the plain target engine for ANY draft — the draft
changes only speed. Acceptance-rate machinery is validated at both
extremes: a self-draft (target drafts for itself → every draft accepted)
and an unrelated random draft (≈ nothing accepted).
"""

import jax
import jax.numpy as jnp
import pytest

from llm_consensus_tpu.engine import Engine, SamplingParams, SpeculativeEngine
from llm_consensus_tpu.models import get_config, init_params
from llm_consensus_tpu.utils import Context


def _engine(preset, seed, **kw):
    cfg = get_config(preset)
    params = init_params(cfg, jax.random.PRNGKey(seed), dtype=jnp.float32)
    kw.setdefault("max_seq", 512)
    kw.setdefault("stream_interval", 8)
    return Engine(cfg, params=params, dtype=jnp.float32, **kw)


@pytest.fixture(scope="module")
def target():
    return _engine("tiny-llama", 0)


@pytest.fixture(scope="module")
def unrelated_draft():
    return _engine("tiny-llama", 7)  # same family, different weights


def test_exact_vs_plain_with_unrelated_draft(target, unrelated_draft):
    """Near-zero acceptance: output still byte-identical to the target."""
    spec = SpeculativeEngine(target, unrelated_draft, k=3)
    s = SamplingParams(max_new_tokens=48, ignore_eos=True)
    prompt = "speculative decoding exactness probe"
    got = spec.generate(prompt, s)
    ref = target.generate(prompt, s)
    assert got.token_ids == ref.token_ids
    assert got.text == ref.text
    assert got.finish_reason == ref.finish_reason
    # Random unrelated draft: acceptance stays near the 1-token floor.
    assert 1.0 <= spec.mean_accepted < 2.0


def test_self_draft_accepts_everything(target):
    """Target drafting for itself: every draft token matches, so each
    round advances k+1 tokens and output stays exact."""
    spec = SpeculativeEngine(target, target, k=3)
    s = SamplingParams(max_new_tokens=40, ignore_eos=True)
    prompt = "self speculation accepts all drafts"
    got = spec.generate(prompt, s)
    ref = target.generate(prompt, s)
    assert got.token_ids == ref.token_ids
    assert spec.mean_accepted == pytest.approx(4.0)  # k+1


def test_self_draft_shares_engine_safely(target):
    """Using one Engine object as both target and draft must not corrupt
    state across generates (separate caches per call)."""
    spec = SpeculativeEngine(target, target, k=2)
    s = SamplingParams(max_new_tokens=16, ignore_eos=True)
    a = spec.generate("first call", s).token_ids
    b = spec.generate("first call", s).token_ids
    assert a == b


def test_eos_respected(target, unrelated_draft):
    spec = SpeculativeEngine(target, unrelated_draft, k=3)
    s = SamplingParams(max_new_tokens=64)  # honors EOS
    got = spec.generate("eos handling probe", s)
    ref = target.generate("eos handling probe", s)
    assert got.finish_reason == ref.finish_reason
    assert got.token_ids == ref.token_ids


def test_streaming_callbacks(target, unrelated_draft):
    spec = SpeculativeEngine(target, unrelated_draft, k=2)
    s = SamplingParams(max_new_tokens=20, ignore_eos=True)
    chunks: list[str] = []
    got = spec.generate("stream me", s, on_text=chunks.append)
    assert "".join(chunks) == got.text


def test_topk_topp_delegate_to_plain_engine(target, unrelated_draft):
    """Truncated-distribution sampling stays on the plain engine (the
    documented rejection-sampling scope is pure temperature)."""
    spec = SpeculativeEngine(target, unrelated_draft, k=2)
    s = SamplingParams(max_new_tokens=12, temperature=0.8, top_k=20, seed=3,
                       ignore_eos=True)
    got = spec.generate("sampled fallback", s)
    ref = target.generate("sampled fallback", s)
    assert got.token_ids == ref.token_ids  # same engine, same seed path


def test_sampled_rejection_speculation_runs(target, unrelated_draft):
    """Pure-temperature sampling rides the draft via rejection sampling:
    requested token count, valid vocabulary ids, sane stats."""
    spec = SpeculativeEngine(target, unrelated_draft, k=3)
    s = SamplingParams(max_new_tokens=24, temperature=0.8, seed=5,
                       ignore_eos=True)
    got = spec.generate("rejection sampling probe", s)
    assert len(got.token_ids) == 24
    assert all(0 <= t < target.cfg.vocab_size for t in got.token_ids)
    assert got.finish_reason == "length"
    assert spec.stats["rounds"] > 0
    assert spec.mean_accepted >= 1.0


def test_sampled_self_draft_mean_acceptance_above_one(target):
    """Correlated draft (the target drafting for itself: p == q, so the
    acceptance probability is exactly 1): mean accepted run length must
    approach k+1 — the >1 acceptance pin for the sampled path (round-2
    VERDICT #4)."""
    spec = SpeculativeEngine(target, target, k=3)
    s = SamplingParams(max_new_tokens=32, temperature=0.7, seed=11,
                       ignore_eos=True)
    got = spec.generate("self drafted sampled speculation", s)
    assert len(got.token_ids) == 32
    assert spec.mean_accepted > 3.0, spec.mean_accepted  # k+1 = 4 ideal


def test_cancellation(target, unrelated_draft):
    spec = SpeculativeEngine(target, unrelated_draft, k=2)
    ctx = Context.background().with_timeout(0.0)
    got = spec.generate(
        "deadline immediately",
        SamplingParams(max_new_tokens=400, ignore_eos=True), ctx=ctx,
    )
    assert got.finish_reason == "deadline"
    assert len(got.token_ids) < 400


def test_partial_acceptance_regime_stays_exact(target):
    """A quantized copy of the target's own weights drafts for it:
    mostly-agreeing but imperfect proposals land acceptance strictly
    between the floor (1) and the ceiling (k+1), exercising the
    mid-round correction path (out[leading-1] re-ingestion) — and the
    output must STILL be token-exact."""
    cfg = get_config("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    draft = Engine(cfg, params=params, dtype=jnp.float32, max_seq=512,
                   stream_interval=8, quant="int8")
    spec = SpeculativeEngine(target, draft, k=4)
    s = SamplingParams(max_new_tokens=64, ignore_eos=True)
    prompt = "partial acceptance statistics probe"
    got = spec.generate(prompt, s)
    ref = target.generate(prompt, s)
    assert got.token_ids == ref.token_ids
    assert 1.0 < spec.mean_accepted < 5.0  # neither floor nor ceiling


def test_draft_window_too_small_delegates(target):
    small_draft = _engine("tiny-llama", 3, max_seq=16)
    spec = SpeculativeEngine(target, small_draft, k=4)
    s = SamplingParams(max_new_tokens=12, ignore_eos=True)
    prompt = "a prompt comfortably longer than the draft's tiny window"
    got = spec.generate(prompt, s)
    ref = target.generate(prompt, s)
    assert got.token_ids == ref.token_ids
    assert len(got.token_ids) == 12


def test_multi_device_engines_rejected(target):
    import numpy as np
    from jax.sharding import Mesh

    sharded = _engine("tiny-llama", 1)
    sharded.mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("tp",))
    with pytest.raises(ValueError, match="unsharded"):
        SpeculativeEngine(target, sharded)


def test_same_single_device_mesh_accepted():
    """The panel planner pins one-chip models to single-device meshes —
    speculation must attach there (pure placement, no sharding)."""
    import numpy as np
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1), ("tp",))
    tgt = _engine("tiny-llama", 0, mesh=mesh)
    drf = _engine("tiny-llama", 7, mesh=mesh)
    spec = SpeculativeEngine(tgt, drf, k=2)
    s = SamplingParams(max_new_tokens=10, ignore_eos=True)
    prompt = "single device mesh speculation"
    assert spec.generate(prompt, s).token_ids == tgt.generate(prompt, s).token_ids


def test_requested_tokens_beyond_draft_window_delegate(target):
    """A draft whose window is smaller than prompt + requested max_new
    must not silently cap the output (the round-1 bug returned 31 of a
    requested 120 tokens): the target's limits alone decide length."""
    small_draft = _engine("tiny-llama", 3, max_seq=64)
    spec = SpeculativeEngine(target, small_draft, k=4)
    s = SamplingParams(max_new_tokens=120, ignore_eos=True)
    prompt = "short prompt"  # fits the draft; prompt + 120 does not
    got = spec.generate(prompt, s)
    ref = target.generate(prompt, s)
    assert got.token_ids == ref.token_ids
    assert len(got.token_ids) == 120
    assert got.finish_reason == ref.finish_reason == "length"


def test_provider_draft_flag_exactness():
    """LLMC_DRAFT through the provider seam: greedy output with a draft
    attached is identical to the plain provider path, and the spec
    engine is actually engaged."""
    from llm_consensus_tpu.providers.base import Request
    from llm_consensus_tpu.providers.tpu import TPUProvider

    plain = TPUProvider(ignore_eos=True, stream_interval=4)
    drafted = TPUProvider(ignore_eos=True, stream_interval=4,
                          draft="tiny-llama")
    req = Request(model="tpu:tiny-mistral", prompt="drafted consensus check",
                  max_tokens=16)
    want = plain.query(Context.background(), req)
    got = drafted.query(Context.background(), req)
    assert got.content == want.content
    entry = drafted._specs.get("tiny-mistral")
    assert entry is not None and entry[1] is not None
    assert entry[1].stats["rounds"] > 0


def test_provider_draft_self_pair_disabled():
    """target == draft configures nothing (a model can't draft itself
    through the map; the self-draft case is a test-only construction)."""
    from llm_consensus_tpu.providers.tpu import TPUProvider

    provider = TPUProvider(ignore_eos=True, stream_interval=4,
                           draft="tiny-llama")
    assert provider._draft_preset_for("tiny-llama") is None
    assert provider._draft_preset_for("tiny-mistral") == "tiny-llama"


def test_provider_draft_pair_spec_parsing():
    from llm_consensus_tpu.providers.tpu import _parse_draft_spec

    assert _parse_draft_spec("") == {}
    assert _parse_draft_spec("tiny-llama") == {"*": "tiny-llama"}
    assert _parse_draft_spec("a=b, c=d") == {"a": "b", "c": "d"}
    assert _parse_draft_spec("a=b,fallback") == {"a": "b", "*": "fallback"}


def test_cli_draft_flag_token_exact(monkeypatch):
    """--draft through the full CLI produces the identical consensus to a
    run without it (greedy exactness at the product surface) — and the
    draft actually engages (placement pinned to one device; a wider
    planner mesh would silently disable speculation and make the
    exactness assertion vacuous)."""
    import io
    import json

    from llm_consensus_tpu.cli.main import main
    from llm_consensus_tpu.providers.tpu import TPUProvider

    orig_prepare = TPUProvider.prepare
    monkeypatch.setattr(
        TPUProvider, "prepare",
        lambda self, models, judge, devices=None: orig_prepare(
            self, models, judge, devices=jax.devices()[:1]
        ),
    )

    def run_cli(extra):
        # Fresh shared provider per invocation: draft state and engines
        # must not carry across the compared runs.
        monkeypatch.setattr(TPUProvider, "_shared", None)
        stdout, stderr = io.StringIO(), io.StringIO()
        code = main(
            ["--models", "tpu:tiny-mistral", "--judge", "tpu:tiny-mistral",
             "--json", "--no-save", "--max-tokens", "16", "exact check"]
            + extra,
            stdin=io.StringIO(""), stdout=stdout, stderr=stderr,
            install_signal_handlers=False,
        )
        assert code == 0, stderr.getvalue()
        return json.loads(stdout.getvalue()), TPUProvider._shared

    plain, _ = run_cli([])
    drafted, provider = run_cli(["--draft", "tiny-llama"])
    assert drafted["responses"][0]["content"] == plain["responses"][0]["content"]
    assert drafted["consensus"] == plain["consensus"]
    entry = provider._specs.get("tiny-mistral")
    assert entry is not None and entry[1] is not None, "draft never engaged"
    assert entry[1].stats["rounds"] > 0
