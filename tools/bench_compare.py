#!/usr/bin/env python3
"""Bench regression sentinel: compare the BENCH_r*.json trajectory.

The repo keeps one ``BENCH_r<NN>.json`` per growth round (bench.py's
``{n, cmd, rc, tail, parsed}`` envelope; ``parsed`` is the flat metric
dict, or null for rounds whose bench crashed before reporting). This
tool normalizes that trajectory and compares the newest parsed round
against the previous parsed round, metric by metric, with a noise band —
the CI job fails when a shared metric regresses past the band, so a
perf-relevant change cannot land silently on a "tests green" signal.

Direction awareness: throughput-like metrics (tokens/s, MFU, MBU, the
headline ``value``) regress DOWN; latency-like metrics (``*latency*``,
``*_ms``, ``*_s``) regress UP. Config echoes (stream counts, chip
counts) and baseline ratios are compared only informationally — a
deliberate config change must not read as a perf regression.

Usage:
    python tools/bench_compare.py                 # newest vs previous
    python tools/bench_compare.py --noise 0.15    # wider band
    python tools/bench_compare.py --self-test     # CI: real pair must
        # pass AND an injected synthetic regression must be flagged

Exit status: 0 = no regression (and, under --self-test, the injected
regression WAS flagged); 1 = regression detected (or self-test failure);
2 = not enough parsed rounds to compare (neutral: does not gate).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Keys that echo CONFIG, not performance: never gate on them.
CONFIG_KEYS = {
    "n_chips", "runs", "tokens_per_run", "batched_streams", "big_streams",
    # Flywheel phase echoes: probe count is config; swap count is the
    # phase's own invariant (always 1 swap), not a performance axis.
    "flywheel_probe_n", "flywheel_swaps",
    # Integrity phase echoes: stream count, the sampling rate, the gate
    # threshold, and the plane's check tally are all config/workload
    # shape — integrity_overhead_pct is the gated metric.
    "integrity_streams", "integrity_sample", "integrity_gate_pct",
    "integrity_checks_on",
}
# Ratios against a fixed baseline move when the baseline is re-anchored;
# informational only.
INFO_KEYS = {"vs_baseline"}

LATENCY_PAT = re.compile(
    r"(latency|_ms$|(?<!per)_s$|wait|ttft)", re.IGNORECASE
)


def load_rounds(bench_dir: str) -> "list[tuple[int, dict]]":
    """Every ``BENCH_r<NN>.json`` as ``(round, envelope)``, ascending."""
    rounds = []
    for path in glob.glob(os.path.join(bench_dir, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(doc, dict):
            rounds.append((int(m.group(1)), doc))
    rounds.sort(key=lambda rd: rd[0])
    return rounds


def numeric_metrics(envelope: dict) -> dict:
    """The round's flat numeric metric dict (empty when unparsed)."""
    parsed = envelope.get("parsed")
    if not isinstance(parsed, dict):
        return {}
    return {
        k: float(v) for k, v in parsed.items()
        if isinstance(v, (int, float)) and not isinstance(v, bool)
    }


def direction(key: str) -> str:
    """``"up"`` when bigger is better, ``"down"`` for latency-like."""
    return "down" if LATENCY_PAT.search(key) else "up"


def compare(prev: dict, cur: dict, noise: float) -> "tuple[list, list]":
    """``(regressions, rows)`` for the shared numeric keys.

    A metric regresses when it moves past the noise band in its bad
    direction: throughput below ``prev*(1-noise)``, latency above
    ``prev*(1+noise)``. Keys only one round has are skipped — phases
    come and go across rounds; the sentinel gates on what both ran.
    """
    regressions, rows = [], []
    for key in sorted(set(prev) & set(cur)):
        p, c = prev[key], cur[key]
        row = {"metric": key, "prev": p, "cur": c}
        if key in CONFIG_KEYS or key in INFO_KEYS:
            row["status"] = "info"
            rows.append(row)
            continue
        if p == 0:
            row["status"] = "skip"  # no meaningful ratio
            rows.append(row)
            continue
        ratio = c / p
        row["ratio"] = round(ratio, 4)
        d = direction(key)
        row["direction"] = d
        bad = ratio < (1.0 - noise) if d == "up" else ratio > (1.0 + noise)
        row["status"] = "regression" if bad else "ok"
        rows.append(row)
        if bad:
            regressions.append(row)
    return regressions, rows


def latest_pair(rounds: "list[tuple[int, dict]]"):
    """The two newest rounds WITH parsed metrics, or None."""
    parsed = [
        (n, numeric_metrics(env)) for n, env in rounds
        if numeric_metrics(env)
    ]
    if len(parsed) < 2:
        return None
    return parsed[-2], parsed[-1]


def inject_regression(prev: dict, cur: dict,
                      noise: float) -> "tuple[dict, str]":
    """A copy of ``cur`` with one gated metric pushed past the band in
    its bad direction RELATIVE TO PREV (degrading the current value
    alone could still sit inside the band when the round genuinely
    improved) — the self-test's synthetic regression."""
    for key in sorted(set(prev) & set(cur)):
        if key in CONFIG_KEYS or key in INFO_KEYS or prev[key] == 0:
            continue
        out = dict(cur)
        factor = 1.0 - 2.0 * noise if direction(key) == "up" else (
            1.0 + 2.0 * noise
        )
        out[key] = prev[key] * factor
        return out, key
    raise SystemExit("self-test: no gateable metric to degrade")


def run_compare(prev_n, prev, cur_n, cur, noise, quiet=False) -> int:
    regressions, rows = compare(prev, cur, noise)
    if not quiet:
        print(f"bench_compare: r{prev_n:02d} -> r{cur_n:02d} "
              f"(noise band {noise:.0%})")
        for row in rows:
            mark = {"regression": "REGRESSION", "ok": "ok",
                    "info": "info", "skip": "skip"}[row["status"]]
            ratio = f" x{row['ratio']}" if "ratio" in row else ""
            print(f"  [{mark:>10}] {row['metric']}: "
                  f"{row['prev']} -> {row['cur']}{ratio}")
    if regressions and not quiet:
        names = ", ".join(r["metric"] for r in regressions)
        print(f"bench_compare: FAIL — {len(regressions)} metric(s) "
              f"regressed past the {noise:.0%} band: {names}")
    return 1 if regressions else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=REPO,
                    help="Directory holding BENCH_r*.json (default: repo root)")
    ap.add_argument("--noise", type=float, default=0.10,
                    help="Relative noise band (default 0.10 = 10%%)")
    ap.add_argument("--self-test", action="store_true",
                    help="CI mode: the real newest pair must pass AND an "
                         "injected synthetic regression must be flagged")
    ap.add_argument("--json", action="store_true",
                    help="Emit the comparison as JSON instead of text")
    args = ap.parse_args(argv)

    rounds = load_rounds(args.dir)
    pair = latest_pair(rounds)
    if pair is None:
        print("bench_compare: fewer than two parsed rounds; nothing to "
              "compare", file=sys.stderr)
        return 2
    (prev_n, prev), (cur_n, cur) = pair

    if args.json:
        regressions, rows = compare(prev, cur, args.noise)
        print(json.dumps({
            "prev_round": prev_n, "cur_round": cur_n,
            "noise": args.noise, "rows": rows,
            "regressions": [r["metric"] for r in regressions],
        }, indent=2))
        return 1 if regressions else 0

    rc = run_compare(prev_n, prev, cur_n, cur, args.noise)
    if not args.self_test:
        return rc
    # Self-test: the real pair must be clean, and a synthetic
    # regression injected into the newest round must be caught — proof
    # the sentinel can actually fire before CI trusts its green.
    if rc != 0:
        return rc
    degraded, key = inject_regression(prev, cur, args.noise)
    rc_injected = run_compare(prev_n, prev, cur_n, degraded, args.noise,
                              quiet=True)
    if rc_injected == 0:
        print(f"bench_compare: SELF-TEST FAIL — injected regression on "
              f"{key!r} was not flagged")
        return 1
    print(f"bench_compare: self-test ok (injected regression on {key!r} "
          f"was flagged; real pair clean)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
