"""Tokenizers for the on-device engine.

Two paths:
  * :class:`ByteTokenizer` — dependency-free byte-level tokenizer (vocab =
    256 bytes + BOS/EOS/PAD). Works with any model whose vocab is ≥ 259;
    the default for random-init demo/bench models and for tests.
  * :func:`load_tokenizer` — loads a real pretrained tokenizer from a local
    HuggingFace directory when one is available (no network access is
    assumed anywhere in this framework).

Streaming: UTF-8 decodes of partial byte sequences are handled by
:class:`StreamDecoder`, which holds back incomplete multi-byte suffixes so
stream callbacks only ever see valid text (the SSE-chunk analog of the
reference's provider streaming, e.g. /root/reference/internal/provider/
openai.go:175-198).
"""

from __future__ import annotations

import os
from typing import Optional, Protocol, Sequence


class Tokenizer(Protocol):
    bos_id: int
    eos_id: int
    pad_id: int

    def encode(self, text: str, add_bos: bool = True) -> list[int]: ...
    def decode(self, ids: Sequence[int]) -> str: ...


class ByteTokenizer:
    """UTF-8 byte tokenizer: ids 0-255 are bytes, then BOS/EOS/PAD.

    Models carry vocabularies much larger than 259 (e.g. 32k/128k); when a
    random-init demo model emits ids beyond the special range they are
    folded back onto bytes (``id % 256``) so generated text is visible
    rather than silently empty. Real checkpoints pair with their own
    pretrained tokenizer and never hit this path.
    """

    def __init__(self) -> None:
        self.bos_id = 256
        self.eos_id = 257
        self.pad_id = 258
        self.vocab_size = 259

    def _to_byte(self, i: int) -> Optional[int]:
        if 0 <= i < 256:
            return i
        if i in (self.bos_id, self.eos_id, self.pad_id):
            return None
        return i % 256

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = list(text.encode("utf-8"))
        return [self.bos_id] + ids if add_bos else ids

    def decode(self, ids: Sequence[int]) -> str:
        data = bytes(b for b in (self._to_byte(i) for i in ids) if b is not None)
        return data.decode("utf-8", errors="replace")


class StreamDecoder:
    """Incremental detokenizer that never emits partial UTF-8 sequences."""

    def __init__(self, tokenizer) -> None:
        self._tok = tokenizer
        self._buf = bytearray()
        self._hf_ids: list[int] = []
        self._hf_emitted = 0
        self._is_byte = isinstance(tokenizer, ByteTokenizer)

    def push(self, token_id: int) -> str:
        """Feed one token id; returns newly-decodable text ('' if none yet)."""
        if self._is_byte:
            b = self._tok._to_byte(token_id)
            if b is not None:
                self._buf.append(b)
            return self._drain()
        # HF tokenizers: decode the full id sequence and emit the stable
        # prefix delta (last char may change while a merge is in flight).
        self._hf_ids.append(token_id)
        text = self._tok.decode(self._hf_ids)
        if text.endswith("�"):  # incomplete sequence pending
            return ""
        delta = text[self._hf_emitted:]
        self._hf_emitted = len(text)
        return delta

    def _drain(self) -> str:
        # Emit the longest prefix of the buffer that is complete UTF-8.
        for cut in range(len(self._buf), max(len(self._buf) - 4, -1), -1):
            try:
                text = self._buf[:cut].decode("utf-8")
            except UnicodeDecodeError:
                continue
            del self._buf[:cut]
            return text
        return ""

    def flush(self) -> str:
        """Emit whatever remains (replacing any dangling partial bytes)."""
        if self._is_byte:
            text = bytes(self._buf).decode("utf-8", errors="replace")
            self._buf.clear()
            return text
        text = self._tok.decode(self._hf_ids)
        delta = text[self._hf_emitted:]
        self._hf_emitted = len(text)
        return delta


def load_tokenizer(path_or_name: Optional[str]):
    """Load a pretrained tokenizer from a local directory, else byte-level.

    ``path_or_name`` may be a filesystem path to a HF tokenizer dir; remote
    lookups are never attempted (zero-egress environment).
    """
    if path_or_name and os.path.isdir(path_or_name):
        try:
            from transformers import AutoTokenizer  # local import: heavy dep

            tok = AutoTokenizer.from_pretrained(
                path_or_name, local_files_only=True
            )
        except Exception as exc:
            # A checkpoint dir without tokenizer files (e.g. an Orbax
            # params-only save) must degrade to the byte tokenizer, not
            # take the engine down inside transformers' loader — but say
            # so: a CORRUPT tokenizer silently downgraded to bytes would
            # otherwise look like a model-quality problem.
            import warnings

            warnings.warn(
                f"no usable tokenizer in {path_or_name!r} "
                f"({type(exc).__name__}: {exc}); using byte-level fallback",
                RuntimeWarning,
                stacklevel=2,
            )
            return ByteTokenizer()
        tok.bos_id = tok.bos_token_id if tok.bos_token_id is not None else 0
        tok.eos_id = tok.eos_token_id if tok.eos_token_id is not None else 0
        tok.pad_id = tok.pad_token_id if tok.pad_token_id is not None else tok.eos_id
        return tok
    return ByteTokenizer()
